"""Channel-backed compiled-DAG execution plane.

Steady-state compiled execution with ZERO control-plane hops per step:
`experimental_compile()` partitions the static schedule into per-actor op
lists, provisions one long-lived execution-loop task per participating
actor (submitted ONCE over the ordered actor plane — the same exec-loop
idiom as `_private/direct.py`), and allocates a seqlock `MutableShmChannel`
per cross-actor edge plus driver input/output channels. After compile,
`execute()` is one shared-memory write and `result()` one shared-memory
read; intermediates flow actor→actor through channels and never touch the
driver, the GCS, or the object store.

Lifecycle contract:
- backpressure — depth-1 mutable channels ack per hop; the driver bounds
  un-drained executions at `max_inflight_executions` by draining the
  oldest result set before admitting a new step;
- errors — a step error is serialized into the faulting op's downstream
  channels as a `_PipelineError` envelope, skips execution of every
  dependent op, and re-raises at the driver with the faulting node named;
- teardown — closing every channel (a shared-memory flag) unblocks all
  loops wherever they are; the driver then joins the loop tasks and
  unlinks every `/dev/shm` file it created;
- fallback — graphs the SPSC channel plane can't serve (task nodes,
  multi-return methods, cross-host actors, local mode) keep the existing
  per-step submit path; `CompiledDAG` records the reason;
- recovery — a dead exec loop (actor crash/SIGKILL) no longer bricks the
  DAG: when the actor has restart budget the driver waits for the core
  restart, re-provisions that actor's loop over FRESH shm channels, and
  rewires the surviving loops in band — a `_Reconfigure` sync/done barrier
  floods the data channels themselves (each loop applies the channel
  remap, forwards the marker downstream, and drains stale payloads), so
  no surviving loop is ever restarted. In-flight steps are replayed from
  the driver's retained input rows when compiled with `enable_retry=True`
  (mirroring `max_task_retries`: execution is at-least-once on surviving
  actors, results exactly-once at the driver), otherwise surfaced as
  per-step errors naming the dead node. Actors out of restart budget
  degrade the whole DAG to the submit-path fallback
  (`fallback_reason="actor_death"`) instead of killing it.

(reference: python/ray/dag/compiled_dag_node.py — do_exec_tasks per-actor
loops, ExecutableTask channel wiring, CompiledDAGRef results; Ray paper
arXiv:1712.05889 §4 motivates keeping the control plane off the ms-scale
hot path.)
"""

from __future__ import annotations

import logging
import os
import threading
import time
import traceback
from typing import Any

from ray_tpu.dag.dag_node import AwaitableDAGFuture
from ray_tpu.exceptions import (ActorDiedError, GetTimeoutError,
                                RayChannelError, RayTaskError)
from ray_tpu.experimental.channel.channel import ChannelClosed
from ray_tpu.experimental.channel.mutable_shm import (MutableShmChannel,
                                                      create_mutable_channel)

logger = logging.getLogger(__name__)

# actor-task method name the worker routes to actor_exec_loop() on a
# dedicated thread (never the shared exec thread — a blocked loop must not
# starve other actors hosted by the same worker process)
from ray_tpu._private.constants import EXEC_LOOP_METHOD  # noqa: E402

# loops re-check liveness at this cadence while blocked on a channel: if the
# backing file vanished (driver died without teardown), they exit instead of
# polling shared memory forever
_LOOP_BLOCK_SLICE_S = 30.0
# driver-side read/write slice between loop-death / drain checks
_DRIVER_BLOCK_SLICE_S = 0.05


# actors currently occupied by a live compiled DAG's exec loop (this
# process's driver). A second compile over the same actor would queue its
# loop task behind the first forever (the GCS caps per-actor dispatch at
# max_concurrency) and hang silently — reject it at compile time instead
# (reference: Ray raises "actor is already in a compiled DAG").
_occupied_actors: set[str] = set()
_occupied_lock = threading.Lock()


def _claim_actors(aids: list) -> None:
    with _occupied_lock:
        busy = [a for a in aids if a in _occupied_actors]
        if busy:
            raise ValueError(
                f"actor {busy[0][:8]} already participates in a live "
                f"compiled DAG; teardown() that DAG first")
        _occupied_actors.update(aids)


def _release_actors(aids: list) -> None:
    with _occupied_lock:
        _occupied_actors.difference_update(aids)


class _PipelineError:
    """Error envelope flowing through channels in place of a value.

    Small and always serializable: downstream ops skip execution and
    forward it; the driver re-raises `.error` (a RayTaskError naming the
    faulting node) from `result()`."""

    def __init__(self, node_label: str, error: RayTaskError):
        self.node_label = node_label
        self.error = error

    def __repr__(self):
        return f"_PipelineError({self.node_label})"


def _task_error(label: str, exc: Exception, tb: str = "") -> _PipelineError:
    if not tb and exc is not None:
        tb = f"{type(exc).__name__}: {exc}"
    err = RayTaskError(label, tb, exc)
    try:
        from ray_tpu._private import serialization as ser

        ser.dumps(err)
    except Exception:
        # unpicklable cause: keep the traceback, drop the cause (mirrors
        # the worker's execute_spec fallback)
        err = RayTaskError(label, tb or repr(exc), None)
    return _PipelineError(label, err)


class _CtrlMsg:
    """Base for control payloads that ride the data channels in place of a
    step value (the in-band recovery protocol)."""


class _Reconfigure(_CtrlMsg):
    """Rewire marker, flooded through the DAG during exec-loop recovery.

    Carries the CUMULATIVE channel remap (old shm path → replacement
    channel) so a loop that missed an earlier epoch still converges to the
    current wiring. A loop receiving one mid-step aborts the step, applies
    the remap, forwards the marker on every out-edge, then drains each
    in-edge up to its own marker — a per-channel barrier that flushes every
    stale payload without restarting the loop."""

    __slots__ = ("epoch", "remap")

    def __init__(self, epoch: int, remap: dict):
        self.epoch = epoch
        self.remap = remap  # {old /dev/shm path: MutableShmChannel}

    def __reduce__(self):
        return (_Reconfigure, (self.epoch, self.remap))

    def __repr__(self):
        return f"_Reconfigure(epoch={self.epoch}, remap={len(self.remap)})"


class _ReconfigureDone(_CtrlMsg):
    """Second barrier wave: a loop forwards this only after draining ALL
    its in-edges, so its receipt downstream proves every upstream loop has
    fully resynced — payloads after it are post-recovery data."""

    __slots__ = ("epoch",)

    def __init__(self, epoch: int):
        self.epoch = epoch

    def __reduce__(self):
        return (_ReconfigureDone, (self.epoch,))

    def __repr__(self):
        return f"_ReconfigureDone(epoch={self.epoch})"


class _ResyncSignal(Exception):
    """Raised inside a step when a channel read returns a `_Reconfigure`:
    unwinds the partial step so the loop can run the resync protocol."""

    def __init__(self, msg: _Reconfigure, channel: MutableShmChannel):
        super().__init__(f"resync epoch {msg.epoch}")
        self.msg = msg
        self.channel = channel


class _PlaneRewired(Exception):
    """Internal driver signal: a recovery completed while the caller was
    blocked on a (now replaced) channel — restart the read/write with the
    executor's fresh channel objects."""


class _PlaneDegraded(Exception):
    """Internal driver signal: the channel plane was dismantled after an
    unrecoverable actor death; `CompiledDAG` catches this and re-dispatches
    on the submit-path fallback."""

    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


class _MoreDead(Exception):
    """Internal driver signal: another exec loop died while a recovery
    barrier was in flight — abort this epoch and fold the new failure into
    the next one."""

    def __init__(self, dead: dict):
        super().__init__(f"{len(dead)} more loop death(s) during recovery")
        self.dead = dead


class _DagInput:
    """Trace-context envelope for channel payloads. The driver wraps the
    input value only when it holds an active trace AND span sampling is on;
    instrumented exec loops re-wrap their sampled intermediates so the
    context propagates DOWNSTREAM through the data channels too — actors
    past the first stage have no driver input channel, and without in-band
    forwarding their sampled steps could never join the caller's trace
    (the channel plane bypasses the submit path where `tracing.inject`
    normally rides, _private/worker.py _trace_field)."""

    __slots__ = ("value", "trace_ctx")

    def __init__(self, value, trace_ctx):
        self.value = value
        self.trace_ctx = trace_ctx

    def __reduce__(self):
        return (_DagInput, (self.value, self.trace_ctx))


# histogram bucket layout for DAG step phases: channel hops are µs-scale,
# user compute can be seconds
_STEP_BUCKETS = (50e-6, 200e-6, 1e-3, 5e-3, 25e-3, 0.1, 0.5, 2.0, 10.0)
_PHASES = (("input_wait", "input/argument wait"),
           ("compute", "user-method compute"),
           ("output_write", "output channel write"))


def _phase_histograms():
    """The three per-step phase histograms, fetched registry-aware (tests
    clear the registry; a module cache would go stale)."""
    from ray_tpu.util.metrics import Histogram, get_or_create

    return tuple(
        get_or_create(Histogram, f"ray_tpu_dag_step_{phase}_seconds",
                      f"compiled-DAG per-step {desc} (channel plane)",
                      boundaries=_STEP_BUCKETS, tag_keys=("dag_id", "node"))
        for phase, desc in _PHASES)


class _LoopInstr:
    """Worker-side exec-loop instrumentation.

    Always-on path (dag_metrics): two `time.monotonic()` reads and one
    PRE-BOUND histogram observe per phase — tag merge/sort happens once at
    loop start, never per step. Every `sample`-th step additionally emits a
    full timeline span into the process task_events buffer, which the
    CoreWorker flusher already ships to the GCS; with an active trace
    context the span joins the caller's trace. When both knobs are off,
    `create` returns None and the loop takes the original untimed path —
    zero emits, zero extra allocation (the tier-1 zero-emit guard)."""

    __slots__ = ("dag_id", "sample", "_bound", "_series")

    def __init__(self, dag_id: str, sample: int, metrics_on: bool, ops):
        self.dag_id = dag_id
        self.sample = sample
        self._bound = None
        self._series: list = []  # (hist, tags) for retirement
        if metrics_on:
            hists = _phase_histograms()
            bound = []
            for op in ops:
                tags = {"dag_id": dag_id, "node": op["label"]}
                bound.append(tuple(h.bind(tags) for h in hists))
                self._series.extend((h, tags) for h in hists)
            self._bound = bound

    @classmethod
    def create(cls, plan: dict) -> "_LoopInstr | None":
        dag_id = plan.get("dag_id")
        sample = int(plan.get("sample") or 0)
        metrics_on = bool(plan.get("metrics"))
        if not dag_id or not (metrics_on or sample):
            return None
        return cls(dag_id, sample, metrics_on, plan["ops"])

    def record(self, i: int, op: dict, step: int, wait_s: float,
               compute_s: float, write_s: float, trace_ctx) -> None:
        if self._bound is not None:
            b = self._bound[i]
            b[0].observe(wait_s)
            b[1].observe(compute_s)
            b[2].observe(write_s)
        if self.sample and step % self.sample == 0:
            self._emit_span(op, step, wait_s, compute_s, write_s, trace_ctx)

    def retire(self) -> None:
        """Drop this DAG's labelsets from the registry (loop exit): dag_id
        is a short-lived tag value — per Metric.remove, leaving it would
        grow every future scrape with dead series across compiles."""
        for h, tags in self._series:
            h.remove(tags)

    def _emit_span(self, op, step, wait_s, compute_s, write_s, trace_ctx):
        from ray_tpu._private import task_events

        end = time.time()
        extra = {"dag_id": self.dag_id, "node": op["label"], "seq": step,
                 "input_wait_s": round(wait_s, 9),
                 "compute_s": round(compute_s, 9),
                 "output_write_s": round(write_s, 9)}
        start = end - (wait_s + compute_s + write_s)
        if trace_ctx:
            # event kind "trace:span" so tracing.assemble() attaches the
            # step under the driver's trace tree
            task_events.emit(
                "trace:span", name=op["label"], start=start, end=end,
                trace_id=trace_ctx["trace_id"],
                span_id=os.urandom(8).hex(),
                parent_span_id=trace_ctx.get("parent_span_id", ""),
                span_kind="dag_step", ok=True, **extra)
        else:
            task_events.emit("dag:step", name=op["label"], start=start,
                             end=end, **extra)


# --------------------------------------------------------------------------
# worker side: the per-actor execution loop
# --------------------------------------------------------------------------


def _loop_read(ch: MutableShmChannel):
    """Blocking read that survives long stalls but notices a vanished
    driver: the backing /dev/shm file disappearing means nobody will ever
    close the channel properly."""
    while True:
        try:
            return ch.read(timeout=_LOOP_BLOCK_SLICE_S)
        except TimeoutError:
            if not os.path.exists(ch.path):
                raise ChannelClosed("channel file unlinked (peer gone)")


def _loop_write(ch: MutableShmChannel, payload: bytes):
    while True:
        try:
            return ch.write_serialized(payload, timeout=_LOOP_BLOCK_SLICE_S)
        except TimeoutError:
            if not os.path.exists(ch.path):
                raise ChannelClosed("channel file unlinked (peer gone)")


def _read_step(ch: MutableShmChannel):
    """Step-path read: data comes back as-is, a `_Reconfigure` aborts the
    step into resync, a stray `_ReconfigureDone` (already honored during a
    prior resync drain) is skipped."""
    while True:
        v = _loop_read(ch)
        if not isinstance(v, _CtrlMsg):
            return v
        if isinstance(v, _Reconfigure):
            raise _ResyncSignal(v, ch)
        # _ReconfigureDone from an epoch this loop already passed: discard


def _bcast(chans: list, blob: bytes) -> None:
    """Round-robin non-blocking fan-out of one control payload. MUST not
    park on a single full channel: during recovery another out-edge's
    reader may be the one whose drain unblocks this one, so every pending
    edge gets retried each round."""
    pending = list(chans)
    while pending:
        progressed = False
        for ch in list(pending):
            try:
                ch.write_serialized(blob, timeout=0)
                pending.remove(ch)
                progressed = True
            except TimeoutError:
                if not os.path.exists(ch.path):
                    raise ChannelClosed("channel file unlinked (peer gone)")
        if pending and not progressed:
            time.sleep(0.0005)


class _LoopState:
    """The exec loop's mutable wiring: op list + driver input channel,
    remappable in place by the recovery protocol (arg encodings and out
    lists are shared structures — one `apply()` rewires every reference)."""

    __slots__ = ("ops", "input", "epoch")

    def __init__(self, ops: list, input_ch):
        self.ops = ops
        self.input = input_ch
        self.epoch = 0

    def in_edges(self) -> list:
        chans = [] if self.input is None else [self.input]
        for op in self.ops:
            for enc in (*op["args"], *op["kwargs"].values()):
                if enc[0] == "chan":
                    chans.append(enc[1])
        return chans

    def out_edges(self) -> list:
        return [ch for op in self.ops for ch in op["out"]]

    def apply(self, remap: dict) -> None:
        if not remap:
            return
        if self.input is not None and self.input.path in remap:
            self.input = remap[self.input.path]
        for op in self.ops:
            op["args"] = [("chan", remap[e[1].path])
                          if e[0] == "chan" and e[1].path in remap else e
                          for e in op["args"]]
            op["kwargs"] = {k: (("chan", remap[e[1].path])
                                if e[0] == "chan" and e[1].path in remap
                                else e)
                            for k, e in op["kwargs"].items()}
            op["out"] = [remap.get(ch.path, ch) for ch in op["out"]]


def _drain_until(state: _LoopState, epoch: int, skip, want_done: bool):
    """Consume every in-edge up to its `_Reconfigure` marker (sync wave) or
    `_ReconfigureDone` (done wave), DISCARDING stale step payloads and
    stale control messages. Returns a higher-epoch `(_Reconfigure, chan)`
    if one arrives mid-drain (another failure during recovery) so the
    caller restarts the protocol, else None."""
    for ch in state.in_edges():
        if skip is not None and ch.path == skip.path:
            continue  # this edge's marker was the trigger, already consumed
        while True:
            v = _loop_read(ch)
            if isinstance(v, _Reconfigure):
                if v.epoch > epoch:
                    return v, ch
                if not want_done and v.epoch >= epoch:
                    break
                continue  # stale sync marker
            if isinstance(v, _ReconfigureDone):
                if want_done and v.epoch >= epoch:
                    break
                continue  # stale done marker
            # stale step payload from the aborted in-flight window
    return None


def _resync(state: _LoopState, rc: _Reconfigure, trigger) -> None:
    """The in-band rewire barrier, run inside the exec loop (the surviving
    loops are never restarted — the protocol rides the data channels):

    1. apply the channel remap (stale endpoints → fresh shm segments);
    2. forward the sync marker on every (post-remap) out-edge, so the
       flood reaches loops the driver cannot safely write to;
    3. drain every in-edge up to its sync marker — flushes in-flight
       payloads of the aborted step window;
    4. wait for the done marker on every in-edge (its writer finished ITS
       drain), proving no stale payload can arrive afterwards;
    5. forward the done marker downstream and resume stepping.

    A higher-epoch marker arriving mid-protocol (another actor died while
    recovering) restarts the procedure at that epoch — the marker carries
    the cumulative remap, so earlier missed epochs are covered."""
    from ray_tpu._private import serialization as ser

    while True:
        state.apply(rc.remap)
        epoch = rc.epoch
        _bcast(state.out_edges(), ser.dumps(rc))
        nxt = _drain_until(state, epoch, trigger, want_done=False)
        if nxt is None:
            nxt = _drain_until(state, epoch, None, want_done=True)
        if nxt is not None:
            rc, trigger = nxt
            continue
        _bcast(state.out_edges(), ser.dumps(_ReconfigureDone(epoch)))
        state.epoch = epoch
        return


def _emit(outs: list, result, label: str):
    """Serialize once, write to every out-edge. Oversized payloads become a
    clear in-band error (the channel stays usable for the next step)."""
    from ray_tpu._private import serialization as ser

    try:
        blob = ser.dumps(result)
    except Exception:
        result = _task_error(label, None, traceback.format_exc())
        blob = ser.dumps(result)
    cap = min(ch.capacity for ch in outs)
    if len(blob) > cap and type(result) is _DagInput:
        # the sampled-step trace envelope must not make a fitting
        # intermediate fail every Nth step: strip it and retry bare
        result = result.value
        blob = ser.dumps(result)
    if len(blob) > cap:
        result = _task_error(label, ValueError(
            f"DAG intermediate from {label} is {len(blob)}B, exceeding the "
            f"channel capacity {cap}B (raise channel_buffer_bytes at "
            f"experimental_compile)"))
        blob = ser.dumps(result)
    for ch in outs:
        _loop_write(ch, blob)


def _run_op(instance, op, args, kwargs, execer):
    """One method invocation; `async def` methods resolve on the actor's
    own event loop (ActorExecutor) so they share its loop-bound state, or
    on a private loop when the actor has none."""
    import inspect

    result = getattr(instance, op["method"])(*args, **kwargs)
    if inspect.iscoroutine(result):
        if execer is not None and getattr(execer, "_loop", None) is not None:
            return execer.run_coroutine_sync(result)
        import asyncio

        return asyncio.run(result)
    return result


def _materialize_args(op: dict, regs: list, inp):
    args = [_decode(e, regs, inp) for e in op["args"]]
    kwargs = {k: _decode(e, regs, inp) for k, e in op["kwargs"].items()}
    return args, kwargs


def _materialize_args_traced(op: dict, regs: list, inp):
    """Instrumented-path variant: channel args may arrive wrapped in a
    _DagInput envelope (an upstream loop forwarding the caller's trace
    context on a sampled step) — unwrap and surface the context."""
    ctx = None

    def dec(e):
        nonlocal ctx
        v = _decode(e, regs, inp)
        if type(v) is _DagInput:
            ctx = v.trace_ctx
            v = v.value
        return v

    args = [dec(e) for e in op["args"]]
    kwargs = {k: dec(e) for k, e in op["kwargs"].items()}
    return args, kwargs, ctx


def _compute_op(instance, op: dict, args, kwargs, execer):
    poisoned = next(
        (v for v in (*args, *kwargs.values())
         if isinstance(v, _PipelineError)), None)
    if poisoned is not None:
        return poisoned  # propagate, don't execute
    try:
        return _run_op(instance, op, args, kwargs, execer)
    except Exception as e:  # noqa: BLE001 — becomes in-band error
        return _task_error(op["label"], e, traceback.format_exc())


def actor_exec_loop(instance, plan: dict, _execer=None) -> dict:
    """Run inside the actor process until the driver tears the DAG down.

    `plan` (built by try_build, shipped once at compile time):
      ops:     [{method, args, kwargs, out, label}] in schedule order; arg
               encodings are ("const", v) | ("reg", i) | ("chan", ch) |
               ("input",)
      input:   driver input channel (also the pacing tick for actors whose
               ops have no channel in-edges), or None
      resync:  recovery epoch when this loop replaces one that died — the
               loop runs the rewire barrier before its first step so its
               fresh channels synchronize with the surviving loops
      dag_id / metrics / sample: instrumentation identity + knobs, stamped
               at compile time from the driver's RayConfig so workers need
               no env propagation
    """
    state = _LoopState(plan["ops"], plan.get("input"))
    instr = _LoopInstr.create(plan)
    try:
        if plan.get("resync"):
            _resync(state, _Reconfigure(int(plan["resync"]), {}), None)
        return _exec_loop_body(instance, state, instr, _execer)
    except ChannelClosed:
        return {"steps": 0, "status": "closed"}
    finally:
        if instr is not None:
            # ANY exit path (ChannelClosed or a crashed loop in a
            # still-alive actor) must drop this DAG's labelsets, or the
            # flusher keeps exporting dead per-dag_id series forever
            instr.retire()


def _exec_loop_body(instance, state: _LoopState, instr, _execer) -> dict:
    steps = 0
    try:
        while True:
            try:
                if instr is None:
                    _one_step(instance, state, _execer)
                else:
                    _one_step_traced(instance, state, instr, steps, _execer)
            except _ResyncSignal as s:
                # a neighbor died and was re-provisioned: abort the partial
                # step (its replay — or its per-step error — is the
                # driver's call), rewire, and keep looping
                _resync(state, s.msg, s.channel)
                continue
            steps += 1
    except ChannelClosed:
        return {"steps": steps, "status": "closed"}


def _one_step(instance, state: _LoopState, _execer) -> None:
    # untimed path: metrics + sampling disabled — no clock reads, no
    # emits, no extra allocation per step
    inp = _read_step(state.input) if state.input is not None else None
    if type(inp) is _DagInput:
        inp = inp.value
    regs: list[Any] = []
    for op in state.ops:
        args, kwargs = _materialize_args(op, regs, inp)
        result = _compute_op(instance, op, args, kwargs, _execer)
        regs.append(result)
        if op["out"]:
            _emit(op["out"], result, op["label"])


def _one_step_traced(instance, state: _LoopState, instr, steps,
                     _execer) -> None:
    t0 = time.monotonic()
    inp = _read_step(state.input) if state.input is not None else None
    t1 = time.monotonic()
    in_wait = t1 - t0
    trace_ctx = None
    if type(inp) is _DagInput:
        trace_ctx = inp.trace_ctx
        inp = inp.value
    regs: list[Any] = []
    sampled = instr.sample and steps % instr.sample == 0
    for i, op in enumerate(state.ops):
        # stamps chain op-to-op: t1 is the previous op's write
        # end (3 clock reads per op, not 5)
        args, kwargs, chan_ctx = _materialize_args_traced(op, regs, inp)
        op_ctx = chan_ctx or trace_ctx
        t2 = time.monotonic()
        result = _compute_op(instance, op, args, kwargs, _execer)
        t3 = time.monotonic()
        regs.append(result)
        if op["out"]:
            wire = result
            if (sampled and op_ctx is not None
                    and not isinstance(result, _PipelineError)):
                # forward the trace context downstream in-band
                # so later stages' sampled steps join the trace
                wire = _DagInput(result, op_ctx)
            _emit(op["out"], wire, op["label"])
        t4 = time.monotonic()
        # the driver-input wait is attributed to the actor's
        # first op (the read happens once per step, loop-level)
        instr.record(i, op, steps,
                     (t2 - t1) + (in_wait if i == 0 else 0.0),
                     t3 - t2, t4 - t3, op_ctx)
        t1 = t4


def _decode(enc, regs, inp):
    kind = enc[0]
    if kind == "const":
        return enc[1]
    if kind == "reg":
        return regs[enc[1]]
    if kind == "chan":
        return _read_step(enc[1])
    if kind == "input":
        return inp
    raise ValueError(f"unknown arg encoding {kind!r}")


# --------------------------------------------------------------------------
# driver side
# --------------------------------------------------------------------------


class ChannelDAGFuture(AwaitableDAGFuture):
    """Handle to one in-flight channel-plane execution. `result()` blocks,
    `done()` polls, `await` works inside a running event loop (via
    AwaitableDAGFuture). Results are delivered in submission order; each
    future caches its own row so `result()` is repeatable."""

    def __init__(self, executor: "ChannelExecutor", seq: int):
        self._ex = executor
        self._seq = seq
        self._have = False
        self._row = None
        self._fetch_lock = threading.Lock()

    def _fetch(self, timeout=None):
        # serialized: `await fut` (a default-executor thread) racing a
        # direct result() must not both _take the row — the loser would
        # see a spurious "already consumed"
        with self._fetch_lock:
            if not self._have:
                self._row = self._ex._take(self._seq, timeout)
                self._have = True
            return self._row

    def result(self, timeout: float | None = None):
        row = self._fetch(timeout)
        for v in row:
            if isinstance(v, _PipelineError):
                raise v.error
        return list(row) if self._ex._multi else row[0]

    def done(self) -> bool:
        return self._have or self._ex._done(self._seq)


class ChannelExecutor:
    """Driver endpoint of the channel plane: owns every channel (creator
    handles → unlink responsibility), the loop-task refs, and the in-order
    result drain."""

    def __init__(self, worker, plans: dict, order: list, in_chans: list,
                 out_chans: list, all_chans: list, *, max_inflight: int,
                 multi: bool, dag_id: str | None = None, sample: int = 0,
                 metrics_on: bool = False, topology: list | None = None,
                 ends: dict | None = None, buffer_bytes: int = 1 << 20,
                 enable_retry: bool = False):
        self._worker = worker
        self._plans = plans
        self._order = order  # actor ids, schedule order
        self._in_chans = in_chans
        self._out_chans = out_chans
        self._all_chans = all_chans
        self._max_inflight = max(1, int(max_inflight))
        self._multi = multi
        self._dag_id = dag_id
        self._sample = int(sample or 0)
        self.topology = list(topology or ())  # channel edges, for registry
        # ---- exec-loop recovery state -----------------------------------
        # channel endpoints by shm path ("driver" or actor id on each side):
        # recovery replaces every channel adjacent to a dead actor and must
        # know who to force-ack (dead reader) vs. where to inject markers
        self._ends: dict[str, tuple[str, str]] = dict(ends or {})
        self._buffer_bytes = int(buffer_bytes)
        self._enable_retry = bool(enable_retry)
        self._inputs: dict[int, bytes] = {}  # seq → retained input payload
        self._epoch = 0  # recovery generation (monotonic per executor)
        # cumulative remap across recoveries, collapsed transitively: a
        # loop that missed epoch N still lands on epoch N+1's channels
        self._cum_remap: dict[str, MutableShmChannel] = {}
        # replaced-but-not-yet-unlinked OLD channels, as (channel,
        # needs_marker, needs_ack) — flags decided with the endpoint
        # knowledge of the epoch that replaced them. Kept across _MoreDead-
        # aborted epochs: a survivor may still be parked on a PREVIOUS
        # epoch's abandoned edge, so every barrier pump serves the whole
        # backlog, and unlink happens only after a barrier completes.
        self._stale: list[tuple[MutableShmChannel, bool, bool]] = []
        self._degraded: str | None = None
        self.recoveries = 0
        # first-op label per actor, for error messages naming the node
        self._labels = {aid: (plans[aid]["ops"][0]["label"]
                              if plans[aid]["ops"] else f"actor:{aid[:8]}")
                        for aid in order}
        self._h_bp = None  # driver-side backpressure-drain phase histogram
        self._h_bp_src = None  # (hist, tags) for series retirement
        if metrics_on and dag_id:
            from ray_tpu.util.metrics import Histogram, get_or_create

            hist = get_or_create(
                Histogram, "ray_tpu_dag_step_backpressure_drain_seconds",
                "compiled-DAG driver wait draining the oldest result at "
                "max_inflight (channel plane)",
                boundaries=_STEP_BUCKETS, tag_keys=("dag_id", "node"))
            tags = {"dag_id": dag_id, "node": "driver"}
            self._h_bp = hist.bind(tags)
            self._h_bp_src = (hist, tags)
        self._loops: dict[str, Any] = {}  # aid → loop-task ObjectRef
        self._lock = threading.Lock()
        self._submitted = 0
        self._drained = 0  # next seq to drain
        self._row: list = []  # partial output row for seq self._drained
        self._results: dict[int, list] = {}
        # fire-and-forget callers (execute() with the future discarded)
        # must not grow driver memory without bound: beyond this depth,
        # drained rows whose future was dropped are evicted oldest-first.
        # Rows with a live future are always kept — the caller can still
        # result() them.
        import weakref

        self._retain = max(2 * self._max_inflight, 32)
        self._live: "weakref.WeakValueDictionary[int, ChannelDAGFuture]" = (
            weakref.WeakValueDictionary())
        self._expired_below = 0  # seqs under this were evicted unconsumed
        # _torn is set OUTSIDE self._lock (own tiny lock for idempotency):
        # teardown must be able to abort a result()/execute() that is
        # blocked on a channel while HOLDING self._lock — those loops poll
        # _torn between read/write slices
        self._torn = False
        self._torn_lock = threading.Lock()

    # ------------------------------------------------------------- provision

    def _provision(self):
        for aid in self._order:
            # max_task_retries=0 per spec: on actor death the GCS must FAIL
            # the loop task (resolving the ref — the driver's liveness
            # signal), never requeue it on the restarted actor, where it
            # would resurrect a stale loop over dead channels and occupy
            # the concurrency slot the re-provisioned loop needs
            ref = self._worker.submit_actor_task(
                aid, EXEC_LOOP_METHOD, (self._plans[aid],), {},
                num_returns=1, max_task_retries=0)[0]
            self._loops[aid] = ref

    @property
    def stats(self) -> dict:
        return {"actors": len(self._order),
                "channels": len(self._all_chans),
                "executions_submitted": self._submitted}

    def _err(self, msg: str, node: str | None = None) -> RayChannelError:
        """Every driver-raised channel error names the dag and, when known,
        the faulting node — a bare 'torn down' is undebuggable once several
        compiled DAGs share a process."""
        where = f" (node {node})" if node else ""
        return RayChannelError(f"compiled DAG {self._dag_id}{where}: {msg}")

    # --------------------------------------------------------------- execute

    def execute(self, input_value) -> ChannelDAGFuture:
        from ray_tpu._private import serialization as ser

        with self._lock:
            if self._torn:
                raise self._err("torn down")
            if self._degraded is not None:
                raise _PlaneDegraded(self._degraded)
            if self._sample and self._submitted % self._sample == 0:
                # envelope the driver's trace context only on steps the
                # loops will actually sample (their step counters advance
                # in lockstep with the submission seq) and only when a
                # trace is active; every other step rides the channel as
                # the raw value
                from ray_tpu.util import tracing

                ctx = tracing.inject()
                if ctx is not None:
                    input_value = _DagInput(input_value, ctx)
            payload = ser.dumps(input_value)
            cap = min(ch.capacity for ch in self._in_chans)
            if len(payload) > cap and type(input_value) is _DagInput:
                # the trace envelope must never turn a fitting input into
                # a 1-in-N failure: drop it (losing this step's trace
                # join), keep the step
                input_value = input_value.value
                payload = ser.dumps(input_value)
            if len(payload) > cap:
                # checked BEFORE any channel write: a partial input fan-out
                # would desynchronize the actor loops
                raise ValueError(
                    f"DAG input is {len(payload)}B, exceeding the channel "
                    f"capacity {cap}B (raise channel_buffer_bytes at "
                    f"experimental_compile)")
            t_bp = None
            while self._submitted - self._drained >= self._max_inflight:
                if t_bp is None:
                    t_bp = time.monotonic()
                try:
                    self._drain_one(deadline=None)
                except _PlaneRewired:
                    continue  # recovery reset the row; re-check the window
            if t_bp is not None and self._h_bp is not None:
                self._h_bp.observe(time.monotonic() - t_bp)
            # the seq is ADMITTED (and its input retained) before the first
            # channel write: a recovery triggered mid-fan-out then treats
            # this step as in-flight — replayed (enable_retry) or failed —
            # instead of leaving the loops half-fed and desynchronized
            seq = self._submitted
            self._submitted += 1
            if self._enable_retry:
                self._inputs[seq] = payload
            fut = ChannelDAGFuture(self, seq)
            self._live[seq] = fut  # registered under the lock: eviction
            # scans _live, so the row must never look abandoned here
            try:
                for ch in self._in_chans:
                    self._write_input(ch, payload)
            except (_PlaneRewired, _PlaneDegraded):
                # the recovery replayed (or error-settled) every in-flight
                # seq — including this one — over the rewired plane; the
                # remaining fan-out writes must not run on top of that
                pass
        return fut

    def _write_input(self, ch, payload: bytes):
        # caller holds the lock. A full input channel means the pipeline is
        # backed up to the driver — drain any completed output rows while
        # waiting, or the driver (sole output consumer) deadlocks the loop
        # it is trying to feed
        while True:
            try:
                return ch.write_serialized(payload,
                                           timeout=_DRIVER_BLOCK_SLICE_S)
            except TimeoutError:
                while self._drain_one_nonblocking():
                    pass
                self._check_loops()
            except ChannelClosed as e:
                dst = self._ends.get(ch.path, ("driver", None))[1]
                node = self._labels.get(dst, dst)
                raise self._err(f"input channel closed: {e}",
                                node=node) from e

    # ----------------------------------------------------------------- drain

    def _take(self, seq: int, timeout: float | None):
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        with self._lock:
            while seq >= self._drained:
                try:
                    self._drain_one(deadline)
                except (_PlaneRewired, _PlaneDegraded):
                    # recovery (or degrade) may have error-settled this seq
                    # already — re-check before reading again
                    continue
            row = self._results.pop(seq, None)
        if row is None:
            if seq < self._expired_below:
                raise self._err(
                    f"result for execution #{seq} expired: it stayed "
                    f"unconsumed beyond the retention window "
                    f"({self._retain} rows)")
            raise self._err(
                f"result for execution #{seq} was already consumed")
        return row

    def _done(self, seq: int) -> bool:
        # true poll: never blocks. The lock-free int read answers already-
        # drained seqs; the opportunistic drain is skipped when a blocked
        # result()/execute() holds the lock (it would block us unboundedly)
        if seq < self._drained:
            return True
        if not self._lock.acquire(blocking=False):
            return False
        try:
            while self._drain_one_nonblocking():
                pass
            return seq < self._drained
        finally:
            self._lock.release()

    def _drain_one(self, deadline):
        """Read one full output row (all output channels, fixed order) into
        the buffer. Caller holds the lock."""
        while len(self._row) < len(self._out_chans):
            ch = self._out_chans[len(self._row)]
            self._row.append(self._read_out(ch, deadline))
        self._store_row()

    def _drain_one_nonblocking(self) -> bool:
        # never blocks, never recovers: this path backs the PUBLIC done()
        # poll (and the recovery pump's own drains), so it must not call
        # _check_loops — a recovery starting inside done() would leak
        # _PlaneRewired out of a non-throwing API
        while len(self._row) < len(self._out_chans):
            ch = self._out_chans[len(self._row)]
            if not ch.poll():
                return False
            try:
                v = ch.read(timeout=0)
            except (TimeoutError, ChannelClosed):
                return False
            if isinstance(v, _CtrlMsg):
                continue  # stray marker from a completed epoch: re-poll
            if type(v) is _DagInput:
                v = v.value
            self._row.append(v)
        self._store_row()
        return True

    def _store_row(self):
        self._results[self._drained] = self._row
        self._row = []
        self._inputs.pop(self._drained, None)  # its replay window closed
        self._drained += 1
        if len(self._results) <= self._retain:
            return
        for seq in list(self._results):  # insertion order = seq order
            if len(self._results) <= self._retain:
                break
            if seq in self._live:
                continue  # future still held: the caller can result() it
            self._results.pop(seq)
            self._expired_below = max(self._expired_below, seq + 1)

    def _read_out(self, ch, deadline):
        while True:
            try:
                v = ch.read(timeout=_DRIVER_BLOCK_SLICE_S)
                if isinstance(v, _CtrlMsg):
                    # stray recovery marker from a completed epoch (e.g. a
                    # done-wave the pump already accounted): not a value
                    continue
                if type(v) is _DagInput:
                    # a sampled step's trace envelope reached a driver
                    # output channel; the caller wants the bare value
                    v = v.value
                return v
            except TimeoutError:
                if self._torn:
                    raise self._err("torn down")
                self._check_loops()
                if deadline is not None and time.monotonic() >= deadline:
                    raise GetTimeoutError(
                        f"timed out waiting for compiled-DAG {self._dag_id} "
                        f"output")
            except ChannelClosed as e:
                if self._torn:
                    raise self._err("torn down") from e
                self._check_loops()
                src = self._ends.get(ch.path, (None, "driver"))[0]
                raise self._err(f"output channel closed: {e}",
                                node=self._labels.get(src, src)) from e

    # ------------------------------------------------------------- recovery

    def _check_loops(self):
        """A loop task resolving while executions are pending means its
        actor died (or the loop crashed). When the actor has restart budget
        the plane is RECOVERED in place: fresh channels for the dead
        actor's edges, a re-provisioned exec loop, and an in-band rewire of
        the surviving loops. Otherwise the DAG degrades to the submit-path
        fallback. Caller holds self._lock."""
        dead = self._dead_loops()
        if dead:
            self._recover(dead)

    def _dead_loops(self) -> dict:
        import ray_tpu

        dead: dict[str, Exception] = {}
        for aid, ref in self._loops.items():
            ready, _ = ray_tpu.wait([ref], num_returns=1, timeout=0)
            if not ready:
                continue
            try:
                out = ray_tpu.get(ref)
                exc: Exception = self._err(
                    f"execution loop exited prematurely: {out!r}",
                    node=self._labels.get(aid))
            except Exception as e:  # noqa: BLE001 — the death reason itself
                exc = e
            dead[aid] = exc
        return dead

    def _recover(self, dead: dict) -> None:
        """Drive recovery to completion (or degrade). Raises _PlaneRewired
        so blocked read/write loops restart on the fresh channel objects,
        or _PlaneDegraded after dismantling the plane."""
        from ray_tpu._private.ray_config import RayConfig

        t0 = time.monotonic()
        deadline = t0 + float(
            getattr(RayConfig.instance(), "dag_recovery_timeout_s", 60.0))
        first_dead = sorted(dead)
        try:
            while dead:
                try:
                    self._recover_epoch(dead, deadline)
                    dead = self._dead_loops()  # another death during replay?
                except _MoreDead as m:
                    # an actor died while the barrier was in flight: fold
                    # the new failure into the next epoch (the cumulative
                    # remap keeps half-rewired loops convergent)
                    dead = m.dead
        except _PlaneDegraded:
            self._note_recovery("degraded", first_dead, t0)
            raise
        self.recoveries += 1
        self._note_recovery("recovered", first_dead, t0)
        raise _PlaneRewired()

    def _note_recovery(self, outcome: str, aids: list, t0: float) -> None:
        """Observability: `ray_tpu_dag_recoveries_total` counter + one
        timeline span per recovery (joins the PR 4 task-events plumbing:
        the driver flusher ships it to the GCS, `ray_tpu timeline` renders
        it under the DAG's row)."""
        try:
            from ray_tpu._private import task_events
            from ray_tpu.util.metrics import Counter, get_or_create

            c = get_or_create(
                Counter, "ray_tpu_dag_recoveries_total",
                "compiled-DAG exec-loop recoveries (channel plane), by "
                "outcome: recovered = plane rewired in place, degraded = "
                "fell back to the submit path",
                tag_keys=("dag_id", "outcome"))
            # unlike the per-step series (retired at teardown), recovery
            # counts survive the DAG: they only exist for DAGs that hit an
            # incident, so cardinality is bounded by actual failures and
            # the evidence outlives the teardown that follows a degrade
            c.inc(tags={"dag_id": self._dag_id or "", "outcome": outcome})
            dur = time.monotonic() - t0
            end = time.time()
            task_events.emit(
                "dag:recovery",
                name="recover:" + "+".join(a[:8] for a in aids),
                start=end - dur, end=end,
                dag_id=self._dag_id, actors=[a[:8] for a in aids],
                epoch=self._epoch, outcome=outcome,
                duration_s=round(dur, 6))
        except Exception:  # noqa: BLE001 — observability must not break recovery
            pass

    def _recover_epoch(self, dead: dict, deadline: float) -> None:
        """One recovery generation: wait for the core restarts, re-channel
        the dead actors' edges, re-provision their loops, then pump the
        in-band barrier until every surviving loop has rewired, and finally
        replay (or error-settle) the in-flight window."""
        from ray_tpu._private import serialization as ser

        self._epoch += 1
        epoch = self._epoch
        for aid in dead:
            self._wait_actor_restart(aid, dead, deadline)

        # fresh segments for every edge touching a dead actor; stale ones
        # are unlinked after the barrier completes. Flags per stale
        # segment, decided NOW (while `dead` describes this epoch):
        # needs_marker — a surviving reader may be parked on it, inject
        # the rewire marker there; needs_ack — its reader is dead, so a
        # surviving writer parked on the ack needs force_ack to move.
        remap: dict[str, MutableShmChannel] = {}
        flags: dict[str, tuple[bool, bool]] = {}
        try:
            for path, (src, dst) in list(self._ends.items()):
                if src in dead or dst in dead:
                    remap[path] = create_mutable_channel(self._buffer_bytes)
                    flags[path] = (
                        src in dead and dst not in dead and dst != "driver",
                        dst in dead)
        except BaseException:
            # a failed create mid-loop (ENOSPC on /dev/shm is the likely
            # one during an incident) must not strand the replacements
            # already created: they are not yet in _all_chans, so neither
            # teardown nor degrade would ever unlink them
            for ch in remap.values():
                ch.close()
                ch.unlink()
            raise
        replaced = self._apply_remap(remap)
        self._stale.extend((ch, *flags[ch.path]) for ch in replaced)

        # re-provision each dead actor's exec loop over the remapped plan;
        # the resync epoch makes the new loop run the barrier before its
        # first step (its fresh in-edges synchronize with the survivors)
        for aid in dead:
            plan = self._plans[aid]
            plan["resync"] = epoch
            try:
                self._loops[aid] = self._worker.submit_actor_task(
                    aid, EXEC_LOOP_METHOD, (plan,), {}, num_returns=1,
                    max_task_retries=0)[0]
            except Exception as e:  # noqa: BLE001 — submit failure → degrade
                self._degrade(dead, f"exec-loop re-provision failed: {e!r}")

        self._pump_barrier(dead, epoch, deadline)
        # barrier done: every loop resynced, so no loop touches ANY stale
        # segment (this epoch's or an aborted predecessor's) anymore
        for ch, _marker, _ack in self._stale:
            try:
                self._all_chans.remove(ch)
            except ValueError:
                pass
            ch.close()
            ch.unlink()
        self._stale.clear()
        # the same invariant retires the remap history: every loop is on
        # the current wiring, so future markers only need remaps newer
        # than this barrier — without this, rc_blob (and every resyncing
        # loop's channel attach set) grows per recovery forever
        self._cum_remap.clear()
        self._replay_or_settle(dead, deadline, ser)

    def _wait_actor_restart(self, aid: str, dead: dict,
                            deadline: float) -> None:
        """Block (poll-style, teardown-abortable) until the GCS restarted
        the actor; degrade when it can't ('actor_death' fallback instead of
        a bricked DAG)."""
        label = self._labels.get(aid, aid[:8])
        while True:
            if self._torn:
                raise self._err("torn down during recovery")
            try:
                info = self._worker.rpc({"type": "actor_info", "aid": aid})
            except Exception as e:  # noqa: BLE001 — GCS unreachable
                self._degrade(dead, f"actor state unavailable ({e!r})")
            if not info.get("found") or info.get("state") == "dead":
                self._degrade(
                    dead, f"actor {aid[:8]} ({label}) died with no restart "
                          f"budget left")
            if info.get("state") == "alive":
                if info.get("host") not in (None, self._worker.host_id):
                    # restarted onto another host: shm channels can't span
                    # hosts — the submit path can
                    self._degrade(
                        dead, f"actor {aid[:8]} restarted on host "
                              f"{info.get('host')} (driver on "
                              f"{self._worker.host_id})")
                return
            if time.monotonic() >= deadline:
                self._degrade(
                    dead, f"actor {aid[:8]} ({label}) restart timed out")
            time.sleep(0.05)

    def _apply_remap(self, remap: dict) -> list:
        """Swap every driver-side reference from the stale channels to the
        fresh ones; returns the replaced (old) channel objects."""
        if not remap:
            return []
        replaced = []
        for plan in self._plans.values():
            st = _LoopState(plan["ops"], plan.get("input"))
            st.apply(remap)
            plan["input"] = st.input
        self._in_chans = [remap.get(c.path, c) for c in self._in_chans]
        self._out_chans = [remap.get(c.path, c) for c in self._out_chans]
        for path, new in remap.items():
            self._ends[new.path] = self._ends.pop(path)
            self._all_chans.append(new)
        for ch in self._all_chans:
            if ch.path in remap:
                replaced.append(ch)
        # collapse the history so older epochs' stale paths point at the
        # CURRENT segment (late loops apply one hop, not a chain)
        for old_path, tgt in list(self._cum_remap.items()):
            if tgt.path in remap:
                self._cum_remap[old_path] = remap[tgt.path]
        self._cum_remap.update(remap)
        return replaced

    def _pump_barrier(self, dead: dict, epoch: int,
                      deadline: float) -> None:
        """Single-threaded driver pump, all non-blocking slices:
        - inject sync+done markers into every channel the DRIVER may write
          (its input channels, post-remap) — the flood covers the rest;
        - inject sync markers into stale out-edges of dead writers, where
          a survivor may be blocked reading a channel no one will feed —
          including edges stranded by a _MoreDead-aborted earlier epoch;
        - force-ack stale channels whose reader died, so survivors blocked
          on a dead reader's ack finish their write and reach the marker;
        - drain every driver out-channel up to its done marker (discarding
          the aborted window's partials);
        - watch for teardown, timeout, and further loop deaths."""
        from ray_tpu._private import serialization as ser

        rc_blob = ser.dumps(_Reconfigure(epoch, dict(self._cum_remap)))
        done_blob = ser.dumps(_ReconfigureDone(epoch))
        # MUST-flush injections: the driver's input channels carry the
        # sync+done waves into the first-stage loops, which consume them
        # during their resync drains — these always land eventually.
        # (channel, [payloads still to write, in order])
        must: list[tuple[MutableShmChannel, list]] = [
            (ch, [rc_blob, done_blob]) for ch in self._in_chans]
        # OPPORTUNISTIC injections: a survivor may be parked reading an
        # abandoned stale edge whose writer died — one sync marker (with
        # the remap) frees it. But if that survivor resynced via ANOTHER
        # in-edge first, nobody ever drains this channel again and the
        # write may never land; the done wave on the output channels
        # already proves every loop resynced, so completion must not wait
        # on these. No done wave here: the edge is abandoned post-remap.
        opportunistic: list[tuple[MutableShmChannel, list]] = [
            (ch, [rc_blob]) for ch, needs_marker, _a in self._stale
            if needs_marker]
        ack = [ch for ch, _m, needs_ack in self._stale if needs_ack]
        out_state = {ch.path: "sync" for ch in self._out_chans}
        self._row = []  # partial pre-crash row: replay regenerates it
        while True:
            if self._torn:
                raise self._err("torn down during recovery")
            if time.monotonic() >= deadline:
                self._degrade(dead, "recovery barrier timed out")
            more = {a: e for a, e in self._dead_loops().items()}
            if more:
                raise _MoreDead(more)
            progressed = False
            for ch, todo in (*must, *opportunistic):
                if todo:
                    try:
                        ch.write_serialized(todo[0], timeout=0)
                        todo.pop(0)
                        progressed = True
                    except (TimeoutError, ValueError):
                        pass
                    except ChannelClosed:
                        todo.clear()
            for ch in ack:
                ch.force_ack()
            for ch in self._out_chans:
                st = out_state[ch.path]
                if st == "done":
                    continue
                try:
                    v = ch.read(timeout=0)
                except (TimeoutError, ChannelClosed):
                    continue
                progressed = True
                if isinstance(v, _Reconfigure) and v.epoch >= epoch:
                    out_state[ch.path] = "sync_seen"
                elif isinstance(v, _ReconfigureDone) and v.epoch >= epoch:
                    out_state[ch.path] = "done"
                # anything else: stale partial-row payload — discarded
            if (all(st == "done" for st in out_state.values())
                    and all(not todo for _ch, todo in must)):
                return
            if not progressed:
                time.sleep(0.001)

    def _replay_or_settle(self, dead: dict, deadline: float, ser) -> None:
        """The in-flight window [drained, submitted): with enable_retry the
        retained input rows are re-fed in order (results stay exactly-once
        at the driver — the barrier flushed every partial payload); without
        it each step settles as an in-band error naming the dead node."""
        pending = range(self._drained, self._submitted)
        if not self._enable_retry:
            labels = ", ".join(
                self._labels.get(a, a[:8]) for a in sorted(dead))
            for seq in pending:
                # settled driver-locally (never rides a channel): keep the
                # BARE ActorDiedError so result() raises the same type the
                # submit plane surfaces for a dead actor
                err = _PipelineError(labels, ActorDiedError(
                    f"compiled DAG {self._dag_id}: execution #{seq} was "
                    f"in flight when node(s) {labels} died "
                    f"(enable_retry=False; compile with "
                    f"enable_retry=True to replay)"))
                self._results[seq] = [err] * len(self._out_chans)
                self._inputs.pop(seq, None)
            self._drained = self._submitted
            self._row = []
            return
        labels = ", ".join(self._labels.get(a, a[:8]) for a in sorted(dead))
        for seq in pending:
            payload = self._inputs.get(seq)
            if payload is None:
                # defensive (every admitted seq retains its row while
                # enable_retry is on): replay a POISON input so the
                # pipeline still produces a row for this seq — skipping it
                # would shift every later seq onto the wrong result row
                payload = ser.dumps(_PipelineError(labels, ActorDiedError(
                    f"compiled DAG {self._dag_id}: execution #{seq} lost "
                    f"its retained input row across the recovery from "
                    f"node(s) {labels}")))
            for ch in self._in_chans:
                while True:
                    if self._torn:
                        raise self._err("torn down during recovery")
                    if time.monotonic() >= deadline:
                        self._degrade(dead, "in-flight replay timed out")
                    more = self._dead_loops()
                    if more:
                        raise _MoreDead(more)
                    try:
                        ch.write_serialized(payload, timeout=0.01)
                        break
                    except TimeoutError:
                        while self._drain_one_nonblocking():
                            pass

    def _degrade(self, dead: dict, detail: str):
        """Dismantle the channel plane after an unrecoverable death: close
        and unlink everything, settle the in-flight window as errors naming
        the dead node, release the actors, and hand the DAG to the
        submit-path fallback. Never returns (raises _PlaneDegraded)."""
        import ray_tpu

        labels = ", ".join(self._labels.get(a, a[:8]) for a in sorted(dead))
        logger.warning(
            "compiled DAG %s: degrading to the submit-path fallback after "
            "death of %s (%s)", self._dag_id, labels, detail)
        self._degraded = f"actor_death: {labels} ({detail})"
        for ch in self._all_chans:
            ch.close()
        for seq in range(self._drained, self._submitted):
            err = _PipelineError(labels, ActorDiedError(
                f"compiled DAG {self._dag_id}: execution #{seq} was in "
                f"flight when node(s) {labels} died and the channel plane "
                f"degraded to the submit path ({detail})"))
            self._results[seq] = [err] * len(self._out_chans)
        self._drained = self._submitted
        self._row = []
        self._inputs.clear()
        # the loops exit via ChannelClosed; join briefly so the actors'
        # concurrency slots free before the submit plane targets them
        t_join = time.monotonic() + 5.0
        for aid, ref in self._loops.items():
            if aid in dead:
                continue  # already resolved (that's how we got here)
            try:
                ray_tpu.get(ref, timeout=max(0.1, t_join - time.monotonic()))
            except Exception:  # noqa: BLE001 — best-effort; teardown re-joins
                pass
        for ch in self._all_chans:
            ch.unlink()
        _release_actors(self._order)
        raise _PlaneDegraded(self._degraded)

    # -------------------------------------------------------------- teardown

    def teardown(self, raise_on_error: bool = False) -> list:
        """Close every channel (unblocking all loops wherever they are),
        join the loops, and unlink every /dev/shm file. Idempotent."""
        import ray_tpu

        with self._torn_lock:  # NOT self._lock: a result()/execute()
            # blocked on a channel holds that and exits via _torn
            if self._torn:
                return []
            self._torn = True
        for ch in self._all_chans:
            ch.close()
        errors: list[tuple[str, Exception]] = []
        still_running: set[str] = set()
        for aid, ref in self._loops.items():
            try:
                ray_tpu.get(ref, timeout=self._join_timeout(aid, ref))
            except GetTimeoutError as e:
                # the loop is wedged in a user op: keep the actor claimed,
                # or a recompile over it would queue behind the stuck loop
                # and hang silently — the very failure the occupancy
                # registry exists to surface
                still_running.add(aid)
                errors.append((aid, e))
            except Exception as e:  # noqa: BLE001 — collected, logged below
                errors.append((aid, e))
        _release_actors([a for a in self._order if a not in still_running])
        for ch in self._all_chans:
            ch.unlink()
        if self._h_bp_src is not None:
            # retire this DAG's driver-side series (see _LoopInstr.retire)
            self._h_bp_src[0].remove(self._h_bp_src[1])
        if errors:
            logger.warning(
                "compiled DAG teardown: %d execution-loop error(s); first "
                "(actor %s): %r", len(errors), errors[0][0][:8],
                errors[0][1])
            if raise_on_error:
                raise errors[0][1]
        return errors

    def _join_timeout(self, aid: str, ref) -> float:
        """Dead-loop fast path: a loop whose ref is unresolved AND whose
        actor is no longer alive will never return on its own — joining it
        with the full budget would burn 30s PER dead actor in teardown.
        The short grace only covers the GCS death-propagation window."""
        import ray_tpu

        try:
            if ray_tpu.wait([ref], num_returns=1, timeout=0)[0]:
                return 30.0  # resolved: the get() below returns immediately
            info = self._worker.rpc({"type": "actor_info", "aid": aid})
            if info.get("found") and info.get("state") == "alive":
                return 30.0
        except Exception:  # noqa: BLE001 — fall through to the full join
            return 30.0
        return 2.0

    def __del__(self):
        # executor dropped without teardown: still release the actors and
        # the /dev/shm bytes. No loop joins here — blocking get()s have no
        # place in GC; the closed flag alone makes the loops exit.
        try:
            with self._torn_lock:
                if self._torn:
                    return
                self._torn = True
            _release_actors(self._order)
            for ch in self._all_chans:
                ch.close()
                ch.unlink()
        except Exception:
            pass


# --------------------------------------------------------------------------
# compile-time planner
# --------------------------------------------------------------------------


def try_build(root, schedule, *, max_inflight: int,
              buffer_bytes: int = 1 << 20, dag_id: str | None = None,
              enable_retry: bool = False):
    """Partition `schedule` into per-actor exec-loop plans and provision
    the channel plane. Returns (executor, None) on success or
    (None, fallback_reason) when the graph/topology can't ride SPSC
    same-host channels."""
    from ray_tpu._private.api import _get_worker
    from ray_tpu._private.ray_config import RayConfig
    from ray_tpu.dag.dag_node import (ClassMethodNode, DAGNode, InputNode,
                                      MultiOutputNode)

    if os.environ.get("RAY_TPU_DAG_CHANNELS", "1") == "0":
        return None, "disabled via RAY_TPU_DAG_CHANNELS=0"
    worker = _get_worker()
    if getattr(worker, "kind", None) != "driver" or not hasattr(worker, "rpc"):
        return None, "channel plane requires a cluster-mode driver"

    multi = isinstance(root, MultiOutputNode)
    outputs = list(root._upstream()) if multi else [root]
    actor_nodes: list = []
    n_inputs = 0
    for node in schedule:
        if node is root and multi:
            continue
        if isinstance(node, InputNode):
            n_inputs += 1
            continue
        if isinstance(node, MultiOutputNode):
            return None, "interior MultiOutputNode requires the submit path"
        if not isinstance(node, ClassMethodNode):
            return None, (f"{type(node).__name__} requires the submit path "
                          "(only actor-method nodes ride channels)")
        if node._method._num_returns != 1:
            return None, "num_returns != 1 requires the submit path"
        actor_nodes.append(node)
    if n_inputs > 1:
        return None, "multiple InputNodes require the submit path"
    if not actor_nodes:
        return None, "no actor-method nodes in the graph"
    for out in outputs:
        if not isinstance(out, ClassMethodNode):
            return None, "non-actor output requires the submit path"

    # same-host gate: SPSC mutable-shm channels need every loop AND the
    # driver on one host; cross-host graphs keep the submit path
    aids: list[str] = []
    for node in actor_nodes:
        aid = node._method._actor_id
        if aid not in aids:
            aids.append(aid)
    try:
        for aid in aids:
            worker.wait_actor_ready(aid, timeout=60.0)
        rows = worker.rpc({"type": "list_workers"}).get("workers", [])
    except Exception as e:  # noqa: BLE001 — compile must not crash; fallback
        return None, f"actor placement unavailable ({e!r})"
    host_of = {r["actor_id"]: r["host"] for r in rows if r.get("actor_id")}
    for aid in aids:
        host = host_of.get(aid)
        if host is None:
            return None, f"actor {aid[:8]} placement unknown"
        if host != worker.host_id:
            return None, (f"actor {aid[:8]} is on host {host} (driver on "
                          f"{worker.host_id}): cross-host edges need the "
                          "submit path")

    # a second compiled DAG over a busy actor would hang, not degrade —
    # raising beats both silent queuing and the submit-path fallback
    # (whose .remote() calls would queue behind the loop just the same)
    _claim_actors(aids)

    # ---- partition into per-actor op lists + allocate per-edge channels
    all_chans: list[MutableShmChannel] = []
    topology: list[dict] = []  # channel edges for the DAG registry
    # shm path → (writer, reader), each "driver" or an actor id: recovery
    # must know every channel adjacent to a dead actor, which old endpoint
    # to force-ack, and where to inject rewire markers
    ends: dict[str, tuple[str, str]] = {}

    def new_chan():
        ch = create_mutable_channel(buffer_bytes)
        all_chans.append(ch)
        return ch

    # instrumentation knobs, stamped into every plan at compile time so
    # the exec loops inherit the DRIVER's config (no worker env plumbing)
    cfg = RayConfig.instance()
    metrics_on = bool(getattr(cfg, "dag_metrics", True))
    sample = max(0, int(getattr(cfg, "dag_span_sample_every", 0)))

    try:
        plans: dict[str, dict] = {
            aid: {"ops": [], "input": None, "needs_input": False,
                  "dag_id": dag_id, "metrics": metrics_on, "sample": sample}
            for aid in aids}
        node_loc: dict[int, tuple[str, int]] = {}  # id(node) → (aid, reg)
        for node in actor_nodes:
            aid = node._method._actor_id
            plan = plans[aid]
            label = f"{node._method._method_name}@actor:{aid[:8]}"

            def enc(a, aid=aid, plan=plan, label=label):
                if isinstance(a, InputNode):
                    plan["needs_input"] = True
                    return ("input",)
                if isinstance(a, DAGNode):
                    p_aid, p_reg = node_loc[id(a)]
                    if p_aid == aid:
                        return ("reg", p_reg)
                    # one channel PER CONSUMING ARG: depth-1 SPSC buffers
                    # can't be read twice per step
                    ch = new_chan()
                    plans[p_aid]["ops"][p_reg]["out"].append(ch)
                    ends[ch.path] = (p_aid, aid)
                    topology.append(
                        {"from": plans[p_aid]["ops"][p_reg]["label"],
                         "to": label})
                    return ("chan", ch)
                return ("const", a)

            op = {"method": node._method._method_name,
                  "args": [enc(a) for a in node._bound_args],
                  "kwargs": {k: enc(v)
                             for k, v in node._bound_kwargs.items()},
                  "out": [],
                  "label": label}
            plan["ops"].append(op)
            node_loc[id(node)] = (aid, len(plan["ops"]) - 1)

        # driver input channels: actors that consume the InputNode, plus a
        # pacing tick for any actor with an un-paced op (no transitive
        # channel/input dependency) — without it a source op would free-run
        # ahead of execute() calls, advancing actor state speculatively
        in_chans: list[MutableShmChannel] = []
        for aid in aids:
            plan = plans[aid]
            paced: list[bool] = []
            for op in plan["ops"]:
                encs = list(op["args"]) + list(op["kwargs"].values())
                paced.append(any(
                    e[0] in ("chan", "input")
                    or (e[0] == "reg" and paced[e[1]]) for e in encs))
            if plan.pop("needs_input") or not all(paced):
                ch = new_chan()
                plan["input"] = ch
                in_chans.append(ch)
                ends[ch.path] = ("driver", aid)
                topology.append({"from": "driver",
                                 "to": f"loop@actor:{aid[:8]}"})

        # driver output channels, one per output occurrence (root order)
        out_chans: list[MutableShmChannel] = []
        for out_node in outputs:
            aid, reg = node_loc[id(out_node)]
            ch = new_chan()
            plans[aid]["ops"][reg]["out"].append(ch)
            out_chans.append(ch)
            ends[ch.path] = (aid, "driver")
            topology.append({"from": plans[aid]["ops"][reg]["label"],
                             "to": "driver"})

        executor = ChannelExecutor(
            worker, plans, aids, in_chans, out_chans, all_chans,
            max_inflight=max_inflight, multi=multi, dag_id=dag_id,
            sample=sample, metrics_on=metrics_on, topology=topology,
            ends=ends, buffer_bytes=buffer_bytes,
            enable_retry=enable_retry)
        executor._provision()
        return executor, None
    except Exception as e:  # noqa: BLE001 — release shm, then fall back
        _release_actors(aids)
        for ch in all_chans:
            ch.close()
            ch.unlink()
        logger.warning("channel-plane compile failed; falling back to the "
                       "submit path: %r", e)
        return None, f"channel plane provisioning failed ({e!r})"
