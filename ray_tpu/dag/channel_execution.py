"""Channel-backed compiled-DAG execution plane.

Steady-state compiled execution with ZERO control-plane hops per step:
`experimental_compile()` partitions the static schedule into per-actor op
lists, provisions one long-lived execution-loop task per participating
actor (submitted ONCE over the ordered actor plane — the same exec-loop
idiom as `_private/direct.py`), and allocates a seqlock `MutableShmChannel`
per cross-actor edge plus driver input/output channels. After compile,
`execute()` is one shared-memory write and `result()` one shared-memory
read; intermediates flow actor→actor through channels and never touch the
driver, the GCS, or the object store.

Lifecycle contract:
- backpressure — depth-1 mutable channels ack per hop; the driver bounds
  un-drained executions at `max_inflight_executions` by draining the
  oldest result set before admitting a new step;
- errors — a step error is serialized into the faulting op's downstream
  channels as a `_PipelineError` envelope, skips execution of every
  dependent op, and re-raises at the driver with the faulting node named;
- teardown — closing every channel (a shared-memory flag) unblocks all
  loops wherever they are; the driver then joins the loop tasks and
  unlinks every `/dev/shm` file it created;
- fallback — graphs the SPSC channel plane can't serve (task nodes,
  multi-return methods, cross-host actors, local mode) keep the existing
  per-step submit path; `CompiledDAG` records the reason.

(reference: python/ray/dag/compiled_dag_node.py — do_exec_tasks per-actor
loops, ExecutableTask channel wiring, CompiledDAGRef results; Ray paper
arXiv:1712.05889 §4 motivates keeping the control plane off the ms-scale
hot path.)
"""

from __future__ import annotations

import logging
import os
import threading
import time
import traceback
from typing import Any

from ray_tpu.dag.dag_node import AwaitableDAGFuture
from ray_tpu.exceptions import (GetTimeoutError, RayChannelError,
                                RayTaskError)
from ray_tpu.experimental.channel.channel import ChannelClosed
from ray_tpu.experimental.channel.mutable_shm import (MutableShmChannel,
                                                      create_mutable_channel)

logger = logging.getLogger(__name__)

# actor-task method name the worker routes to actor_exec_loop() on a
# dedicated thread (never the shared exec thread — a blocked loop must not
# starve other actors hosted by the same worker process)
from ray_tpu._private.task_spec import EXEC_LOOP_METHOD  # noqa: E402

# loops re-check liveness at this cadence while blocked on a channel: if the
# backing file vanished (driver died without teardown), they exit instead of
# polling shared memory forever
_LOOP_BLOCK_SLICE_S = 30.0
# driver-side read/write slice between loop-death / drain checks
_DRIVER_BLOCK_SLICE_S = 0.05


# actors currently occupied by a live compiled DAG's exec loop (this
# process's driver). A second compile over the same actor would queue its
# loop task behind the first forever (the GCS caps per-actor dispatch at
# max_concurrency) and hang silently — reject it at compile time instead
# (reference: Ray raises "actor is already in a compiled DAG").
_occupied_actors: set[str] = set()
_occupied_lock = threading.Lock()


def _claim_actors(aids: list) -> None:
    with _occupied_lock:
        busy = [a for a in aids if a in _occupied_actors]
        if busy:
            raise ValueError(
                f"actor {busy[0][:8]} already participates in a live "
                f"compiled DAG; teardown() that DAG first")
        _occupied_actors.update(aids)


def _release_actors(aids: list) -> None:
    with _occupied_lock:
        _occupied_actors.difference_update(aids)


class _PipelineError:
    """Error envelope flowing through channels in place of a value.

    Small and always serializable: downstream ops skip execution and
    forward it; the driver re-raises `.error` (a RayTaskError naming the
    faulting node) from `result()`."""

    def __init__(self, node_label: str, error: RayTaskError):
        self.node_label = node_label
        self.error = error

    def __repr__(self):
        return f"_PipelineError({self.node_label})"


def _task_error(label: str, exc: Exception, tb: str = "") -> _PipelineError:
    if not tb and exc is not None:
        tb = f"{type(exc).__name__}: {exc}"
    err = RayTaskError(label, tb, exc)
    try:
        from ray_tpu._private import serialization as ser

        ser.dumps(err)
    except Exception:
        # unpicklable cause: keep the traceback, drop the cause (mirrors
        # the worker's execute_spec fallback)
        err = RayTaskError(label, tb or repr(exc), None)
    return _PipelineError(label, err)


class _DagInput:
    """Trace-context envelope for channel payloads. The driver wraps the
    input value only when it holds an active trace AND span sampling is on;
    instrumented exec loops re-wrap their sampled intermediates so the
    context propagates DOWNSTREAM through the data channels too — actors
    past the first stage have no driver input channel, and without in-band
    forwarding their sampled steps could never join the caller's trace
    (the channel plane bypasses the submit path where `tracing.inject`
    normally rides, _private/worker.py _trace_field)."""

    __slots__ = ("value", "trace_ctx")

    def __init__(self, value, trace_ctx):
        self.value = value
        self.trace_ctx = trace_ctx

    def __reduce__(self):
        return (_DagInput, (self.value, self.trace_ctx))


# histogram bucket layout for DAG step phases: channel hops are µs-scale,
# user compute can be seconds
_STEP_BUCKETS = (50e-6, 200e-6, 1e-3, 5e-3, 25e-3, 0.1, 0.5, 2.0, 10.0)
_PHASES = (("input_wait", "input/argument wait"),
           ("compute", "user-method compute"),
           ("output_write", "output channel write"))


def _phase_histograms():
    """The three per-step phase histograms, fetched registry-aware (tests
    clear the registry; a module cache would go stale)."""
    from ray_tpu.util.metrics import Histogram, get_or_create

    return tuple(
        get_or_create(Histogram, f"ray_tpu_dag_step_{phase}_seconds",
                      f"compiled-DAG per-step {desc} (channel plane)",
                      boundaries=_STEP_BUCKETS, tag_keys=("dag_id", "node"))
        for phase, desc in _PHASES)


class _LoopInstr:
    """Worker-side exec-loop instrumentation.

    Always-on path (dag_metrics): two `time.monotonic()` reads and one
    PRE-BOUND histogram observe per phase — tag merge/sort happens once at
    loop start, never per step. Every `sample`-th step additionally emits a
    full timeline span into the process task_events buffer, which the
    CoreWorker flusher already ships to the GCS; with an active trace
    context the span joins the caller's trace. When both knobs are off,
    `create` returns None and the loop takes the original untimed path —
    zero emits, zero extra allocation (the tier-1 zero-emit guard)."""

    __slots__ = ("dag_id", "sample", "_bound", "_series")

    def __init__(self, dag_id: str, sample: int, metrics_on: bool, ops):
        self.dag_id = dag_id
        self.sample = sample
        self._bound = None
        self._series: list = []  # (hist, tags) for retirement
        if metrics_on:
            hists = _phase_histograms()
            bound = []
            for op in ops:
                tags = {"dag_id": dag_id, "node": op["label"]}
                bound.append(tuple(h.bind(tags) for h in hists))
                self._series.extend((h, tags) for h in hists)
            self._bound = bound

    @classmethod
    def create(cls, plan: dict) -> "_LoopInstr | None":
        dag_id = plan.get("dag_id")
        sample = int(plan.get("sample") or 0)
        metrics_on = bool(plan.get("metrics"))
        if not dag_id or not (metrics_on or sample):
            return None
        return cls(dag_id, sample, metrics_on, plan["ops"])

    def record(self, i: int, op: dict, step: int, wait_s: float,
               compute_s: float, write_s: float, trace_ctx) -> None:
        if self._bound is not None:
            b = self._bound[i]
            b[0].observe(wait_s)
            b[1].observe(compute_s)
            b[2].observe(write_s)
        if self.sample and step % self.sample == 0:
            self._emit_span(op, step, wait_s, compute_s, write_s, trace_ctx)

    def retire(self) -> None:
        """Drop this DAG's labelsets from the registry (loop exit): dag_id
        is a short-lived tag value — per Metric.remove, leaving it would
        grow every future scrape with dead series across compiles."""
        for h, tags in self._series:
            h.remove(tags)

    def _emit_span(self, op, step, wait_s, compute_s, write_s, trace_ctx):
        from ray_tpu._private import task_events

        end = time.time()
        extra = {"dag_id": self.dag_id, "node": op["label"], "seq": step,
                 "input_wait_s": round(wait_s, 9),
                 "compute_s": round(compute_s, 9),
                 "output_write_s": round(write_s, 9)}
        start = end - (wait_s + compute_s + write_s)
        if trace_ctx:
            # event kind "trace:span" so tracing.assemble() attaches the
            # step under the driver's trace tree
            task_events.emit(
                "trace:span", name=op["label"], start=start, end=end,
                trace_id=trace_ctx["trace_id"],
                span_id=os.urandom(8).hex(),
                parent_span_id=trace_ctx.get("parent_span_id", ""),
                span_kind="dag_step", ok=True, **extra)
        else:
            task_events.emit("dag:step", name=op["label"], start=start,
                             end=end, **extra)


# --------------------------------------------------------------------------
# worker side: the per-actor execution loop
# --------------------------------------------------------------------------


def _loop_read(ch: MutableShmChannel):
    """Blocking read that survives long stalls but notices a vanished
    driver: the backing /dev/shm file disappearing means nobody will ever
    close the channel properly."""
    while True:
        try:
            return ch.read(timeout=_LOOP_BLOCK_SLICE_S)
        except TimeoutError:
            if not os.path.exists(ch.path):
                raise ChannelClosed("channel file unlinked (peer gone)")


def _loop_write(ch: MutableShmChannel, payload: bytes):
    while True:
        try:
            return ch.write_serialized(payload, timeout=_LOOP_BLOCK_SLICE_S)
        except TimeoutError:
            if not os.path.exists(ch.path):
                raise ChannelClosed("channel file unlinked (peer gone)")


def _emit(outs: list, result, label: str):
    """Serialize once, write to every out-edge. Oversized payloads become a
    clear in-band error (the channel stays usable for the next step)."""
    from ray_tpu._private import serialization as ser

    try:
        blob = ser.dumps(result)
    except Exception:
        result = _task_error(label, None, traceback.format_exc())
        blob = ser.dumps(result)
    cap = min(ch.capacity for ch in outs)
    if len(blob) > cap and type(result) is _DagInput:
        # the sampled-step trace envelope must not make a fitting
        # intermediate fail every Nth step: strip it and retry bare
        result = result.value
        blob = ser.dumps(result)
    if len(blob) > cap:
        result = _task_error(label, ValueError(
            f"DAG intermediate from {label} is {len(blob)}B, exceeding the "
            f"channel capacity {cap}B (raise channel_buffer_bytes at "
            f"experimental_compile)"))
        blob = ser.dumps(result)
    for ch in outs:
        _loop_write(ch, blob)


def _run_op(instance, op, args, kwargs, execer):
    """One method invocation; `async def` methods resolve on the actor's
    own event loop (ActorExecutor) so they share its loop-bound state, or
    on a private loop when the actor has none."""
    import inspect

    result = getattr(instance, op["method"])(*args, **kwargs)
    if inspect.iscoroutine(result):
        if execer is not None and getattr(execer, "_loop", None) is not None:
            return execer.run_coroutine_sync(result)
        import asyncio

        return asyncio.run(result)
    return result


def _materialize_args(op: dict, regs: list, inp):
    args = [_decode(e, regs, inp) for e in op["args"]]
    kwargs = {k: _decode(e, regs, inp) for k, e in op["kwargs"].items()}
    return args, kwargs


def _materialize_args_traced(op: dict, regs: list, inp):
    """Instrumented-path variant: channel args may arrive wrapped in a
    _DagInput envelope (an upstream loop forwarding the caller's trace
    context on a sampled step) — unwrap and surface the context."""
    ctx = None

    def dec(e):
        nonlocal ctx
        v = _decode(e, regs, inp)
        if type(v) is _DagInput:
            ctx = v.trace_ctx
            v = v.value
        return v

    args = [dec(e) for e in op["args"]]
    kwargs = {k: dec(e) for k, e in op["kwargs"].items()}
    return args, kwargs, ctx


def _compute_op(instance, op: dict, args, kwargs, execer):
    poisoned = next(
        (v for v in (*args, *kwargs.values())
         if isinstance(v, _PipelineError)), None)
    if poisoned is not None:
        return poisoned  # propagate, don't execute
    try:
        return _run_op(instance, op, args, kwargs, execer)
    except Exception as e:  # noqa: BLE001 — becomes in-band error
        return _task_error(op["label"], e, traceback.format_exc())


def actor_exec_loop(instance, plan: dict, _execer=None) -> dict:
    """Run inside the actor process until the driver tears the DAG down.

    `plan` (built by try_build, shipped once at compile time):
      ops:     [{method, args, kwargs, out, label}] in schedule order; arg
               encodings are ("const", v) | ("reg", i) | ("chan", ch) |
               ("input",)
      input:   driver input channel (also the pacing tick for actors whose
               ops have no channel in-edges), or None
      dag_id / metrics / sample: instrumentation identity + knobs, stamped
               at compile time from the driver's RayConfig so workers need
               no env propagation
    """
    ops = plan["ops"]
    input_ch = plan.get("input")
    instr = _LoopInstr.create(plan)
    try:
        return _exec_loop_body(instance, ops, input_ch, instr, _execer)
    finally:
        if instr is not None:
            # ANY exit path (ChannelClosed or a crashed loop in a
            # still-alive actor) must drop this DAG's labelsets, or the
            # flusher keeps exporting dead per-dag_id series forever
            instr.retire()


def _exec_loop_body(instance, ops, input_ch, instr, _execer) -> dict:
    steps = 0
    try:
        while True:
            if instr is None:
                # untimed path: metrics + sampling disabled — no clock
                # reads, no emits, no extra allocation per step
                inp = _loop_read(input_ch) if input_ch is not None else None
                if type(inp) is _DagInput:
                    inp = inp.value
                regs: list[Any] = []
                for op in ops:
                    args, kwargs = _materialize_args(op, regs, inp)
                    result = _compute_op(instance, op, args, kwargs, _execer)
                    regs.append(result)
                    if op["out"]:
                        _emit(op["out"], result, op["label"])
            else:
                t0 = time.monotonic()
                inp = _loop_read(input_ch) if input_ch is not None else None
                t1 = time.monotonic()
                in_wait = t1 - t0
                trace_ctx = None
                if type(inp) is _DagInput:
                    trace_ctx = inp.trace_ctx
                    inp = inp.value
                regs = []
                sampled = instr.sample and steps % instr.sample == 0
                for i, op in enumerate(ops):
                    # stamps chain op-to-op: t1 is the previous op's write
                    # end (3 clock reads per op, not 5)
                    args, kwargs, chan_ctx = _materialize_args_traced(
                        op, regs, inp)
                    op_ctx = chan_ctx or trace_ctx
                    t2 = time.monotonic()
                    result = _compute_op(instance, op, args, kwargs, _execer)
                    t3 = time.monotonic()
                    regs.append(result)
                    if op["out"]:
                        wire = result
                        if (sampled and op_ctx is not None
                                and not isinstance(result, _PipelineError)):
                            # forward the trace context downstream in-band
                            # so later stages' sampled steps join the trace
                            wire = _DagInput(result, op_ctx)
                        _emit(op["out"], wire, op["label"])
                    t4 = time.monotonic()
                    # the driver-input wait is attributed to the actor's
                    # first op (the read happens once per step, loop-level)
                    instr.record(i, op, steps,
                                 (t2 - t1) + (in_wait if i == 0 else 0.0),
                                 t3 - t2, t4 - t3, op_ctx)
                    t1 = t4
            steps += 1
    except ChannelClosed:
        return {"steps": steps, "status": "closed"}


def _decode(enc, regs, inp):
    kind = enc[0]
    if kind == "const":
        return enc[1]
    if kind == "reg":
        return regs[enc[1]]
    if kind == "chan":
        return _loop_read(enc[1])
    if kind == "input":
        return inp
    raise ValueError(f"unknown arg encoding {kind!r}")


# --------------------------------------------------------------------------
# driver side
# --------------------------------------------------------------------------


class ChannelDAGFuture(AwaitableDAGFuture):
    """Handle to one in-flight channel-plane execution. `result()` blocks,
    `done()` polls, `await` works inside a running event loop (via
    AwaitableDAGFuture). Results are delivered in submission order; each
    future caches its own row so `result()` is repeatable."""

    def __init__(self, executor: "ChannelExecutor", seq: int):
        self._ex = executor
        self._seq = seq
        self._have = False
        self._row = None
        self._fetch_lock = threading.Lock()

    def _fetch(self, timeout=None):
        # serialized: `await fut` (a default-executor thread) racing a
        # direct result() must not both _take the row — the loser would
        # see a spurious "already consumed"
        with self._fetch_lock:
            if not self._have:
                self._row = self._ex._take(self._seq, timeout)
                self._have = True
            return self._row

    def result(self, timeout: float | None = None):
        row = self._fetch(timeout)
        for v in row:
            if isinstance(v, _PipelineError):
                raise v.error
        return list(row) if self._ex._multi else row[0]

    def done(self) -> bool:
        return self._have or self._ex._done(self._seq)


class ChannelExecutor:
    """Driver endpoint of the channel plane: owns every channel (creator
    handles → unlink responsibility), the loop-task refs, and the in-order
    result drain."""

    def __init__(self, worker, plans: dict, order: list, in_chans: list,
                 out_chans: list, all_chans: list, *, max_inflight: int,
                 multi: bool, dag_id: str | None = None, sample: int = 0,
                 metrics_on: bool = False, topology: list | None = None):
        self._worker = worker
        self._plans = plans
        self._order = order  # actor ids, schedule order
        self._in_chans = in_chans
        self._out_chans = out_chans
        self._all_chans = all_chans
        self._max_inflight = max(1, int(max_inflight))
        self._multi = multi
        self._dag_id = dag_id
        self._sample = int(sample or 0)
        self.topology = list(topology or ())  # channel edges, for registry
        self._h_bp = None  # driver-side backpressure-drain phase histogram
        self._h_bp_src = None  # (hist, tags) for series retirement
        if metrics_on and dag_id:
            from ray_tpu.util.metrics import Histogram, get_or_create

            hist = get_or_create(
                Histogram, "ray_tpu_dag_step_backpressure_drain_seconds",
                "compiled-DAG driver wait draining the oldest result at "
                "max_inflight (channel plane)",
                boundaries=_STEP_BUCKETS, tag_keys=("dag_id", "node"))
            tags = {"dag_id": dag_id, "node": "driver"}
            self._h_bp = hist.bind(tags)
            self._h_bp_src = (hist, tags)
        self._loops: dict[str, Any] = {}  # aid → loop-task ObjectRef
        self._lock = threading.Lock()
        self._submitted = 0
        self._drained = 0  # next seq to drain
        self._row: list = []  # partial output row for seq self._drained
        self._results: dict[int, list] = {}
        # fire-and-forget callers (execute() with the future discarded)
        # must not grow driver memory without bound: beyond this depth,
        # drained rows whose future was dropped are evicted oldest-first.
        # Rows with a live future are always kept — the caller can still
        # result() them.
        import weakref

        self._retain = max(2 * self._max_inflight, 32)
        self._live: "weakref.WeakValueDictionary[int, ChannelDAGFuture]" = (
            weakref.WeakValueDictionary())
        self._expired_below = 0  # seqs under this were evicted unconsumed
        # _torn is set OUTSIDE self._lock (own tiny lock for idempotency):
        # teardown must be able to abort a result()/execute() that is
        # blocked on a channel while HOLDING self._lock — those loops poll
        # _torn between read/write slices
        self._torn = False
        self._torn_lock = threading.Lock()

    # ------------------------------------------------------------- provision

    def _provision(self):
        for aid in self._order:
            ref = self._worker.submit_actor_task(
                aid, EXEC_LOOP_METHOD, (self._plans[aid],), {},
                num_returns=1)[0]
            self._loops[aid] = ref

    @property
    def stats(self) -> dict:
        return {"actors": len(self._order),
                "channels": len(self._all_chans),
                "executions_submitted": self._submitted}

    # --------------------------------------------------------------- execute

    def execute(self, input_value) -> ChannelDAGFuture:
        from ray_tpu._private import serialization as ser

        with self._lock:
            if self._torn:
                raise RayChannelError("compiled DAG was torn down")
            if self._sample and self._submitted % self._sample == 0:
                # envelope the driver's trace context only on steps the
                # loops will actually sample (their step counters advance
                # in lockstep with the submission seq) and only when a
                # trace is active; every other step rides the channel as
                # the raw value
                from ray_tpu.util import tracing

                ctx = tracing.inject()
                if ctx is not None:
                    input_value = _DagInput(input_value, ctx)
            payload = ser.dumps(input_value)
            cap = min(ch.capacity for ch in self._in_chans)
            if len(payload) > cap and type(input_value) is _DagInput:
                # the trace envelope must never turn a fitting input into
                # a 1-in-N failure: drop it (losing this step's trace
                # join), keep the step
                input_value = input_value.value
                payload = ser.dumps(input_value)
            if len(payload) > cap:
                # checked BEFORE any channel write: a partial input fan-out
                # would desynchronize the actor loops
                raise ValueError(
                    f"DAG input is {len(payload)}B, exceeding the channel "
                    f"capacity {cap}B (raise channel_buffer_bytes at "
                    f"experimental_compile)")
            t_bp = None
            while self._submitted - self._drained >= self._max_inflight:
                if t_bp is None:
                    t_bp = time.monotonic()
                self._drain_one(deadline=None)
            if t_bp is not None and self._h_bp is not None:
                self._h_bp.observe(time.monotonic() - t_bp)
            for ch in self._in_chans:
                self._write_input(ch, payload)
            seq = self._submitted
            self._submitted += 1
            fut = ChannelDAGFuture(self, seq)
            self._live[seq] = fut  # registered under the lock: eviction
            # scans _live, so the row must never look abandoned here
        return fut

    def _write_input(self, ch, payload: bytes):
        # caller holds the lock. A full input channel means the pipeline is
        # backed up to the driver — drain any completed output rows while
        # waiting, or the driver (sole output consumer) deadlocks the loop
        # it is trying to feed
        while True:
            try:
                return ch.write_serialized(payload,
                                           timeout=_DRIVER_BLOCK_SLICE_S)
            except TimeoutError:
                while self._drain_one_nonblocking():
                    pass
                self._raise_if_loops_dead()
            except ChannelClosed as e:
                raise RayChannelError(
                    f"DAG input channel closed: {e}") from e

    # ----------------------------------------------------------------- drain

    def _take(self, seq: int, timeout: float | None):
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        with self._lock:
            while seq >= self._drained:
                self._drain_one(deadline)
            row = self._results.pop(seq, None)
        if row is None:
            if seq < self._expired_below:
                raise RayChannelError(
                    f"result for execution #{seq} expired: it stayed "
                    f"unconsumed beyond the retention window "
                    f"({self._retain} rows)")
            raise RayChannelError(
                f"result for execution #{seq} was already consumed")
        return row

    def _done(self, seq: int) -> bool:
        # true poll: never blocks. The lock-free int read answers already-
        # drained seqs; the opportunistic drain is skipped when a blocked
        # result()/execute() holds the lock (it would block us unboundedly)
        if seq < self._drained:
            return True
        if not self._lock.acquire(blocking=False):
            return False
        try:
            while self._drain_one_nonblocking():
                pass
            return seq < self._drained
        finally:
            self._lock.release()

    def _drain_one(self, deadline):
        """Read one full output row (all output channels, fixed order) into
        the buffer. Caller holds the lock."""
        while len(self._row) < len(self._out_chans):
            ch = self._out_chans[len(self._row)]
            self._row.append(self._read_out(ch, deadline))
        self._store_row()

    def _drain_one_nonblocking(self) -> bool:
        while len(self._row) < len(self._out_chans):
            ch = self._out_chans[len(self._row)]
            if not ch.poll():
                return False
            self._row.append(self._read_out(ch, None))
        self._store_row()
        return True

    def _store_row(self):
        self._results[self._drained] = self._row
        self._row = []
        self._drained += 1
        if len(self._results) <= self._retain:
            return
        for seq in list(self._results):  # insertion order = seq order
            if len(self._results) <= self._retain:
                break
            if seq in self._live:
                continue  # future still held: the caller can result() it
            self._results.pop(seq)
            self._expired_below = max(self._expired_below, seq + 1)

    def _read_out(self, ch, deadline):
        while True:
            try:
                v = ch.read(timeout=_DRIVER_BLOCK_SLICE_S)
                if type(v) is _DagInput:
                    # a sampled step's trace envelope reached a driver
                    # output channel; the caller wants the bare value
                    v = v.value
                return v
            except TimeoutError:
                if self._torn:
                    raise RayChannelError("compiled DAG was torn down")
                self._raise_if_loops_dead()
                if deadline is not None and time.monotonic() >= deadline:
                    raise GetTimeoutError(
                        "timed out waiting for compiled-DAG output")
            except ChannelClosed as e:
                if self._torn:
                    raise RayChannelError(
                        "compiled DAG was torn down") from e
                self._raise_if_loops_dead()
                raise RayChannelError(
                    f"DAG output channel closed: {e}") from e

    def _raise_if_loops_dead(self):
        """A loop task resolving while executions are pending means its
        actor died (or the loop crashed) — surface that instead of letting
        the driver block on a channel nobody will ever write."""
        import ray_tpu

        for aid, ref in self._loops.items():
            ready, _ = ray_tpu.wait([ref], num_returns=1, timeout=0)
            if not ready:
                continue
            try:
                out = ray_tpu.get(ref)
            except Exception as e:
                raise RayChannelError(
                    f"compiled-DAG execution loop on actor {aid[:8]} died: "
                    f"{e}") from e
            raise RayChannelError(
                f"compiled-DAG execution loop on actor {aid[:8]} exited "
                f"prematurely: {out!r}")

    # -------------------------------------------------------------- teardown

    def teardown(self, raise_on_error: bool = False) -> list:
        """Close every channel (unblocking all loops wherever they are),
        join the loops, and unlink every /dev/shm file. Idempotent."""
        import ray_tpu

        with self._torn_lock:  # NOT self._lock: a result()/execute()
            # blocked on a channel holds that and exits via _torn
            if self._torn:
                return []
            self._torn = True
        for ch in self._all_chans:
            ch.close()
        errors: list[tuple[str, Exception]] = []
        still_running: set[str] = set()
        for aid, ref in self._loops.items():
            try:
                ray_tpu.get(ref, timeout=30.0)
            except GetTimeoutError as e:
                # the loop is wedged in a user op: keep the actor claimed,
                # or a recompile over it would queue behind the stuck loop
                # and hang silently — the very failure the occupancy
                # registry exists to surface
                still_running.add(aid)
                errors.append((aid, e))
            except Exception as e:  # noqa: BLE001 — collected, logged below
                errors.append((aid, e))
        _release_actors([a for a in self._order if a not in still_running])
        for ch in self._all_chans:
            ch.unlink()
        if self._h_bp_src is not None:
            # retire this DAG's driver-side series (see _LoopInstr.retire)
            self._h_bp_src[0].remove(self._h_bp_src[1])
        if errors:
            logger.warning(
                "compiled DAG teardown: %d execution-loop error(s); first "
                "(actor %s): %r", len(errors), errors[0][0][:8],
                errors[0][1])
            if raise_on_error:
                raise errors[0][1]
        return errors

    def __del__(self):
        # executor dropped without teardown: still release the actors and
        # the /dev/shm bytes. No loop joins here — blocking get()s have no
        # place in GC; the closed flag alone makes the loops exit.
        try:
            with self._torn_lock:
                if self._torn:
                    return
                self._torn = True
            _release_actors(self._order)
            for ch in self._all_chans:
                ch.close()
                ch.unlink()
        except Exception:
            pass


# --------------------------------------------------------------------------
# compile-time planner
# --------------------------------------------------------------------------


def try_build(root, schedule, *, max_inflight: int,
              buffer_bytes: int = 1 << 20, dag_id: str | None = None):
    """Partition `schedule` into per-actor exec-loop plans and provision
    the channel plane. Returns (executor, None) on success or
    (None, fallback_reason) when the graph/topology can't ride SPSC
    same-host channels."""
    from ray_tpu._private.api import _get_worker
    from ray_tpu._private.ray_config import RayConfig
    from ray_tpu.dag.dag_node import (ClassMethodNode, DAGNode, InputNode,
                                      MultiOutputNode)

    if os.environ.get("RAY_TPU_DAG_CHANNELS", "1") == "0":
        return None, "disabled via RAY_TPU_DAG_CHANNELS=0"
    worker = _get_worker()
    if getattr(worker, "kind", None) != "driver" or not hasattr(worker, "rpc"):
        return None, "channel plane requires a cluster-mode driver"

    multi = isinstance(root, MultiOutputNode)
    outputs = list(root._upstream()) if multi else [root]
    actor_nodes: list = []
    n_inputs = 0
    for node in schedule:
        if node is root and multi:
            continue
        if isinstance(node, InputNode):
            n_inputs += 1
            continue
        if isinstance(node, MultiOutputNode):
            return None, "interior MultiOutputNode requires the submit path"
        if not isinstance(node, ClassMethodNode):
            return None, (f"{type(node).__name__} requires the submit path "
                          "(only actor-method nodes ride channels)")
        if node._method._num_returns != 1:
            return None, "num_returns != 1 requires the submit path"
        actor_nodes.append(node)
    if n_inputs > 1:
        return None, "multiple InputNodes require the submit path"
    if not actor_nodes:
        return None, "no actor-method nodes in the graph"
    for out in outputs:
        if not isinstance(out, ClassMethodNode):
            return None, "non-actor output requires the submit path"

    # same-host gate: SPSC mutable-shm channels need every loop AND the
    # driver on one host; cross-host graphs keep the submit path
    aids: list[str] = []
    for node in actor_nodes:
        aid = node._method._actor_id
        if aid not in aids:
            aids.append(aid)
    try:
        for aid in aids:
            worker.wait_actor_ready(aid, timeout=60.0)
        rows = worker.rpc({"type": "list_workers"}).get("workers", [])
    except Exception as e:  # noqa: BLE001 — compile must not crash; fallback
        return None, f"actor placement unavailable ({e!r})"
    host_of = {r["actor_id"]: r["host"] for r in rows if r.get("actor_id")}
    for aid in aids:
        host = host_of.get(aid)
        if host is None:
            return None, f"actor {aid[:8]} placement unknown"
        if host != worker.host_id:
            return None, (f"actor {aid[:8]} is on host {host} (driver on "
                          f"{worker.host_id}): cross-host edges need the "
                          "submit path")

    # a second compiled DAG over a busy actor would hang, not degrade —
    # raising beats both silent queuing and the submit-path fallback
    # (whose .remote() calls would queue behind the loop just the same)
    _claim_actors(aids)

    # ---- partition into per-actor op lists + allocate per-edge channels
    all_chans: list[MutableShmChannel] = []
    topology: list[dict] = []  # channel edges for the DAG registry

    def new_chan():
        ch = create_mutable_channel(buffer_bytes)
        all_chans.append(ch)
        return ch

    # instrumentation knobs, stamped into every plan at compile time so
    # the exec loops inherit the DRIVER's config (no worker env plumbing)
    cfg = RayConfig.instance()
    metrics_on = bool(getattr(cfg, "dag_metrics", True))
    sample = max(0, int(getattr(cfg, "dag_span_sample_every", 0)))

    try:
        plans: dict[str, dict] = {
            aid: {"ops": [], "input": None, "needs_input": False,
                  "dag_id": dag_id, "metrics": metrics_on, "sample": sample}
            for aid in aids}
        node_loc: dict[int, tuple[str, int]] = {}  # id(node) → (aid, reg)
        for node in actor_nodes:
            aid = node._method._actor_id
            plan = plans[aid]
            label = f"{node._method._method_name}@actor:{aid[:8]}"

            def enc(a, aid=aid, plan=plan, label=label):
                if isinstance(a, InputNode):
                    plan["needs_input"] = True
                    return ("input",)
                if isinstance(a, DAGNode):
                    p_aid, p_reg = node_loc[id(a)]
                    if p_aid == aid:
                        return ("reg", p_reg)
                    # one channel PER CONSUMING ARG: depth-1 SPSC buffers
                    # can't be read twice per step
                    ch = new_chan()
                    plans[p_aid]["ops"][p_reg]["out"].append(ch)
                    topology.append(
                        {"from": plans[p_aid]["ops"][p_reg]["label"],
                         "to": label})
                    return ("chan", ch)
                return ("const", a)

            op = {"method": node._method._method_name,
                  "args": [enc(a) for a in node._bound_args],
                  "kwargs": {k: enc(v)
                             for k, v in node._bound_kwargs.items()},
                  "out": [],
                  "label": label}
            plan["ops"].append(op)
            node_loc[id(node)] = (aid, len(plan["ops"]) - 1)

        # driver input channels: actors that consume the InputNode, plus a
        # pacing tick for any actor with an un-paced op (no transitive
        # channel/input dependency) — without it a source op would free-run
        # ahead of execute() calls, advancing actor state speculatively
        in_chans: list[MutableShmChannel] = []
        for aid in aids:
            plan = plans[aid]
            paced: list[bool] = []
            for op in plan["ops"]:
                encs = list(op["args"]) + list(op["kwargs"].values())
                paced.append(any(
                    e[0] in ("chan", "input")
                    or (e[0] == "reg" and paced[e[1]]) for e in encs))
            if plan.pop("needs_input") or not all(paced):
                ch = new_chan()
                plan["input"] = ch
                in_chans.append(ch)
                topology.append({"from": "driver",
                                 "to": f"loop@actor:{aid[:8]}"})

        # driver output channels, one per output occurrence (root order)
        out_chans: list[MutableShmChannel] = []
        for out_node in outputs:
            aid, reg = node_loc[id(out_node)]
            ch = new_chan()
            plans[aid]["ops"][reg]["out"].append(ch)
            out_chans.append(ch)
            topology.append({"from": plans[aid]["ops"][reg]["label"],
                             "to": "driver"})

        executor = ChannelExecutor(
            worker, plans, aids, in_chans, out_chans, all_chans,
            max_inflight=max_inflight, multi=multi, dag_id=dag_id,
            sample=sample, metrics_on=metrics_on, topology=topology)
        executor._provision()
        return executor, None
    except Exception as e:  # noqa: BLE001 — release shm, then fall back
        _release_actors(aids)
        for ch in all_chans:
            ch.close()
            ch.unlink()
        logger.warning("channel-plane compile failed; falling back to the "
                       "submit path: %r", e)
        return None, f"channel plane provisioning failed ({e!r})"
