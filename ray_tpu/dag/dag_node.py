"""DAG nodes: build lazily with .bind(), run with .execute() or compile.

(reference: python/ray/dag/dag_node.py (base), input_node.py:InputNode,
output_node.py:MultiOutputNode, class_node.py (actor-method binding),
compiled_dag_node.py:805 CompiledDAG — compile pre-plans a static execution
schedule (topological, per-actor serialized) so repeated executions skip
graph traversal and argument re-resolution (:2002 _build_execution_schedule).

Execution maps each node to the existing task/actor planes: FunctionNode →
task submit, ClassMethodNode → ordered actor submit; intermediate values
never return to the driver — downstream nodes consume upstream ObjectRefs.)
"""

from __future__ import annotations

from typing import Any

import ray_tpu


class DAGNode:
    def __init__(self, args: tuple, kwargs: dict):
        self._bound_args = args
        self._bound_kwargs = kwargs

    # ------------------------------------------------------------- traversal

    def _upstream(self) -> list["DAGNode"]:
        ups = [a for a in self._bound_args if isinstance(a, DAGNode)]
        ups += [v for v in self._bound_kwargs.values() if isinstance(v, DAGNode)]
        return ups

    def _topo(self) -> list["DAGNode"]:
        order: list[DAGNode] = []
        seen: set[int] = set()

        def visit(n: DAGNode):
            if id(n) in seen:
                return
            seen.add(id(n))
            for u in n._upstream():
                visit(u)
            order.append(n)

        visit(self)
        return order

    # ------------------------------------------------------------- execution

    def _resolve(self, values: dict, input_value) -> tuple[tuple, dict]:
        def sub(a):
            return values[id(a)] if isinstance(a, DAGNode) else a

        args = tuple(sub(a) for a in self._bound_args)
        kwargs = {k: sub(v) for k, v in self._bound_kwargs.items()}
        return args, kwargs

    def _submit(self, args: tuple, kwargs: dict):
        raise NotImplementedError

    def execute(self, input_value: Any = None):
        """Eager one-shot execution; returns ObjectRef(s) of this node."""
        values: dict[int, Any] = {}
        for node in self._topo():
            if isinstance(node, InputNode):
                values[id(node)] = input_value
            elif isinstance(node, MultiOutputNode):
                values[id(node)] = [values[id(u)] for u in node._upstream()]
            else:
                args, kwargs = node._resolve(values, input_value)
                values[id(node)] = node._submit(args, kwargs)
        return values[id(self)]

    def experimental_compile(self) -> "CompiledDAG":
        return CompiledDAG(self)


class InputNode(DAGNode):
    """(reference: dag/input_node.py — context-manager style `with InputNode()
    as inp:`.)"""

    def __init__(self):
        super().__init__((), {})

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class FunctionNode(DAGNode):
    def __init__(self, remote_fn, args, kwargs):
        super().__init__(args, kwargs)
        self._fn = remote_fn

    def _submit(self, args, kwargs):
        return self._fn.remote(*args, **kwargs)


class ClassMethodNode(DAGNode):
    def __init__(self, actor_method, args, kwargs):
        super().__init__(args, kwargs)
        self._method = actor_method

    def _submit(self, args, kwargs):
        return self._method.remote(*args, **kwargs)


class MultiOutputNode(DAGNode):
    """(reference: dag/output_node.py — groups several leaves.)"""

    def __init__(self, outputs: list[DAGNode]):
        super().__init__(tuple(outputs), {})


class CompiledDAG:
    """(reference: dag/compiled_dag_node.py:805 — the compiled form caches
    the schedule; execute() is the steady-state entry point (:2546).)"""

    def __init__(self, root: DAGNode):
        self._root = root
        self._schedule = root._topo()  # static schedule, computed once
        self._input_nodes = [n for n in self._schedule if isinstance(n, InputNode)]

    def execute(self, input_value: Any = None):
        values: dict[int, Any] = {}
        for node in self._schedule:
            if isinstance(node, InputNode):
                values[id(node)] = input_value
            elif isinstance(node, MultiOutputNode):
                values[id(node)] = [values[id(u)] for u in node._upstream()]
            else:
                args, kwargs = node._resolve(values, input_value)
                values[id(node)] = node._submit(args, kwargs)
        return values[id(self._root)]

    def teardown(self):
        self._schedule = []


def _function_bind(self, *args, **kwargs) -> FunctionNode:
    return FunctionNode(self, args, kwargs)


def _method_bind(self, *args, **kwargs) -> ClassMethodNode:
    return ClassMethodNode(self, args, kwargs)


# graft .bind onto the existing handle types (the reference defines bind on
# RemoteFunction and ActorMethod the same way)
from ray_tpu.actor import ActorMethod  # noqa: E402
from ray_tpu.remote_function import RemoteFunction  # noqa: E402

RemoteFunction.bind = _function_bind
ActorMethod.bind = _method_bind
