"""DAG nodes: build lazily with .bind(), run with .execute() or compile.

(reference: python/ray/dag/dag_node.py (base), input_node.py:InputNode,
output_node.py:MultiOutputNode, class_node.py (actor-method binding),
compiled_dag_node.py:805 CompiledDAG — compile pre-plans a static execution
schedule (topological, per-actor serialized) so repeated executions skip
graph traversal and argument re-resolution (:2002 _build_execution_schedule).

Execution maps each node to the existing task/actor planes: FunctionNode →
task submit, ClassMethodNode → ordered actor submit; intermediate values
never return to the driver — downstream nodes consume upstream ObjectRefs.)
"""

from __future__ import annotations

from typing import Any

import ray_tpu


class DAGNode:
    def __init__(self, args: tuple, kwargs: dict):
        self._bound_args = args
        self._bound_kwargs = kwargs

    # ------------------------------------------------------------- traversal

    def _upstream(self) -> list["DAGNode"]:
        ups = [a for a in self._bound_args if isinstance(a, DAGNode)]
        ups += [v for v in self._bound_kwargs.values() if isinstance(v, DAGNode)]
        return ups

    def _topo(self) -> list["DAGNode"]:
        order: list[DAGNode] = []
        seen: set[int] = set()

        def visit(n: DAGNode):
            if id(n) in seen:
                return
            seen.add(id(n))
            for u in n._upstream():
                visit(u)
            order.append(n)

        visit(self)
        return order

    # ------------------------------------------------------------- execution

    def _resolve(self, values: dict, input_value) -> tuple[tuple, dict]:
        def sub(a):
            return values[id(a)] if isinstance(a, DAGNode) else a

        args = tuple(sub(a) for a in self._bound_args)
        kwargs = {k: sub(v) for k, v in self._bound_kwargs.items()}
        return args, kwargs

    def _submit(self, args: tuple, kwargs: dict):
        raise NotImplementedError

    def execute(self, input_value: Any = None):
        """Eager one-shot execution; returns ObjectRef(s) of this node."""
        values: dict[int, Any] = {}
        for node in self._topo():
            if isinstance(node, InputNode):
                values[id(node)] = input_value
            elif isinstance(node, MultiOutputNode):
                values[id(node)] = [values[id(u)] for u in node._upstream()]
            else:
                args, kwargs = node._resolve(values, input_value)
                values[id(node)] = node._submit(args, kwargs)
        return values[id(self)]

    def experimental_compile(self, *, max_inflight_executions: int = 10,
                             enable_channel_execution: bool = True,
                             channel_buffer_bytes: int = 1 << 20,
                             enable_retry: bool = False) -> "CompiledDAG":
        """Compile the graph for repeated steady-state execution. When the
        topology allows (actor-method nodes only, every actor on the
        driver's host), per-actor execution loops are provisioned over
        mutable-shm channels and each step skips the task-submission
        control plane entirely; otherwise the cached-schedule submit path
        is used (`CompiledDAG.fallback_reason` says why).

        `enable_retry` mirrors `max_task_retries` semantics for the channel
        plane's exec-loop recovery: when an actor with restart budget dies
        mid-step, the driver retains each in-flight input row and REPLAYS
        it over the rewired plane (execution becomes at-least-once on
        surviving actors; results stay exactly-once at the driver). Default
        off: in-flight steps then surface per-step errors naming the dead
        node while the recovered DAG keeps serving later executions."""
        return CompiledDAG(self,
                           max_inflight_executions=max_inflight_executions,
                           enable_channel_execution=enable_channel_execution,
                           channel_buffer_bytes=channel_buffer_bytes,
                           enable_retry=enable_retry)


class InputNode(DAGNode):
    """(reference: dag/input_node.py — context-manager style `with InputNode()
    as inp:`.)"""

    def __init__(self):
        super().__init__((), {})

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class FunctionNode(DAGNode):
    def __init__(self, remote_fn, args, kwargs):
        super().__init__(args, kwargs)
        self._fn = remote_fn

    def _submit(self, args, kwargs):
        return self._fn.remote(*args, **kwargs)


class ClassMethodNode(DAGNode):
    def __init__(self, actor_method, args, kwargs):
        super().__init__(args, kwargs)
        self._method = actor_method

    def _submit(self, args, kwargs):
        return self._method.remote(*args, **kwargs)


class MultiOutputNode(DAGNode):
    """(reference: dag/output_node.py — groups several leaves.)"""

    def __init__(self, outputs: list[DAGNode]):
        super().__init__(tuple(outputs), {})


class AwaitableDAGFuture:
    """Shared future protocol for both execution planes: marks the handle
    for `ray_tpu.get()` resolution and adapts blocking `.result()` to
    `await` (subclasses provide `result`)."""

    __dag_future__ = True  # ray_tpu.get() resolves these via .result()

    def __await__(self):
        import asyncio

        # get_event_loop() is deprecated and raises on 3.12 without a
        # running loop; awaiting implies one is running
        loop = asyncio.get_running_loop()
        fut = loop.run_in_executor(None, self.result)
        return fut.__await__()


class DAGFuture(AwaitableDAGFuture):
    """Handle to one in-flight compiled-DAG execution: blocking `.result()`
    or `await` (reference: compiled execute_async returns an awaitable,
    compiled_dag_node.py:2627)."""

    def __init__(self, output):
        self._output = output

    def _refs(self):
        return (self._output if isinstance(self._output, list)
                else [self._output])

    def done(self) -> bool:
        ready, _ = ray_tpu.wait(self._refs(),
                                num_returns=len(self._refs()), timeout=0)
        return len(ready) == len(self._refs())

    def result(self, timeout: float | None = None):
        vals = ray_tpu.get(self._refs(), timeout=timeout)
        return vals if isinstance(self._output, list) else vals[0]

    @property
    def refs(self):
        return self._output


class CompiledDAG:
    """(reference: dag/compiled_dag_node.py:805 — the compiled form caches
    a static execution schedule; execute()/execute_async() are the
    steady-state entry points (:2546, :2627); in-flight executions overlap
    up to max_inflight_executions, pipelining the actors.)

    Two execution planes:
    - channel plane (default when eligible): per-actor exec loops over
      mutable-shm channels, provisioned once at compile time — a step is
      one channel write + one channel read, no task submission at all;
    - submit plane (fallback): the cached schedule is replayed through
      `.remote()` per step. `fallback_reason` records why."""

    def __init__(self, root: DAGNode, *, max_inflight_executions: int = 10,
                 enable_channel_execution: bool = True,
                 channel_buffer_bytes: int = 1 << 20,
                 enable_retry: bool = False):
        import uuid

        self._root = root
        self._max_inflight = max(1, int(max_inflight_executions))
        self._inflight: list[DAGFuture] = []
        self._torn = False
        self._dag_id = f"dag-{uuid.uuid4().hex[:12]}"
        # static schedule, computed once: topological, with per-actor op
        # lists so repeated executions skip traversal entirely
        # (reference: _build_execution_schedule, compiled_dag_node.py:2002)
        self._schedule = root._topo()
        self._input_nodes = [n for n in self._schedule if isinstance(n, InputNode)]
        self._channel = None
        self._fallback_reason: str | None = None
        if enable_channel_execution:
            from ray_tpu.dag.channel_execution import try_build

            self._channel, self._fallback_reason = try_build(
                root, self._schedule, max_inflight=self._max_inflight,
                buffer_bytes=channel_buffer_bytes, dag_id=self._dag_id,
                enable_retry=enable_retry)
        else:
            self._fallback_reason = "channel execution disabled by caller"
        # observability: every compile registers its metadata in the GCS
        # DAG table (state API `list_compiled_dags`, dashboard /api/dags,
        # `ray_tpu dag` CLI); teardown deregisters, driver death retires
        self._registered = False
        self._register()

    @property
    def dag_id(self) -> str:
        return self._dag_id

    @property
    def uses_channels(self) -> bool:
        return self._channel is not None

    @property
    def fallback_reason(self) -> str | None:
        return self._fallback_reason

    # ------------------------------------------------------------- registry

    def _registry_record(self) -> dict:
        import time

        nodes = []
        for i, n in enumerate(self._schedule):
            label = ""
            if isinstance(n, FunctionNode):
                label = getattr(n._fn, "__name__", "fn")
            elif isinstance(n, ClassMethodNode):
                label = (f"{getattr(n._method, '_method_name', '?')}"
                         f"@actor:{getattr(n._method, '_actor_id', '?')[:8]}")
            nodes.append({"index": i, "type": type(n).__name__,
                          "label": label,
                          "deps": [self._schedule.index(u)
                                   for u in n._upstream()]})
        actors: list[str] = []
        for n in self._schedule:
            if isinstance(n, ClassMethodNode):
                aid = getattr(n._method, "_actor_id", None)
                if aid and aid not in actors:
                    actors.append(aid)
        ch = self._channel
        return {
            "dag_id": self._dag_id,
            "plane": "channels" if ch is not None else "submit",
            "fallback_reason": self._fallback_reason,
            "nodes": nodes,
            "actors": actors,
            "channels": len(ch._all_chans) if ch is not None else 0,
            "topology": list(ch.topology) if ch is not None else [],
            "max_inflight": self._max_inflight,
            "sample_every": getattr(ch, "_sample", 0) if ch is not None else 0,
            "created_at": time.time(),
        }

    def _register(self) -> None:
        try:
            from ray_tpu._private.api import _get_worker

            w = _get_worker()
            if getattr(w, "rpc", None) is None:
                return  # local mode: no GCS to register with
            w.rpc({"type": "dag_register", "dag": self._registry_record()})
            self._registered = True
        except Exception:  # noqa: BLE001 — observability must not break compile
            pass

    def _deregister(self) -> None:
        if not self._registered:
            return
        self._registered = False
        try:
            from ray_tpu._private.api import _get_worker

            _get_worker().rpc({"type": "dag_deregister",
                               "dag_id": self._dag_id})
        except Exception:  # noqa: BLE001 — teardown best-effort
            pass

    def _submit_once(self, input_value):
        values: dict[int, Any] = {}
        for node in self._schedule:
            if isinstance(node, InputNode):
                values[id(node)] = input_value
            elif isinstance(node, MultiOutputNode):
                values[id(node)] = [values[id(u)] for u in node._upstream()]
            else:
                args, kwargs = node._resolve(values, input_value)
                values[id(node)] = node._submit(args, kwargs)
        return values[id(self._root)]

    def _reap_inflight(self):
        self._inflight = [f for f in self._inflight if not f.done()]
        while len(self._inflight) >= self._max_inflight:
            # backpressure: wait on the oldest execution's refs without
            # materializing its outputs on the driver
            oldest = self._inflight[0]
            ray_tpu.wait(oldest._refs(), num_returns=len(oldest._refs()))
            self._inflight = [f for f in self._inflight if not f.done()]

    def _channel_execute(self, input_value):
        """One channel-plane submission, degrading THIS DAG to the submit
        path when the executor reports an unrecoverable actor death.
        Returns (handled, future)."""
        from ray_tpu.dag.channel_execution import _PlaneDegraded

        try:
            return True, self._channel.execute(input_value)
        except _PlaneDegraded as e:
            self._degrade_to_submit(e.reason)
            return False, None

    def _degrade_to_submit(self, reason: str) -> None:
        """An actor died beyond recovery (no restart budget, cross-host
        restart, or a timed-out rewire): the channel plane was dismantled,
        but the DAG keeps serving on the cached-schedule submit path —
        degrade, don't brick. `fallback_reason` records the death."""
        import logging

        ex, self._channel = self._channel, None
        self._fallback_reason = reason
        logging.getLogger(__name__).warning(
            "compiled DAG %s: channel plane degraded to the submit path "
            "(%s)", self._dag_id, reason)
        try:
            # idempotent on a degraded executor: joins the already-exited
            # loops fast, releases the occupancy claims, retires the
            # driver-side metric series, re-unlinks the shm files
            ex.teardown(raise_on_error=False)
        except Exception:  # noqa: BLE001 — degrade must leave a usable DAG
            pass
        if self._registered:
            self._registered = False
            self._register()  # refresh plane/fallback_reason in the GCS

    def execute(self, input_value: Any = None):
        """Submit one execution. Channel plane → a ChannelDAGFuture
        (`.result()` / `await` / `ray_tpu.get()`); submit plane → the
        output ObjectRef(s). Executions overlap up to the cap."""
        if self._torn:
            raise ValueError(f"compiled DAG {self._dag_id} was torn down")
        if self._channel is not None:
            handled, fut = self._channel_execute(input_value)
            if handled:
                return fut
        self._reap_inflight()
        out = self._submit_once(input_value)
        self._inflight.append(DAGFuture(out))
        return out

    def execute_async(self, input_value: Any = None):
        """Submit one execution; returns a future (`.result()`/`await`)."""
        if self._torn:
            raise ValueError(f"compiled DAG {self._dag_id} was torn down")
        if self._channel is not None:
            handled, fut = self._channel_execute(input_value)
            if handled:
                return fut
        self._reap_inflight()
        fut = DAGFuture(self._submit_once(input_value))
        self._inflight.append(fut)
        return fut

    def visualize(self) -> str:
        """Text rendering of the static schedule (reference: CompiledDAG
        visualize)."""
        lines = []
        for i, n in enumerate(self._schedule):
            kind = type(n).__name__
            deps = [self._schedule.index(u) for u in n._upstream()]
            label = ""
            if isinstance(n, FunctionNode):
                label = getattr(n._fn, "__name__", "fn")
            elif isinstance(n, ClassMethodNode):
                label = (f"{getattr(n._method, '_actor_id', '?')[:8]}."
                         f"{getattr(n._method, '_method_name', '?')}")
            lines.append(f"{i:3d} {kind:16s} {label:24s} deps={deps}")
        if self._channel is not None:
            s = self._channel.stats
            lines.append(f"plane: channels ({s['actors']} exec loops, "
                         f"{s['channels']} shm channels)")
        else:
            lines.append(f"plane: submit ({self._fallback_reason})")
        return "\n".join(lines)

    def teardown(self, raise_on_error: bool = False):
        """Stop the channel plane (close channels, join exec loops, unlink
        /dev/shm files) and settle in-flight submit-plane executions.
        Errors from in-flight steps are logged once; `raise_on_error=True`
        re-raises the first one."""
        if self._torn:
            return
        self._torn = True
        self._deregister()
        errors: list[Exception] = []
        if self._channel is not None:
            errors.extend(e for _aid, e in
                          self._channel.teardown(raise_on_error=False))
        for f in self._inflight:
            try:
                f.result(timeout=5)
            except Exception as e:  # noqa: BLE001 — collected, logged below
                errors.append(e)
        self._inflight = []
        self._schedule = []
        if errors:
            import logging

            logging.getLogger(__name__).warning(
                "CompiledDAG.teardown: %d in-flight execution error(s); "
                "first: %r", len(errors), errors[0])
            if raise_on_error:
                raise errors[0]


def _function_bind(self, *args, **kwargs) -> FunctionNode:
    return FunctionNode(self, args, kwargs)


def _method_bind(self, *args, **kwargs) -> ClassMethodNode:
    return ClassMethodNode(self, args, kwargs)


# graft .bind onto the existing handle types (the reference defines bind on
# RemoteFunction and ActorMethod the same way)
from ray_tpu.actor import ActorMethod  # noqa: E402
from ray_tpu.remote_function import RemoteFunction  # noqa: E402

RemoteFunction.bind = _function_bind
ActorMethod.bind = _method_bind
