from .head import DashboardHead, start_dashboard

__all__ = ["DashboardHead", "start_dashboard"]
