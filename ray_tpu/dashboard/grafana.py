"""Grafana dashboard + Prometheus provisioning factory.

Reference capability:
python/ray/dashboard/modules/metrics/grafana_dashboard_factory.py — panel
configs rendered into Grafana dashboard JSON, written next to provisioning
YAML so `docker run grafana` (or an operator) picks everything up with zero
clicks (metrics_head.py writes the same artifacts on dashboard startup).

Here: panels target the metric names this framework's ``/metrics``
Prometheus endpoint actually exports (util/metrics.py to_prometheus +
the GCS's built-in ``ray_tpu_*`` gauges), laid out on Grafana's 24-column
grid, two panels per row. ``provision(out_dir)`` writes:

    grafana/dashboards/ray_tpu_core.json
    grafana/dashboards/ray_tpu_serve.json
    grafana/dashboards/ray_tpu_data.json
    grafana/provisioning/dashboards/ray_tpu.yml
    grafana/provisioning/datasources/ray_tpu.yml
    prometheus/prometheus.yml

CLI: ``ray_tpu grafana --out DIR`` (scripts/cli.py).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field

PANEL_WIDTH = 12   # 24-column grid, two panels per row
PANEL_HEIGHT = 8


@dataclass
class Panel:
    title: str
    unit: str
    targets: list  # list of (promql_expr, legend)
    description: str = ""
    stack: bool = False


@dataclass
class DashboardConfig:
    name: str
    uid: str
    panels: list = field(default_factory=list)


CORE_DASHBOARD = DashboardConfig(
    name="ray_tpu core",
    uid="raytpucore",
    panels=[
        Panel("Pending tasks", "short",
              [("ray_tpu_pending_tasks", "queued")],
              "tasks queued in the GCS scheduler"),
        Panel("Live actors", "short",
              [("ray_tpu_live_actors", "alive")]),
        Panel("Object store bytes", "bytes",
              [("ray_tpu_object_store_bytes", "{{host}}")],
              "live shm bytes per host", stack=True),
        Panel("Worker processes", "short",
              [("ray_tpu_live_workers", "workers")]),
        Panel("Task throughput", "ops",
              [('rate(ray_tpu_tasks_total{state="finished"}[1m])',
                "finished/s")]),
        Panel("Node memory usage", "percentunit",
              [("ray_tpu_node_mem_usage", "{{host}}")]),
    ])

SERVE_DASHBOARD = DashboardConfig(
    name="ray_tpu serve",
    uid="raytpuserve",
    panels=[
        Panel("Requests per second", "reqps",
              [("rate(ray_tpu_serve_requests_total[1m])", "{{deployment}}")],
              stack=True),
        Panel("Request latency p50/p95", "ms",
              [("histogram_quantile(0.5, rate(ray_tpu_serve_request_latency_ms_bucket[5m]))", "p50"),
               ("histogram_quantile(0.95, rate(ray_tpu_serve_request_latency_ms_bucket[5m]))", "p95")]),
        Panel("Requests by replica", "reqps",
              [("rate(ray_tpu_serve_requests_total[1m])", "{{replica}}")],
              stack=True),
        Panel("Latency mean", "ms",
              [("rate(ray_tpu_serve_request_latency_ms_sum[5m]) / "
                "rate(ray_tpu_serve_request_latency_ms_count[5m])", "mean")]),
    ])

DATA_DASHBOARD = DashboardConfig(
    name="ray_tpu data",
    uid="raytpudata",
    panels=[
        Panel("Bytes in flight", "bytes",
              [("ray_tpu_data_bytes_in_flight", "{{pipeline}}")], stack=True),
        Panel("Items queued", "short",
              [("ray_tpu_data_blocks_queued", "{{pipeline}}")], stack=True),
        Panel("Backpressure deferrals", "ops",
              [("rate(ray_tpu_data_backpressure_waits[1m])", "{{pipeline}}")]),
        Panel("Tasks finished (cluster)", "ops",
              [('rate(ray_tpu_tasks_total{state="finished"}[1m])',
                "finished/s")]),
    ])


def _panel_json(p: Panel, panel_id: int, x: int, y: int) -> dict:
    return {
        "id": panel_id,
        "title": p.title,
        "description": p.description,
        "type": "timeseries",
        "datasource": {"type": "prometheus", "uid": "raytpuprom"},
        "gridPos": {"h": PANEL_HEIGHT, "w": PANEL_WIDTH, "x": x, "y": y},
        "fieldConfig": {
            "defaults": {
                "unit": p.unit,
                "custom": {"stacking": {"mode": "normal" if p.stack
                                        else "none"}},
            },
            "overrides": [],
        },
        "targets": [
            {"expr": expr, "legendFormat": legend, "refId": chr(65 + i)}
            for i, (expr, legend) in enumerate(p.targets)
        ],
    }


def generate_dashboard(cfg: DashboardConfig) -> str:
    """One Grafana dashboard JSON document (import-ready: wrapped the way
    provisioning file providers expect)."""
    panels = []
    for i, p in enumerate(cfg.panels):
        x = (i % 2) * PANEL_WIDTH
        y = (i // 2) * PANEL_HEIGHT
        panels.append(_panel_json(p, i + 1, x, y))
    return json.dumps({
        "uid": cfg.uid,
        "title": cfg.name,
        "tags": ["ray_tpu"],
        "timezone": "browser",
        "refresh": "5s",
        "time": {"from": "now-30m", "to": "now"},
        "schemaVersion": 39,
        "panels": panels,
        "templating": {"list": []},
    }, indent=2)


_DASHBOARD_PROVIDER = """\
apiVersion: 1
providers:
  - name: ray_tpu
    folder: ray_tpu
    type: file
    options:
      path: /var/lib/grafana/dashboards
"""

_DATASOURCE = """\
apiVersion: 1
datasources:
  - name: ray_tpu_prometheus
    uid: raytpuprom
    type: prometheus
    access: proxy
    url: http://{prometheus_host}
    isDefault: true
"""

_PROMETHEUS = """\
global:
  scrape_interval: 5s
scrape_configs:
  - job_name: ray_tpu
    metrics_path: /metrics
    static_configs:
      - targets: ['{dashboard_host}']
"""


def provision(out_dir: str, *, dashboard_host: str = "127.0.0.1:8265",
              prometheus_host: str = "127.0.0.1:9090") -> list[str]:
    """Write every provisioning artifact under ``out_dir``; returns the
    written paths. Idempotent — safe to re-run on upgrade."""
    written = []

    def w(rel: str, content: str) -> None:
        path = os.path.join(out_dir, rel)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            f.write(content)
        written.append(path)

    for cfg, fname in ((CORE_DASHBOARD, "ray_tpu_core.json"),
                       (SERVE_DASHBOARD, "ray_tpu_serve.json"),
                       (DATA_DASHBOARD, "ray_tpu_data.json")):
        w(os.path.join("grafana", "dashboards", fname),
          generate_dashboard(cfg))
    w(os.path.join("grafana", "provisioning", "dashboards", "ray_tpu.yml"),
      _DASHBOARD_PROVIDER)
    w(os.path.join("grafana", "provisioning", "datasources", "ray_tpu.yml"),
      _DATASOURCE.format(prometheus_host=prometheus_host))
    w(os.path.join("prometheus", "prometheus.yml"),
      _PROMETHEUS.format(dashboard_host=dashboard_host))
    return written
