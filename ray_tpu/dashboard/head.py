"""Dashboard head: HTTP server over cluster state, logs, metrics, timeline.

Reference capability: the aiohttp dashboard head + state aggregator + metrics
and log modules (reference: python/ray/dashboard/head.py,
dashboard/http_server_head.py, dashboard/state_aggregator.py,
dashboard/modules/{log,metrics,job}/). TPU build keeps it dependency-free:
a stdlib ThreadingHTTPServer reading the GCS over the session socket.

Endpoints:
  GET /                      — HTML overview
  GET /api/cluster           — cluster_state JSON
  GET /api/nodes|actors|placement_groups|jobs|tasks
  GET /api/dags              — compiled-DAG registry (state API twin)
  GET /api/events            — cluster event log (?limit/severity/type/node)
  GET /api/explain?target=   — scheduler decision attribution for one id
  GET /api/requests          — serve flight-recorder request log
  GET /api/logs              — list log files; /api/logs/<name>?tail=N
  GET /api/timeline          — chrome://tracing JSON of task events
  GET /metrics               — Prometheus text format
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ray_tpu._private.protocol import connect_unix


class _Gcs:
    """Small resilient GCS client (reconnects on failure)."""

    def __init__(self, session_dir: str):
        self.session_dir = session_dir
        self._conn = None
        self._rid = itertools.count(1)
        self._lock = threading.Lock()

    def rpc(self, msg: dict) -> dict:
        with self._lock:
            for attempt in (0, 1):
                try:
                    if self._conn is None:
                        self._conn = connect_unix(
                            os.path.join(self.session_dir, "gcs.sock"),
                            timeout=5.0)
                    m = dict(msg)
                    m["rid"] = next(self._rid)
                    self._conn.send(m)
                    return self._conn.recv()
                except Exception:
                    try:
                        if self._conn is not None:
                            self._conn.close()
                    finally:
                        self._conn = None
                    if attempt:
                        raise
        raise RuntimeError("unreachable")


class _Handler(BaseHTTPRequestHandler):
    server_version = "ray_tpu_dashboard/1"

    def log_message(self, fmt, *args):  # quiet
        pass

    def _send(self, body: bytes, ctype: str = "application/json",
              code: int = 200):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _json(self, obj, code: int = 200):
        self._send(json.dumps(obj, indent=1, default=str).encode(),
                   "application/json", code)

    def do_GET(self):  # noqa: N802
        gcs: _Gcs = self.server.gcs  # type: ignore[attr-defined]
        parsed = urllib.parse.urlparse(self.path)
        path = parsed.path.rstrip("/") or "/"
        q = urllib.parse.parse_qs(parsed.query)
        try:
            if path == "/":
                # single-file web UI over the JSON API (reference: the
                # dashboard React client, python/ray/dashboard/client/)
                ui = os.path.join(os.path.dirname(__file__), "ui.html")
                with open(ui, "rb") as f:
                    self._send(f.read(), "text/html")
            elif path == "/api/cluster":
                self._json(gcs.rpc({"type": "cluster_state"})["state"])
            elif path == "/api/nodes":
                self._json(gcs.rpc({"type": "list_nodes"})["nodes"])
            elif path == "/api/workers":
                self._json(gcs.rpc({"type": "list_workers"})["workers"])
            elif path == "/api/objects":
                resp = gcs.rpc({"type": "list_objects"})
                self._json({"objects": resp.get("objects", []),
                            "total": resp.get("total", 0)})
            elif path == "/api/actors":
                st = gcs.rpc({"type": "cluster_state"})["state"]
                self._json(st.get("actors", {}))
            elif path == "/api/placement_groups":
                self._json(gcs.rpc({"type": "pg_table"})["table"])
            elif path == "/api/tasks":
                self._json(gcs.rpc({"type": "task_events"}).get("events", []))
            elif path == "/api/timeline":
                from ray_tpu._private.task_events import (
                    fetch_worker_names, normalize_events, to_chrome_trace)

                evs = gcs.rpc({"type": "task_events"}).get("events", [])
                # control-plane events ride along as ctrl:<node> rows
                cevs = gcs.rpc({"type": "list_events"}).get("events", [])
                # actor-worker rows labeled with class/name, not bare pid
                self._send(to_chrome_trace(
                    normalize_events(list(evs) + list(cevs)),
                    fetch_worker_names(gcs.rpc)).encode())
            elif path == "/api/dags":
                # compiled-DAG registry (registered at experimental_compile,
                # dropped at teardown/driver death)
                self._json(gcs.rpc({"type": "dag_list"}).get("dags", []))
            elif path == "/api/events":
                # structured cluster event log with server-side filtering
                # (limit/severity/type/node/after_seq match the CLI flags)
                self._json(gcs.rpc({
                    "type": "list_events",
                    "limit": int(q.get("limit", [0])[0] or 0),
                    "severity": q.get("severity", [""])[0] or "",
                    "etype": q.get("type", [""])[0] or "",
                    "node": q.get("node", [""])[0] or "",
                    "after_seq": int(q.get("after_seq", [0])[0] or 0),
                }).get("events", []))
            elif path == "/api/explain":
                target = (q.get("target", [""])[0] or "").strip()
                if not target:
                    self._json({"error": "missing ?target="}, 400)
                    return
                self._json(gcs.rpc({"type": "sched_explain",
                                    "target": target}))
            elif path == "/api/requests":
                # serve flight-recorder log: last-N request summaries with
                # per-phase seconds (request tracing tentpole) — newest last
                limit = int(q.get("limit", [0])[0] or 0)
                self._json(gcs.rpc({"type": "list_requests",
                                    "limit": limit}).get("requests", []))
            elif path == "/api/serve":
                # serve control plane straight from the persisted GCS
                # `serve` table — works even while the controller is down
                # mid-recovery. Per-replica health states let an operator
                # watch a probe-driven replacement happen.
                rows = gcs.rpc({"type": "serve_list",
                                "light": True}).get("rows", {})
                meta = rows.get("meta") or {}
                deployments: dict = {}
                for key, rec in rows.items():
                    if key.startswith("dep:"):
                        deployments[key[4:]] = {
                            "app": rec.get("app_name"),
                            "target": rec.get("target"),
                            "deleted": rec.get("deleted", False),
                            "replicas": {},
                        }
                for key, rec in rows.items():
                    if not key.startswith("rep:"):
                        continue
                    dep = deployments.setdefault(
                        rec.get("full_name"), {"replicas": {}})
                    state = rec.get("state")
                    health = {"starting": "recovering",
                              "running": "healthy",
                              "unhealthy": "unhealthy-probing",
                              "draining": "draining",
                              "stopping": "draining"}.get(state, state)
                    dep["replicas"][rec.get("tag")] = {
                        "actor_id": rec.get("actor_id"),
                        "state": state, "health": health,
                        "addr": rec.get("addr")}
                self._json({"version": meta.get("version"),
                            "routes": meta.get("routes", {}),
                            "apps": meta.get("apps", {}),
                            "deployments": deployments})
            elif path == "/api/jobs":
                keys = gcs.rpc({"type": "kv_keys", "prefix": "job:"})["keys"]
                jobs = []
                for k in keys:
                    v = gcs.rpc({"type": "kv_get", "key": k}).get("value")
                    if v:
                        try:
                            jobs.append(json.loads(v))
                        except Exception:
                            pass
                self._json(jobs)
            elif path == "/api/logs":
                log_dir = os.path.join(gcs.session_dir, "logs")
                names = sorted(os.listdir(log_dir)) if os.path.isdir(log_dir) else []
                self._json([{"name": n, "size": os.path.getsize(
                    os.path.join(log_dir, n))} for n in names])
            elif path.startswith("/api/logs/"):
                name = os.path.basename(path[len("/api/logs/"):])
                fp = os.path.join(gcs.session_dir, "logs", name)
                if not os.path.isfile(fp):
                    self._json({"error": f"no such log {name!r}"}, 404)
                    return
                with open(fp, "rb") as f:
                    data = f.read()
                tail = int(q.get("tail", [0])[0] or 0)
                if tail:
                    data = b"\n".join(data.splitlines()[-tail:])
                self._send(data, "text/plain")
            elif path == "/api/metrics/history":
                limit = int(q.get("limit", [0])[0] or 0)
                resp = gcs.rpc({"type": "metrics_history", "limit": limit})
                self._json({"nodes": resp.get("nodes", {}),
                            "cluster": resp.get("cluster", [])})
            elif path == "/api/profile":
                # profile-from-UI: trigger the existing in-worker sampling
                # profiler and return its flat report (reference capability:
                # dashboard/modules/reporter — py-spy from the UI)
                wid = (q.get("wid", [""])[0] or "").strip()
                if not wid:
                    self._json({"error": "missing ?wid="}, 400)
                    return
                import math as _math

                duration = float(q.get("duration", [5])[0] or 5)
                hz = float(q.get("hz", [50])[0] or 50)
                # NaN survives min() (comparisons are False) and would make
                # the GCS relay TTL never expire — reject non-finite input
                if not (_math.isfinite(duration) and _math.isfinite(hz)):
                    self._json({"error": "duration/hz must be finite"}, 400)
                    return
                duration = min(duration, 60.0)
                # a profile blocks for its whole duration: use a dedicated
                # connection so the shared _Gcs lock (and with it every
                # other dashboard endpoint + /metrics scrape) isn't held
                # hostage for up to 60s
                own = _Gcs(gcs.session_dir)
                try:
                    reply = own.rpc({"type": "worker_profile", "wid": wid,
                                     "duration_s": duration, "hz": hz})
                finally:
                    try:
                        if own._conn is not None:
                            own._conn.close()
                    except Exception:
                        pass
                if not reply.get("ok", False):
                    self._json({"error": reply.get("error", "profile failed")},
                               503)
                    return
                self._json({"wid": wid, "duration_s": duration,
                            "profile": reply.get("stacks")
                            or reply.get("profile", "")})
            elif path == "/metrics":
                from ray_tpu.util.metrics import to_prometheus

                agg = gcs.rpc({"type": "metrics_snapshot"}).get("metrics", {})
                self._send(to_prometheus(agg).encode(),
                           "text/plain; version=0.0.4")
            else:
                self._json({"error": "not found"}, 404)
        except BrokenPipeError:
            pass
        except Exception as e:  # surface GCS errors as 503
            try:
                self._json({"error": repr(e)}, 503)
            except Exception:
                pass


class DashboardHead:
    def __init__(self, session_dir: str, host: str = "127.0.0.1",
                 port: int = 0):
        self.httpd = ThreadingHTTPServer((host, port), _Handler)
        self.httpd.gcs = _Gcs(session_dir)  # type: ignore[attr-defined]
        self.port = self.httpd.server_address[1]
        self._thread: threading.Thread | None = None

    def start(self) -> "DashboardHead":
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, name="dashboard", daemon=True)
        self._thread.start()
        # advertise for CLI / users
        try:
            with open(os.path.join(self.httpd.gcs.session_dir,  # type: ignore
                                   "dashboard_url"), "w") as f:
                f.write(f"http://127.0.0.1:{self.port}")
        except OSError:
            pass
        return self

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()


def start_dashboard(session_dir: str, host: str = "127.0.0.1",
                    port: int = 0) -> DashboardHead:
    return DashboardHead(session_dir, host, port).start()


def main(argv=None):
    import argparse

    p = argparse.ArgumentParser()
    p.add_argument("--session-dir", required=True)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8265)
    args = p.parse_args(argv)
    head = DashboardHead(args.session_dir, args.host, args.port)
    print(f"dashboard on http://{args.host}:{head.port}")
    head.httpd.serve_forever()


if __name__ == "__main__":
    main()
