"""ray_tpu.data — streaming datasets for TPU pipelines.

(reference: python/ray/data/ — SURVEY.md §2.4. Lazy logical plans, fused
physical stages, a pull-based streaming executor over the task runtime, and
device-prefetching iterators feeding jax device_puts.)
"""

from ray_tpu.data import aggregate
from ray_tpu.data.block import Block, BlockAccessor
from ray_tpu.data.dataset import (
    DataIterator,
    from_torch,
    Dataset,
    GroupedData,
    MaterializedDataset,
    from_arrow,
    from_items,
    from_numpy,
    from_pandas,
    range,
    read_arrow,
    read_audio,
    read_avro,
    read_binary_files,
    read_csv,
    read_datasource,
    read_delta,
    read_hudi,
    read_iceberg,
    read_images,
    read_json,
    read_lance,
    read_numpy,
    read_parquet,
    read_sql,
    read_text,
    read_tfrecords,
    read_videos,
    read_webdataset,
)
from ray_tpu.data.datasource import Datasource, ReadTask

__all__ = [
    "Block",
    "BlockAccessor",
    "DataIterator",
    "Dataset",
    "Datasource",
    "GroupedData",
    "MaterializedDataset",
    "aggregate",
    "ReadTask",
    "from_arrow",
    "from_items",
    "from_numpy",
    "from_pandas",
    "range",
    "read_arrow",
    "read_audio",
    "read_avro",
    "read_binary_files",
    "read_csv",
    "read_datasource",
    "read_delta",
    "read_hudi",
    "read_iceberg",
    "read_images",
    "read_json",
    "read_lance",
    "read_numpy",
    "read_parquet",
    "read_sql",
    "read_text",
    "read_tfrecords",
    "read_videos",
    "read_webdataset",
    "from_torch",
]
