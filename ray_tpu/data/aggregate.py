"""Aggregation functions for Dataset.groupby.

Vectorized over sorted groups: the reduce task sorts its hash partition by
the group keys once, then every aggregator computes all its groups with one
`ufunc.reduceat` pass (TPU-host friendly: numpy, no per-row Python).

(reference: python/ray/data/aggregate.py — Count/Sum/Min/Max/Mean/Std/
AbsMax/Quantile/Unique over grouped data, python/ray/data/grouped_data.py:23.)
"""

from __future__ import annotations

import numpy as np


class AggregateFn:
    """Base aggregator. `on` is the input column (None = whole row count).
    Subclasses implement `compute(col, starts, counts)` returning one value
    per group; `col` is the column sorted in group order."""

    name = "agg"

    def __init__(self, on: str | None = None, alias_name: str | None = None):
        self.on = on
        self.alias = alias_name or (f"{self.name}({on})" if on else f"{self.name}()")

    def compute(self, col: np.ndarray, starts: np.ndarray,
                counts: np.ndarray):
        raise NotImplementedError


class Count(AggregateFn):
    name = "count"

    def compute(self, col, starts, counts):
        return counts


class Sum(AggregateFn):
    name = "sum"

    def compute(self, col, starts, counts):
        return np.add.reduceat(col, starts)


class Min(AggregateFn):
    name = "min"

    def compute(self, col, starts, counts):
        return np.minimum.reduceat(col, starts)


class Max(AggregateFn):
    name = "max"

    def compute(self, col, starts, counts):
        return np.maximum.reduceat(col, starts)


class AbsMax(AggregateFn):
    name = "abs_max"

    def compute(self, col, starts, counts):
        return np.maximum.reduceat(np.abs(col), starts)


class Mean(AggregateFn):
    name = "mean"

    def compute(self, col, starts, counts):
        return np.add.reduceat(col, starts) / counts


class Std(AggregateFn):
    name = "std"

    def __init__(self, on: str | None = None, ddof: int = 1,
                 alias_name: str | None = None):
        super().__init__(on, alias_name)
        self.ddof = ddof

    def compute(self, col, starts, counts):
        col = col.astype(np.float64, copy=False)
        s = np.add.reduceat(col, starts)
        ss = np.add.reduceat(col * col, starts)
        var = (ss - s * s / counts) / np.maximum(counts - self.ddof, 1)
        var = np.maximum(var, 0.0)  # numeric noise can go slightly negative
        out = np.sqrt(var)
        return np.where(counts > self.ddof, out, np.nan)


class Quantile(AggregateFn):
    name = "quantile"

    def __init__(self, on: str | None = None, q: float = 0.5,
                 alias_name: str | None = None):
        super().__init__(on, alias_name)
        self.q = q

    def compute(self, col, starts, counts):
        ends = np.concatenate([starts[1:], [len(col)]])
        return np.asarray([np.quantile(col[s:e], self.q)
                           for s, e in zip(starts, ends)])


class Unique(AggregateFn):
    name = "unique"

    def compute(self, col, starts, counts):
        ends = np.concatenate([starts[1:], [len(col)]])
        return [np.unique(col[s:e]) for s, e in zip(starts, ends)]
