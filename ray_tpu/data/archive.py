"""Sharded-archive datasources: TFRecord files and WebDataset-style tars.

These are the archive formats large image/text pipelines ship training
data in (reference capability: python/ray/data/_internal/datasource/
tfrecords_datasource.py and webdataset_datasource.py) — one archive file is
one read task, so a directory of shards parallelizes naturally and feeds
`iter_jax_batches`'s host→device prefetch.

The TFRecord wire format (public spec): per record
  uint64 length | uint32 masked_crc32c(length) | bytes data |
  uint32 masked_crc32c(data)
implemented here without a tensorflow dependency (crc32c is the Castagnoli
polynomial, software table; records round-trip against the spec's test
vectors). Payload parsing is the caller's business — records surface as
{"bytes": ...} rows, with an optional tf.train.Example feature decoder for
the common case.
"""

from __future__ import annotations

import io
import json
import os
import struct
import tarfile
from typing import Any, Callable, Iterator

import numpy as np

from ray_tpu.data.block import rows_to_block
from ray_tpu.data.datasource import FileDatasource

# ------------------------------------------------------------------ crc32c


def _make_crc32c_table() -> list[int]:
    poly = 0x82F63B78  # Castagnoli, reflected
    table = []
    for n in range(256):
        c = n
        for _ in range(8):
            c = (c >> 1) ^ poly if c & 1 else c >> 1
        table.append(c)
    return table


_CRC_TABLE = _make_crc32c_table()


def _crc32c_py(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


try:  # the C implementations are ~100x the pure-Python table loop
    from crc32c import crc32c as crc32c  # type: ignore[no-redef]
except ImportError:
    try:
        from google_crc32c import value as crc32c  # type: ignore[no-redef]
    except ImportError:
        crc32c = _crc32c_py


def _masked_crc(data: bytes) -> int:
    crc = crc32c(data)
    return ((crc >> 15 | crc << 17) + 0xA282EAD8) & 0xFFFFFFFF


# ----------------------------------------------------------------- tfrecord


def iter_tfrecords(path: str, *, verify_crc: bool = True) -> Iterator[bytes]:
    with open(path, "rb") as f:
        while True:
            head = f.read(12)
            if not head:
                return
            if len(head) < 12:
                raise ValueError(f"truncated tfrecord header in {path}")
            (length,), (len_crc,) = (struct.unpack("<Q", head[:8]),
                                     struct.unpack("<I", head[8:]))
            if verify_crc and _masked_crc(head[:8]) != len_crc:
                raise ValueError(f"corrupt tfrecord length crc in {path}")
            data = f.read(length)
            if len(data) < length:
                raise ValueError(f"truncated tfrecord payload in {path}")
            crc_bytes = f.read(4)
            if len(crc_bytes) < 4:
                raise ValueError(f"truncated tfrecord crc in {path}")
            (data_crc,) = struct.unpack("<I", crc_bytes)
            if verify_crc and _masked_crc(data) != data_crc:
                raise ValueError(f"corrupt tfrecord data crc in {path}")
            yield data


def write_tfrecord_file(path: str, records) -> int:
    n = 0
    with open(path, "wb") as f:
        for rec in records:
            rec = bytes(rec)
            head = struct.pack("<Q", len(rec))
            f.write(head)
            f.write(struct.pack("<I", _masked_crc(head)))
            f.write(rec)
            f.write(struct.pack("<I", _masked_crc(rec)))
            n += 1
    return n


def _pad_rows(rows: list[dict]) -> list[dict]:
    """Archive samples may have optional members/features: block columns
    are the key UNION, absent values become None (rows_to_block schemas
    off row 0, so ragged rows would KeyError or silently drop columns)."""
    keys: list[str] = []
    seen = set()
    for r in rows:
        for k in r:
            if k not in seen:
                seen.add(k)
                keys.append(k)
    return [{k: r.get(k) for k in keys} for r in rows]


class TFRecordDatasource(FileDatasource):
    """{"bytes": record} rows, or decoded feature columns with a decoder.

    `decode="example"` parses tf.train.Example protos with a minimal
    hand-rolled wire-format reader (bytes_list/float_list/int64_list) — no
    tensorflow/protobuf dependency.
    """

    suffixes = (".tfrecord", ".tfrecords")

    def __init__(self, paths, *, decode: str | Callable | None = None,
                 verify_crc: bool = True):
        super().__init__(paths)
        self.decode = decode
        self.verify_crc = verify_crc

    def read_file(self, path: str) -> list:
        rows = []
        for rec in iter_tfrecords(path, verify_crc=self.verify_crc):
            if self.decode is None:
                rows.append({"bytes": rec})
            elif self.decode == "example":
                rows.append(parse_example(rec))
            else:
                rows.append(self.decode(rec))
        return [rows_to_block(_pad_rows(rows))] if rows else []


# A minimal tf.train.Example reader. Wire format (public protobuf spec):
# Example{ features: Features{ feature: map<string, Feature> } } where
# Feature is a oneof of BytesList/FloatList/Int64List.


def _read_varint(buf: memoryview, i: int) -> tuple[int, int]:
    shift = result = 0
    while True:
        b = buf[i]
        i += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, i
        shift += 7


def _fields(buf: memoryview) -> Iterator[tuple[int, int, Any]]:
    """(field_number, wire_type, value) for one message."""
    i = 0
    n = len(buf)
    while i < n:
        tag, i = _read_varint(buf, i)
        field, wt = tag >> 3, tag & 7
        if wt == 0:  # varint
            v, i = _read_varint(buf, i)
        elif wt == 2:  # length-delimited
            ln, i = _read_varint(buf, i)
            v = buf[i:i + ln]
            i += ln
        elif wt == 5:  # 32-bit
            v = buf[i:i + 4]
            i += 4
        elif wt == 1:  # 64-bit
            v = buf[i:i + 8]
            i += 8
        else:
            raise ValueError(f"unsupported protobuf wire type {wt}")
        yield field, wt, v


def _parse_feature(buf: memoryview):
    # protobuf repeated scalars arrive packed (one length-delimited blob)
    # OR unpacked (one wire entry per element) — parsers must accept both
    for field, _wt, v in _fields(buf):
        if field == 1:  # BytesList
            return [bytes(x) for f2, _w, x in _fields(v) if f2 == 1]
        if field == 2:  # FloatList.value
            vals: list = []
            for f2, w2, x in _fields(v):
                if f2 != 1:
                    continue
                if w2 == 2:  # packed
                    vals.extend(struct.unpack(f"<{len(x) // 4}f", bytes(x)))
                elif w2 == 5:  # unpacked fixed32
                    vals.extend(struct.unpack("<f", bytes(x)))
            return vals
        if field == 3:  # Int64List.value
            ints: list = []
            for f2, w2, x in _fields(v):
                if f2 != 1:
                    continue
                if w2 == 2:  # packed varints
                    i = 0
                    while i < len(x):
                        val, i = _read_varint(x, i)
                        if val >= 1 << 63:
                            val -= 1 << 64  # two's-complement int64
                        ints.append(val)
                elif w2 == 0:  # unpacked varint
                    val = x
                    if val >= 1 << 63:
                        val -= 1 << 64
                    ints.append(val)
            return ints
    return []


def parse_example(rec: bytes) -> dict:
    """tf.train.Example bytes → {feature_name: value(s)}; single-element
    lists unwrap to scalars, matching common pipelines."""
    row: dict = {}
    buf = memoryview(rec)
    for field, _wt, feats in _fields(buf):
        if field != 1:  # Example.features
            continue
        for f2, _w, entry in _fields(feats):
            if f2 != 1:  # Features.feature map entry
                continue
            name, value = None, []
            for f3, _w3, v3 in _fields(entry):
                if f3 == 1:
                    name = bytes(v3).decode()
                elif f3 == 2:
                    value = _parse_feature(v3)
            if name is not None:
                row[name] = value[0] if len(value) == 1 else value
    return row


def encode_example(row: dict) -> bytes:
    """{name: scalar|list of bytes/float/int} → tf.train.Example bytes
    (the writer-side twin of parse_example; used by write_tfrecords)."""

    def varint(n: int) -> bytes:
        if n < 0:
            n += 1 << 64
        out = bytearray()
        while True:
            b = n & 0x7F
            n >>= 7
            out.append(b | (0x80 if n else 0))
            if not n:
                return bytes(out)

    def ld(field: int, payload: bytes) -> bytes:
        return varint(field << 3 | 2) + varint(len(payload)) + payload

    entries = b""
    for name, val in row.items():
        vals = val if isinstance(val, (list, tuple, np.ndarray)) else [val]
        vals = list(vals)
        if all(isinstance(v, (bytes, str)) for v in vals):
            bl = b"".join(ld(1, v.encode() if isinstance(v, str) else v)
                          for v in vals)
            feature = ld(1, bl)
        elif all(isinstance(v, (int, np.integer)) for v in vals):
            packed = b"".join(varint(int(v)) for v in vals)
            feature = ld(3, ld(1, packed))
        else:
            packed = struct.pack(f"<{len(vals)}f", *[float(v) for v in vals])
            feature = ld(2, ld(1, packed))
        entries += ld(1, ld(1, name.encode()) + ld(2, feature))
    return ld(1, entries)


# --------------------------------------------------------------- webdataset


_WDS_DECODERS: dict[str, Callable[[bytes], Any]] = {
    "txt": lambda b: b.decode(),
    "cls": lambda b: int(b.decode()),
    "json": lambda b: json.loads(b.decode()),
    "npy": lambda b: np.load(io.BytesIO(b), allow_pickle=False),
}


def _decode_wds(ext: str, data: bytes, decode_images: bool):
    if ext in _WDS_DECODERS:
        return _WDS_DECODERS[ext](data)
    if decode_images and ext in ("jpg", "jpeg", "png", "bmp"):
        try:
            from PIL import Image
        except ImportError:
            return data
        return np.asarray(Image.open(io.BytesIO(data)).convert("RGB"))
    return data


class WebDatasetDatasource(FileDatasource):
    """POSIX-tar shards where files sharing a basename prefix form one
    sample: ``000017.jpg`` + ``000017.cls`` → {"__key__": "000017",
    "jpg": <HWC array>, "cls": 17}. One tar = one read task."""

    suffixes = (".tar",)

    def __init__(self, paths, *, decode: bool = True):
        super().__init__(paths)
        self.decode_payloads = decode

    def read_file(self, path: str) -> list:
        samples: dict[str, dict] = {}
        order: list[str] = []
        with tarfile.open(path) as tf:
            for m in tf:
                if not m.isfile():
                    continue
                dirname, _, base = m.name.rpartition("/")
                stem, _, ext = base.partition(".")
                # WebDataset keys are the full member path minus the
                # extension: train/0001 and val/0001 are DIFFERENT samples
                key = f"{dirname}/{stem}" if dirname else stem
                ext = ext.lower()
                data = tf.extractfile(m).read()
                if key not in samples:
                    samples[key] = {"__key__": key}
                    order.append(key)
                samples[key][ext] = (
                    _decode_wds(ext, data, True) if self.decode_payloads
                    else data)
        rows = [samples[k] for k in order]
        return [rows_to_block(_pad_rows(rows))] if rows else []


def write_webdataset_shard(path: str, rows, *, index: int) -> str:
    """Rows → one tar shard; array/image members as .npy, str as .txt,
    int as .cls, dict/list as .json, bytes verbatim with their ext."""
    out = os.path.join(path, f"shard-{index:06d}.tar")
    os.makedirs(path, exist_ok=True)
    with tarfile.open(out, "w") as tf:
        for i, row in enumerate(rows):
            key = str(row.get("__key__", f"{index:06d}{i:06d}"))
            for name, val in row.items():
                if name == "__key__":
                    continue
                if isinstance(val, bytes):
                    ext, data = name, val
                elif isinstance(val, str):
                    ext, data = name, val.encode()
                elif isinstance(val, (int, np.integer)):
                    ext, data = name, str(int(val)).encode()
                elif isinstance(val, np.ndarray):
                    buf = io.BytesIO()
                    np.save(buf, val, allow_pickle=False)
                    ext, data = name, buf.getvalue()
                else:
                    ext, data = name, json.dumps(val).encode()
                info = tarfile.TarInfo(f"{key}.{ext}")
                info.size = len(data)
                tf.addfile(info, io.BytesIO(data))
    return out
