"""Apache Avro object-container-file codec, dependency-free.

Implements the Avro 1.11 spec subset needed for data interchange and for
reading Iceberg manifest files: binary encoding (zigzag varints), the
object container file layout (header, codec'd data blocks, sync markers),
null/deflate codecs, and these schema types: null, boolean, int, long,
float, double, bytes, string, record, enum, array, map, union, fixed.

(reference capability: python/ray/data/read_api.py read_avro /
_internal/datasource/avro_datasource.py — which delegates to the `fastavro`
wheel; this is a from-scratch codec, no third-party reader in the image.)
"""

from __future__ import annotations

import io
import json
import os
import struct
import zlib
from typing import Any, BinaryIO

MAGIC = b"Obj\x01"

# ---------------------------------------------------------------- primitives


def _read_long(buf: BinaryIO) -> int:
    """Zigzag varint decode."""
    shift = 0
    acc = 0
    while True:
        b = buf.read(1)
        if not b:
            raise EOFError("truncated varint")
        byte = b[0]
        acc |= (byte & 0x7F) << shift
        if not byte & 0x80:
            break
        shift += 7
    return (acc >> 1) ^ -(acc & 1)


def _zigzag(n: int) -> int:
    return (n << 1) if n >= 0 else ((-n) << 1) - 1


def _write_varint(out, n: int) -> None:
    v = _zigzag(n)
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.write(bytes([b | 0x80]))
        else:
            out.write(bytes([b]))
            return


def _read_bytes(buf: BinaryIO) -> bytes:
    n = _read_long(buf)
    data = buf.read(n)
    if len(data) != n:
        raise EOFError("truncated bytes")
    return data


# ------------------------------------------------------------------- decoder


class _Decoder:
    def __init__(self, schema: Any):
        self.schema = schema

    def read(self, buf: BinaryIO, schema: Any = None) -> Any:
        s = self.schema if schema is None else schema
        if isinstance(s, str):
            return self._read_primitive(buf, s)
        if isinstance(s, list):  # union: long index then value
            idx = _read_long(buf)
            return self.read(buf, s[idx])
        t = s["type"]
        if t == "record":
            return {f["name"]: self.read(buf, f["type"]) for f in s["fields"]}
        if t == "enum":
            return s["symbols"][_read_long(buf)]
        if t == "array":
            out = []
            while True:
                n = _read_long(buf)
                if n == 0:
                    break
                if n < 0:  # block with byte-size prefix
                    n = -n
                    _read_long(buf)
                for _ in range(n):
                    out.append(self.read(buf, s["items"]))
            return out
        if t == "map":
            out = {}
            while True:
                n = _read_long(buf)
                if n == 0:
                    break
                if n < 0:
                    n = -n
                    _read_long(buf)
                for _ in range(n):
                    k = _read_bytes(buf).decode()
                    out[k] = self.read(buf, s["values"])
            return out
        if t == "fixed":
            return buf.read(s["size"])
        return self._read_primitive(buf, t)

    def _read_primitive(self, buf: BinaryIO, t: str) -> Any:
        if t == "null":
            return None
        if t == "boolean":
            return buf.read(1) == b"\x01"
        if t in ("int", "long"):
            return _read_long(buf)
        if t == "float":
            return struct.unpack("<f", buf.read(4))[0]
        if t == "double":
            return struct.unpack("<d", buf.read(8))[0]
        if t == "bytes":
            return _read_bytes(buf)
        if t == "string":
            return _read_bytes(buf).decode()
        raise ValueError(f"unsupported avro type {t!r}")


# ------------------------------------------------------------------- encoder


class _Encoder:
    def __init__(self, schema: Any):
        self.schema = schema

    def write(self, out: io.BytesIO, value: Any, schema: Any = None) -> None:
        s = self.schema if schema is None else schema
        if isinstance(s, str):
            return self._write_primitive(out, value, s)
        if isinstance(s, list):  # union: pick the branch matching the value
            idx = self._union_index(s, value)
            _write_varint(out, idx)
            return self.write(out, value, s[idx])
        t = s["type"]
        if t == "record":
            for f in s["fields"]:
                self.write(out, value.get(f["name"]), f["type"])
            return
        if t == "enum":
            _write_varint(out, s["symbols"].index(value))
            return
        if t == "array":
            if value:
                _write_varint(out, len(value))
                for item in value:
                    self.write(out, item, s["items"])
            _write_varint(out, 0)
            return
        if t == "map":
            if value:
                _write_varint(out, len(value))
                for k, v in value.items():
                    kb = str(k).encode()
                    _write_varint(out, len(kb))
                    out.write(kb)
                    self.write(out, v, s["values"])
            _write_varint(out, 0)
            return
        if t == "fixed":
            out.write(value)
            return
        return self._write_primitive(out, value, t)

    @staticmethod
    def _union_index(union: list, value: Any) -> int:
        kind = ("null" if value is None else
                "boolean" if isinstance(value, bool) else
                "long" if isinstance(value, int) else
                "double" if isinstance(value, float) else
                "bytes" if isinstance(value, bytes) else
                "string")
        for i, branch in enumerate(union):
            b = branch if isinstance(branch, str) else branch.get("type")
            if b == kind or (kind == "long" and b in ("int", "float",
                                                      "double")) or (
                    kind == "double" and b == "float"):
                return i
        # complex (non-primitive) values route to the first structured
        # branch; a primitive with no matching branch must NOT fall back
        # (e.g. a float into a long branch would silently truncate)
        if isinstance(value, (list, tuple, dict)) or not isinstance(
                value, (bool, int, float, bytes, str)):
            for i, branch in enumerate(union):
                if branch != "null":
                    return i
        raise TypeError(
            f"no union branch in {union} for value of type {type(value)}")

    def _write_primitive(self, out: io.BytesIO, v: Any, t: str) -> None:
        if t == "null":
            return
        if t == "boolean":
            out.write(b"\x01" if v else b"\x00")
        elif t in ("int", "long"):
            _write_varint(out, int(v))
        elif t == "float":
            out.write(struct.pack("<f", float(v)))
        elif t == "double":
            out.write(struct.pack("<d", float(v)))
        elif t == "bytes":
            _write_varint(out, len(v))
            out.write(v)
        elif t == "string":
            b = str(v).encode()
            _write_varint(out, len(b))
            out.write(b)
        else:
            raise ValueError(f"unsupported avro type {t!r}")


# --------------------------------------------------------------- file layout


def _resolve_named(schema: Any, env: dict | None = None) -> Any:
    """Inline previously-defined named types referenced by name (Iceberg
    manifests use them) so the decoder never sees a bare reference."""
    env = {} if env is None else env
    if isinstance(schema, str):
        return env.get(schema, schema)
    if isinstance(schema, list):
        return [_resolve_named(s, env) for s in schema]
    if isinstance(schema, dict):
        out = dict(schema)
        if out.get("type") in ("record", "enum", "fixed") and "name" in out:
            env[out["name"]] = out
        for key in ("items", "values", "type"):
            if key in out and not isinstance(out[key], str):
                out[key] = _resolve_named(out[key], env)
        if "fields" in out:
            out["fields"] = [
                {**f, "type": _resolve_named(f["type"], env)}
                for f in out["fields"]]
        return out
    return schema


def read_avro_file(path: str) -> tuple[list[dict], dict]:
    """Read an Avro object container file → (records, metadata)."""
    with open(path, "rb") as f:
        if f.read(4) != MAGIC:
            raise ValueError(f"{path}: not an Avro object container file")
        meta_schema = {"type": "map", "values": "bytes"}
        dec = _Decoder(meta_schema)
        meta = dec.read(f, meta_schema)
        sync = f.read(16)
        schema = _resolve_named(json.loads(meta["avro.schema"].decode()))
        codec = meta.get("avro.codec", b"null").decode()
        rdec = _Decoder(schema)
        records: list = []
        while True:
            head = f.read(1)
            if not head:
                break
            f.seek(-1, os.SEEK_CUR)
            count = _read_long(f)
            size = _read_long(f)
            payload = f.read(size)
            if codec == "deflate":
                payload = zlib.decompress(payload, -15)
            elif codec != "null":
                raise ValueError(f"unsupported avro codec {codec!r}")
            if f.read(16) != sync:
                raise ValueError(f"{path}: sync marker mismatch")
            buf = io.BytesIO(payload)
            for _ in range(count):
                records.append(rdec.read(buf))
        return records, {k: v for k, v in meta.items()}


def infer_schema(rows: list[dict], name: str = "row") -> dict:
    """Infer a nullable record schema from python/numpy row values."""
    import numpy as np

    def widen(t: Any, cand: Any) -> Any:
        """Least common avro type of two inferred types; raises on
        incompatible mixes (no silent truncation)."""
        if t is None or t == cand:
            return cand
        if cand is None:
            return t
        if isinstance(t, str) and isinstance(cand, str) and \
                {t, cand} <= {"long", "double"}:
            return "double"
        if (isinstance(t, dict) and isinstance(cand, dict)
                and t.get("type") == cand.get("type") == "array"):
            return {"type": "array", "items": widen(t["items"], cand["items"])}
        raise TypeError(f"incompatible avro types {t} and {cand}")

    def of(v: Any) -> Any:
        if isinstance(v, bool) or isinstance(v, np.bool_):
            return "boolean"
        if isinstance(v, (int, np.integer)):
            return "long"
        if isinstance(v, (float, np.floating)):
            return "double"
        if isinstance(v, bytes):
            return "bytes"
        if isinstance(v, str):
            return "string"
        if isinstance(v, (list, tuple, np.ndarray)):
            inner: Any = None
            for el in v[:100]:  # widen over elements, not just element 0
                inner = widen(inner, of(el))
            return {"type": "array", "items": inner or "double"}
        if isinstance(v, dict):
            return {"type": "map", "values": "string"}
        if v is None:
            return "null"
        raise TypeError(f"cannot map {type(v)} to an avro type")

    # ONE pass over ALL rows: the key union must see every row (a column
    # first appearing after row 100 must not be silently dropped from
    # every written row), while type widening stops after the first 100
    # non-null values per key. dict preserves first-seen order with O(1)
    # membership.
    inferred: dict = {}  # key -> [widened type or None, non-null count]
    for r in rows:
        for k, v in r.items():
            ent = inferred.get(k)
            if ent is None:
                ent = inferred[k] = [None, 0]
            if v is None or ent[1] >= 100:
                continue
            try:
                ent[0] = widen(ent[0], of(v))
            except TypeError as e:
                raise TypeError(f"column {k!r} mixes incompatible types: {e}")
            ent[1] += 1
    fields = []
    for k, (t, _) in inferred.items():
        fields.append({"name": str(k),
                       "type": ["null", t] if t else "null"})
    return {"type": "record", "name": name, "fields": fields}


def write_avro_file(path: str, rows: list[dict], schema: dict | None = None,
                    *, codec: str = "deflate",
                    sync: bytes = b"ray_tpu_avro_syn") -> int:
    """Write rows as an Avro object container file. Returns row count."""
    import numpy as np

    if schema is None:
        if not rows:
            schema = {"type": "record", "name": "row", "fields": []}
        else:
            schema = infer_schema(rows)
    enc = _Encoder(schema)
    body = io.BytesIO()
    for r in rows:
        clean = {k: (v.tolist() if isinstance(v, np.ndarray)
                     else v.item() if isinstance(v, np.generic) else v)
                 for k, v in r.items()}
        enc.write(body, clean)
    payload = body.getvalue()
    if codec == "deflate":
        comp = zlib.compressobj(wbits=-15)
        payload = comp.compress(payload) + comp.flush()
    elif codec != "null":
        raise ValueError(f"unsupported avro codec {codec!r}")
    with open(path, "wb") as f:
        f.write(MAGIC)
        meta = {"avro.schema": json.dumps(schema).encode(),
                "avro.codec": codec.encode()}
        out = io.BytesIO()
        _write_varint(out, len(meta))
        for k, v in meta.items():
            kb = k.encode()
            _write_varint(out, len(kb))
            out.write(kb)
            _write_varint(out, len(v))
            out.write(v)
        _write_varint(out, 0)
        f.write(out.getvalue())
        f.write(sync)
        blk = io.BytesIO()
        _write_varint(blk, len(rows))
        _write_varint(blk, len(payload))
        f.write(blk.getvalue())
        f.write(payload)
        f.write(sync)
    return len(rows)
