"""Blocks: the unit of data exchanged between dataset operators.

A block is a column-batch: `dict[str, np.ndarray | list]`. Simple rows are
normalized into an `{"item": ...}` column, matching the reference's treatment
of non-tabular data. Arrow tables interop via to_arrow/from_arrow.

(reference: python/ray/data/block.py — Block = Arrow/Pandas table; the
BlockAccessor idiom is mirrored here. We default to numpy-backed columns
because the consumers are jax device_puts, not Arrow compute.)
"""

from __future__ import annotations

from typing import Any, Iterable, Iterator

import numpy as np

Block = dict  # dict[str, np.ndarray | list]

ITEM_COL = "item"


def _col_len(v) -> int:
    return len(v)


class BlockAccessor:
    """Uniform view over a block (reference: data/block.py BlockAccessor)."""

    def __init__(self, block: Block):
        self._b = block

    @staticmethod
    def for_block(block: Block) -> "BlockAccessor":
        return BlockAccessor(block)

    def num_rows(self) -> int:
        if not self._b:
            return 0
        return _col_len(next(iter(self._b.values())))

    def size_bytes(self) -> int:
        total = 0
        for v in self._b.values():
            if isinstance(v, np.ndarray):
                total += v.nbytes
            else:
                total += sum(len(x) if isinstance(x, (bytes, str)) else 8 for x in v)
        return total

    def slice(self, start: int, end: int) -> Block:
        return {k: v[start:end] for k, v in self._b.items()}

    def iter_rows(self) -> Iterator[dict]:
        n = self.num_rows()
        keys = list(self._b.keys())
        for i in range(n):
            yield {k: self._b[k][i] for k in keys}

    def to_arrow(self):
        import pyarrow as pa

        return pa.table({k: list(v) if not isinstance(v, np.ndarray) else v
                         for k, v in self._b.items()})

    def to_pandas(self):
        import pandas as pd

        return pd.DataFrame(self._b)

    def to_numpy(self) -> dict[str, np.ndarray]:
        return {k: np.asarray(v) for k, v in self._b.items()}

    def schema(self) -> dict[str, str]:
        out = {}
        for k, v in self._b.items():
            if isinstance(v, np.ndarray):
                out[k] = str(v.dtype)
            elif len(v):
                out[k] = type(v[0]).__name__
            else:
                out[k] = "unknown"
        return out


def normalize_block(data: Any) -> Block:
    """Coerce rows / arrays / tables into the canonical column-batch form."""
    if isinstance(data, dict):
        return data
    try:
        import pyarrow as pa

        if isinstance(data, pa.Table):
            return {name: data.column(name).to_numpy(zero_copy_only=False)
                    for name in data.column_names}
    except ImportError:
        pass
    try:
        import pandas as pd

        if isinstance(data, pd.DataFrame):
            return {c: data[c].to_numpy() for c in data.columns}
    except ImportError:
        pass
    if isinstance(data, np.ndarray):
        return {ITEM_COL: data}
    raise TypeError(f"cannot interpret {type(data)} as a block")


def rows_to_block(rows: Iterable[Any]) -> Block:
    rows = list(rows)
    if not rows:
        return {}
    if isinstance(rows[0], dict):
        # key UNION over all rows (first-seen order): a column appearing
        # only in later rows must not be silently dropped, and a row
        # missing a column fills with None instead of raising KeyError
        keys: dict = {}
        for r in rows:
            for k in r:
                keys.setdefault(k)
        out = {}
        for k in keys:
            vals = [r.get(k) for r in rows]
            try:
                out[k] = np.asarray(vals)
            except (ValueError, TypeError):
                out[k] = vals
        return out
    try:
        return {ITEM_COL: np.asarray(rows)}
    except (ValueError, TypeError):
        return {ITEM_COL: rows}


def concat_blocks(blocks: list[Block]) -> Block:
    blocks = [b for b in blocks if BlockAccessor(b).num_rows() > 0]
    if not blocks:
        return {}
    if len(blocks) == 1:
        return blocks[0]
    keys: dict = {}  # union across blocks, first-seen order
    for b in blocks:
        for k in b:
            keys.setdefault(k)
    out: Block = {}
    for k in keys:
        vals = [b[k] if k in b
                else [None] * BlockAccessor(b).num_rows() for b in blocks]
        if all(isinstance(v, np.ndarray) for v in vals):
            out[k] = np.concatenate(vals)
        else:
            merged: list = []
            for v in vals:
                merged.extend(list(v))
            out[k] = merged
    return out
