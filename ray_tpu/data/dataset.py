"""Dataset: the lazy, streaming dataset API.

(reference: python/ray/data/dataset.py:167 — map_batches:450,
streaming_split:1854, iter_batches:5163, materialize:5994; read_api.py for
the read_* constructors. Execution is deferred: transformations append
logical ops; consumption builds fused physical stages and streams blocks
through the ray_tpu task runtime.)
"""

from __future__ import annotations

import builtins
import collections
from typing import Any, Callable, Iterator

import numpy as np

import ray_tpu
from ray_tpu.data import logical as L
from ray_tpu.data.block import Block, BlockAccessor, concat_blocks, rows_to_block
from ray_tpu.data.datasource import (
    BinaryDatasource,
    TextDatasource,
    CSVDatasource,
    Datasource,
    ImageDatasource,
    ItemsDatasource,
    JSONDatasource,
    NumpyDatasource,
    ParquetDatasource,
    RangeDatasource,
    write_arrow_block,
    write_avro_block,
    write_csv_block,
    write_json_block,
    write_parquet_block,
    write_parquet_partitioned,
)
from ray_tpu.data.execution import (
    StreamingExecutor,
    _rebatch,
    _robust_get,
    build_stages,
    iter_result_blocks,
)

DEFAULT_PARALLELISM = 8


class Dataset:
    def __init__(self, last_op: L.LogicalOp, exec_opts: dict | None = None):
        self._op = last_op
        # execution policy (on_block_error / max_errored_blocks), threaded
        # through every derived Dataset so execute_options() set early in
        # a chain governs the eventual consumption
        self._exec_opts: dict = dict(exec_opts or {})

    # ------------------------------------------------------------ transforms

    def _append(self, op: L.LogicalOp) -> "Dataset":
        op.input = self._op
        return Dataset(op, self._exec_opts)

    def execute_options(self, *, on_block_error: str | None = None,
                        max_errored_blocks: int | None = None) -> "Dataset":
        """Dataset with updated fault-handling policy for UDF errors:
        `on_block_error` "raise" (default) surfaces the first errored
        block, "skip" drops-and-counts up to `max_errored_blocks`
        (-1 = unlimited). System faults (dead actors, lost blocks) are
        always retried and never consult these knobs."""
        opts = dict(self._exec_opts)
        if on_block_error is not None:
            opts["on_block_error"] = on_block_error
        if max_errored_blocks is not None:
            opts["max_errored_blocks"] = max_errored_blocks
        return Dataset(self._op, opts)

    def map_batches(self, fn: Callable, *, batch_size: int | None = None,
                    batch_format: str = "numpy", fn_kwargs: dict | None = None,
                    num_cpus: float = 1.0, num_tpus: float = 0.0,
                    concurrency: int | None = None, compute: str = "tasks") -> "Dataset":
        if compute not in ("tasks", "actors"):
            raise ValueError(
                f"compute must be 'tasks' or 'actors', got {compute!r}")
        return self._append(L.MapBatches(
            fn, batch_size=batch_size, batch_format=batch_format,
            fn_kwargs=fn_kwargs or {}, num_cpus=num_cpus, num_tpus=num_tpus,
            concurrency=concurrency, compute=compute))

    def map(self, fn: Callable) -> "Dataset":
        return self._append(L.MapRows(fn, kind="map"))

    def filter(self, fn: Callable | None = None, *,
               expr: str | None = None) -> "Dataset":
        """Row predicate (callable) or expression string. Expressions
        (`expr="label >= 3 and split == 'train'"`) vectorize over batches
        and push down into parquet reads as row-group pruning (reference:
        Dataset.filter(expr=...) pushes into the read)."""
        if (fn is None) == (expr is None):
            raise ValueError("filter() takes exactly one of fn or expr")
        if expr is not None:
            from ray_tpu.data.expressions import parse_filter

            parse_filter(expr)  # fail fast on bad grammar at plan time
            return self._append(L.FilterExpr(expr))
        return self._append(L.MapRows(fn, kind="filter"))

    def flat_map(self, fn: Callable) -> "Dataset":
        return self._append(L.MapRows(fn, kind="flat_map"))

    def limit(self, n: int) -> "Dataset":
        return self._append(L.Limit(n))

    def repartition(self, num_blocks: int) -> "Dataset":
        return self._append(L.Repartition(num_blocks))

    def random_shuffle(self, *, seed: int | None = None) -> "Dataset":
        return self._append(L.RandomShuffle(seed))

    def sort(self, key: str, *, descending: bool = False) -> "Dataset":
        return self._append(L.Sort(key, descending))

    def union(self, *others: "Dataset") -> "Dataset":
        refs = [ray_tpu.put(list(self._materialize_blocks()))]
        for o in others:
            refs.append(ray_tpu.put(list(o._materialize_blocks())))
        return Dataset(L.InputBlocks(refs=refs))

    def zip(self, other: "Dataset") -> "Dataset":
        """Positionally combine columns of two equal-length datasets
        (reference: Dataset.zip). Duplicate column names from `other` get
        a `_1` suffix. Row order follows each dataset's block order; both
        sides stream through the driver for alignment (like the
        reference, zip is a materializing operation)."""
        import itertools as _it

        out_blocks: list[Block] = []
        rows_l = self.iter_rows()
        rows_r = other.iter_rows()
        batch: list[dict] = []
        for left, right in _it.zip_longest(rows_l, rows_r):
            if left is None or right is None:
                raise ValueError(
                    "Dataset.zip requires equal-length datasets")
            row = dict(left)
            for k, v in right.items():
                name = k
                suffix = 0
                while name in row:  # never clobber an existing column
                    suffix += 1
                    name = f"{k}_{suffix}"
                row[name] = v
            batch.append(row)
            if len(batch) >= 1024:
                out_blocks.append(rows_to_block(batch))
                batch = []
        if batch:
            out_blocks.append(rows_to_block(batch))
        # one ref per block: a single ref would collapse every downstream
        # stage to one task regardless of dataset size
        return Dataset(L.InputBlocks(
            refs=[ray_tpu.put([b]) for b in out_blocks]))

    def groupby(self, key) -> "GroupedData":
        """Group by one column (or a list of columns); aggregate with the
        returned handle (reference: Dataset.groupby, data/grouped_data.py:23)."""
        return GroupedData(self, [key] if isinstance(key, str) else list(key))

    def join(self, other: "Dataset", on, *, right_on=None, how: str = "inner",
             suffixes: tuple = ("", "_r"),
             num_partitions: int | None = None) -> "Dataset":
        """Distributed hash join (reference: Dataset.join,
        data/_internal/execution/operators/join.py:54).

        how: "inner" | "left" | "right" | "outer"."""
        on = [on] if isinstance(on, str) else list(on)
        right_on = on if right_on is None else (
            [right_on] if isinstance(right_on, str) else list(right_on))
        if how not in ("inner", "left", "right", "outer"):
            raise ValueError(f"unsupported join type {how!r}")
        return self._append(L.Join(
            right_last=other._op, on=on, right_on=right_on, how=how,
            suffixes=tuple(suffixes), num_partitions=num_partitions))

    def sum(self, on: str):
        """Global sum of one column (reference: Dataset.sum). Reduction
        runs in the read/map tasks; only per-block scalars reach the
        driver."""
        return self._global_agg(on, "sum")

    def min(self, on: str):
        return self._global_agg(on, "min")

    def max(self, on: str):
        return self._global_agg(on, "max")

    def mean(self, on: str):
        """Global mean of one column (reference: Dataset.mean)."""
        out = self._global_agg(on, "mean")
        return out

    def _global_agg(self, on: str, op: str):
        # per-block partial aggregation ships ONE scalar row per block to
        # the driver instead of the block itself
        def partial(batch):
            if on not in batch:
                raise KeyError(
                    f"column {on!r} not in dataset columns "
                    f"{sorted(batch)}")
            arr = np.asarray(batch[on])
            if op == "mean":
                return {"_s": np.asarray([arr.astype(np.float64).sum()]),
                        "_n": np.asarray([arr.size])}
            return {"_v": np.asarray([getattr(arr, op)()])}

        reduced = self.map_batches(partial, batch_size=None)
        if op == "mean":
            total, count = 0.0, 0
            for row in reduced.iter_rows():
                total += float(row["_s"])
                count += int(row["_n"])
            return total / count if count else None
        out = None
        for row in reduced.iter_rows():
            v = row["_v"]
            if out is None:
                out = v
            elif op == "sum":
                out = out + v
            elif op == "min":
                out = min(out, v)
            else:
                out = max(out, v)
        return None if out is None else out.item() if hasattr(out, "item") else out

    def unique(self, column: str) -> list:
        """Distinct values of one column."""
        out = self.groupby(column).count().take_all()
        return [r[column] for r in out]

    def add_column(self, name: str, fn: Callable) -> "Dataset":
        def add(batch):
            batch[name] = fn(batch)
            return batch

        return self.map_batches(add)

    def drop_columns(self, cols: list[str]) -> "Dataset":
        def drop(batch):
            return {k: v for k, v in batch.items() if k not in cols}

        return self.map_batches(drop)

    def select_columns(self, cols: list[str]) -> "Dataset":
        # a real logical op (not an opaque map) so the optimizer can push
        # the projection into columnar reads as IO pruning
        return self._append(L.Project(list(cols)))

    def rename_columns(self, mapping: dict[str, str]) -> "Dataset":
        def rename(batch):
            return {mapping.get(k, k): v for k, v in batch.items()}

        return self.map_batches(rename)

    # ----------------------------------------------------------- consumption

    def _stages(self):
        ops = L.optimize(self._op.chain())
        return build_stages(ops, DEFAULT_PARALLELISM)

    def iter_blocks(self) -> Iterator[Block]:
        yield from iter_result_blocks(self._stages(), **self._exec_opts)

    def _materialize_blocks(self) -> list[Block]:
        return list(self.iter_blocks())

    def iter_batches(self, *, batch_size: int | None = 256,
                     batch_format: str = "numpy",
                     drop_last: bool = False) -> Iterator[Any]:
        for b in _rebatch(self.iter_blocks(), batch_size):
            if drop_last and batch_size is not None and BlockAccessor(b).num_rows() < batch_size:
                continue
            yield _format_batch(b, batch_format)

    def iter_rows(self) -> Iterator[dict]:
        for b in self.iter_blocks():
            yield from BlockAccessor(b).iter_rows()

    def iter_jax_batches(self, *, batch_size: int = 256, device=None,
                         prefetch: int = 2, drop_last: bool = True,
                         dtypes: dict | None = None) -> Iterator[dict]:
        """Batches as device arrays with async host→device prefetch.

        (reference: data/iterator.py iter_torch_batches:269 moves batches to
        GPU with a prefetch window; here the window is a deque of in-flight
        `jax.device_put` transfers so the TPU never waits on PCIe.)"""
        import jax

        pending: collections.deque = collections.deque()
        for batch in self.iter_batches(batch_size=batch_size, drop_last=drop_last):
            arrs = {k: np.asarray(v) for k, v in batch.items()}
            if dtypes:
                arrs = {k: (v.astype(dtypes[k]) if k in dtypes else v) for k, v in arrs.items()}
            fut = jax.device_put(arrs, device)  # async dispatch
            pending.append(fut)
            while len(pending) > prefetch:
                yield pending.popleft()
        while pending:
            yield pending.popleft()

    def iter_torch_batches(self, *, batch_size: int = 256,
                           drop_last: bool = False,
                           dtypes: dict | None = None) -> Iterator[dict]:
        """Batches as torch tensors (reference: data/iterator.py
        iter_torch_batches:269; CPU tensors — this image's torch has no
        accelerator)."""
        import torch

        for batch in self.iter_batches(batch_size=batch_size,
                                       drop_last=drop_last):
            out = {}
            for k, v in batch.items():
                arr = np.asarray(v)
                if arr.dtype.kind in "OUS":
                    out[k] = list(arr)  # strings/bytes/objects stay python
                    continue
                arr = np.ascontiguousarray(arr)
                if not arr.flags.writeable:
                    # zero-copy views of read-only shm blocks: hand users a
                    # writable tensor, not silent UB on in-place mutation
                    arr = arr.copy()
                t = torch.from_numpy(arr)
                if dtypes and k in dtypes:
                    t = t.to(dtypes[k])
                out[k] = t
            yield out

    def take(self, n: int = 20) -> list[dict]:
        out: list[dict] = []
        for row in self.iter_rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    def take_all(self) -> list[dict]:
        return list(self.iter_rows())

    def to_pandas(self):
        """Materialize the whole dataset as one pandas DataFrame
        (reference: Dataset.to_pandas)."""
        import pandas as pd

        frames = [BlockAccessor(b).to_pandas() for b in self.iter_blocks()
                  if BlockAccessor(b).num_rows()]
        if not frames:
            return pd.DataFrame()
        return pd.concat(frames, ignore_index=True)

    def to_arrow(self):
        """Materialize as a single pyarrow Table (reference:
        Dataset.to_arrow_refs, driver-side variant)."""
        import pyarrow as pa

        tables = [BlockAccessor(b).to_arrow() for b in self.iter_blocks()
                  if BlockAccessor(b).num_rows()]
        if not tables:
            return pa.table({})
        return pa.concat_tables(tables)

    def count(self) -> int:
        return sum(BlockAccessor(b).num_rows() for b in self.iter_blocks())

    def schema(self) -> dict[str, str] | None:
        for b in self.iter_blocks():
            if BlockAccessor(b).num_rows():
                return BlockAccessor(b).schema()
        return None

    def materialize(self) -> "MaterializedDataset":
        blocks = self._materialize_blocks()
        refs = [ray_tpu.put([b]) for b in blocks]
        return MaterializedDataset(L.InputBlocks(refs=refs), blocks_meta=[
            BlockAccessor(b).num_rows() for b in blocks])

    def split(self, n: int) -> list["Dataset"]:
        blocks = self._materialize_blocks()
        merged = concat_blocks(blocks)
        acc = BlockAccessor(merged)
        total = acc.num_rows()
        shards = []
        step = total // n
        for i in builtins.range(n):
            start = i * step
            end = total if i == n - 1 else (i + 1) * step
            shards.append(Dataset(L.InputBlocks(refs=[ray_tpu.put([acc.slice(start, end)])])))
        return shards

    def streaming_split(self, n: int, *, equal: bool = True) -> list["DataIterator"]:
        """N coordinated iterators backed by one shared executor actor.
        (reference: dataset.py streaming_split:1854 + output_splitter.py)"""
        coordinator = _SplitCoordinator.options(name=None).remote(
            self._op, n, self._exec_opts)
        return [DataIterator(coordinator, i) for i in builtins.range(n)]

    # ---------------------------------------------------------------- writes

    def write_parquet(self, path: str,
                      partition_cols: list[str] | None = None) -> list[str]:
        """Parquet files under `path`; with `partition_cols`, hive-style
        `col=value/` subdirectories whose files omit the partition columns
        (reference: Dataset.write_parquet(partition_cols=...))."""
        if not partition_cols:
            return self._write(path, write_parquet_block)
        files: list[str] = []
        for i, b in enumerate(self.iter_blocks()):
            if BlockAccessor(b).num_rows():
                files.extend(write_parquet_partitioned(
                    b, path, i, partition_cols))
        return files

    def write_tfrecords(self, path: str) -> list[str]:
        """One .tfrecord file per block; rows become tf.train.Example
        records (see data/archive.py encode_example)."""
        import os as _os

        from ray_tpu.data.archive import encode_example, write_tfrecord_file

        files = []
        for i, b in enumerate(self.iter_blocks()):
            acc = BlockAccessor(b)
            if not acc.num_rows():
                continue
            _os.makedirs(path, exist_ok=True)
            out = _os.path.join(path, f"part-{i:05d}.tfrecord")
            write_tfrecord_file(out, (encode_example(r)
                                      for r in acc.iter_rows()))
            files.append(out)
        return files

    def write_webdataset(self, path: str) -> list[str]:
        """One .tar shard per block (WebDataset layout)."""
        from ray_tpu.data.archive import write_webdataset_shard

        files = []
        for i, b in enumerate(self.iter_blocks()):
            acc = BlockAccessor(b)
            if acc.num_rows():
                files.append(write_webdataset_shard(
                    path, acc.iter_rows(), index=i))
        return files

    def write_csv(self, path: str) -> list[str]:
        return self._write(path, write_csv_block)

    def write_json(self, path: str) -> list[str]:
        return self._write(path, write_json_block)

    def write_avro(self, path: str) -> list[str]:
        """One Avro object container file per block (data/avro.py codec)."""
        return self._write(path, write_avro_block)

    def write_arrow(self, path: str) -> list[str]:
        """One Arrow IPC file per block."""
        return self._write(path, write_arrow_block)

    def write_delta(self, table: str, *, mode: str = "append",
                    partition_cols: list[str] | None = None) -> list[str]:
        """Commit to a Delta Lake table: parquet data files plus a
        `_delta_log` JSON commit (create/append/overwrite — see
        data/lakehouse.py)."""
        from ray_tpu.data.lakehouse import write_delta

        return write_delta(self, table, mode=mode,
                           partition_cols=partition_cols)

    def _write(self, path: str, writer) -> list[str]:
        files = []
        for i, b in enumerate(self.iter_blocks()):
            if BlockAccessor(b).num_rows():
                files.append(writer(b, path, i))
        return files

    def stats(self) -> str:
        ops = [type(o).__name__ for o in self._op.chain()]
        stages = self._stages()
        return (f"logical: {' -> '.join(ops)}\n"
                f"physical: {' -> '.join(s.name for s in stages)}")

    def __repr__(self):
        return f"Dataset({' -> '.join(type(o).__name__ for o in self._op.chain())})"


class GroupedData:
    """Handle returned by Dataset.groupby: terminal aggregation methods
    append a GroupByAgg (or MapGroups) op to the plan.

    (reference: python/ray/data/grouped_data.py:23 — aggregate, count, sum,
    min, max, mean, std, map_groups.)"""

    def __init__(self, ds: Dataset, keys: list):
        self._ds = ds
        self._keys = keys

    def aggregate(self, *aggs) -> Dataset:
        from ray_tpu.data.aggregate import AggregateFn

        for a in aggs:
            if not isinstance(a, AggregateFn):
                raise TypeError(f"expected AggregateFn, got {type(a)}")
        return self._ds._append(L.GroupByAgg(keys=self._keys, aggs=list(aggs)))

    def count(self) -> Dataset:
        from ray_tpu.data.aggregate import Count

        return self.aggregate(Count(alias_name="count()"))

    def sum(self, on: str) -> Dataset:
        from ray_tpu.data.aggregate import Sum

        return self.aggregate(Sum(on))

    def min(self, on: str) -> Dataset:
        from ray_tpu.data.aggregate import Min

        return self.aggregate(Min(on))

    def max(self, on: str) -> Dataset:
        from ray_tpu.data.aggregate import Max

        return self.aggregate(Max(on))

    def mean(self, on: str) -> Dataset:
        from ray_tpu.data.aggregate import Mean

        return self.aggregate(Mean(on))

    def std(self, on: str, ddof: int = 1) -> Dataset:
        from ray_tpu.data.aggregate import Std

        return self.aggregate(Std(on, ddof=ddof))

    def map_groups(self, fn: Callable, *, batch_format: str = "numpy") -> Dataset:
        """Apply fn to each whole group; fn receives the group's rows as one
        batch and returns a batch (dict of columns) or list of rows."""
        return self._ds._append(L.MapGroups(keys=self._keys, fn=fn,
                                            batch_format=batch_format))


class MaterializedDataset(Dataset):
    def __init__(self, op, blocks_meta=None):
        super().__init__(op)
        self._blocks_meta = blocks_meta or []

    def num_blocks(self) -> int:
        return len(self._blocks_meta)


def _format_batch(block: Block, batch_format: str):
    if batch_format == "numpy":
        return BlockAccessor(block).to_numpy()
    if batch_format == "pandas":
        return BlockAccessor(block).to_pandas()
    if batch_format == "pyarrow":
        return BlockAccessor(block).to_arrow()
    if batch_format in (None, "native"):
        return block
    raise ValueError(f"unknown batch_format {batch_format!r}")


@ray_tpu.remote
class _SplitCoordinator:
    """Actor running the shared executor for streaming_split consumers.

    (reference: _internal/execution/streaming_executor takes this role via
    OutputSplitter, execution/operators/output_splitter.py — blocks are
    routed round-robin to N registered consumers with per-split queues.)"""

    def __init__(self, last_op, n: int, exec_opts: dict | None = None):
        self.n = n
        stages = build_stages(L.optimize(last_op.chain()), DEFAULT_PARALLELISM)
        self._queues: list[collections.deque] = [collections.deque() for _ in builtins.range(n)]
        self._ex = StreamingExecutor(stages, **(exec_opts or {}))
        self._gen = self._ex.execute()
        self._rr = 0
        self._done = False

    def _pump_until(self, split: int) -> None:
        while not self._queues[split] and not self._done:
            try:
                item = next(self._gen)
            except StopIteration:
                self._done = True
                return
            got = _robust_get(item) if hasattr(item, "hex") else item
            self._ex._free_if_owned(item)
            blocks = got if isinstance(got, list) else [got]
            for b in blocks:
                if BlockAccessor(b).num_rows():
                    self._queues[self._rr % self.n].append(b)
                    self._rr += 1

    def get_next(self, split: int):
        self._pump_until(split)
        if self._queues[split]:
            return self._queues[split].popleft()
        return None  # exhausted


class DataIterator:
    """Per-consumer handle for one split of a streaming_split.

    (reference: data/iterator.py DataIterator — iter_batches on a shard.)"""

    def __init__(self, coordinator, split: int):
        self._coord = coordinator
        self._split = split

    def iter_blocks(self) -> Iterator[Block]:
        while True:
            ref = self._coord.get_next.remote(self._split)
            block = ray_tpu.get(ref)
            ray_tpu.free([ref])  # actor-returned copies are single-consumer
            if block is None:
                return
            yield block

    def iter_batches(self, *, batch_size: int | None = 256,
                     batch_format: str = "numpy") -> Iterator[Any]:
        for b in _rebatch(self.iter_blocks(), batch_size):
            yield _format_batch(b, batch_format)

    def iter_rows(self) -> Iterator[dict]:
        for b in self.iter_blocks():
            yield from BlockAccessor(b).iter_rows()


# ------------------------------------------------------------------- readers


def range(n: int, *, parallelism: int = -1) -> Dataset:  # noqa: A001 — mirrors reference name
    return Dataset(L.Read(RangeDatasource(n), parallelism))


def from_items(items: list, *, parallelism: int = -1) -> Dataset:
    return Dataset(L.Read(ItemsDatasource(items), parallelism))


def read_parquet(paths, *, columns=None, filter: str | list | None = None,
                 parallelism: int = -1) -> Dataset:
    """`columns` prunes at IO; `filter` (expression string or pyarrow DNF
    tuples) prunes row groups by their statistics before decode."""
    filters = None
    if isinstance(filter, str):
        from ray_tpu.data.expressions import parse_filter

        filters = parse_filter(filter)
    elif filter:
        filters = list(filter)
    return Dataset(L.Read(ParquetDatasource(paths, columns, filters),
                          parallelism))


def read_tfrecords(paths, *, decode="example", verify_crc: bool = True,
                   parallelism: int = -1) -> Dataset:
    """Sharded .tfrecord archives; `decode="example"` parses tf.train
    .Example features, None yields raw {"bytes": ...} rows, or pass a
    callable (reference: read_api.py read_tfrecords)."""
    from ray_tpu.data.archive import TFRecordDatasource

    return Dataset(L.Read(TFRecordDatasource(
        paths, decode=decode, verify_crc=verify_crc), parallelism))


def read_webdataset(paths, *, decode: bool = True,
                    parallelism: int = -1) -> Dataset:
    """WebDataset-style .tar shards: files sharing a basename prefix form
    one sample (reference: read_api.py read_webdataset)."""
    from ray_tpu.data.archive import WebDatasetDatasource

    return Dataset(L.Read(WebDatasetDatasource(paths, decode=decode),
                          parallelism))


def read_csv(paths, *, parallelism: int = -1) -> Dataset:
    return Dataset(L.Read(CSVDatasource(paths), parallelism))


def read_json(paths, *, parallelism: int = -1) -> Dataset:
    return Dataset(L.Read(JSONDatasource(paths), parallelism))


def read_numpy(paths, *, parallelism: int = -1) -> Dataset:
    return Dataset(L.Read(NumpyDatasource(paths), parallelism))


def read_binary_files(paths, *, parallelism: int = -1) -> Dataset:
    return Dataset(L.Read(BinaryDatasource(paths), parallelism))


def read_images(paths, *, size=None, parallelism: int = -1) -> Dataset:
    return Dataset(L.Read(ImageDatasource(paths, size), parallelism))


def read_sql(sql: str, connection_factory, *, params: tuple = (),
             parallelism: int = 1) -> Dataset:
    """Query any DBAPI database (reference: read_api.py read_sql). Pass
    parallelism > 1 only for dialects where `LIMIT ? OFFSET ?` over the
    query is stable (e.g. an ORDER BY in `sql`)."""
    from ray_tpu.data.datasource import SQLDatasource

    return Dataset(L.Read(SQLDatasource(sql, connection_factory,
                                        params=params), parallelism))


def read_avro(paths, *, parallelism: int = -1) -> Dataset:
    """Avro object container files (reference: read_api.py read_avro)."""
    from ray_tpu.data.datasource import AvroDatasource

    return Dataset(L.Read(AvroDatasource(paths), parallelism))


def read_arrow(paths, *, parallelism: int = -1) -> Dataset:
    """Arrow IPC / Feather V2 files."""
    from ray_tpu.data.datasource import ArrowDatasource

    return Dataset(L.Read(ArrowDatasource(paths), parallelism))


def read_audio(paths, *, parallelism: int = -1) -> Dataset:
    """Audio files → {"amplitude": (C, N) float32, "sample_rate", "path"}
    rows (reference: read_api.py read_audio — soundfile there; WAV/AIFF/AU
    decode dependency-free here)."""
    from ray_tpu.data.datasource import AudioDatasource

    return Dataset(L.Read(AudioDatasource(paths), parallelism))


def read_videos(paths, *, include_timestamps: bool = False,
                frame_step: int = 1, parallelism: int = -1) -> Dataset:
    """Video files → one row per frame: {"frame": HWC uint8 RGB,
    "frame_index", "path"} (reference: read_api.py read_videos — decord
    there; OpenCV here)."""
    from ray_tpu.data.datasource import VideoDatasource

    return Dataset(L.Read(VideoDatasource(
        paths, include_timestamps=include_timestamps,
        frame_step=frame_step), parallelism))


def read_hudi(table_uri: str, *, columns=None, filter=None,
              as_of: str | None = None, parallelism: int = -1) -> Dataset:
    """Apache Hudi copy-on-write snapshot read: `.hoodie` commit timeline
    → latest base parquet per file group, columns/filter pushed into the
    parquet scans; `as_of` time-travels to an instant (reference:
    read_api.py read_hudi)."""
    from ray_tpu.data.datasource import HudiDatasource

    return Dataset(L.Read(HudiDatasource(
        table_uri, columns=columns, filters=_parse_filter_arg(filter),
        as_of=as_of), parallelism))


def read_lance(uri: str, *, columns=None, filter: str | None = None,
               scanner_options: dict | None = None,
               parallelism: int = -1) -> Dataset:
    """Lance dataset, one read task per fragment (reference: read_api.py
    read_lance:4044). Requires the `lance` package (import-gated, absent
    from this image)."""
    from ray_tpu.data.datasource import LanceDatasource

    return Dataset(L.Read(LanceDatasource(
        uri, columns=columns, filter=filter,
        scanner_options=scanner_options), parallelism))


def _parse_filter_arg(filter):
    if isinstance(filter, str):
        from ray_tpu.data.expressions import parse_filter

        return parse_filter(filter)
    return list(filter) if filter else None


def read_delta(table: str, *, columns=None, filter=None,
               parallelism: int = -1) -> Dataset:
    """Delta Lake table: replays `_delta_log` (JSON commits + parquet
    checkpoint) into the active file set; `columns`/`filter` push down
    into the parquet scans and partition values (reference: read_api.py
    read_delta)."""
    from ray_tpu.data.lakehouse import DeltaDatasource

    return Dataset(L.Read(DeltaDatasource(
        table, columns, _parse_filter_arg(filter)), parallelism))


def read_iceberg(table: str, *, columns=None, filter=None,
                 snapshot_id: int | None = None,
                 parallelism: int = -1) -> Dataset:
    """Apache Iceberg table: metadata.json → snapshot → avro manifest list
    → avro manifests → parquet data files (reference: read_api.py
    read_iceberg). Local/file:// warehouses."""
    from ray_tpu.data.lakehouse import IcebergDatasource

    return Dataset(L.Read(IcebergDatasource(
        table, columns, _parse_filter_arg(filter), snapshot_id),
        parallelism))


def read_datasource(ds: Datasource, *, parallelism: int = -1) -> Dataset:
    return Dataset(L.Read(ds, parallelism))


def read_text(paths, *, parallelism: int = -1, drop_empty_lines: bool = True,
              encoding: str = "utf-8") -> Dataset:
    """One row per line: {"text": ...} (reference: read_api.py read_text)."""
    return Dataset(L.Read(TextDatasource(paths, drop_empty_lines=drop_empty_lines,
                                         encoding=encoding), parallelism))


def from_huggingface(hf_dataset, *, parallelism: int = -1) -> Dataset:
    """Materialize a Hugging Face ``datasets.Dataset`` (reference:
    read_api.py from_huggingface — arrow-backed conversion). Batched arrow
    extraction, not row loops; ``DatasetDict`` callers pick a split first."""
    if isinstance(hf_dataset, dict):  # DatasetDict subclasses dict
        raise ValueError(
            "from_huggingface expects one split (e.g. ds['train']), got a "
            f"DatasetDict with splits {list(hf_dataset.keys())}")
    # select/shuffle/train_test_split keep the FULL table in .data and
    # record the view in a lazy _indices mapping — materialize it or the
    # handoff would silently return all rows in original order
    if getattr(hf_dataset, "_indices", None) is not None:
        hf_dataset = hf_dataset.flatten_indices()
    try:
        table = hf_dataset.data.table  # arrow-backed: zero-copy handoff
    except AttributeError:
        table = None
    import pyarrow as pa

    if isinstance(table, pa.Table):
        return from_arrow(table)
    return from_items([dict(r) for r in hf_dataset],
                      parallelism=parallelism)


def from_torch(torch_dataset, *, parallelism: int = -1) -> Dataset:
    """Materialize a map-style torch Dataset (reference: read_api.py
    from_torch). Rows become {"item": sample} (or dict samples verbatim)."""
    items = []
    for i in builtins.range(len(torch_dataset)):  # module range() is a reader
        sample = torch_dataset[i]
        items.append(sample if isinstance(sample, dict) else {"item": sample})
    return from_items(items, parallelism=parallelism)


def from_numpy(arr) -> Dataset:
    return Dataset(L.InputBlocks(refs=[ray_tpu.put([{"data": np.asarray(arr)}])]))


def from_pandas(df) -> Dataset:
    from ray_tpu.data.block import normalize_block

    return Dataset(L.InputBlocks(refs=[ray_tpu.put([normalize_block(df)])]))


def from_arrow(table) -> Dataset:
    from ray_tpu.data.block import normalize_block

    return Dataset(L.InputBlocks(refs=[ray_tpu.put([normalize_block(table)])]))
