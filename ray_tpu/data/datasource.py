"""Datasources and sinks: pluggable readers/writers producing ReadTasks.

(reference: python/ray/data/read_api.py + _internal/datasource/* — each
datasource yields ReadTasks, one per file/fragment, executed as remote tasks
by the streaming executor.)
"""

from __future__ import annotations

import glob as _glob
import os
from dataclasses import dataclass, field
from typing import Any, Callable

import numpy as np

from ray_tpu.data.block import Block, rows_to_block


@dataclass
class ReadTask:
    """A zero-arg callable returning a list of blocks, plus size metadata."""

    fn: Callable[[], list]
    num_rows: int | None = None
    input_files: list = field(default_factory=list)

    def __call__(self) -> list:
        return self.fn()


class Datasource:
    def get_read_tasks(self, parallelism: int) -> list[ReadTask]:
        raise NotImplementedError


def round_robin(items: list, parallelism: int) -> list[list]:
    """Split `items` into ≤parallelism non-empty groups, round-robin — the
    shared grouping for every per-file/per-fragment datasource."""
    if not items:
        return []
    groups: list[list] = [[] for _ in
                          range(max(1, min(parallelism, len(items))))]
    for i, it in enumerate(items):
        groups[i % len(groups)].append(it)
    return [g for g in groups if g]


class RangeDatasource(Datasource):
    """(reference: read_api.py range():245)"""

    def __init__(self, n: int, *, column: str = "id"):
        self.n = n
        self.column = column

    def get_read_tasks(self, parallelism: int) -> list[ReadTask]:
        if self.n == 0:
            return []  # empty range: no read tasks (step would be 0)
        parallelism = max(1, min(parallelism, self.n))
        step = (self.n + parallelism - 1) // parallelism
        tasks = []
        for start in range(0, self.n, step):
            end = min(start + step, self.n)
            col = self.column

            def fn(start=start, end=end):
                return [{col: np.arange(start, end)}]

            tasks.append(ReadTask(fn, num_rows=end - start))
        return tasks


class ItemsDatasource(Datasource):
    def __init__(self, items: list):
        self.items = list(items)

    def get_read_tasks(self, parallelism: int) -> list[ReadTask]:
        n = len(self.items)
        if n == 0:
            return []  # empty dataset: no read tasks (step would be 0)
        parallelism = max(1, min(parallelism, n))
        step = (n + parallelism - 1) // parallelism
        tasks = []
        for start in range(0, n, step):
            chunk = self.items[start:start + step]

            def fn(chunk=chunk):
                return [rows_to_block(chunk)]

            tasks.append(ReadTask(fn, num_rows=len(chunk)))
        return tasks


def _expand_paths(paths, suffixes: tuple[str, ...]) -> list[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            for suf in suffixes:
                out.extend(sorted(_glob.glob(os.path.join(p, f"*{suf}"))))
        elif _glob.has_magic(p):
            out.extend(sorted(_glob.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no files matched {paths}")
    return out


def _read_with_retries(reader: Callable, path: str) -> list:
    """One file read with bounded transient-IO retries (jittered backoff).

    Runs INSIDE the read task, so a flaky filesystem degrades to latency
    instead of failing the block; the executor's per-block retry above it
    only sees errors that survived this budget. A persistent failure
    carries per-file attribution (the path and attempt count), and
    `FileNotFoundError` is never retried — a missing file will not
    reappear."""
    import random as _random
    import time as _time

    from ray_tpu._private.ray_config import RayConfig

    cfg = RayConfig.instance()
    retries = cfg.data_read_retries if cfg.data_fault_tolerance else 0
    base = cfg.data_read_retry_backoff_s
    attempt = 0
    while True:
        try:
            return reader(path)
        except FileNotFoundError:
            raise
        except OSError as exc:
            if attempt >= retries:
                # re-raise as the SAME subclass: upstream handlers dispatch
                # on the OSError subtype (PermissionError vs ConnectionError
                # vs ...), which a bare OSError wrapper would collapse
                msg = (f"read of {path!r} failed after {attempt + 1} "
                       f"attempt(s): {exc}")
                try:
                    wrapped = type(exc)(msg)
                except Exception:
                    wrapped = OSError(msg)  # exotic constructor signature
                raise wrapped from exc
            _time.sleep(_random.uniform(
                0.0, min(base * (2 ** attempt), base * 8.0)))
            attempt += 1


class FileDatasource(Datasource):
    suffixes: tuple[str, ...] = ()

    def __init__(self, paths):
        self.paths = _expand_paths(paths, self.suffixes)

    def read_file(self, path: str) -> list:
        raise NotImplementedError

    def get_read_tasks(self, parallelism: int) -> list[ReadTask]:
        tasks = []
        for grp in round_robin(self.paths, parallelism):

            def fn(grp=grp, reader=self.read_file):
                blocks = []
                for path in grp:
                    blocks.extend(_read_with_retries(reader, path))
                return blocks

            tasks.append(ReadTask(fn, input_files=grp))
        return tasks


class ParquetDatasource(FileDatasource):
    """Columnar reads with projection (column pruning) and predicate
    pushdown: `columns` prunes at the IO layer, `filters` (pyarrow DNF
    conjunction, e.g. [("x", ">", 3)]) prunes whole row groups via their
    min/max statistics before any decode (reference:
    data/_internal/datasource/parquet_datasource.py)."""

    suffixes = (".parquet",)
    supports_projection = True
    supports_predicates = True

    def __init__(self, paths, columns=None, filters=None):
        super().__init__(paths)
        self.columns = list(columns) if columns else None
        self.filters = list(filters) if filters else None

    def read_file(self, path: str) -> list:
        import pyarrow.parquet as pq

        table = pq.read_table(path, columns=self.columns,
                              filters=self.filters)
        from ray_tpu.data.block import normalize_block

        return [normalize_block(table)]


class CSVDatasource(FileDatasource):
    suffixes = (".csv",)

    def read_file(self, path: str) -> list:
        import pyarrow.csv as pacsv

        from ray_tpu.data.block import normalize_block

        return [normalize_block(pacsv.read_csv(path))]


class JSONDatasource(FileDatasource):
    suffixes = (".json", ".jsonl")

    def read_file(self, path: str) -> list:
        import json

        rows = []
        with open(path) as f:
            text = f.read().strip()
        if text.startswith("["):
            rows = json.loads(text)
        else:
            for line in text.splitlines():
                if line.strip():
                    rows.append(json.loads(line))
        return [rows_to_block(rows)]


class NumpyDatasource(FileDatasource):
    suffixes = (".npy",)

    def read_file(self, path: str) -> list:
        return [{"data": np.load(path)}]


class TextDatasource(FileDatasource):
    """One row per line: {"text": line} (reference: read_api.py read_text)."""

    suffixes = (".txt", ".text", ".log", ".md")

    def __init__(self, paths, *, drop_empty_lines: bool = True,
                 encoding: str = "utf-8"):
        super().__init__(paths)
        self.drop_empty = drop_empty_lines
        self.encoding = encoding

    def read_file(self, path: str) -> list:
        with open(path, encoding=self.encoding, errors="replace") as f:
            lines = f.read().splitlines()
        if self.drop_empty:
            lines = [ln for ln in lines if ln.strip()]
        return [{"text": lines}] if lines else []


class BinaryDatasource(FileDatasource):
    suffixes = ()

    def read_file(self, path: str) -> list:
        with open(path, "rb") as f:
            return [{"bytes": [f.read()], "path": [path]}]


class ImageDatasource(FileDatasource):
    """Decoded image files → {"image": HWC uint8 array, "path"} rows.
    (reference: read_api.py read_images:1048)"""

    suffixes = (".png", ".jpg", ".jpeg", ".bmp")

    def __init__(self, paths, size: tuple[int, int] | None = None):
        super().__init__(paths)
        self.size = size

    def read_file(self, path: str) -> list:
        try:
            from PIL import Image
        except ImportError as e:  # pillow is optional in this image
            raise ImportError("read_images requires pillow") from e

        img = Image.open(path).convert("RGB")
        if self.size is not None:
            img = img.resize(self.size)
        arr = np.asarray(img)
        return [{"image": arr[None, ...], "path": [path]}]


class AvroDatasource(FileDatasource):
    """Avro object container files → rows, via the dependency-free codec in
    data/avro.py (reference: read_api.py read_avro — fastavro-backed there)."""

    suffixes = (".avro",)

    def read_file(self, path: str) -> list:
        from ray_tpu.data.avro import read_avro_file
        from ray_tpu.data.block import rows_to_block

        records, _ = read_avro_file(path)
        return [rows_to_block(records)] if records else []


class ArrowDatasource(FileDatasource):
    """Arrow IPC / Feather V2 files (reference capability: Dataset
    round-trips through Arrow; file-level IPC reads are the natural TPU
    interchange for zero-copy numpy columns)."""

    suffixes = (".arrow", ".feather", ".ipc")

    def read_file(self, path: str) -> list:
        import pyarrow as pa

        from ray_tpu.data.block import normalize_block

        with pa.memory_map(path) as src:
            try:
                table = pa.ipc.open_file(src).read_all()
            except pa.ArrowInvalid:
                src.seek(0)
                table = pa.ipc.open_stream(src).read_all()
        return [normalize_block(table)]


class AudioDatasource(FileDatasource):
    """Audio files → {"amplitude": (channels, samples) float32 in [-1, 1],
    "sample_rate", "path"} rows, matching the reference's row shape
    (_internal/datasource/audio_datasource.py: soundfile always_2d read
    transposed to channels-first). WAV/AIFF/AU decode here dependency-free
    via the stdlib (soundfile is absent from this image); other containers
    raise with a clear message instead of importing a missing backend."""

    suffixes = (".wav", ".wave", ".aiff", ".aif", ".au")

    def read_file(self, path: str) -> list:
        ext = os.path.splitext(path)[1].lower()
        if ext in (".wav", ".wave"):
            sr, amp = _decode_wav(path)
        elif ext in (".aiff", ".aif"):
            sr, amp = _decode_aiff(path)
        else:
            sr, amp = _decode_au(path)
        return [{"amplitude": amp[None, ...], "sample_rate": [sr],
                 "path": [path]}]


def _pcm_to_float(raw: bytes, sampwidth: int, nchannels: int,
                  big_endian: bool = False,
                  signed8: bool = False) -> np.ndarray:
    """Interleaved integer PCM → (channels, samples) float32 in [-1, 1].

    8-bit convention differs by container: WAV stores unsigned bytes
    (recentred here), AIFF/AU store signed (signed8=True)."""
    order = ">" if big_endian else "<"
    if sampwidth == 1:
        if signed8:
            x = np.frombuffer(raw, dtype=np.int8).astype(np.float32) / 128.0
        else:
            x = np.frombuffer(raw, dtype=np.uint8).astype(np.float32)
            x = (x - 128.0) / 128.0
    elif sampwidth == 2:
        x = np.frombuffer(raw, dtype=f"{order}i2").astype(np.float32) / 32768.0
    elif sampwidth == 3:
        b = np.frombuffer(raw, dtype=np.uint8).reshape(-1, 3)
        if big_endian:
            b = b[:, ::-1]
        x = (b[:, 0].astype(np.int32)
             | (b[:, 1].astype(np.int32) << 8)
             | (b[:, 2].astype(np.int32) << 16))
        x = np.where(x >= 1 << 23, x - (1 << 24), x).astype(np.float32)
        x /= float(1 << 23)
    elif sampwidth == 4:
        x = np.frombuffer(raw, dtype=f"{order}i4").astype(np.float32)
        x /= float(1 << 31)
    else:
        raise ValueError(f"unsupported PCM sample width {sampwidth}")
    if nchannels > 1:
        x = x.reshape(-1, nchannels).T
    else:
        x = x[None, :]
    return np.ascontiguousarray(x)


def _decode_wav(path: str):
    import wave

    with wave.open(path, "rb") as w:
        raw = w.readframes(w.getnframes())
        amp = _pcm_to_float(raw, w.getsampwidth(), w.getnchannels())
        return w.getframerate(), amp


def _decode_aiff(path: str):
    try:
        import aifc
    except ImportError as e:  # removed in Python 3.13 (PEP 594)
        raise ValueError(
            f"cannot decode {path!r}: the stdlib 'aifc' module is gone on "
            "this interpreter (PEP 594); convert to WAV or install an "
            "audio backend") from e

    with aifc.open(path, "rb") as a:
        raw = a.readframes(a.getnframes())
        amp = _pcm_to_float(raw, a.getsampwidth(), a.getnchannels(),
                            big_endian=True, signed8=True)
        return int(a.getframerate()), amp


def _decode_au(path: str):
    try:
        import sunau
    except ImportError as e:  # removed in Python 3.13 (PEP 594)
        raise ValueError(
            f"cannot decode {path!r}: the stdlib 'sunau' module is gone on "
            "this interpreter (PEP 594); convert to WAV or install an "
            "audio backend") from e

    with sunau.open(path, "rb") as a:
        raw = a.readframes(a.getnframes())
        amp = _pcm_to_float(raw, a.getsampwidth(), a.getnchannels(),
                            big_endian=True, signed8=True)
        return int(a.getframerate()), amp


class VideoDatasource(FileDatasource):
    """Video files → one row per decoded frame: {"frame": HWC uint8 RGB,
    "frame_index", "path"} (+ "frame_timestamp" seconds when requested),
    matching the reference's row shape
    (_internal/datasource/video_datasource.py — decord there; OpenCV is
    the decoder available in this image). ``frame_step=k`` keeps every
    k-th frame so long clips can subsample at the IO layer."""

    suffixes = (".mp4", ".mkv", ".mov", ".avi", ".webm", ".m4v", ".mpeg",
                ".mpg")

    def __init__(self, paths, *, include_timestamps: bool = False,
                 frame_step: int = 1, frames_per_block: int = 64):
        super().__init__(paths)
        self.include_timestamps = include_timestamps
        self.frame_step = max(1, int(frame_step))
        self.frames_per_block = max(1, int(frames_per_block))

    def read_file(self, path: str) -> list:
        try:
            import cv2
        except ImportError as e:
            raise ImportError("read_videos requires opencv (cv2)") from e

        cap = cv2.VideoCapture(path)
        if not cap.isOpened():
            raise ValueError(f"could not open video {path!r}")
        blocks: list = []
        frames, idxs, stamps = [], [], []

        def flush():
            if not frames:
                return
            block = {"frame": np.stack(frames),
                     "frame_index": np.asarray(idxs),
                     "path": [path] * len(frames)}
            if self.include_timestamps:
                block["frame_timestamp"] = np.asarray(stamps)
            blocks.append(block)
            frames.clear(); idxs.clear(); stamps.clear()

        i = 0
        try:
            while True:
                ok, bgr = cap.read()
                if not ok:
                    break
                if i % self.frame_step == 0:
                    if self.include_timestamps:
                        stamps.append(cap.get(cv2.CAP_PROP_POS_MSEC) / 1e3)
                    frames.append(cv2.cvtColor(bgr, cv2.COLOR_BGR2RGB))
                    idxs.append(i)
                    # bound resident uncompressed frames: a long clip must
                    # stream out as multiple blocks, not one giant stack
                    if len(frames) >= self.frames_per_block:
                        flush()
                i += 1
        finally:
            cap.release()
        flush()
        return blocks


class HudiDatasource(Datasource):
    """Apache Hudi copy-on-write SNAPSHOT reads, dependency-free
    (reference: _internal/datasource/hudi_datasource.py — hudi-python
    there, absent from this image, so the table protocol is implemented
    directly like data/lakehouse.py does for Delta/Iceberg).

    Protocol: ``.hoodie/`` holds the commit timeline — ``<ts>.commit``
    JSON files (completed commits only; ``.inflight``/``.requested`` are
    pending) whose ``partitionToWriteStats`` lists the parquet base file
    each write produced per file group. A snapshot is, per file group
    (fileId), the base file of the LATEST completed commit ≤ the
    requested instant. Columns/filters push down into the parquet reads."""

    def __init__(self, table_uri: str, *, columns=None, filters=None,
                 as_of: str | None = None):
        self.table_uri = table_uri
        self.columns = list(columns) if columns else None
        self.filters = list(filters) if filters else None
        self.as_of = as_of  # instant ts string: time-travel cutoff

    def _snapshot_files(self) -> list[str]:
        import json

        tl_dir = os.path.join(self.table_uri, ".hoodie")
        if not os.path.isdir(tl_dir):
            raise FileNotFoundError(
                f"not a Hudi table (no .hoodie timeline): {self.table_uri!r}")
        instants = sorted(
            f for f in os.listdir(tl_dir)
            if f.endswith(".commit") or f.endswith(".replacecommit"))
        latest: dict[str, tuple[str, str]] = {}  # fileId → (ts, relpath)
        for fname in instants:
            ts = fname.split(".")[0]
            if self.as_of is not None and ts > self.as_of:
                continue
            with open(os.path.join(tl_dir, fname)) as f:
                meta = json.load(f)
            # clustering / insert_overwrite (replacecommit): the replaced
            # file groups leave the snapshot entirely — without this, their
            # rows would appear alongside the rewritten copies
            for fids in (meta.get("partitionToReplaceFileIds") or {}).values():
                for fid in fids:
                    latest.pop(fid, None)
            for stats in (meta.get("partitionToWriteStats") or {}).values():
                for st in stats:
                    fid, rel = st.get("fileId"), st.get("path")
                    if fid and rel and ts >= latest.get(fid, ("",))[0]:
                        latest[fid] = (ts, rel)
        return [os.path.join(self.table_uri, rel)
                for _, rel in sorted(latest.values())]

    def get_read_tasks(self, parallelism: int) -> list[ReadTask]:
        files = self._snapshot_files()
        if not files:
            return []
        inner = ParquetDatasource(files, columns=self.columns,
                                  filters=self.filters)
        return inner.get_read_tasks(parallelism)


class LanceDatasource(Datasource):
    """Lance dataset reads, one ReadTask per fragment, with column
    projection and filter pushdown into the scanner (reference:
    _internal/datasource/lance_datasource.py:19). The ``lance`` package is
    not in this image and the columnar format has no offline spec to
    reimplement, so this connector is import-gated exactly like the
    reference (``_check_import``); it activates unchanged where pylance
    is installed."""

    def __init__(self, uri: str, *, columns=None, filter: str | None = None,
                 scanner_options: dict | None = None):
        try:
            import lance  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "read_lance requires the 'lance' package (pylance), which "
                "is not available in this environment") from e
        self.uri = uri
        self.scanner_options = dict(scanner_options or {})
        if columns is not None:
            self.scanner_options["columns"] = list(columns)
        if filter is not None:
            self.scanner_options["filter"] = filter

    def get_read_tasks(self, parallelism: int) -> list[ReadTask]:
        import lance

        from ray_tpu.data.block import normalize_block

        ds = lance.dataset(uri=self.uri)
        fragment_ids = [f.fragment_id for f in ds.get_fragments()]
        tasks = []
        for grp in round_robin(fragment_ids, parallelism):

            def fn(grp=grp, uri=self.uri, opts=self.scanner_options):
                import lance as _lance

                d = _lance.dataset(uri=uri)
                frags = [f for f in d.get_fragments()
                         if f.fragment_id in grp]
                table = d.scanner(fragments=frags, **opts).to_table()
                return [normalize_block(table)]

            tasks.append(ReadTask(fn))
        return tasks


# --------------------------------------------------------------------- writes


def write_parquet_block(block: Block, path: str, index: int) -> str:
    import pyarrow.parquet as pq

    from ray_tpu.data.block import BlockAccessor

    os.makedirs(path, exist_ok=True)
    out = os.path.join(path, f"part-{index:05d}.parquet")
    pq.write_table(BlockAccessor(block).to_arrow(), out)
    return out


def write_csv_block(block: Block, path: str, index: int) -> str:
    import pyarrow.csv as pacsv

    from ray_tpu.data.block import BlockAccessor

    os.makedirs(path, exist_ok=True)
    out = os.path.join(path, f"part-{index:05d}.csv")
    pacsv.write_csv(BlockAccessor(block).to_arrow(), out)
    return out


def write_json_block(block: Block, path: str, index: int) -> str:
    import json

    from ray_tpu.data.block import BlockAccessor

    os.makedirs(path, exist_ok=True)
    out = os.path.join(path, f"part-{index:05d}.jsonl")
    with open(out, "w") as f:
        for row in BlockAccessor(block).iter_rows():
            f.write(json.dumps({k: _json_safe(v) for k, v in row.items()}) + "\n")
    return out


def write_avro_block(block: Block, path: str, index: int) -> str:
    from ray_tpu.data.avro import write_avro_file
    from ray_tpu.data.block import BlockAccessor

    os.makedirs(path, exist_ok=True)
    out = os.path.join(path, f"part-{index:05d}.avro")
    write_avro_file(out, list(BlockAccessor(block).iter_rows()))
    return out


def write_arrow_block(block: Block, path: str, index: int) -> str:
    import pyarrow as pa

    from ray_tpu.data.block import BlockAccessor

    os.makedirs(path, exist_ok=True)
    out = os.path.join(path, f"part-{index:05d}.arrow")
    table = BlockAccessor(block).to_arrow()
    with pa.OSFile(out, "wb") as sink:
        with pa.ipc.new_file(sink, table.schema) as writer:
            writer.write_table(table)
    return out


def write_parquet_partitioned(block: Block, path: str, index: int,
                              partition_cols: list[str]) -> list[str]:
    """Hive-style partitioned write: rows fan out to
    `col1=val1/col2=val2/part-<index>.parquet`, partition columns dropped
    from the files (they're encoded in the directory names — reference:
    Dataset.write_parquet(partition_cols=...))."""
    import pyarrow.parquet as pq

    from ray_tpu.data.block import BlockAccessor, rows_to_block

    groups: dict[tuple, list] = {}
    for row in BlockAccessor(block).iter_rows():
        key = tuple(row[c] for c in partition_cols)
        groups.setdefault(key, []).append(
            {k: v for k, v in row.items() if k not in partition_cols})
    out: list[str] = []
    for key, rows in groups.items():
        sub = os.path.join(path, *(
            f"{c}={_part_str(v)}" for c, v in zip(partition_cols, key)))
        os.makedirs(sub, exist_ok=True)
        f = os.path.join(sub, f"part-{index:05d}.parquet")
        pq.write_table(BlockAccessor(rows_to_block(rows)).to_arrow(), f)
        out.append(f)
    return out


def _part_str(v: Any) -> str:
    if isinstance(v, np.generic):
        v = v.item()
    if v is None:
        return "__HIVE_DEFAULT_PARTITION__"  # hive's null sentinel
    from urllib.parse import quote

    # url-encode separators so values like "a/b" or "x=y" stay one
    # directory component a hive-aware reader parses back losslessly
    return quote(str(v), safe="")


def _json_safe(v: Any):
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, np.ndarray):
        return v.tolist()
    return v


class SQLDatasource(Datasource):
    """Rows from any DBAPI-2.0 connection (reference:
    data/_internal/datasource/sql_datasource.py — a connection FACTORY plus
    a query; partitions read disjoint row ranges via OFFSET/LIMIT when a
    parallelism > 1 is requested and the dialect supports it)."""

    def __init__(self, sql: str, connection_factory: Callable,
                 *, params: tuple = ()):
        self.sql = sql
        self.connection_factory = connection_factory
        self.params = tuple(params)

    def _read(self, suffix: str = "", extra: tuple = ()) -> list:
        conn = self.connection_factory()
        try:
            cur = conn.cursor()
            cur.execute(self.sql + suffix, self.params + extra)
            cols = [d[0] for d in cur.description]
            rows = [dict(zip(cols, r)) for r in cur.fetchall()]
        finally:
            conn.close()
        return rows

    def get_read_tasks(self, parallelism: int) -> list[ReadTask]:
        if parallelism <= 1:
            return [ReadTask(lambda: [rows_to_block(r)]
                             if (r := self._read()) else [])]
        # count once, then hand each task a disjoint OFFSET/LIMIT window —
        # the reference's sharded-read strategy for partitionable dialects
        conn = self.connection_factory()
        try:
            cur = conn.cursor()
            # the derived-table alias is REQUIRED by postgres/mysql and
            # harmless on sqlite
            cur.execute(f"SELECT COUNT(*) FROM ({self.sql}) AS _sub",
                        self.params)
            total = int(cur.fetchone()[0])
        finally:
            conn.close()
        if total == 0:
            return []
        parallelism = max(1, min(parallelism, total))
        step = (total + parallelism - 1) // parallelism
        tasks = []
        for start in range(0, total, step):
            limit = min(step, total - start)

            def fn(start=start, limit=limit):
                rows = self._read(" LIMIT ? OFFSET ?", (limit, start))
                return [rows_to_block(rows)] if rows else []

            tasks.append(ReadTask(fn, num_rows=limit))
        return tasks
