"""Physical plan + streaming executor.

Stages are fused chains of block transforms executed as remote tasks over the
ray_tpu runtime; the executor is a driver-side scheduling loop with bounded
per-stage concurrency and bounded output queues (backpressure), pulling
blocks through the pipeline as the consumer iterates.

(reference: python/ray/data/_internal/execution/streaming_executor.py:64 —
the _scheduling_loop_step:444 select/dispatch/process loop;
operators/map_operator.py:68 for task-pool maps; backpressure policies under
execution/backpressure_policy/. Ours is deliberately simpler: per-stage
in-flight caps + output-queue caps give the same streaming property.)
"""

from __future__ import annotations

import collections
import heapq
import itertools
import logging
import random
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Iterator

import numpy as np

import ray_tpu
from ray_tpu._private import constants as const
from ray_tpu._private.ray_config import RayConfig
from ray_tpu.data import logical as L
from ray_tpu.data.block import Block, BlockAccessor, concat_blocks, rows_to_block
from ray_tpu.exceptions import (
    ActorDiedError,
    DataBlockError,
    ObjectLostError,
    WorkerCrashedError,
)

logger = logging.getLogger(__name__)

# Retry taxonomy: SYSTEM errors are the runtime's fault — the task never
# (fully) ran because its actor/worker died or an input copy vanished —
# and resubmission from the retained input is safe and invisible.
# Everything else reached the UDF and is an APPLICATION error, governed by
# the on_block_error policy (reference: Ray Data's task retry vs
# max_errored_blocks split).
_SYSTEM_ERRORS = (ActorDiedError, WorkerCrashedError, ObjectLostError)


def _is_system_error(exc) -> bool:
    if isinstance(exc, _SYSTEM_ERRORS):
        return True
    return isinstance(getattr(exc, "cause", None), _SYSTEM_ERRORS)


def _backoff_delay(attempt: int, base: float, rng) -> float:
    """Full-jitter exponential backoff, capped at 8x base (PR 2 idiom —
    rng is injectable so tests pin the schedule)."""
    return rng.uniform(0.0, min(base * (2 ** attempt), base * 8.0))


def _ref_error(ref):
    """The exception a wait()-ready ref carries, or None. `wait` reports
    errored objects as ready, so completion polls must probe before
    forwarding a ref downstream — via the owner's status cache, never by
    fetching successful payloads."""
    if not hasattr(ref, "hex"):
        return None
    try:
        from ray_tpu._private.api import _get_worker

        return _get_worker().error_of(ref.hex())
    except Exception:
        return None


def _actor_dead(actor) -> bool:
    """GCS `actor_info` liveness probe (the same poll PR 17's collectives
    use): dead only on a positive answer — an RPC failure is inconclusive
    and must never condemn a healthy actor."""
    try:
        from ray_tpu._private.api import _get_worker

        info = _get_worker().rpc(
            {"type": "actor_info", "aid": actor._actor_id}, timeout=10.0)
    except Exception:
        return False
    return (not info.get("found")) or info.get("state") == "dead"


def _emit_data_event(etype: str, message: str, **fields) -> None:
    try:
        from ray_tpu._private.events import emit_event

        emit_event(etype, severity=const.EVENT_SEVERITY_WARNING,
                   message=message, **fields)
    except Exception:  # noqa: BLE001 — telemetry must not kill the pipeline
        pass


def _robust_get(refs, *, rng=None):
    """Driver-side barrier `get` riding lineage recovery: a lost copy is
    reconstructed inside the worker's `_ensure_local` loop, and the rare
    `ObjectLostError` that still escapes (reconstruction racing eviction)
    gets a bounded, jittered re-get before surfacing."""
    cfg = RayConfig.instance()
    if not cfg.data_fault_tolerance:
        return ray_tpu.get(refs)
    rng = rng if rng is not None else random.Random()
    attempt = 0
    while True:
        try:
            return ray_tpu.get(refs)
        except ObjectLostError:
            if attempt >= cfg.data_max_block_retries:
                raise
            time.sleep(_backoff_delay(attempt, cfg.data_retry_backoff_s,
                                      rng))
            attempt += 1


# Transform fns operate on list[Block] → list[Block]; a stage fuses several.


def _rows_transform(fn: Callable, kind: str) -> Callable:
    def transform(blocks: list[Block]) -> list[Block]:
        out = []
        for b in blocks:
            acc = BlockAccessor(b)
            if kind == "map":
                out.append(rows_to_block([fn(r) for r in acc.iter_rows()]))
            elif kind == "filter":
                out.append(rows_to_block([r for r in acc.iter_rows() if fn(r)]))
            else:  # flat_map
                rows: list = []
                for r in acc.iter_rows():
                    rows.extend(fn(r))
                out.append(rows_to_block(rows))
        return out

    return transform


def _batches_transform(fn: Callable, batch_size: int | None, batch_format: str,
                       fn_kwargs: dict) -> Callable:
    from ray_tpu.data.block import normalize_block

    # a CLASS fn is a stateful UDF: instantiate lazily, once per process —
    # expensive setup (model load) happens once per map actor/worker
    # (reference: ActorPoolMapOperator with callable-class UDFs)
    is_class_fn = isinstance(fn, type)
    state: dict = {}

    def transform(blocks: list[Block]) -> list[Block]:
        if is_class_fn and "inst" not in state:
            state["inst"] = fn()
        call = state["inst"] if is_class_fn else fn
        out = []
        for b in _rebatch(blocks, batch_size):
            if batch_format == "pandas":
                b = BlockAccessor(b).to_pandas()
            elif batch_format == "pyarrow":
                b = BlockAccessor(b).to_arrow()
            else:
                b = BlockAccessor(b).to_numpy()
            res = call(b, **fn_kwargs)
            out.append(normalize_block(res))
        return out

    return transform


def _rebatch(blocks: list[Block], batch_size: int | None) -> Iterator[Block]:
    if batch_size is None:
        yield from (b for b in blocks if BlockAccessor(b).num_rows() > 0)
        return
    buf: list[Block] = []
    buffered = 0
    for b in blocks:
        n = BlockAccessor(b).num_rows()
        if n == 0:
            continue
        buf.append(b)
        buffered += n
        while buffered >= batch_size:
            merged = concat_blocks(buf)
            acc = BlockAccessor(merged)
            yield acc.slice(0, batch_size)
            rest = acc.slice(batch_size, acc.num_rows())
            buf = [rest] if BlockAccessor(rest).num_rows() else []
            buffered = BlockAccessor(rest).num_rows() if buf else 0
    if buffered:
        yield concat_blocks(buf)


@dataclass
class Stage:
    """A fused physical stage: source tasks or a transform over input refs."""

    name: str
    transforms: list[Callable] = field(default_factory=list)
    read_tasks: list | None = None        # source stage if set
    input_refs: list | None = None        # pre-materialized source
    all_to_all: Callable | None = None    # driver-side barrier stage if set
    a2a_refs: Callable | None = None      # distributed barrier: refs -> refs
    resources: dict = field(default_factory=lambda: {"CPU": 1.0})
    max_in_flight: int = 8
    concurrency: object = None  # int or (min, max) for actor pools
    compute: str = "tasks"  # "tasks" | "actors" (stateful UDF pool)

    def run_chain(self, blocks: list[Block]) -> list[Block]:
        for t in self.transforms:
            blocks = t(blocks)
        return blocks


def _stage_task(transforms: list[Callable]):
    def run(payload) -> list[Block]:
        blocks = payload() if callable(payload) else payload
        for t in transforms:
            blocks = t(blocks)
        return blocks

    return run


def build_stages(ops: list[L.LogicalOp], default_parallelism: int) -> list[Stage]:
    """Logical ops → fused physical stages.
    (reference: _internal/planner/planner.py + rules/operator_fusion.py)"""
    stages: list[Stage] = []
    cur: Stage | None = None

    def flush():
        nonlocal cur
        if cur is not None:
            stages.append(cur)
            cur = None

    for op in ops:
        if isinstance(op, L.Read):
            flush()
            par = op.parallelism if op.parallelism > 0 else default_parallelism
            tasks = op.datasource.get_read_tasks(par)
            if op.limit is not None:
                tasks = _cap_read_tasks(tasks, op.limit)
            cur = Stage(name="Read", read_tasks=list(tasks))
        elif isinstance(op, L.InputBlocks):
            flush()
            cur = Stage(name="Input", input_refs=list(op.refs))
        elif isinstance(op, L.MapBatches):
            t = _batches_transform(op.fn, op.batch_size, op.batch_format, op.fn_kwargs)
            res = {"CPU": op.num_cpus}
            if op.num_tpus:
                res["TPU"] = op.num_tpus
            if (cur is not None and cur.all_to_all is None
                    and res == cur.resources
                    and cur.compute == (op.compute or "tasks")):
                cur.name += "->MapBatches"
                cur.transforms.append(t)
            else:
                flush()
                conc = op.concurrency or 8
                # (min, max) tuples configure an autoscaling actor pool
                # (reference: concurrency=(m, n) on map_batches)
                mif = max(conc) if isinstance(conc, (tuple, list)) else conc
                cur = Stage(name="MapBatches", transforms=[t], resources=res,
                            max_in_flight=mif, concurrency=conc,
                            compute=op.compute or "tasks")
        elif isinstance(op, L.MapRows):
            t = _rows_transform(op.fn, op.kind)
            if cur is not None and cur.all_to_all is None:
                cur.name += f"->{op.kind}"
                cur.transforms.append(t)
            else:
                flush()
                cur = Stage(name=op.kind, transforms=[t])
        elif isinstance(op, L.Project):
            cols = list(op.cols)
            t = _batches_transform(
                lambda batch, _c=cols: {k: batch[k] for k in _c},
                None, "numpy", {})
            if cur is not None and cur.all_to_all is None:
                cur.name += "->Project"
                cur.transforms.append(t)
            else:
                flush()
                cur = Stage(name="Project", transforms=[t])
        elif isinstance(op, L.FilterExpr):
            from ray_tpu.data.expressions import compile_predicate

            pred = compile_predicate(op.expr)

            def fexpr(batch, _p=pred):
                m = _p(batch)
                return {k: np.asarray(v)[m] for k, v in batch.items()}

            t = _batches_transform(fexpr, None, "numpy", {})
            if cur is not None and cur.all_to_all is None:
                cur.name += "->FilterExpr"
                cur.transforms.append(t)
            else:
                flush()
                cur = Stage(name="FilterExpr", transforms=[t])
        elif isinstance(op, L.Limit):
            flush()
            stages.append(Stage(name="Limit", all_to_all=_limit_fn(op.n)))
        elif isinstance(op, L.Repartition):
            flush()
            stages.append(Stage(name="Repartition", a2a_refs=_dist_repartition_refs(op.num_blocks)))
        elif isinstance(op, L.RandomShuffle):
            flush()
            stages.append(Stage(name="RandomShuffle", a2a_refs=_dist_shuffle_refs(op.seed)))
        elif isinstance(op, L.Sort):
            flush()
            stages.append(Stage(name="Sort", a2a_refs=_dist_sort_refs(op.key, op.descending)))
        elif isinstance(op, L.GroupByAgg):
            from ray_tpu._private import serialization as ser

            flush()
            stages.append(Stage(
                name="GroupByAgg",
                a2a_refs=_dist_groupby_refs(op.keys, ser.dumps(op.aggs))))
        elif isinstance(op, L.MapGroups):
            from ray_tpu._private import serialization as ser

            flush()
            stages.append(Stage(
                name="MapGroups",
                a2a_refs=_dist_groupby_refs(op.keys, ser.dumps(op.fn),
                                            map_groups=True)))
        elif isinstance(op, L.Join):
            flush()
            stages.append(Stage(name="Join", a2a_refs=_dist_join_refs(op)))
        elif isinstance(op, L.Union):
            pass  # handled at Dataset level by ref concatenation
        else:
            raise TypeError(f"unknown logical op {op}")
    flush()
    if not stages:
        stages = [Stage(name="Input", input_refs=[])]
    return stages


def _cap_read_tasks(tasks, n):
    out, left = [], n
    for t in tasks:
        if left <= 0:
            break
        out.append(t)
        if t.num_rows is not None:
            left -= t.num_rows
    return out


def _limit_fn(n: int):
    def cut(all_blocks: list[Block]) -> list[list[Block]]:
        out, left = [], n
        for b in all_blocks:
            if left <= 0:
                break
            acc = BlockAccessor(b)
            take = min(left, acc.num_rows())
            out.append(acc.slice(0, take))
            left -= take
        return [out]

    return cut


def _repartition_fn(k: int):
    def repart(all_blocks: list[Block]) -> list[list[Block]]:
        merged = concat_blocks(all_blocks)
        total = BlockAccessor(merged).num_rows()
        step = max(1, (total + k - 1) // k)
        acc = BlockAccessor(merged)
        return [[acc.slice(i, min(i + step, total))] for i in range(0, total, step)] or [[{}]]

    return repart


def _shuffle_fn(seed):
    def shuf(all_blocks: list[Block]) -> list[list[Block]]:
        merged = concat_blocks(all_blocks)
        acc = BlockAccessor(merged)
        n = acc.num_rows()
        rng = np.random.default_rng(seed)
        perm = rng.permutation(n)
        out = {k: (np.asarray(v)[perm] if isinstance(v, np.ndarray) else [v[i] for i in perm])
               for k, v in merged.items()}
        return [[out]]

    return shuf


def _sort_fn(key: str, descending: bool):
    def srt(all_blocks: list[Block]) -> list[list[Block]]:
        merged = concat_blocks(all_blocks)
        idx = np.argsort(np.asarray(merged[key]), kind="stable")
        if descending:
            idx = idx[::-1]
        out = {k: (np.asarray(v)[idx] if isinstance(v, np.ndarray) else [v[i] for i in idx])
               for k, v in merged.items()}
        return [[out]]

    return srt


# ------------------------------------------------------------- distributed
# Task-based all-to-all: map tasks partition each input, reduce tasks merge
# one partition each — the driver only routes ObjectRefs, blocks never
# materialize on it (reference: data/_internal/execution/operators/
# hash_shuffle.py; replaces the round-1 driver-side materialization flagged
# in VERDICT item 6).


def _as_blocks(payload) -> list[Block]:
    return payload if isinstance(payload, list) else [payload]


def _take_rows(block: Block, idx) -> Block:
    return {k: (np.asarray(v)[idx] if isinstance(v, np.ndarray)
                else [v[i] for i in idx])
            for k, v in block.items()}


def _split_by_assignment(merged: Block, assign: np.ndarray, w: int):
    parts = []
    for j in range(w):
        idx = np.nonzero(assign == j)[0]
        parts.append([_take_rows(merged, idx)])
    return tuple(parts) if w > 1 else parts[0]


@ray_tpu.remote
def _rows_of(payload) -> int:
    return sum(BlockAccessor(b).num_rows() for b in _as_blocks(payload))


@ray_tpu.remote
def _sample_keys(payload, key: str, k: int):
    merged = concat_blocks(_as_blocks(payload))
    arr = np.asarray(merged.get(key, []))
    if arr.size <= k:
        return arr
    sel = np.random.default_rng(0).choice(arr.size, size=k, replace=False)
    return arr[sel]


@ray_tpu.remote
def _split_random(payload, w: int, seed, salt: int):
    merged = concat_blocks(_as_blocks(payload))
    n = BlockAccessor(merged).num_rows()
    rng = np.random.default_rng(None if seed is None else seed * 100_003 + salt)
    return _split_by_assignment(merged, rng.integers(0, w, n), w)


@ray_tpu.remote
def _split_range(payload, w: int, key: str, boundaries):
    merged = concat_blocks(_as_blocks(payload))
    vals = np.asarray(merged.get(key, []))
    assign = np.searchsorted(np.asarray(boundaries), vals, side="right")
    return _split_by_assignment(merged, assign, w)


@ray_tpu.remote
def _split_offsets(payload, w: int, start: int, bounds):
    merged = concat_blocks(_as_blocks(payload))
    n = BlockAccessor(merged).num_rows()
    global_idx = np.arange(start, start + n)
    assign = np.searchsorted(np.asarray(bounds), global_idx, side="right")
    return _split_by_assignment(merged, assign, w)


@ray_tpu.remote
def _merge_plain(*parts):
    blocks = [b for p in parts for b in _as_blocks(p) if BlockAccessor(b).num_rows()]
    return [concat_blocks(blocks)] if blocks else [{}]


@ray_tpu.remote
def _merge_shuffled(seed, j: int, *parts):
    merged = concat_blocks([b for p in parts for b in _as_blocks(p)])
    n = BlockAccessor(merged).num_rows()
    rng = np.random.default_rng(None if seed is None else seed * 7 + j)
    return [_take_rows(merged, rng.permutation(n))]


@ray_tpu.remote
def _merge_sorted(key: str, descending: bool, *parts):
    merged = concat_blocks([b for p in parts for b in _as_blocks(p)])
    idx = np.argsort(np.asarray(merged.get(key, [])), kind="stable")
    if descending:
        idx = idx[::-1]
    return [_take_rows(merged, idx)]


def _normalize_parts(handle, w: int):
    """options(num_returns=w) returns a single ref for w==1."""
    return handle if isinstance(handle, list) else [handle]


# ------------------------------------------------- groupby / join (hashed)
# (reference: data/grouped_data.py:23 groupby/aggregate over a hash shuffle,
# _internal/execution/operators/hash_shuffle.py + join.py:54)


def _row_hashes(cols, n: int) -> np.ndarray:
    """Stable per-row hash of the key columns (same value → same partition)."""
    import zlib

    h = np.zeros(n, dtype=np.uint64)
    for c in cols:
        a = np.asarray(c)
        if a.dtype.kind in "iubf":
            # ALL numerics hash through float64 so equal values co-locate
            # across dtypes (int64 5 must meet float64 5.0 in a join);
            # precision collisions just share a partition, which is fine
            az = a.astype(np.float64)
            az = np.where(az == 0.0, 0.0, az)  # -0.0 and 0.0 must co-locate
            v = az.view(np.uint64)
        else:
            v = np.fromiter((zlib.crc32(str(x).encode()) for x in a),
                            dtype=np.uint64, count=n)
        h = h * np.uint64(1099511628211) + v
    return h


@ray_tpu.remote
def _split_hash(payload, w: int, keys: list):
    merged = concat_blocks(_as_blocks(payload))
    if not merged:
        return tuple([{}] for _ in range(w)) if w > 1 else [{}]
    n = BlockAccessor(merged).num_rows()
    cols = [merged[k] for k in keys]
    assign = (_row_hashes(cols, n) % np.uint64(w)).astype(np.int64)
    return _split_by_assignment(merged, assign, w)


def _group_sorted(merged: Block, keys: list):
    """Sort rows into group order; return (sorted block, group starts,
    group counts)."""
    n = BlockAccessor(merged).num_rows()
    cols = [np.asarray(merged[k]) for k in keys]
    order = np.lexsort(tuple(reversed(cols)))
    srt = _take_rows(merged, order)
    scols = [np.asarray(srt[k]) for k in keys]
    if n == 0:
        return srt, np.asarray([], dtype=np.int64), np.asarray([], dtype=np.int64)
    newgrp = np.zeros(n, dtype=bool)
    newgrp[0] = True
    for c in scols:
        newgrp[1:] |= c[1:] != c[:-1]
    starts = np.nonzero(newgrp)[0]
    counts = np.diff(np.concatenate([starts, [n]]))
    return srt, starts, counts


@ray_tpu.remote
def _agg_partition(keys: list, aggs_blob: bytes, *parts):
    from ray_tpu._private import serialization as ser

    aggs = ser.loads(aggs_blob)
    blocks = [b for p in parts for b in _as_blocks(p) if BlockAccessor(b).num_rows()]
    if not blocks:
        return [{}]
    srt, starts, counts = _group_sorted(concat_blocks(blocks), keys)
    out: Block = {k: np.asarray(srt[k])[starts] for k in keys}
    for agg in aggs:
        col = np.asarray(srt[agg.on]) if agg.on else None
        vals = agg.compute(col, starts, counts)
        out[agg.alias] = vals if isinstance(vals, list) else np.asarray(vals)
    return [out]


@ray_tpu.remote
def _map_groups_partition(keys: list, fn_blob: bytes, *parts):
    from ray_tpu._private import serialization as ser
    from ray_tpu.data.block import rows_to_block

    fn = ser.loads(fn_blob)
    blocks = [b for p in parts for b in _as_blocks(p) if BlockAccessor(b).num_rows()]
    if not blocks:
        return [{}]
    srt, starts, counts = _group_sorted(concat_blocks(blocks), keys)
    n = BlockAccessor(srt).num_rows()
    ends = np.concatenate([starts[1:], [n]])
    outs = []
    for s, e in zip(starts, ends):
        group = {k: (np.asarray(v)[s:e] if isinstance(v, np.ndarray)
                     else v[s:e]) for k, v in srt.items()}
        res = fn(group)
        if isinstance(res, dict):
            outs.append(res)
        else:  # list of rows
            outs.append(rows_to_block(list(res)))
    return [concat_blocks(outs)] if outs else [{}]


@ray_tpu.remote
def _join_partition(on: list, right_on: list, how: str, suffixes: tuple,
                    n_left: int, *parts):
    lparts, rparts = parts[:n_left], parts[n_left:]
    lb = [b for p in lparts for b in _as_blocks(p) if BlockAccessor(b).num_rows()]
    rb = [b for p in rparts for b in _as_blocks(p) if BlockAccessor(b).num_rows()]
    left = concat_blocks(lb) if lb else {}
    right = concat_blocks(rb) if rb else {}
    ln = BlockAccessor(left).num_rows() if left else 0
    rn = BlockAccessor(right).num_rows() if right else 0

    lkeys = list(zip(*[np.asarray(left[k]) for k in on])) if ln else []
    rkeys = list(zip(*[np.asarray(right[k]) for k in right_on])) if rn else []
    rindex: dict = {}
    for i, k in enumerate(rkeys):
        rindex.setdefault(k, []).append(i)

    li_out: list[int] = []
    ri_out: list[int] = []   # -1 = no right match
    r_matched = np.zeros(rn, dtype=bool)
    for i, k in enumerate(lkeys):
        hits = rindex.get(k)
        if hits:
            for j in hits:
                li_out.append(i)
                ri_out.append(j)
                r_matched[j] = True
        elif how in ("left", "outer"):
            li_out.append(i)
            ri_out.append(-1)
    if how in ("right", "outer"):
        for j in np.nonzero(~r_matched)[0]:
            li_out.append(-1)
            ri_out.append(int(j))
    if not li_out:
        return [{}]
    li = np.asarray(li_out)
    ri = np.asarray(ri_out)

    ls, rs = suffixes
    lcols = list(left.keys()) if ln else []
    rcols = [c for c in (right.keys() if rn else []) if c not in right_on]
    out: Block = {}

    def gather(col_vals, idx, n_src):
        arr = np.asarray(col_vals)
        missing = idx < 0
        if not missing.any():
            return arr[idx]
        if arr.dtype.kind in "fiub":
            res = np.full(len(idx), np.nan, dtype=np.float64)
            res[~missing] = arr[idx[~missing]].astype(np.float64)
            return res
        res = np.empty(len(idx), dtype=object)
        res[~missing] = arr[idx[~missing]]
        return res

    # join keys: from the left side, falling back to the right for
    # right/outer rows with no left match
    for kl, kr in zip(on, right_on):
        kv = gather(left[kl], li, ln) if ln else None
        if how in ("right", "outer") and rn:
            rv = gather(right[kr], ri, rn)
            if kv is None:
                kv = rv
            else:
                miss = li < 0
                if miss.any():
                    kv = np.asarray(kv, dtype=object)
                    kv[miss] = np.asarray(rv, dtype=object)[miss]
        out[kl] = kv
    for c in lcols:
        if c in on:
            continue
        name = c + (ls if c in rcols else "")
        out[name] = gather(left[c], li, ln)
    for c in rcols:
        # suffix on ANY collision with an already-emitted left column —
        # including the join keys, which a right non-key column may shadow
        name = c + (rs if (c in lcols or c in on) else "")
        out[name] = gather(right[c], ri, rn)
    return [out]


def _dist_groupby_refs(keys: list, aggs_blob: bytes, map_groups: bool = False):
    def run(inputs: list) -> list:
        if not inputs:
            return []
        w = len(inputs)
        parts = [_normalize_parts(
            _split_hash.options(num_returns=w).remote(it, w, keys), w)
            for it in inputs]
        task = _map_groups_partition if map_groups else _agg_partition
        return [task.remote(keys, aggs_blob, *[p[j] for p in parts])
                for j in range(w)]

    return run


def _dist_join_refs(op):
    """op: logical.Join — the right plan executes to refs inside the stage
    (a barrier anyway), then both sides hash-shuffle into w partitions and
    one join task merges each."""

    def run(inputs: list) -> list:
        from ray_tpu.data import logical as L

        right_stages = build_stages(L.optimize(op.right_last.chain()), 8)
        ex = StreamingExecutor(right_stages)
        right_refs = []
        try:
            for item in ex.execute():
                if not hasattr(item, "hex"):
                    item = ray_tpu.put(item if isinstance(item, list) else [item])
                else:
                    ex.owned.discard(item.hex())  # ownership moves to this stage
                right_refs.append(item)
        finally:
            ex.release_owned()
        w = op.num_partitions or max(len(inputs), len(right_refs), 1)
        lparts = [_normalize_parts(
            _split_hash.options(num_returns=w).remote(it, w, op.on), w)
            for it in inputs]
        rparts = [_normalize_parts(
            _split_hash.options(num_returns=w).remote(it, w, op.right_on), w)
            for it in right_refs]
        return [_join_partition.remote(
            op.on, op.right_on, op.how, op.suffixes, len(lparts),
            *[p[j] for p in lparts], *[p[j] for p in rparts])
            for j in range(w)]

    return run


def _dist_shuffle_refs(seed):
    def run(inputs: list) -> list:
        if not inputs:
            return []
        w = len(inputs)
        parts = [_normalize_parts(
            _split_random.options(num_returns=w).remote(it, w, seed, i), w)
            for i, it in enumerate(inputs)]
        return [_merge_shuffled.remote(seed, j, *[p[j] for p in parts])
                for j in range(w)]

    return run


def _dist_sort_refs(key: str, descending: bool):
    def run(inputs: list) -> list:
        if not inputs:
            return []
        w = len(inputs)
        # sample pass → range boundaries (small arrays; fine on the
        # driver); the get rides lineage recovery like every barrier get
        samples = _robust_get(
            [_sample_keys.remote(it, key, 64) for it in inputs])
        allk = np.sort(np.concatenate([np.asarray(s) for s in samples])
                       if samples else np.asarray([]))
        if allk.size == 0 or w == 1:
            return [_merge_sorted.remote(key, descending, *inputs)]
        bounds = allk[[min(allk.size - 1, int(allk.size * j / w))
                       for j in range(1, w)]]
        parts = [_normalize_parts(
            _split_range.options(num_returns=w).remote(it, w, key, bounds), w)
            for it in inputs]
        out = [_merge_sorted.remote(key, descending, *[p[j] for p in parts])
               for j in range(w)]
        # global order = partition order; descending reverses partitions too
        return out[::-1] if descending else out

    return run


def _dist_repartition_refs(k: int):
    def run(inputs: list) -> list:
        if not inputs:
            return []
        counts = _robust_get([_rows_of.remote(it) for it in inputs])
        total = sum(counts)
        bounds = [round(total * (j + 1) / k) for j in range(k - 1)]
        starts = np.concatenate([[0], np.cumsum(counts)[:-1]]).tolist()
        parts = [_normalize_parts(
            _split_offsets.options(num_returns=k).remote(it, k, int(starts[i]), bounds), k)
            for i, it in enumerate(inputs)]
        return [_merge_plain.remote(*[p[j] for p in parts]) for j in range(k)]

    return run


@ray_tpu.remote
class _MapPoolActor:
    """Stateful map worker: holds the stage's transform chain (a callable-
    class UDF instantiates ONCE here) and applies it per input."""

    def __init__(self, transforms_blob: bytes):
        from ray_tpu._private import serialization as ser

        self._run = _stage_task(ser.loads(transforms_blob))

    def run(self, payload):
        return self._run(payload)


class _ActorPool:
    """Least-loaded autoscaling pool exposing the task-API shape
    (`.remote(payload)`): dispatch routes to the actor with the fewest
    outstanding inputs, the pool grows toward max_size while every actor is
    backed up, and idle actors above min_size are released. The executor
    reports completions via note_done() (reference:
    execution/operators/actor_pool_map_operator.py:47 — load-based routing
    + pool autoscaling, replacing round-1's blind round-robin)."""

    IDLE_RELEASE_S = 10.0

    def __init__(self, stage: "Stage", size, min_size: int | None = None):
        from ray_tpu._private import serialization as ser

        if isinstance(size, (tuple, list)):
            min_size, size = int(size[0]), int(size[1])
        self.min_size = max(1, int(min_size if min_size is not None else size))
        self.max_size = max(self.min_size, int(size))
        res = stage.resources
        blob = ser.dumps(stage.transforms)
        self._cls = _MapPoolActor.options(
            num_cpus=res.get("CPU", 1.0),
            num_tpus=res.get("TPU", 0.0) or None)
        self._blob = blob
        self._stage_name = stage.name
        self.actors = [self._cls.remote(blob) for _ in range(self.min_size)]
        self._outstanding: dict[str, int] = {}  # ref hex → actor index
        self._load = [0] * len(self.actors)
        self._idle_since = [time.monotonic()] * len(self.actors)
        cfg = RayConfig.instance()
        # lifetime dead-actor replacement budget (-1 = unlimited); FT off
        # pins it to 0 so a dead actor is dropped, never respawned
        self._restart_budget = (cfg.data_actor_restart_budget
                                if cfg.data_fault_tolerance else 0)
        self.replacements = 0

    def remote(self, payload):
        # grow whenever every live actor is already busy — the executor
        # caps total outstanding at max_size, so requiring a deeper backlog
        # would plateau the pool below the requested maximum
        if (len(self.actors) < self.max_size
                and self._load and min(self._load) >= 1):
            self.actors.append(self._cls.remote(self._blob))
            self._load.append(0)
            self._idle_since.append(time.monotonic())
        idx = min(range(len(self.actors)), key=lambda i: self._load[i])
        self._load[idx] += 1
        ref = self.actors[idx].run.remote(payload)
        self._outstanding[ref.hex()] = idx
        return ref

    def note_done(self, ref_hex: str) -> None:
        idx = self._outstanding.pop(ref_hex, None)
        if idx is None or idx >= len(self.actors):
            return
        self._load[idx] -= 1
        now = time.monotonic()
        if self._load[idx] == 0:
            self._idle_since[idx] = now
        # release ONE idle actor above min (newest first) per completion
        if len(self.actors) > self.min_size:
            for i in range(len(self.actors) - 1, self.min_size - 1, -1):
                if (self._load[i] == 0
                        and now - self._idle_since[i] > self.IDLE_RELEASE_S):
                    a = self.actors.pop(i)
                    self._load.pop(i)
                    self._idle_since.pop(i)
                    # reindex outstanding entries above i
                    for k, v in list(self._outstanding.items()):
                        if v > i:
                            self._outstanding[k] = v - 1
                    try:
                        ray_tpu.kill(a)
                    except Exception:
                        pass
                    break

    def note_failed(self, ref_hex: str) -> tuple[list[str], int]:
        """A task this pool dispatched came back errored: release its
        slot, probe the actor that ran it, and if dead, replace it within
        the restart budget. Returns (orphaned ref hexes — the dead actor's
        OTHER in-flight tasks, for the executor to re-dispatch from its
        retained payloads — and how many actors were replaced)."""
        idx = self._outstanding.pop(ref_hex, None)
        if idx is None or idx >= len(self.actors):
            return [], 0
        self._load[idx] -= 1
        if self._load[idx] == 0:
            self._idle_since[idx] = time.monotonic()
        if not _actor_dead(self.actors[idx]):
            return [], 0  # plain task failure on a live actor
        return self._replace(idx)

    def _replace(self, idx: int) -> tuple[list[str], int]:
        orphans = [k for k, v in self._outstanding.items() if v == idx]
        for k in orphans:
            del self._outstanding[k]
        dead = self.actors.pop(idx)
        self._load.pop(idx)
        self._idle_since.pop(idx)
        for k, v in list(self._outstanding.items()):
            if v > idx:
                self._outstanding[k] = v - 1
        try:
            ray_tpu.kill(dead)  # reap the corpse's GCS record
        except Exception:
            pass
        replaced = 0
        if self._restart_budget != 0:
            if self._restart_budget > 0:
                self._restart_budget -= 1
            self.actors.append(self._cls.remote(self._blob))
            self._load.append(0)
            self._idle_since.append(time.monotonic())
            self.replacements += 1
            replaced = 1
        if not self.actors:
            raise DataBlockError(
                f"map-actor pool for stage {self._stage_name!r} has no "
                f"survivors and its restart budget is exhausted",
                stage=self._stage_name, kind="system")
        return orphans, replaced

    def shutdown(self):
        for a in self.actors:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass


_pipeline_metric_cache: tuple | None = None
_pipeline_seq = itertools.count(1)  # collision-free pipeline tags


def _pipeline_metrics() -> tuple:
    """Process-wide executor gauges/counters (one registration per process;
    concurrent executors share them, distinguished by a pipeline tag)."""
    global _pipeline_metric_cache
    if _pipeline_metric_cache is None:
        from ray_tpu.util import metrics as _met

        _pipeline_metric_cache = (
            _met.Gauge("ray_tpu_data_bytes_in_flight",
                       "queued bytes across executor stages",
                       tag_keys=("pipeline",)),
            _met.Gauge("ray_tpu_data_blocks_queued",
                       "queued items across executor stages",
                       tag_keys=("pipeline",)),
            _met.Counter("ray_tpu_data_backpressure_waits",
                         "dispatches deferred by queue/byte backpressure",
                         tag_keys=("pipeline",)),
            _met.Counter("ray_tpu_data_block_retries_total",
                         "block tasks resubmitted after SYSTEM errors "
                         "(actor death / worker crash / lost object)",
                         tag_keys=("pipeline",)),
            _met.Counter("ray_tpu_data_actor_replacements_total",
                         "dead map-pool actors replaced by supervision",
                         tag_keys=("pipeline",)),
            _met.Counter("ray_tpu_data_blocks_errored_total",
                         "blocks permanently errored by UDF raises "
                         "(skipped or surfaced per on_block_error)",
                         tag_keys=("pipeline",)),
        )
    return _pipeline_metric_cache


class StreamingExecutor:
    """Pull-based streaming executor: yields lists of blocks as they finish.

    Backpressure: per-stage `max_in_flight` remote tasks + `max_queued`
    finished-but-unconsumed outputs; upstream dispatch stalls while a
    downstream queue is full.
    """

    def __init__(self, stages: list[Stage], *, max_queued: int = 16,
                 max_queued_bytes: int | None = None,
                 on_block_error: str | None = None,
                 max_errored_blocks: int | None = None, rng=None):
        self.stages = stages
        self.max_queued = max_queued
        cfg = RayConfig.instance()
        # APPLICATION-error policy (UDF raises): "raise" surfaces the
        # first errored block; "skip" drops-and-counts until
        # max_errored_blocks is exceeded (-1 = unlimited). SYSTEM errors
        # never consult either — they are retried, and only a retry
        # budget exhaustion raises.
        self.on_block_error = (on_block_error if on_block_error is not None
                               else cfg.data_on_block_error)
        if self.on_block_error not in ("raise", "skip"):
            raise ValueError(
                f"on_block_error must be 'raise' or 'skip', "
                f"got {self.on_block_error!r}")
        self.max_errored_blocks = (
            max_errored_blocks if max_errored_blocks is not None
            else cfg.data_max_errored_blocks)
        self._rng = rng if rng is not None else random.Random()
        self.errored_blocks = 0
        self.errored_block_ids: list = []
        # reservation-style memory backpressure (reference:
        # data/_internal/execution/resource_manager.py — operator output
        # budgets in BYTES, not just counts): dispatch into a queue stalls
        # while its object-store-resident bytes exceed the budget, so one
        # stage producing huge blocks cannot OOM the store no matter how
        # small max_queued is. Sizes come from the local store's metadata
        # (free for refs this driver produced); unknown sizes count 0, so
        # the byte gate degrades to the count gate, never deadlocks.
        import os as _os

        self.max_queued_bytes = (
            max_queued_bytes if max_queued_bytes is not None
            else int(_os.environ.get("RAY_TPU_DATA_MAX_QUEUED_BYTES",
                                     256 << 20)))
        # refs produced by THIS execution (not caller-owned input refs); safe
        # to free once consumed — keeps streaming memory bounded instead of
        # pinning every block in the driver for the run's lifetime
        self.owned: set[str] = set()

    def _free_if_owned(self, item) -> None:
        if hasattr(item, "hex") and item.hex() in self.owned:
            self.owned.discard(item.hex())
            try:
                ray_tpu.free([item])
            except Exception:  # noqa: BLE001 — cleanup must not kill the stream
                pass

    def release_owned(self) -> None:
        """Free every ref this execution still owns (idempotent).

        The teardown half of the owned-ref ledger: `execute()` calls it
        from its `finally` so an error or abandoned iteration never
        strands store segments, and consumers that construct an executor
        must call it on every path — graft_check's resource-leak pair
        (`StreamingExecutor` / `release_owned`) holds them to it."""
        if not self.owned:
            return
        from ray_tpu._private.worker import ObjectRef

        refs = [ObjectRef(h) for h in self.owned]
        self.owned.clear()
        try:
            ray_tpu.free(refs)
        except Exception:  # noqa: BLE001 — cleanup must not kill teardown
            pass

    def execute(self) -> Iterator[list]:
        """Yield ObjectRefs of list[Block] results of the final stage."""
        remote_cache: dict[int, Any] = {}
        actor_pools: list = []
        self._actor_pools = actor_pools  # introspection (chaos tests)

        def stage_remote(i: int, stage: Stage):
            if i not in remote_cache:
                res = stage.resources
                if stage.compute == "actors":
                    # stateful UDF pool (reference: ActorPoolMapOperator,
                    # execution/operators/actor_pool_map_operator.py:47):
                    # one actor per concurrency slot, round-robin dispatch
                    pool = _ActorPool(stage,
                                      size=stage.concurrency
                                      or stage.max_in_flight)
                    actor_pools.append(pool)
                    remote_cache[i] = pool
                else:
                    remote_cache[i] = ray_tpu.remote(
                        num_cpus=res.get("CPU", 1.0),
                        num_tpus=res.get("TPU", 0.0) or None,
                    )(_stage_task(stage.transforms))
            return remote_cache[i]

        # Coalesce [source(+fused maps)] [a2a] [maps] ... into pipeline phases.
        first = self.stages[0]
        rest = self.stages[1:]

        source_payloads: collections.deque = collections.deque()
        if first.read_tasks is not None:
            source_payloads.extend(first.read_tasks)
            source_is_refs = False
        else:
            source_payloads.extend(first.input_refs or [])
            source_is_refs = True

        # state per downstream stage
        in_flight: list[dict] = [{} for _ in rest]  # ref -> None
        queues: list[collections.deque] = [collections.deque() for _ in range(len(rest) + 1)]
        src_in_flight: dict = {}

        # Submission-order sequence tags. Completions enter queues in
        # COMPLETION order (nondeterministic under load); map stages don't
        # care, but barrier stages salt their partition tasks by positional
        # index, so a reordered input list would silently change e.g. a
        # seeded random_shuffle's permutation. Tags flow through map stages
        # (the output ref inherits the input's tag) and barriers sort by
        # them before fanning out.
        import itertools as _it

        seq_counter = _it.count()
        seq_of: dict[str, int] = {}

        def _skey(item) -> str:
            return item.hex() if hasattr(item, "hex") else str(id(item))

        def _tag(item) -> None:
            seq_of[_skey(item)] = next(seq_counter)

        def _inherit(new_item, old_item) -> None:
            seq_of[_skey(new_item)] = seq_of.pop(_skey(old_item),
                                                 next(seq_counter))

        def _ordered(items):
            return sorted(items, key=lambda it: seq_of.get(_skey(it), 1 << 60))

        # byte accounting for the reservation-style backpressure: size
        # looked up ONCE at enqueue (local-store metadata for refs, block
        # sizes for materialized lists), remembered until dequeue
        qbytes = [0] * (len(rest) + 1)
        size_of: dict[str, int] = {}

        def _nbytes(item) -> int:
            if hasattr(item, "hex"):
                try:
                    from ray_tpu._private.api import _get_worker

                    return _get_worker().store.size(item.hex())
                except Exception:  # remote/inline/unknown: count 0
                    return 0
            blocks = item if isinstance(item, list) else [item]
            try:
                return sum(BlockAccessor(b).size_bytes() for b in blocks)
            except Exception:
                return 0

        # pipeline observability on the cluster metrics plane (reference:
        # Data's dashboard metrics tab — operator bytes/queue gauges);
        # process-wide gauges tagged per pipeline, updated at the same
        # sites that maintain the byte accounting
        (m_bytes, m_blocks, m_bp, m_retries, m_replacements,
         m_errored) = _pipeline_metrics()
        pipeline_tag = {"pipeline": f"exec-{next(_pipeline_seq)}"}
        bp_blocked = [False] * (len(rest) + 1)  # per-queue deferral state
        # per-pipeline counter tallies, folded into the stable
        # {"pipeline": "_retired"} aggregate at teardown: cumulative
        # *_total counters must outlive the pipeline that earned them,
        # while the per-pipeline series still retires (bounded cardinality)
        tally = {"bp": 0.0, "retries": 0.0, "repl": 0.0, "errored": 0.0}

        # ---- fault handling state (tentpole, ISSUE 20) ----
        cfg = RayConfig.instance()
        ft_on = cfg.data_fault_tolerance
        max_retries = cfg.data_max_block_retries
        backoff_s = cfg.data_retry_backoff_s
        rng = self._rng
        # block id = the block's submission-order sequence tag, which
        # `_inherit` threads through every map stage — so the attempt
        # count follows the BLOCK, not any one task ref, and a poison
        # payload bouncing between replacement actors stays bounded
        attempts: dict[int, int] = {}
        retry_heap: list = []  # (due, tiebreak, stage idx | -1=source, item)
        retry_tick = _it.count()

        def _probe_ready(ready):
            """Split wait()-ready refs into (ok, [(ref, exc)])."""
            if not ft_on:
                return ready, []
            ok, bad = [], []
            for r in ready:
                exc = _ref_error(r)
                (ok.append(r) if exc is None else bad.append((r, exc)))
            return ok, bad

        def _drop_item(item) -> None:
            # forget a permanently-dead block's input: its tag must leave
            # seq_of or the ordered-emission min-live gate stalls forever
            seq_of.pop(_skey(item), None)
            size_of.pop(_skey(item), None)
            self._free_if_owned(item)

        def _handle_failure(stage_idx: int, stage_name: str, ref, item,
                            exc) -> None:
            """One dispatched block task came back errored: classify, then
            resubmit the retained input (SYSTEM, within budget), skip the
            block (APPLICATION under the skip policy), or raise."""
            _inherit(item, ref)  # the block id follows the input back
            bid = seq_of.get(_skey(item), -1)
            self.owned.discard(ref.hex())
            try:
                ray_tpu.free([ref])
            except Exception:
                pass
            if _is_system_error(exc):
                done = attempts.get(bid, 0)
                if done < max_retries:
                    attempts[bid] = done + 1
                    tally["retries"] += 1
                    try:
                        m_retries.inc(tags=pipeline_tag)
                    except Exception:
                        pass
                    _emit_data_event(
                        const.EVENT_DATA_BLOCK_RETRY,
                        f"block {bid} stage {stage_name!r}: retry "
                        f"{done + 1}/{max_retries} after {type(exc).__name__}",
                        block_id=bid, stage=stage_name)
                    logger.warning(
                        "data: retrying block %s in stage %r "
                        "(attempt %d/%d) after %r",
                        bid, stage_name, done + 1, max_retries, exc)
                    heapq.heappush(
                        retry_heap,
                        (time.monotonic()
                         + _backoff_delay(done, backoff_s, rng),
                         next(retry_tick), stage_idx, item))
                    return
                _drop_item(item)
                raise DataBlockError(
                    f"block {bid} failed in stage {stage_name!r} after "
                    f"{done} retries: {exc!r}", block_id=bid,
                    stage=stage_name, kind="system") from exc
            # APPLICATION error (the UDF itself raised)
            if self.on_block_error == "skip":
                self.errored_blocks += 1
                self.errored_block_ids.append(bid)
                tally["errored"] += 1
                try:
                    m_errored.inc(tags=pipeline_tag)
                except Exception:
                    pass
                _emit_data_event(
                    const.EVENT_DATA_BLOCK_ERRORED,
                    f"block {bid} stage {stage_name!r} skipped: "
                    f"{type(exc).__name__}",
                    block_id=bid, stage=stage_name)
                logger.warning(
                    "data: skipping errored block %s in stage %r "
                    "(%d skipped so far): %r",
                    bid, stage_name, self.errored_blocks, exc)
                _drop_item(item)
                if 0 <= self.max_errored_blocks < self.errored_blocks:
                    raise DataBlockError(
                        f"{self.errored_blocks} errored blocks exceed "
                        f"max_errored_blocks={self.max_errored_blocks} "
                        f"(last: block {bid} in stage {stage_name!r}: "
                        f"{exc!r})", block_id=bid, stage=stage_name,
                        kind="application") from exc
                return
            _drop_item(item)
            raise DataBlockError(
                f"block {bid} failed in stage {stage_name!r}: UDF raised "
                f"{exc!r}", block_id=bid, stage=stage_name,
                kind="application") from exc

        def _note_replacements(pool, stage_name: str, n: int) -> None:
            if not n:
                return
            tally["repl"] += float(n)
            try:
                m_replacements.inc(float(n), tags=pipeline_tag)
            except Exception:
                pass
            _emit_data_event(
                const.EVENT_DATA_ACTOR_REPLACED,
                f"stage {stage_name!r}: replaced {n} dead map-pool "
                f"actor(s) ({pool.replacements} lifetime)",
                stage=stage_name)
            logger.warning(
                "data: replaced %d dead map-pool actor(s) in stage %r",
                n, stage_name)

        def _pending_retries_before(i: int) -> bool:
            # a pending retry for the source or any stage < i means the
            # barrier at i has NOT seen all of its input yet
            return any(entry[2] < i for entry in retry_heap)

        def _note_queues() -> None:
            try:
                m_bytes.set(float(sum(qbytes)), pipeline_tag)
                m_blocks.set(float(sum(len(dq) for dq in queues)),
                             pipeline_tag)
            except Exception:
                pass

        def _q_add(j: int, item) -> None:
            n = _nbytes(item)
            size_of[_skey(item)] = n
            qbytes[j] += n
            queues[j].append(item)
            _note_queues()

        def _q_pop(j: int):
            # min-tag-first: dispatching the oldest pending work bounds how
            # far ahead out-of-order completions can run (smaller ordered-
            # emission buffer, stragglers never starve behind newer items)
            # removal is by INDEX: deque.remove would compare payloads
            # with == (ambiguous for block lists holding numpy arrays).
            # Single O(n) enumerate pass — indexing a deque is O(n) itself.
            idx, item = min(enumerate(queues[j]),
                            key=lambda p: seq_of.get(_skey(p[1]), 1 << 60))
            del queues[j][idx]
            qbytes[j] -= size_of.pop(_skey(item), 0)
            _note_queues()
            return item

        def _q_clear(j: int) -> None:
            for item in queues[j]:
                key = _skey(item)
                size_of.pop(key, None)
                seq_of.pop(key, None)  # a leaked tag would stall ordered
                # emission at the consumer (min-live-tag gate) forever
            queues[j].clear()
            qbytes[j] = 0

        def _q_room(j: int) -> bool:
            # a queue feeding a BARRIER stage is exempt from both gates:
            # the barrier consumes only after upstream fully drains, so
            # capping its input (by count or bytes) deadlocks the pipeline
            # the moment the dataset outgrows the cap. Barrier inputs are
            # store-resident refs; accumulation is the design.
            if j < len(rest) and is_barrier(rest[j]):
                return True
            # the FINAL queue is also exempt: ordered emission holds items
            # until every smaller tag lands, so capping it deadlocks when
            # >= max_queued out-of-order results pile up ahead of one
            # straggler (the gate blocks the straggler's dispatch, the
            # ordering gate blocks emission). Min-tag-first dispatch below
            # keeps the out-of-order horizon small in practice.
            if j == len(queues) - 1:
                return True
            room = (len(queues[j]) < self.max_queued
                    and qbytes[j] < self.max_queued_bytes)
            # edge-triggered: count DEFERRAL EPISODES, not poll frequency —
            # the pump loop re-probes a full queue every tick, which would
            # otherwise inflate the counter at spin rate
            if not room and not bp_blocked[j]:
                bp_blocked[j] = True
                tally["bp"] += 1
                try:
                    m_bp.inc(tags=pipeline_tag)
                except Exception:
                    pass
            elif room:
                bp_blocked[j] = False
            return room

        def is_barrier(s: Stage) -> bool:
            return s.all_to_all is not None or s.a2a_refs is not None

        a2a_done = [False] * len(rest)

        def pump() -> None:
            # due retries re-enter the normal dispatch queues first: a
            # source payload returns to the head of the backlog, a map
            # input back to its stage queue (min-tag-first dispatch then
            # favors it — the retried block is the oldest pending work)
            if retry_heap:
                now = time.monotonic()
                deferred = []
                while retry_heap and retry_heap[0][0] <= now:
                    entry = heapq.heappop(retry_heap)
                    _, _, j, item = entry
                    if j < 0:
                        source_payloads.appendleft(item)
                    elif _q_room(j):
                        _q_add(j, item)
                    else:
                        # queue full: the retry stays parked on the heap
                        # (already due, so the next pump re-probes) rather
                        # than overshooting the max_queued/byte budgets —
                        # barrier gating and all_done() still see it pending
                        deferred.append(entry)
                for entry in deferred:
                    heapq.heappush(retry_heap, entry)

            # source dispatch
            while (source_payloads and len(src_in_flight) < first.max_in_flight
                   and _q_room(0)):
                payload = source_payloads.popleft()
                if source_is_refs and not first.transforms:
                    _tag(payload)
                    _q_add(0, payload)
                    continue
                fn = stage_remote(-1, first)
                ref = fn.remote(payload)
                # a retried payload already carries its block tag; fresh
                # payloads are tagged here, at first dispatch
                if _skey(payload) in seq_of:
                    _inherit(ref, payload)
                else:
                    _tag(ref)
                self.owned.add(ref.hex())
                # the payload is RETAINED while in flight: resubmission
                # after a SYSTEM failure needs it
                src_in_flight[ref.hex()] = (ref, payload)

            # poll source completions
            if src_in_flight:
                refs = [r for r, _ in src_in_flight.values()]
                ready, _ = ray_tpu.wait(refs, num_returns=len(refs), timeout=0)
                ok, bad = _probe_ready(ready)
                for r in ok:
                    src_in_flight.pop(r.hex(), None)
                    _q_add(0, r)
                for r, exc in bad:
                    _, payload = src_in_flight.pop(r.hex())
                    _handle_failure(-1, first.name, r, payload, exc)

            # downstream stages
            for i, stage in enumerate(rest):
                if is_barrier(stage):
                    # barrier: wait until everything upstream drained —
                    # including blocks parked on the retry heap, which
                    # will re-enter an upstream queue when due
                    upstream_done = (not source_payloads and not src_in_flight
                                     and all(not f for f in in_flight[:i])
                                     and all(not queues[j] or j == i for j in range(i + 1))
                                     and not _pending_retries_before(i))
                    if a2a_done[i] or not upstream_done or not _upstream_a2a_done(i):
                        continue
                    inputs = _ordered(queues[i])
                    _q_clear(i)
                    if stage.a2a_refs is not None:
                        # distributed: hand refs to the partition/merge task
                        # graph; blocks never touch the driver
                        in_refs = []
                        for item in inputs:
                            if hasattr(item, "hex"):
                                in_refs.append(item)
                            else:
                                r = ray_tpu.put(item if isinstance(item, list) else [item])
                                self.owned.add(r.hex())
                                in_refs.append(r)
                        for r in stage.a2a_refs(in_refs):
                            self.owned.add(r.hex())
                            _tag(r)
                            _q_add(i + 1, r)
                        # inputs: drop our handles only — the partition tasks
                        # hold them as deps; manual free here would race arg
                        # resolution. Auto-GC reclaims after the tasks finish.
                        for item in in_refs:
                            self.owned.discard(item.hex())
                    else:
                        blocks: list[Block] = []
                        for item in inputs:
                            # lineage-backed: a block whose only copy was
                            # lost is reconstructed inside the get
                            got = (_robust_get(item, rng=rng)
                                   if hasattr(item, "hex") else item)
                            blocks.extend(got if isinstance(got, list) else [got])
                            self._free_if_owned(item)
                        for out_blocks in stage.all_to_all(blocks):
                            _tag(out_blocks)
                            _q_add(i + 1, out_blocks)  # plain lists, not refs
                    a2a_done[i] = True
                    continue
                # map stage
                while (queues[i] and len(in_flight[i]) < stage.max_in_flight
                       and _q_room(i + 1)):
                    item = _q_pop(i)
                    fn = stage_remote(i, stage)
                    ref = fn.remote(item)
                    _inherit(ref, item)
                    self.owned.add(ref.hex())
                    in_flight[i][ref.hex()] = (ref, item)
                if in_flight[i]:
                    refs = [r for r, _ in in_flight[i].values()]
                    ready, _ = ray_tpu.wait(refs, num_returns=len(refs), timeout=0)
                    pool = remote_cache.get(i)
                    ok, bad = _probe_ready(ready)
                    for r in ok:
                        _, consumed = in_flight[i].pop(r.hex())
                        self._free_if_owned(consumed)
                        if hasattr(pool, "note_done"):
                            pool.note_done(r.hex())
                        _q_add(i + 1, r)
                    for r, exc in bad:
                        # default pop: a second failed task of the same dead
                        # actor may already have been handled as an orphan of
                        # the first — each failure is classified exactly once
                        entry = in_flight[i].pop(r.hex(), None)
                        if entry is None:
                            continue
                        _, item = entry
                        if hasattr(pool, "note_failed"):
                            # pool supervision: probe + replace the dead
                            # actor, then re-dispatch every OTHER payload
                            # it held from our retained inputs (each one
                            # consumes a retry attempt, so a poison
                            # payload cannot ping-pong forever)
                            orphans, replaced = pool.note_failed(r.hex())
                            _note_replacements(pool, stage.name, replaced)
                            for oh in orphans:
                                oe = in_flight[i].pop(oh, None)
                                if oe is not None:
                                    _handle_failure(
                                        i, stage.name, oe[0], oe[1],
                                        ActorDiedError(
                                            "map-pool actor died with "
                                            "this block in flight"))
                        _handle_failure(i, stage.name, r, item, exc)

        def _upstream_a2a_done(i):
            return all(a2a_done[j] for j, s in enumerate(rest[:i]) if is_barrier(s))

        def all_done() -> bool:
            return (not source_payloads and not src_in_flight and not retry_heap
                    and all(not f for f in in_flight)
                    and all(not q for q in queues[:-1])
                    and all(a2a_done[i] for i, s in enumerate(rest) if is_barrier(s)))

        def _pop_in_order():
            """Yieldable final items, SUBMISSION order (reference: Ray Data
            preserves block order end to end). An item may leave only when
            no smaller sequence tag is live anywhere upstream — tags are
            monotonic, future dispatches always tag higher, so the minimum
            live tag being ours proves nothing earlier can still arrive."""
            last = len(queues) - 1
            while queues[last]:
                min_live = min(seq_of.values(), default=None)
                # index-based removal: == on block payloads is unsafe;
                # single enumerate pass (deque indexing is O(n))
                idx, head = min(enumerate(queues[last]),
                                key=lambda p: seq_of.get(
                                    _skey(p[1]), 1 << 60))
                if (min_live is not None
                        and seq_of.get(_skey(head), 1 << 60) > min_live):
                    return  # something earlier is still in flight upstream
                del queues[last][idx]
                qbytes[last] -= size_of.pop(_skey(head), 0)
                seq_of.pop(_skey(head), None)
                _note_queues()
                yield head

        idle_spin = 0.0
        try:
            while True:
                pump()
                if queues[-1]:
                    emitted = False
                    for item in _pop_in_order():
                        emitted = True
                        yield item
                    if emitted:
                        idle_spin = 0.0
                        continue
                if all_done():
                    # defensive: flush any remaining final items in tag
                    # order — nothing upstream can produce anymore, so the
                    # min-live gate no longer applies
                    last = len(queues) - 1
                    for item in sorted(queues[last],
                                       key=lambda it: seq_of.get(
                                           _skey(it), 1 << 60)):
                        yield item
                    queues[last].clear()
                    return
                time.sleep(min(0.05, 0.001 + idle_spin))
                idle_spin = min(0.05, idle_spin + 0.002)
        finally:
            # retire this pipeline's labelsets once it stops (normal end,
            # consumer abandonment, or error) — stale series would both
            # mislead /metrics and accumulate one labelset per lifetime
            # pipeline in a long-lived driver. Counters first fold into a
            # stable {"pipeline": "_retired"} aggregate: a *_total counter
            # that vanished with its pipeline could never be scraped
            # reliably, while gauges are point-in-time and just retire.
            try:
                for met, key in ((m_bp, "bp"), (m_retries, "retries"),
                                 (m_replacements, "repl"),
                                 (m_errored, "errored")):
                    if tally[key]:
                        met.inc(tally[key], tags={"pipeline": "_retired"})
                m_bytes.remove(pipeline_tag)
                m_blocks.remove(pipeline_tag)
                m_bp.remove(pipeline_tag)
                m_retries.remove(pipeline_tag)
                m_replacements.remove(pipeline_tag)
                m_errored.remove(pipeline_tag)
            except Exception:
                pass
            for pool in actor_pools:
                pool.shutdown()
            # every exception/abandonment path releases the owned-ref
            # ledger — yielded-but-unconsumed and in-flight outputs never
            # strand store segments (ISSUE 20 satellite)
            self.release_owned()


def iter_result_blocks(stages: list[Stage], **exec_opts) -> Iterator[Block]:
    """Execute and yield individual blocks (driver-side materialized)."""
    ex = StreamingExecutor(stages, **exec_opts)
    try:
        for item in ex.execute():
            got = (_robust_get(item, rng=ex._rng)
                   if hasattr(item, "hex") else item)
            ex._free_if_owned(item)
            if isinstance(got, list):
                yield from got
            else:
                yield got
    finally:
        ex.release_owned()
