"""Filter expressions: one string both the executor and datasources
understand, so predicates can run as a batch filter OR be pushed into a
parquet read's row-group pruning.

Supported grammar (parsed with `ast`, never eval'd): AND-chains of
comparisons between a column name and a literal —
``"label >= 3 and split == 'train'"``; also ``in`` / ``not in`` with
list/tuple/set literals. This mirrors the subset pyarrow's
``filters=[(col, op, val), ...]`` accepts (reference capability:
data reads push predicates into parquet fragments,
python/ray/data/_internal/datasource/parquet_datasource.py).
"""

from __future__ import annotations

import ast
from typing import Any, Callable

import numpy as np

_OPS = {
    ast.Eq: "==", ast.NotEq: "!=", ast.Lt: "<", ast.LtE: "<=",
    ast.Gt: ">", ast.GtE: ">=", ast.In: "in", ast.NotIn: "not in",
}


def parse_filter(expr: str) -> list[tuple[str, str, Any]]:
    """``"a > 3 and b == 'x'"`` → ``[("a", ">", 3), ("b", "==", "x")]``
    (pyarrow DNF conjunction). Raises ValueError on anything outside the
    grammar — filters never execute arbitrary code."""
    try:
        tree = ast.parse(expr, mode="eval").body
    except SyntaxError as e:
        raise ValueError(f"bad filter expression {expr!r}: {e}") from e
    out: list[tuple[str, str, Any]] = []
    _collect(tree, out, expr)
    return out


def _collect(node: ast.AST, out: list, expr: str) -> None:
    if isinstance(node, ast.BoolOp) and isinstance(node.op, ast.And):
        for v in node.values:
            _collect(v, out, expr)
        return
    if not isinstance(node, ast.Compare) or len(node.ops) != 1:
        raise ValueError(
            f"unsupported filter {expr!r}: only AND-chains of single "
            "comparisons (col <op> literal) are allowed")
    op_t = type(node.ops[0])
    if op_t not in _OPS:
        raise ValueError(f"unsupported operator in filter {expr!r}")
    left, right = node.left, node.comparators[0]
    col, lit, flipped = _classify(left, right, expr)
    op = _OPS[op_t]
    if flipped:
        op = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}.get(op, op)
        if op in ("in", "not in"):
            raise ValueError(f"'in' needs the column on the left: {expr!r}")
    out.append((col, op, lit))


def _classify(left, right, expr):
    if isinstance(left, ast.Name):
        return left.id, _literal(right, expr), False
    if isinstance(right, ast.Name):
        return right.id, _literal(left, expr), True
    raise ValueError(f"filter {expr!r} needs a bare column name on one side")


def _literal(node, expr):
    try:
        return ast.literal_eval(node)
    except (ValueError, SyntaxError) as e:
        raise ValueError(f"non-literal operand in filter {expr!r}") from e


def compile_predicate(expr: str) -> Callable[[dict], np.ndarray]:
    """Batch-level predicate: {col: array} → boolean mask. Used when the
    filter can't be pushed into the read (non-parquet source, or an op in
    between changed the rows)."""
    conj = parse_filter(expr)

    def mask(batch: dict) -> np.ndarray:
        m: np.ndarray | None = None
        for col, op, lit in conj:
            v = np.asarray(batch[col])
            if op == "==":
                part = v == lit
            elif op == "!=":
                part = v != lit
            elif op == "<":
                part = v < lit
            elif op == "<=":
                part = v <= lit
            elif op == ">":
                part = v > lit
            elif op == ">=":
                part = v >= lit
            elif op == "in":
                part = np.isin(v, list(lit))
            else:  # not in
                part = ~np.isin(v, list(lit))
            m = part if m is None else (m & part)
        if m is None:
            raise ValueError(f"empty filter {expr!r}")
        return m

    return mask
