"""Lakehouse table formats: Delta Lake and Apache Iceberg.

Both are implemented over this package's own parquet + avro IO rather than
the `deltalake` / `pyiceberg` wheels the reference delegates to
(reference: python/ray/data/read_api.py read_delta / read_iceberg,
_internal/datasource/{delta,iceberg}_datasource.py — neither wheel is in
this image, and the formats themselves are small enough to speak natively):

- Delta: the `_delta_log/` transaction log (JSON commits + optional parquet
  checkpoints) is replayed into the active file set; reads push column
  projection and row-group predicates into the underlying parquet scans;
  writes produce real commits other Delta readers accept (protocol 1/2,
  metaData on create, add actions with partition values).
- Iceberg: `metadata/*.metadata.json` -> snapshot -> manifest-list (avro)
  -> manifests (avro) -> data files; deleted entries are dropped. The avro
  manifests are decoded by ray_tpu.data.avro.
"""

from __future__ import annotations

import glob as _glob
import json
import os
import time
import uuid
from typing import Any

import numpy as np

from ray_tpu.data.block import Block, BlockAccessor, normalize_block
from ray_tpu.data.datasource import Datasource, ReadTask, round_robin

# ------------------------------------------------------------------- delta


def _delta_log_dir(table: str) -> str:
    return os.path.join(table, "_delta_log")


def _replay_delta_log(table: str) -> tuple[list[dict], dict]:
    """Replay the transaction log → (active add actions, metaData)."""
    log = _delta_log_dir(table)
    if not os.path.isdir(log):
        raise FileNotFoundError(f"{table}: no _delta_log — not a Delta table")
    adds: dict[str, dict] = {}
    meta: dict = {}
    start_version = -1
    ckpt_file = os.path.join(log, "_last_checkpoint")
    if os.path.exists(ckpt_file):
        with open(ckpt_file) as f:
            ckpt = json.load(f)
        start_version = int(ckpt["version"])
        import pyarrow.parquet as pq

        ckpt_path = os.path.join(
            log, f"{start_version:020d}.checkpoint.parquet")
        for row in pq.read_table(ckpt_path).to_pylist():
            if row.get("add"):
                a = row["add"]
                adds[a["path"]] = a
            if row.get("metaData"):
                meta = row["metaData"]
    for path in sorted(_glob.glob(os.path.join(log, "*.json"))):
        version = int(os.path.basename(path).split(".")[0])
        if version <= start_version:
            continue
        with open(path) as f:
            for line in f:
                if not line.strip():
                    continue
                action = json.loads(line)
                if "add" in action:
                    adds[action["add"]["path"]] = action["add"]
                elif "remove" in action:
                    adds.pop(action["remove"]["path"], None)
                elif "metaData" in action:
                    meta = action["metaData"]
    return list(adds.values()), meta


def _partition_caster(meta: dict):
    """Partition values are stored as strings in the log; cast them back
    per the table schema."""
    types: dict[str, str] = {}
    try:
        schema = json.loads(meta.get("schemaString", "{}"))
        for f in schema.get("fields", []):
            types[f["name"]] = f.get("type", "string")
    except (ValueError, TypeError):
        pass

    def cast(col: str, v: str | None):
        if v is None:
            return None
        t = types.get(col, "string")
        if t in ("long", "integer", "short", "byte"):
            return int(v)
        if t in ("double", "float"):
            return float(v)
        if t == "boolean":
            return v == "true"
        return v

    return cast


class DeltaDatasource(Datasource):
    supports_projection = True
    supports_predicates = True

    def __init__(self, table: str, columns=None, filters=None):
        self.table = table
        self.columns = list(columns) if columns else None
        self.filters = list(filters) if filters else None
        self.adds, self.meta = _replay_delta_log(table)

    def get_read_tasks(self, parallelism: int) -> list[ReadTask]:
        if not self.adds:
            return []
        cast = _partition_caster(self.meta)
        tasks = []
        for grp in round_robin(self.adds, parallelism):

            def fn(grp=grp, table=self.table, columns=self.columns,
                   filters=self.filters, cast=cast):
                import pyarrow.parquet as pq

                blocks = []
                for a in grp:
                    part = a.get("partitionValues") or {}
                    cols = ([c for c in columns if c not in part]
                            if columns else None)
                    filt = ([f for f in filters if f[0] not in part]
                            if filters else None) or None
                    table_path = os.path.join(table, a["path"])
                    t = pq.read_table(table_path, columns=cols, filters=filt)
                    blk = normalize_block(t)
                    n = BlockAccessor(blk).num_rows()
                    for col, v in part.items():
                        if columns and col not in columns:
                            continue
                        blk[col] = np.asarray([cast(col, v)] * n)
                    # partition-column predicates: evaluate on constants
                    if filters:
                        for col, op, val in filters:
                            if col not in part:
                                continue
                            cv = cast(col, part[col])
                            # lazy dispatch: a dict literal would evaluate
                            # every branch (e.g. `cv in val` with scalar val)
                            keep = {"=": lambda: cv == val,
                                    "==": lambda: cv == val,
                                    "!=": lambda: cv != val,
                                    ">": lambda: cv > val,
                                    ">=": lambda: cv >= val,
                                    "<": lambda: cv < val,
                                    "<=": lambda: cv <= val,
                                    "in": lambda: cv in val,
                                    "not in": lambda: cv not in val}[op]()
                            if not keep:
                                blk = {k: v[:0] for k, v in blk.items()}
                                break
                    blocks.append(blk)
                return blocks

            tasks.append(ReadTask(fn, input_files=[a["path"] for a in grp]))
        return tasks


def write_delta(ds, table: str, *, mode: str = "append",
                partition_cols: list[str] | None = None) -> list[str]:
    """Commit the dataset to a Delta table (create or append). Returns the
    data file paths written. `mode="overwrite"` logically removes the
    previous active files in the same commit."""
    from ray_tpu.data.datasource import (write_parquet_block,
                                         write_parquet_partitioned)

    log = _delta_log_dir(table)
    os.makedirs(log, exist_ok=True)
    existing = sorted(_glob.glob(os.path.join(log, "*.json")))
    last = (int(os.path.basename(existing[-1]).split(".")[0])
            if existing else -1)
    # after log cleanup only the checkpoint may remain: it also pins the
    # version floor, or a new commit would silently shadow history
    ckpt_file = os.path.join(log, "_last_checkpoint")
    if os.path.exists(ckpt_file):
        with open(ckpt_file) as f:
            last = max(last, int(json.load(f)["version"]))
    version = last + 1
    prior_adds: list[dict] = []
    if mode == "overwrite" and version > 0:
        prior_adds, _ = _replay_delta_log(table)
    elif mode not in ("append", "overwrite"):
        raise ValueError(f"mode must be append|overwrite, got {mode!r}")

    files: list[str] = []
    parts: dict[str, dict] = {}
    first_block: Block | None = None
    for i, b in enumerate(ds.iter_blocks()):
        acc = BlockAccessor(b)
        if not acc.num_rows():
            continue
        if first_block is None:
            first_block = b
        if partition_cols:
            written = write_parquet_partitioned(b, table, i, partition_cols)
            for w in written:
                # commit-unique rename: partitioned filenames are only
                # block-indexed, so a later commit writing the same
                # partition would overwrite this commit's physical file
                unique = os.path.join(
                    os.path.dirname(w),
                    f"part-{version:05d}-{uuid.uuid4().hex[:12]}-"
                    f"{os.path.basename(w)[len('part-'):]}")
                os.replace(w, unique)
                rel = os.path.relpath(unique, table)
                pv = {}
                for seg in rel.split(os.sep)[:-1]:
                    if "=" in seg:
                        k, _, v = seg.partition("=")
                        pv[k] = v
                parts[rel] = pv
                files.append(unique)
        else:
            w = write_parquet_block(b, table, i)
            # unique names: delta file sets are immutable across commits
            unique = os.path.join(
                table, f"part-{version:05d}-{uuid.uuid4().hex[:12]}-{i:05d}"
                       ".parquet")
            os.replace(w, unique)
            parts[os.path.relpath(unique, table)] = {}
            files.append(unique)

    now_ms = int(time.time() * 1000)
    actions: list[dict] = []
    if version == 0:
        fields = []
        if first_block is not None:
            for k, v in first_block.items():
                arr = np.asarray(v[:1]) if len(v) else np.asarray(v)
                kind = (
                    "long" if arr.dtype.kind in "iu" else
                    "double" if arr.dtype.kind == "f" else
                    "boolean" if arr.dtype.kind == "b" else "string")
                fields.append({"name": str(k), "type": kind,
                               "nullable": True, "metadata": {}})
        actions.append({"protocol": {"minReaderVersion": 1,
                                     "minWriterVersion": 2}})
        actions.append({"metaData": {
            "id": str(uuid.uuid4()),
            "format": {"provider": "parquet", "options": {}},
            "schemaString": json.dumps({"type": "struct", "fields": fields}),
            "partitionColumns": partition_cols or [],
            "configuration": {}, "createdTime": now_ms}})
    for a in prior_adds:
        actions.append({"remove": {"path": a["path"], "dataChange": True,
                                   "deletionTimestamp": now_ms}})
    for rel, pv in parts.items():
        actions.append({"add": {
            "path": rel, "partitionValues": pv,
            "size": os.path.getsize(os.path.join(table, rel)),
            "modificationTime": now_ms, "dataChange": True}})
    actions.append({"commitInfo": {"timestamp": now_ms,
                                   "operation": "WRITE",
                                   "engineInfo": "ray_tpu"}})
    commit = os.path.join(log, f"{version:020d}.json")
    with open(commit + ".tmp", "w") as f:
        for a in actions:
            f.write(json.dumps(a) + "\n")
    os.replace(commit + ".tmp", commit)
    return files


# ----------------------------------------------------------------- iceberg


def _iceberg_current_metadata(table: str) -> dict:
    mdir = os.path.join(table, "metadata")
    hint = os.path.join(mdir, "version-hint.text")
    path = None
    if os.path.exists(hint):
        with open(hint) as f:
            v = f.read().strip()
        for cand in (f"v{v}.metadata.json", f"{v}.metadata.json"):
            if os.path.exists(os.path.join(mdir, cand)):
                path = os.path.join(mdir, cand)
                break
    if path is None:
        cands = sorted(_glob.glob(os.path.join(mdir, "*.metadata.json")))
        if not cands:
            raise FileNotFoundError(
                f"{table}: no metadata/*.metadata.json — not an Iceberg table")
        path = cands[-1]
    with open(path) as f:
        return json.load(f)


def _localize(path: str, table: str) -> str:
    """Iceberg stores absolute URIs; map file:// (and bare absolute paths
    recorded under a different root) onto this table directory."""
    if path.startswith("file://"):
        path = path[len("file://"):]
    if os.path.exists(path):
        return path
    # re-root: find the table's basename inside the recorded path
    base = os.path.basename(os.path.normpath(table))
    idx = path.find(f"/{base}/")
    if idx >= 0:
        cand = os.path.join(table, path[idx + len(base) + 2:])
        if os.path.exists(cand):
            return cand
    return path


def iceberg_data_files(table: str, *, snapshot_id: int | None = None) -> list[dict]:
    """List live data files for a snapshot: [{path, format, record_count}]."""
    from ray_tpu.data.avro import read_avro_file

    meta = _iceberg_current_metadata(table)
    snap_id = snapshot_id if snapshot_id is not None else meta.get(
        "current-snapshot-id")
    snaps = {s["snapshot-id"]: s for s in meta.get("snapshots", [])}
    if snap_id is None or snap_id == -1 or snap_id not in snaps:
        return []
    snap = snaps[snap_id]
    manifests: list[str] = []
    if "manifest-list" in snap:
        records, _ = read_avro_file(_localize(snap["manifest-list"], table))
        manifests = [r["manifest_path"] for r in records]
    else:  # v1 tables may inline the manifest paths
        manifests = list(snap.get("manifests", []))
    out: list[dict] = []
    for mpath in manifests:
        entries, _ = read_avro_file(_localize(mpath, table))
        for e in entries:
            if e.get("status") == 2:  # DELETED
                continue
            df = e["data_file"]
            out.append({"path": _localize(df["file_path"], table),
                        "format": df.get("file_format", "PARQUET"),
                        "record_count": df.get("record_count")})
    return out


class IcebergDatasource(Datasource):
    supports_projection = True
    supports_predicates = True

    def __init__(self, table: str, columns=None, filters=None,
                 snapshot_id: int | None = None):
        self.files = iceberg_data_files(table, snapshot_id=snapshot_id)
        self.columns = list(columns) if columns else None
        self.filters = list(filters) if filters else None

    def get_read_tasks(self, parallelism: int) -> list[ReadTask]:
        if not self.files:
            return []
        tasks = []
        for grp in round_robin(self.files, parallelism):

            def fn(grp=grp, columns=self.columns, filters=self.filters):
                import pyarrow.parquet as pq

                blocks = []
                for f in grp:
                    if f["format"].upper() != "PARQUET":
                        raise ValueError(
                            f"unsupported iceberg data file format "
                            f"{f['format']!r} (parquet only)")
                    blocks.append(normalize_block(pq.read_table(
                        f["path"], columns=columns, filters=filters)))
                return blocks

            tasks.append(ReadTask(
                fn, num_rows=sum(f.get("record_count") or 0 for f in grp) or None,
                input_files=[f["path"] for f in grp]))
        return tasks
