"""Logical plan: lazy operator DAG + rewrite rules.

(reference: python/ray/data/_internal/logical/operators/* for the op
vocabulary and _internal/logical/rules/{operator_fusion,limit_pushdown}.py
for the rules mirrored here.)
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable

from ray_tpu.data.datasource import Datasource


class LogicalOp:
    input: "LogicalOp | None" = None

    def chain(self) -> list["LogicalOp"]:
        ops: list[LogicalOp] = []
        cur: LogicalOp | None = self
        while cur is not None:
            ops.append(cur)
            cur = cur.input
        return list(reversed(ops))


@dataclass
class Read(LogicalOp):
    datasource: Datasource
    parallelism: int = -1
    input: LogicalOp | None = None
    limit: int | None = None  # pushed-down row cap


@dataclass
class InputBlocks(LogicalOp):
    """Pre-materialized blocks (from_blocks / from_pandas / union output)."""

    refs: list = field(default_factory=list)
    input: LogicalOp | None = None


@dataclass
class MapBatches(LogicalOp):
    fn: Callable
    input: LogicalOp | None = None
    batch_size: int | None = None
    fn_kwargs: dict = field(default_factory=dict)
    compute: str = "tasks"  # "tasks" | "actors"
    num_cpus: float = 1.0
    num_tpus: float = 0.0
    concurrency: int | None = None
    batch_format: str = "numpy"


@dataclass
class MapRows(LogicalOp):
    fn: Callable
    input: LogicalOp | None = None
    kind: str = "map"  # map | filter | flat_map


@dataclass
class Project(LogicalOp):
    """Column selection — pushes into columnar reads as IO pruning.
    (reference: _internal/logical/rules/projection_pushdown.py)"""

    cols: list = field(default_factory=list)
    input: LogicalOp | None = None


@dataclass
class FilterExpr(LogicalOp):
    """Expression filter (see data/expressions.py) — pushes into parquet
    reads as row-group pruning when directly above the Read."""

    expr: str = ""
    input: LogicalOp | None = None


@dataclass
class Limit(LogicalOp):
    n: int
    input: LogicalOp | None = None


@dataclass
class Repartition(LogicalOp):
    num_blocks: int
    input: LogicalOp | None = None


@dataclass
class RandomShuffle(LogicalOp):
    seed: int | None = None
    input: LogicalOp | None = None


@dataclass
class Sort(LogicalOp):
    key: str
    descending: bool = False
    input: LogicalOp | None = None


@dataclass
class Union(LogicalOp):
    others: list = field(default_factory=list)  # list[LogicalOp]
    input: LogicalOp | None = None


@dataclass
class GroupByAgg(LogicalOp):
    """Hash-shuffle by key columns, then aggregate each partition.
    (reference: data/grouped_data.py:23 + hash_shuffle.py)"""

    keys: list = field(default_factory=list)   # list[str]
    aggs: list = field(default_factory=list)   # list[AggregateFn]
    input: LogicalOp | None = None


@dataclass
class MapGroups(LogicalOp):
    """Hash-shuffle by key columns, then apply fn per group."""

    keys: list = field(default_factory=list)
    fn: Callable = None
    input: LogicalOp | None = None
    batch_format: str = "numpy"


@dataclass
class Join(LogicalOp):
    """Distributed hash join against another dataset's plan.
    (reference: data/_internal/execution/operators/join.py:54)"""

    right_last: LogicalOp = None               # other dataset's plan tail
    on: list = field(default_factory=list)     # left key columns
    right_on: list = field(default_factory=list)
    how: str = "inner"                         # inner | left | right | outer
    suffixes: tuple = ("", "_r")
    num_partitions: int | None = None
    input: LogicalOp | None = None


# ----------------------------------------------------------------- optimizer


def apply_limit_pushdown(ops: list[LogicalOp]) -> list[LogicalOp]:
    """Move a Limit below strictly row-preserving ops (MapRows kind="map"
    only — map_batches/filter/flat_map may change row counts) and into Read
    as a row cap. (reference: _internal/logical/rules/limit_pushdown.py)"""
    out = list(ops)
    changed = True
    while changed:
        changed = False
        for i in range(1, len(out)):
            if isinstance(out[i], Limit):
                prev = out[i - 1]
                if isinstance(prev, MapRows) and prev.kind == "map":
                    out[i - 1], out[i] = out[i], out[i - 1]
                    changed = True
                elif isinstance(prev, Read) and prev.limit is None:
                    prev.limit = out[i].n
                    # keep the Limit too: reads are per-task capped, the
                    # executor still needs the global cut
    return out


def apply_projection_pushdown(ops: list[LogicalOp]) -> list[LogicalOp]:
    """Project directly above a projection-capable Read becomes IO column
    pruning; the Project op disappears. Consecutive Projects collapse to
    the outermost (it sees only what earlier ones kept).
    (reference: _internal/logical/rules/projection_pushdown.py)"""
    import copy

    out = list(ops)
    i = 1
    while i < len(out):
        op = out[i]
        prev = out[i - 1]
        if (isinstance(op, Project) and isinstance(prev, Read)
                and getattr(prev.datasource, "supports_projection", False)
                and prev.datasource.columns is None):
            # plans share datasource objects across sibling datasets:
            # mutate a copy, not the original
            ds = copy.copy(prev.datasource)
            ds.columns = list(op.cols)
            prev.datasource = ds
            out.pop(i)
            continue
        if (isinstance(op, Project) and isinstance(prev, Project)
                and set(op.cols) <= set(prev.cols)):
            # collapse only when the outer projection is a subset — an
            # outer col the inner already dropped must still KeyError at
            # runtime, not silently resurrect from the source
            out.pop(i - 1)
            continue
        i += 1
    return out


def apply_predicate_pushdown(ops: list[LogicalOp]) -> list[LogicalOp]:
    """FilterExpr directly above a predicate-capable Read prunes row
    groups at the IO layer instead of running as a stage."""
    import copy

    from ray_tpu.data.expressions import parse_filter

    out = list(ops)
    i = 1
    while i < len(out):
        op = out[i]
        prev = out[i - 1]
        if (isinstance(op, FilterExpr) and isinstance(prev, Read)
                and getattr(prev.datasource, "supports_predicates", False)):
            conj = parse_filter(op.expr)
            cols = prev.datasource.columns
            if cols is not None and not all(c in cols for c in
                                            (t[0] for t in conj)):
                # a projection already dropped a filter column: keep the
                # stage so the user still sees the KeyError they wrote
                i += 1
                continue
            ds = copy.copy(prev.datasource)
            ds.filters = (list(ds.filters) + conj) if ds.filters else conj
            prev.datasource = ds
            out.pop(i)
            continue
        i += 1
    return out


def optimize(ops: list[LogicalOp]) -> list[LogicalOp]:
    # operate on copies: plans are shared between sibling datasets derived
    # from the same source, and rules mutate ops (e.g. Read.limit)
    import copy

    out = [copy.copy(op) for op in ops]
    # pushdowns can unlock each other (a pushed filter makes a Project
    # adjacent to the Read and vice versa): iterate to fixpoint
    while True:
        n = len(out)
        out = apply_projection_pushdown(out)
        out = apply_predicate_pushdown(out)
        if len(out) == n:
            break
    return apply_limit_pushdown(out)
