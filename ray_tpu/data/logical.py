"""Logical plan: lazy operator DAG + rewrite rules.

(reference: python/ray/data/_internal/logical/operators/* for the op
vocabulary and _internal/logical/rules/{operator_fusion,limit_pushdown}.py
for the rules mirrored here.)
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable

from ray_tpu.data.datasource import Datasource


class LogicalOp:
    input: "LogicalOp | None" = None

    def chain(self) -> list["LogicalOp"]:
        ops: list[LogicalOp] = []
        cur: LogicalOp | None = self
        while cur is not None:
            ops.append(cur)
            cur = cur.input
        return list(reversed(ops))


@dataclass
class Read(LogicalOp):
    datasource: Datasource
    parallelism: int = -1
    input: LogicalOp | None = None
    limit: int | None = None  # pushed-down row cap


@dataclass
class InputBlocks(LogicalOp):
    """Pre-materialized blocks (from_blocks / from_pandas / union output)."""

    refs: list = field(default_factory=list)
    input: LogicalOp | None = None


@dataclass
class MapBatches(LogicalOp):
    fn: Callable
    input: LogicalOp | None = None
    batch_size: int | None = None
    fn_kwargs: dict = field(default_factory=dict)
    compute: str = "tasks"  # "tasks" | "actors"
    num_cpus: float = 1.0
    num_tpus: float = 0.0
    concurrency: int | None = None
    batch_format: str = "numpy"


@dataclass
class MapRows(LogicalOp):
    fn: Callable
    input: LogicalOp | None = None
    kind: str = "map"  # map | filter | flat_map


@dataclass
class Limit(LogicalOp):
    n: int
    input: LogicalOp | None = None


@dataclass
class Repartition(LogicalOp):
    num_blocks: int
    input: LogicalOp | None = None


@dataclass
class RandomShuffle(LogicalOp):
    seed: int | None = None
    input: LogicalOp | None = None


@dataclass
class Sort(LogicalOp):
    key: str
    descending: bool = False
    input: LogicalOp | None = None


@dataclass
class Union(LogicalOp):
    others: list = field(default_factory=list)  # list[LogicalOp]
    input: LogicalOp | None = None


@dataclass
class GroupByAgg(LogicalOp):
    """Hash-shuffle by key columns, then aggregate each partition.
    (reference: data/grouped_data.py:23 + hash_shuffle.py)"""

    keys: list = field(default_factory=list)   # list[str]
    aggs: list = field(default_factory=list)   # list[AggregateFn]
    input: LogicalOp | None = None


@dataclass
class MapGroups(LogicalOp):
    """Hash-shuffle by key columns, then apply fn per group."""

    keys: list = field(default_factory=list)
    fn: Callable = None
    input: LogicalOp | None = None
    batch_format: str = "numpy"


@dataclass
class Join(LogicalOp):
    """Distributed hash join against another dataset's plan.
    (reference: data/_internal/execution/operators/join.py:54)"""

    right_last: LogicalOp = None               # other dataset's plan tail
    on: list = field(default_factory=list)     # left key columns
    right_on: list = field(default_factory=list)
    how: str = "inner"                         # inner | left | right | outer
    suffixes: tuple = ("", "_r")
    num_partitions: int | None = None
    input: LogicalOp | None = None


# ----------------------------------------------------------------- optimizer


def apply_limit_pushdown(ops: list[LogicalOp]) -> list[LogicalOp]:
    """Move a Limit below strictly row-preserving ops (MapRows kind="map"
    only — map_batches/filter/flat_map may change row counts) and into Read
    as a row cap. (reference: _internal/logical/rules/limit_pushdown.py)"""
    out = list(ops)
    changed = True
    while changed:
        changed = False
        for i in range(1, len(out)):
            if isinstance(out[i], Limit):
                prev = out[i - 1]
                if isinstance(prev, MapRows) and prev.kind == "map":
                    out[i - 1], out[i] = out[i], out[i - 1]
                    changed = True
                elif isinstance(prev, Read) and prev.limit is None:
                    prev.limit = out[i].n
                    # keep the Limit too: reads are per-task capped, the
                    # executor still needs the global cut
    return out


def optimize(ops: list[LogicalOp]) -> list[LogicalOp]:
    # operate on copies: plans are shared between sibling datasets derived
    # from the same source, and rules mutate ops (e.g. Read.limit)
    import copy

    return apply_limit_pushdown([copy.copy(op) for op in ops])
