"""Public exception types (reference: python/ray/exceptions.py)."""

from __future__ import annotations


class RayTpuError(Exception):
    pass


class RayTaskError(RayTpuError):
    """Wraps an exception raised inside a remote task/actor method.

    Re-raised at `get()` on the caller, with the remote traceback appended
    (reference: python/ray/exceptions.py RayTaskError)."""

    def __init__(self, function_name: str, traceback_str: str, cause: Exception):
        self.function_name = function_name
        self.traceback_str = traceback_str
        self.cause = cause
        super().__init__(f"task {function_name} failed:\n{traceback_str}")

    def __reduce__(self):
        return (RayTaskError, (self.function_name, self.traceback_str, self.cause))


class WorkerCrashedError(RayTpuError):
    pass


class ActorDiedError(RayTpuError):
    pass


class TaskCancelledError(RayTpuError):
    """The task was cancelled via ray_tpu.cancel() (reference:
    ray.exceptions.TaskCancelledError)."""


class GetTimeoutError(RayTpuError, TimeoutError):
    pass


class ObjectLostError(RayTpuError):
    pass


class OwnerDiedError(ObjectLostError):
    """The process that owned an object died before publishing/recovering it
    (reference: ray.exceptions.OwnerDiedError)."""


class PlacementGroupUnschedulableError(RayTpuError):
    pass


class RayChannelError(RayTpuError):
    """A compiled-DAG channel operation failed: peer loop/actor died, the
    channel was closed mid-execution, or the DAG was torn down (reference:
    ray.exceptions.RayChannelError)."""


class RequestCancelledError(RayTpuError):
    """The serve request was cancelled before completing: the client
    disconnected mid-stream, `DeploymentResponse.cancel()` was called, or a
    timed-out caller sent a best-effort cancel (reference:
    ray.serve.exceptions.RequestCancelledError)."""


class DeadlineExceededError(RayTpuError, TimeoutError):
    """The request's deadline expired before this hop could finish it.

    Raised per-hop: the proxy refuses dispatch, the replica refuses
    admission after queue-wait, and the engine aborts expired rows between
    decode steps — work the client will never see is never started."""


class CollectiveError(RayTpuError):
    """A host-plane collective failed because a peer rank is dead (or the
    group was already aborted by another rank's detection).

    Raised by util/collective ops well before the op's data timeout: every
    blocking wait polls peer-actor liveness alongside its data probe, so a
    SIGKILLed rank surfaces on all survivors within the configured
    detection interval instead of as an opaque TimeoutError. Carries the
    group, op seq, and the dead/suspect ranks so the train controller can
    log the failure precisely before the elastic restart."""

    def __init__(self, msg: str, *, group: str = "", seq: int | None = None,
                 dead_ranks: tuple = (), kind: str = "peer_death"):
        self.group = group
        self.seq = seq
        self.dead_ranks = tuple(dead_ranks)
        self.kind = kind
        super().__init__(msg)

    def __reduce__(self):
        return (_rebuild_collective_error,
                (self.args[0], self.group, self.seq, self.dead_ranks,
                 self.kind))


def _rebuild_collective_error(msg, group, seq, dead_ranks, kind):
    return CollectiveError(msg, group=group, seq=seq, dead_ranks=dead_ranks,
                           kind=kind)


class DataBlockError(RayTpuError):
    """A Data-plane block permanently failed after fault handling ran out.

    Raised by the streaming executor with the block id and stage name
    attached: either a SYSTEM failure (actor death / worker crash / lost
    object) exhausted its resubmission budget (``kind="system"``), or a
    UDF raised and the ``on_block_error`` policy surfaced it — directly
    under ``"raise"``, or once skipped blocks exceeded
    ``max_errored_blocks`` under ``"skip"`` (``kind="application"``)."""

    def __init__(self, msg: str, *, block_id=None, stage: str = "",
                 kind: str = "application"):
        self.block_id = block_id
        self.stage = stage
        self.kind = kind
        super().__init__(msg)

    def __reduce__(self):
        return (_rebuild_data_block_error,
                (self.args[0], self.block_id, self.stage, self.kind))


def _rebuild_data_block_error(msg, block_id, stage, kind):
    return DataBlockError(msg, block_id=block_id, stage=stage, kind=kind)


class RequestShedError(RayTpuError):
    """Admission control refused the request instead of queueing it.

    Raised when the replica's admission queue is at `max_queued_requests`
    or the router's client-side in-flight window is saturated; the HTTP
    proxy maps it to `503` + `Retry-After` (reference:
    ray.serve BackPressureError semantics)."""

    def __init__(self, msg: str = "request shed by admission control",
                 retry_after_s: float = 1.0):
        self.retry_after_s = retry_after_s
        super().__init__(msg)

    def __reduce__(self):
        return (RequestShedError, (self.args[0], self.retry_after_s))
