"""Public exception types (reference: python/ray/exceptions.py)."""

from __future__ import annotations


class RayTpuError(Exception):
    pass


class RayTaskError(RayTpuError):
    """Wraps an exception raised inside a remote task/actor method.

    Re-raised at `get()` on the caller, with the remote traceback appended
    (reference: python/ray/exceptions.py RayTaskError)."""

    def __init__(self, function_name: str, traceback_str: str, cause: Exception):
        self.function_name = function_name
        self.traceback_str = traceback_str
        self.cause = cause
        super().__init__(f"task {function_name} failed:\n{traceback_str}")

    def __reduce__(self):
        return (RayTaskError, (self.function_name, self.traceback_str, self.cause))


class WorkerCrashedError(RayTpuError):
    pass


class ActorDiedError(RayTpuError):
    pass


class TaskCancelledError(RayTpuError):
    """The task was cancelled via ray_tpu.cancel() (reference:
    ray.exceptions.TaskCancelledError)."""


class GetTimeoutError(RayTpuError, TimeoutError):
    pass


class ObjectLostError(RayTpuError):
    pass


class OwnerDiedError(ObjectLostError):
    """The process that owned an object died before publishing/recovering it
    (reference: ray.exceptions.OwnerDiedError)."""


class PlacementGroupUnschedulableError(RayTpuError):
    pass


class RayChannelError(RayTpuError):
    """A compiled-DAG channel operation failed: peer loop/actor died, the
    channel was closed mid-execution, or the DAG was torn down (reference:
    ray.exceptions.RayChannelError)."""
