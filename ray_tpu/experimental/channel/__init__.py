"""Zero-copy-ish actor↔actor channels.

(reference: python/ray/experimental/channel/ — shm `Channel` over mutable
plasma objects (shared_memory_channel.py:151), buffered/composite variants,
and the pluggable AcceleratorContext (accelerator_context.py:222). Here a
channel is a bounded SPSC pipe: payloads ride the shm object store, only the
refs pass through the rendezvous actor, and reads free the slot — the same
backpressure contract without the mutable-buffer C++ plane.)
"""

from ray_tpu.experimental.channel.channel import Channel, ChannelClosed, create_channel
from ray_tpu.experimental.channel.mutable_shm import (MutableShmChannel,
                                                      create_mutable_channel)

__all__ = ["Channel", "ChannelClosed", "create_channel",
           "MutableShmChannel", "create_mutable_channel"]
