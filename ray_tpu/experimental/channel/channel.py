"""Bounded SPSC channel: object-store payloads, actor-brokered refs.

(reference: experimental/channel/shared_memory_channel.py:151 — write blocks
when the buffer is full until the reader consumes (backpressure); close
raises in blocked peers. The C++ mutable-object plane
(src/ray/core_worker/experimental_mutable_object_manager.h:44) is collapsed
into ref-passing through a tiny broker actor; numpy payloads still move
zero-copy through shm via pickle-5 buffers.)
"""

from __future__ import annotations

import time

import ray_tpu


class ChannelClosed(Exception):
    pass


@ray_tpu.remote
class _Broker:
    def __init__(self, maxsize: int):
        self.maxsize = maxsize
        self.items: list = []
        self.closed = False

    def offer(self, ref_hex: str) -> bool:
        if self.closed:
            return False
        if len(self.items) >= self.maxsize:
            return None  # full: caller retries (backpressure)
        self.items.append(ref_hex)
        return True

    def take(self):
        if self.items:
            return self.items.pop(0)
        return False if self.closed else None

    def close(self):
        self.closed = True

    def size(self) -> int:
        return len(self.items)


class Channel:
    def __init__(self, broker, maxsize: int):
        self._broker = broker
        self.maxsize = maxsize

    def write(self, value, timeout: float | None = 60.0) -> None:
        ref = ray_tpu.put(value)
        deadline = None if timeout is None else time.monotonic() + timeout
        poll_s = 0.0005
        while True:
            ok = ray_tpu.get(self._broker.offer.remote(ref.hex()))
            if ok is True:
                return
            if ok is False:
                raise ChannelClosed("channel closed")
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError("channel write timed out (reader too slow)")
            time.sleep(poll_s)
            poll_s = min(poll_s * 2, 0.02)

    def read(self, timeout: float | None = 60.0):
        from ray_tpu._private.worker import ObjectRef

        deadline = None if timeout is None else time.monotonic() + timeout
        poll_s = 0.0005
        while True:
            got = ray_tpu.get(self._broker.take.remote())
            if isinstance(got, str):
                ref = ObjectRef(got)
                value = ray_tpu.get(ref)
                ray_tpu.free([ref])  # slot consumed: single-consumer semantics
                return value
            if got is False:
                raise ChannelClosed("channel closed and drained")
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError("channel read timed out")
            time.sleep(poll_s)
            poll_s = min(poll_s * 2, 0.02)

    def close(self) -> None:
        try:
            ray_tpu.get(self._broker.close.remote())
        except Exception:
            pass

    def __reduce__(self):
        return (Channel, (self._broker, self.maxsize))


def create_channel(maxsize: int = 2) -> Channel:
    broker = _Broker.options(num_cpus=0.1).remote(maxsize)
    return Channel(broker, maxsize)
