"""Bounded SPSC channel: object-store payloads, actor-brokered refs.

(reference: experimental/channel/shared_memory_channel.py:151 — write blocks
when the buffer is full until the reader consumes (backpressure); close
raises in blocked peers. The C++ mutable-object plane
(src/ray/core_worker/experimental_mutable_object_manager.h:44) is collapsed
into ref-passing through a tiny broker actor; numpy payloads still move
zero-copy through shm via pickle-5 buffers.)
"""

from __future__ import annotations


import ray_tpu


class ChannelClosed(Exception):
    pass


@ray_tpu.remote
class _Broker:
    def __init__(self, maxsize: int):
        self.maxsize = maxsize
        self.items: list = []
        self.closed = False

    def offer(self, ref_hex: str) -> bool:
        if self.closed:
            return False
        if len(self.items) >= self.maxsize:
            return None  # full: caller retries (backpressure)
        self.items.append(ref_hex)
        return True

    def take(self):
        if self.items:
            return self.items.pop(0)
        return False if self.closed else None

    def close(self, drain: bool = False) -> list:
        self.closed = True
        if not drain:
            return []  # readers consume (and free) what's queued, then see closed
        leftover, self.items = self.items, []
        return leftover  # refs the closer must free (no reader will)

    def size(self) -> int:
        return len(self.items)


class Channel:
    def __init__(self, broker, maxsize: int):
        self._broker = broker
        self.maxsize = maxsize

    def write(self, value, timeout: float | None = 60.0) -> None:
        from ray_tpu._private.poll import poll_until

        # pinned: the payload travels broker→reader as a raw id, invisible to
        # the reference counter; the reader (or close) frees it explicitly
        from ray_tpu._private.api import _get_worker

        ref = _get_worker().put(value, pin=True)

        def offer():
            ok = ray_tpu.get(self._broker.offer.remote(ref.hex()))
            if ok is False:
                raise ChannelClosed("channel closed")
            return True if ok else None

        try:
            poll_until(offer, timeout, "channel write timed out (reader too slow)")
        except (ChannelClosed, TimeoutError):
            ray_tpu.free([ref])  # never enqueued: don't leak the payload
            raise

    def read(self, timeout: float | None = 60.0):
        from ray_tpu._private.poll import poll_until
        from ray_tpu._private.worker import ObjectRef

        def take():
            got = ray_tpu.get(self._broker.take.remote())
            if got is False:
                raise ChannelClosed("channel closed and drained")
            return got  # str ref hex, or None → keep polling

        hex_id = poll_until(take, timeout, "channel read timed out")
        ref = ObjectRef(hex_id)
        value = ray_tpu.get(ref)
        ray_tpu.free([ref])  # slot consumed: single-consumer semantics
        return value

    def close(self, drain: bool = False) -> None:
        """Graceful by default: queued items remain readable, then readers see
        ChannelClosed. `drain=True` abandons unread items (frees their
        payloads) — use when no reader will ever come."""
        from ray_tpu._private.worker import ObjectRef

        try:
            leftover = ray_tpu.get(self._broker.close.remote(drain))
            if leftover:
                ray_tpu.free([ObjectRef(h) for h in leftover])
        except Exception:
            pass

    def __reduce__(self):
        return (Channel, (self._broker, self.maxsize))


def create_channel(maxsize: int = 2, *, transport: str = "broker",
                   buffer_bytes: int = 1 << 20):
    """transport="broker" (default): cross-host-capable ref-passing channel.
    transport="shm": same-host mutable shared-memory channel — microsecond
    hops, maxsize fixed at 1 (the mutable-buffer semantics of the
    reference's shared_memory_channel.py:151)."""
    if transport == "shm":
        from ray_tpu.experimental.channel.mutable_shm import (
            create_mutable_channel)

        return create_mutable_channel(buffer_bytes)
    broker = _Broker.options(num_cpus=0.1).remote(maxsize)
    return Channel(broker, maxsize)
