"""Mutable shared-memory SPSC channel: zero control-plane hops per message.

One writer and one reader on the SAME host map one /dev/shm buffer; a
seqlock-style header synchronizes them — the writer waits until the reader
consumed the previous payload (write_seq == read_seq), writes bytes, bumps
write_seq; the reader waits for write_seq > read_seq, reads, bumps
read_seq. No GCS, no broker actor, no object store on the hot path: a hop
is two shared-memory writes and the payload copy, the microsecond-scale
path the reference gets from its mutable-object plane.

Ordering note: header fields are 8-byte-aligned int64s written via
struct.pack_into on an mmap; x86-64's total-store-order makes the
payload-then-len-then-seq write sequence safe without explicit fences.

(reference: python/ray/experimental/channel/shared_memory_channel.py:151 +
src/ray/core_worker/experimental_mutable_object_manager.h:44 — mutable
plasma objects with writer/reader acquire-release semantics — VERDICT
round-2 missing item 10.)
"""

from __future__ import annotations

import mmap
import os
import struct
import time
import uuid

from ray_tpu._private.constants import SHM_CHANNEL_PREFIX, SHM_DIR
from ray_tpu.experimental.channel.channel import ChannelClosed

_HDR = struct.Struct("<qqqq")  # write_seq, read_seq, payload_len, closed
_HDR_SIZE = 64  # padded: keep the data region cacheline-separated
_DIR = SHM_DIR


class MutableShmChannel:
    """Single-producer single-consumer; both ends must be on one host."""

    def __init__(self, path: str, capacity: int, _create: bool = False):
        self.path = path
        self.capacity = capacity
        if _create:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_RDWR, 0o600)
            try:
                os.ftruncate(fd, _HDR_SIZE + capacity)
                self._mm = mmap.mmap(fd, _HDR_SIZE + capacity)
            except BaseException:
                # the O_EXCL create already burned the NAME: rolling back
                # only the fd would leave a zero-reader tmpfs file no
                # teardown sweep owns (creation failed, so no handle with
                # _creator=True will ever unlink it)
                os.close(fd)
                try:
                    os.unlink(path)
                except OSError:
                    pass
                raise
            os.close(fd)
        else:
            fd = os.open(path, os.O_RDWR)
            try:
                self._mm = mmap.mmap(fd, _HDR_SIZE + capacity)
            finally:
                os.close(fd)

    # ------------------------------------------------------------- header

    _FIELD = struct.Struct("<q")
    _OFF = {"write_seq": 0, "read_seq": 8, "plen": 16, "closed": 24}

    def _hdr(self):
        return _HDR.unpack_from(self._mm, 0)

    def _set(self, **fields):
        # one aligned 8-byte store per field — a read-modify-write of the
        # whole header could resurrect a flag the peer just set (e.g. its
        # close() racing our plen update)
        for name, val in fields.items():
            self._FIELD.pack_into(self._mm, self._OFF[name], val)

    def _wait(self, check, timeout: float | None, what: str):
        # `check` takes one header tuple — ONE _hdr() unpack per iteration
        # serves both the condition and the progress snapshot on this
        # per-message hot path. The deadline is checked BEFORE any sleep
        # so a timeout=0 poll is a true non-blocking probe (one condition
        # check, immediate raise). The spin phase is SHORT: with several
        # channel endpoints parked on one small host, long hot spins
        # starve the one thread that has real work. After it, sleeps
        # escalate while the channel is quiet; any header progress (e.g.
        # the peer published plen but not yet the seq bump) drops the
        # sleep back to the lowest tier so the follow-on update is caught
        # at low latency.
        deadline = None if timeout is None else time.monotonic() + timeout
        spins = 0
        slept_since = None
        snap = None
        while True:
            hdr = self._hdr()
            if check(hdr):
                return
            if hdr != snap:
                snap = hdr
                slept_since = None  # progress: reset the sleep escalation
            if deadline is not None and time.monotonic() >= deadline:
                raise TimeoutError(what)
            spins += 1
            if spins <= 100:  # spin briefly, then yield the core
                continue
            now = time.monotonic()
            if slept_since is None:
                slept_since = now
            quiet = now - slept_since
            time.sleep(50e-6 if quiet < 0.002
                       else (200e-6 if quiet < 0.02
                             else (1e-3 if quiet < 0.25 else 5e-3)))

    # ---------------------------------------------------------------- api

    def poll(self) -> bool:
        """Non-blocking: True iff a payload is ready to read."""
        w, r, _n, _c = self._hdr()
        return w > r

    def closed(self) -> bool:
        """Non-blocking: True iff a peer flipped the closed flag. An
        unread payload may still be pending — poll()/read() first if the
        stream should be drained before treating the close as death."""
        _w, _r, _n, c = self._hdr()
        return bool(c)

    def drained(self) -> bool:
        """Non-blocking: True iff at least one payload was published and
        every published payload was consumed (the poll twin of
        wait_drained)."""
        w, r, _n, _c = self._hdr()
        return w > 0 and r >= w

    def write(self, value, timeout: float | None = 60.0) -> None:
        from ray_tpu._private import serialization as ser

        self.write_serialized(ser.dumps(value), timeout)

    def write_serialized(self, payload: bytes,
                         timeout: float | None = 60.0) -> None:
        """Write pre-serialized bytes (one serialization for a fan-out of
        writes, and size-checking before committing to any channel)."""
        if len(payload) > self.capacity:
            raise ValueError(
                f"payload {len(payload)}B exceeds channel capacity "
                f"{self.capacity}B (pick buffer_bytes at create_channel)")

        def writable(hdr):
            w, r, _n, c = hdr
            if c:
                raise ChannelClosed("channel closed")
            return w == r  # previous payload consumed

        self._wait(writable, timeout,
                   "channel write timed out (reader too slow)")
        self._mm[_HDR_SIZE:_HDR_SIZE + len(payload)] = payload
        w, r, _n, _c = self._hdr()
        self._set(plen=len(payload))
        self._set(write_seq=w + 1)  # publish LAST (TSO: payload visible)

    def write_vectored(self, parts, timeout: float | None = 60.0) -> None:
        """Write the concatenation of ``parts`` (bytes-like, e.g. numpy
        memoryviews) as ONE payload without materializing the join — the
        zero-copy path for multi-buffer messages (PD KV pages: header +
        raw page bytes)."""
        total = sum(len(memoryview(p).cast("B")) for p in parts)
        if total > self.capacity:
            raise ValueError(
                f"payload {total}B exceeds channel capacity "
                f"{self.capacity}B (pick buffer_bytes at create_channel)")

        def writable(hdr):
            w, r, _n, c = hdr
            if c:
                raise ChannelClosed("channel closed")
            return w == r  # previous payload consumed

        self._wait(writable, timeout,
                   "channel write timed out (reader too slow)")
        off = _HDR_SIZE
        for p in parts:
            b = memoryview(p).cast("B")
            self._mm[off:off + len(b)] = b
            off += len(b)
        w, r, _n, _c = self._hdr()
        self._set(plen=total)
        self._set(write_seq=w + 1)  # publish LAST (TSO: payload visible)

    def read_view(self, timeout: float | None = 60.0):
        """Zero-copy read: a memoryview over the published payload, valid
        ONLY until ``ack_read()`` — the caller must copy what it keeps
        BEFORE acking (the writer may overwrite the buffer after)."""

        def readable(hdr):
            w, r, _n, c = hdr
            if w > r:
                return True
            if c:
                raise ChannelClosed("channel closed and drained")
            return False

        self._wait(readable, timeout, "channel read timed out")
        _w, _r, n, _c = self._hdr()
        return memoryview(self._mm)[_HDR_SIZE:_HDR_SIZE + n]

    def ack_read(self) -> None:
        """Consume the payload returned by ``read_view``: the writer may
        overwrite the buffer from here on."""
        _w, r, _n, _c = self._hdr()
        self._set(read_seq=r + 1)

    def read(self, timeout: float | None = 60.0):
        from ray_tpu._private import serialization as ser

        def readable(hdr):
            w, r, _n, c = hdr
            if w > r:
                return True
            if c:
                raise ChannelClosed("channel closed and drained")
            return False

        self._wait(readable, timeout, "channel read timed out")
        w, r, n, _c = self._hdr()
        value = ser.loads(bytes(self._mm[_HDR_SIZE:_HDR_SIZE + n]))
        self._set(read_seq=r + 1)  # ack: the writer may overwrite now
        return value

    def wait_drained(self, timeout: float | None = 60.0) -> None:
        """Block until the reader consumed the LAST published payload
        (read_seq caught up to write_seq). The writer's end-of-stream
        barrier: after it returns, close()+unlink() cannot strand an
        unread payload in a segment nobody will ever map again. Raises
        ChannelClosed if the channel was closed underneath the wait."""

        def drained(hdr):
            w, r, _n, c = hdr
            if w == r:  # drained wins over closed: the stream completed
                return True
            if c:
                raise ChannelClosed("channel closed")
            return False

        self._wait(drained, timeout,
                   "channel drain wait timed out (reader gone?)")

    def close(self, drain: bool = False) -> None:
        """Mark closed; peers already attached observe ChannelClosed. The
        NAME stays linked — a consumer that deserializes its channel arg
        after close must still be able to attach and drain. The creator's
        GC (or an explicit unlink()) removes the file. `drain` is accepted
        for broker-channel signature parity (a mutable buffer holds at most
        one unread payload; nothing to drain)."""
        try:
            self._set(closed=1)
        except ValueError:
            pass  # already unmapped

    def force_ack(self) -> None:
        """Driver-side recovery aid: mark whatever the writer last published
        as consumed (read_seq = write_seq) so a writer blocked on a DEAD
        reader's ack can finish its write and reach its next channel read
        (where the rewire message is waiting). Violates SPSC on purpose —
        only ever called while the channel's real reader is known dead."""
        try:
            w, _r, _n, _c = self._hdr()
            self._set(read_seq=w)
        except ValueError:
            pass  # already unmapped

    def close_mapping(self) -> None:
        """Release THIS handle's mmap without touching the header: the
        reader-side detach. close() would flip the shared closed flag and
        make a still-draining writer read its own successful stream as a
        peer death."""
        try:
            self._mm.close()
        except Exception:
            pass

    def unlink(self) -> None:
        try:
            os.unlink(self.path)
        except OSError:
            pass

    def __reduce__(self):
        # deserialized copies attach to the existing file (never creators)
        return (MutableShmChannel, (self.path, self.capacity))

    def __del__(self):
        try:
            self._mm.close()
        except Exception:
            pass
        if getattr(self, "_creator", False):
            # the creating handle owns the name: releasing it reclaims the
            # tmpfs bytes even if close()/unlink() were never called.
            # Existing mappings stay valid per POSIX.
            self.unlink()


def create_mutable_channel(buffer_bytes: int = 1 << 20) -> MutableShmChannel:
    path = os.path.join(_DIR, f"{SHM_CHANNEL_PREFIX}{uuid.uuid4().hex[:12]}")
    ch = MutableShmChannel(path, buffer_bytes, _create=True)
    ch._creator = True  # this handle's GC unlinks the backing file
    return ch
