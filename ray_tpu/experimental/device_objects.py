"""RDT — device-tensor pass-by-reference between actors.

Reference capability: Ray Direct Transport / GPU objects
(reference: python/ray/experimental/gpu_object_manager/gpu_object_manager.py:84
— `@ray.method(tensor_transport="nccl")` keeps tensors in device memory and
passes them by reference through actor calls; transport managers in
experimental/collective/collective_tensor_transport.py:17).

TPU-native design: a per-process **HBM object registry** holds jax.Arrays by
tensor id. A method declared `@ray_tpu.method(tensor_transport="device")`
(alias "tpu") has its result's arrays swapped for small markers before
serialization — the bytes never leave HBM for the control plane. Consumers:

- same process (self-calls, co-located consumers): zero-copy registry hit;
- other process: on-demand export — the owner is asked (via the GCS) to
  serialize that one tensor into the shared-memory object plane, and the
  consumer reads it from there (device_put back to its own chips). This is
  the host-staged fallback; chip-to-chip ICI movement belongs to jitted
  collectives over a shared mesh (parallel/collectives.py), which is the
  TPU-idiomatic hot path the reference reaches with NCCL p2p.

Registry entries are owned by the actor that produced them: they are freed
when the cluster frees the enclosing object (the marker rides the normal
contained-refs channel), or explicitly via `free_device_tensors`.
"""

from __future__ import annotations

import threading
import uuid
from typing import Any

_lock = threading.Lock()
_registry: dict[str, Any] = {}
# owner-side cache of host-staged exports: tensor_id -> pinned store oid
_exports: dict[str, str] = {}
# per-tensor in-flight export guard: two concurrent export requests must not
# both stage a device→host copy (the loser's pinned oid would leak)
_export_inflight: dict[str, threading.Lock] = {}
# unpickle-time detection: constructing a marker during ser.loads flips the
# active capture, so consumers restore exactly when needed (any nesting
# depth, registered pytrees included)
_capture = threading.local()


class marker_capture:
    """Context manager: `with marker_capture() as saw: ...; saw()` is True
    iff a DeviceTensorMarker was constructed inside the block (valid after
    the block exits too)."""

    def __enter__(self):
        self._prev = getattr(_capture, "seen", None)
        self._result = False
        _capture.seen = False
        return lambda: self._result or bool(getattr(_capture, "seen", False))

    def __exit__(self, *exc):
        self._result = bool(getattr(_capture, "seen", False))
        _capture.seen = self._prev
        return False


class DeviceTensorMarker:
    """Placeholder serialized in place of an in-HBM jax.Array."""

    __slots__ = ("tensor_id", "owner_wid", "shape", "dtype")

    def __init__(self, tensor_id: str, owner_wid: str, shape, dtype):
        self.tensor_id = tensor_id
        self.owner_wid = owner_wid
        self.shape = shape
        self.dtype = dtype
        if getattr(_capture, "seen", None) is False:
            _capture.seen = True

    def __repr__(self):
        return (f"DeviceTensorMarker({self.tensor_id[:8]}…, "
                f"shape={self.shape}, dtype={self.dtype})")

    def __reduce__(self):
        return (DeviceTensorMarker,
                (self.tensor_id, self.owner_wid, self.shape, str(self.dtype)))


def _is_device_array(x) -> bool:
    try:
        import jax
        return isinstance(x, jax.Array)
    except ImportError:
        return False


def extract(value: Any, owner_wid: str) -> "tuple[Any, list[str]]":
    """Replace every jax.Array leaf in `value` with a marker, registering
    the array in this process's HBM registry. Returns (value, tensor_ids)
    so the producer can tie registry lifetime to the enclosing object."""
    import jax

    tids: list[str] = []

    def swap(leaf):
        if _is_device_array(leaf):
            tid = uuid.uuid4().hex
            with _lock:
                _registry[tid] = leaf
            tids.append(tid)
            return DeviceTensorMarker(tid, owner_wid, tuple(leaf.shape),
                                      leaf.dtype)
        return leaf

    return jax.tree_util.tree_map(swap, value,
                                  is_leaf=_is_device_array), tids


def restore(value: Any, worker) -> Any:
    """Resolve markers: registry hit in-process, host-staged export pull
    across processes."""
    import jax

    def is_marker(x):
        return isinstance(x, DeviceTensorMarker)

    def unswap(leaf):
        if not is_marker(leaf):
            return leaf
        with _lock:
            arr = _registry.get(leaf.tensor_id)
        if arr is not None:
            return arr  # zero-copy: same process owns the HBM buffer
        return _fetch_remote(leaf, worker)

    return jax.tree_util.tree_map(unswap, value, is_leaf=is_marker)


def _fetch_remote(marker: DeviceTensorMarker, worker):
    """Ask the owner (through the GCS) to export the tensor into the object
    plane, then read it locally (reference: RDT transport fallback path)."""
    reply = worker.rpc({"type": "export_tensor",
                        "tensor_id": marker.tensor_id,
                        "owner_wid": marker.owner_wid}, timeout=120.0)
    if not reply.get("ok"):
        raise RuntimeError(
            f"device tensor {marker.tensor_id[:8]}… unavailable: "
            f"{reply.get('error')}")
    return worker.get_object(reply["oid"], timeout=120.0)


def export_to_store(tensor_id: str, worker) -> str | None:
    """Owner-side: serialize one registered array into the object store and
    register it with the GCS; returns the oid (None if unknown)."""
    import numpy as np

    from ray_tpu._private import serialization as ser
    from ray_tpu._private.ids import ObjectID

    with _lock:
        cached = _exports.get(tensor_id)
        if cached is not None:
            return cached  # each tensor is host-staged at most once
        if tensor_id not in _registry:
            return None
        guard = _export_inflight.setdefault(tensor_id, threading.Lock())
    with guard:
        with _lock:  # the race loser re-checks under the guard
            cached = _exports.get(tensor_id)
            arr = _registry.get(tensor_id)
        if cached is not None:
            return cached
        if arr is None:
            return None  # freed while we waited
        host = np.asarray(arr)  # one device→host copy, on cross-process use
        oid = ObjectID.for_put().hex()
        parts, total = ser.dumps_into(host)
        tier = worker.store.put_parts(oid, parts, total)
        worker.send_no_reply({"type": "object_put", "oid": oid, "where": "shm",
                              "size": total, "host": worker.host_id,
                              "tier": tier, "pin": True})
        with _lock:
            if tensor_id not in _registry:
                freed = True  # freed mid-copy: our staged oid must not leak
            else:
                freed = False
                _exports[tensor_id] = oid
                _export_inflight.pop(tensor_id, None)
        if freed:
            try:
                worker.send_no_reply({"type": "free_objects_async",
                                      "oids": [oid]})
            except Exception:
                pass
            return None
        return oid


def free_device_tensors(tensor_ids, worker=None) -> None:
    """Drop registry entries (owner process); with `worker` given, also
    free the host-staged export copies cluster-wide."""
    stale_oids = []
    with _lock:
        for tid in tensor_ids:
            _registry.pop(tid, None)
            _export_inflight.pop(tid, None)
            oid = _exports.pop(tid, None)
            if oid:
                stale_oids.append(oid)
    if worker is not None and stale_oids:
        try:
            worker.send_no_reply({"type": "free_objects_async",
                                  "oids": stale_oids})
        except Exception:
            pass


def registry_size() -> int:
    with _lock:
        return len(_registry)
