"""Job submission: run a driver command on the cluster under supervision.

(reference capability: python/ray/dashboard/modules/job/ — REST+SDK
`JobSubmissionClient.submit_job` (sdk.py:36,126), `JobManager` (job_manager.py:60)
spawning a per-job `JobSupervisor` actor (job_supervisor.py:56) that runs the
entrypoint, streams its logs, and exposes status. Here the SDK talks straight
to the session (no dashboard hop): job state lives in GCS KV under `job:<id>`,
logs under `<session>/logs/job-<id>.log`, and the supervisor is an actor.)
"""

from __future__ import annotations

import json
import os
import time
import uuid

import ray_tpu

PENDING = "PENDING"
RUNNING = "RUNNING"
SUCCEEDED = "SUCCEEDED"
FAILED = "FAILED"
STOPPED = "STOPPED"
TERMINAL = (SUCCEEDED, FAILED, STOPPED)


@ray_tpu.remote(num_cpus=0, max_concurrency=4)
class JobSupervisor:
    """Owns one job subprocess: spawn, pump logs, record status in GCS KV."""

    def __init__(self, job_id: str, entrypoint: str, metadata: dict,
                 session_dir: str, socket_path: str, session_id: str):
        import subprocess
        import threading

        self.job_id = job_id
        self.entrypoint = entrypoint
        self.log_path = os.path.join(session_dir, "logs", f"job-{job_id}.log")
        self._status = RUNNING
        self._record(metadata)
        env = dict(os.environ)
        # the job's driver joins THIS session instead of starting its own
        env["RAY_TPU_ADDRESS"] = f"unix:{socket_path}"
        env["RAY_TPU_SESSION"] = session_id
        # each job's driver (and its nested workloads) reports its own id
        # (reference: runtime_context.get_job_id)
        env["RAY_TPU_JOB_ID"] = self.job_id
        self._log_f = open(self.log_path, "ab")
        self._proc = subprocess.Popen(
            entrypoint, shell=True, stdout=self._log_f,
            stderr=subprocess.STDOUT, cwd=os.getcwd(), env=env,
            start_new_session=True)  # own pgid: stop() kills the whole tree
        self._waiter = threading.Thread(target=self._wait, daemon=True)
        self._waiter.start()

    def _record(self, metadata: dict | None = None):
        from ray_tpu._private.worker import get_global_worker

        rec = {"job_id": self.job_id, "status": self._status,
               "entrypoint": self.entrypoint, "updated_at": time.time()}
        if metadata:
            rec["metadata"] = metadata
        get_global_worker().kv_put(f"job:{self.job_id}", json.dumps(rec))

    def _wait(self):
        rc = self._proc.wait()
        self._log_f.close()
        if self._status != STOPPED:
            self._status = SUCCEEDED if rc == 0 else FAILED
        self._record()

    def status(self) -> str:
        return self._status

    def stop(self) -> None:
        import signal

        if self._proc.poll() is None:
            self._status = STOPPED
            try:
                os.killpg(self._proc.pid, signal.SIGTERM)
            except ProcessLookupError:
                pass
            self._record()

    def logs(self) -> str:
        try:
            with open(self.log_path, "rb") as f:
                return f.read().decode("utf-8", "replace")
        except OSError:
            return ""

    def ping(self) -> bool:
        return True


class JobSubmissionClient:
    """SDK mirroring the reference's (sdk.py): submit/status/logs/stop/list.

    Uses the already-initialized session if any, else joins the newest live
    session on this host as a secondary driver."""

    def __init__(self, session_dir: str | None = None):
        if ray_tpu.is_initialized():
            ctx = ray_tpu.init()  # returns existing context
            self.session_dir = ctx.get("session_dir") or self._newest(session_dir)
        else:
            self.session_dir = session_dir or self._newest(None)
            socket_path = os.path.join(self.session_dir, "gcs.sock")
            session_id = os.path.basename(self.session_dir)[len("session_"):]
            os.environ["RAY_TPU_ADDRESS"] = f"unix:{socket_path}"
            os.environ["RAY_TPU_SESSION"] = session_id
            ray_tpu.init()
        self.socket_path = os.path.join(self.session_dir, "gcs.sock")
        self.session_id = os.path.basename(self.session_dir)[len("session_"):]

    @staticmethod
    def _newest(hint: str | None) -> str:
        if hint:
            return hint
        from ray_tpu.scripts.cli import find_sessions

        sessions = find_sessions()
        if not sessions:
            raise RuntimeError("no live ray_tpu session to submit to")
        return sessions[0]

    # -- API ---------------------------------------------------------------

    def submit_job(self, *, entrypoint: str, metadata: dict | None = None,
                   submission_id: str | None = None) -> str:
        job_id = submission_id or f"job_{uuid.uuid4().hex[:10]}"
        sup = JobSupervisor.options(name=f"_job_supervisor:{job_id}",
                            namespace="_system").remote(
            job_id, entrypoint, metadata or {}, self.session_dir,
            self.socket_path, self.session_id)
        ray_tpu.get(sup.ping.remote())  # surface spawn errors here
        return job_id

    def _supervisor(self, job_id: str):
        return ray_tpu.get_actor(f"_job_supervisor:{job_id}", namespace="_system")

    def get_job_status(self, job_id: str) -> str:
        try:
            return ray_tpu.get(self._supervisor(job_id).status.remote())
        except Exception:
            rec = self._kv_record(job_id)
            if rec:
                return rec["status"]
            raise

    def get_job_logs(self, job_id: str) -> str:
        try:
            return ray_tpu.get(self._supervisor(job_id).logs.remote())
        except Exception:
            path = os.path.join(self.session_dir, "logs", f"job-{job_id}.log")
            try:
                with open(path, "rb") as f:
                    return f.read().decode("utf-8", "replace")
            except OSError:
                return ""

    def stop_job(self, job_id: str) -> None:
        ray_tpu.get(self._supervisor(job_id).stop.remote())

    def wait_until_finished(self, job_id: str, timeout: float = 300.0) -> str:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            status = self.get_job_status(job_id)
            if status in TERMINAL:
                return status
            time.sleep(0.25)
        raise TimeoutError(f"job {job_id} still {status} after {timeout}s")

    def _kv_record(self, job_id: str) -> dict | None:
        from ray_tpu._private.api import _get_worker

        raw = _get_worker().kv_get(f"job:{job_id}")
        return json.loads(raw) if raw else None

    def list_jobs(self) -> list[dict]:
        from ray_tpu._private.api import _get_worker

        w = _get_worker()
        out = []
        for key in w.kv_keys("job:"):
            raw = w.kv_get(key)
            if raw:
                out.append(json.loads(raw))
        return out
