"""ray_tpu.llm — LLM serving and batch inference, TPU-native.

(reference: python/ray/llm/ — vLLM-backed LLMServer + OpenAI ingress, PD
disaggregation, Ray-Data batch processor. The engine here is the in-repo TPU
continuous-batching engine (ray_tpu/llm/engine.py) instead of vLLM.)
"""

from ray_tpu.llm.batch import Processor, build_llm_processor
from ray_tpu.llm.config import LLMConfig, ModelLoadingConfig, PDConfig
from ray_tpu.llm.engine import SamplingParams, TPUEngine
from ray_tpu.llm.guided import GuidedFSM
from ray_tpu.llm.kv_transfer import KVTransferError, PagedKVExporter
from ray_tpu.llm.pd import build_pd_openai_app
from ray_tpu.llm.server import LLMServer, build_openai_app
from ray_tpu.llm.tokenizer import ByteTokenizer, load_tokenizer

__all__ = [
    "ByteTokenizer",
    "GuidedFSM",
    "KVTransferError",
    "LLMConfig",
    "LLMServer",
    "ModelLoadingConfig",
    "PDConfig",
    "PagedKVExporter",
    "Processor",
    "SamplingParams",
    "TPUEngine",
    "build_llm_processor",
    "build_openai_app",
    "build_pd_openai_app",
    "load_tokenizer",
]
