"""Batch LLM inference as a data-pipeline stage.

(reference: llm/_internal/batch/processor/ — build_llm_processor composes
preprocess → engine → postprocess stages over Ray Data
(vllm_engine_proc.py); stages in _internal/batch/stages/. Here the engine
stage is an actor pool of TPUEngine replicas consumed via map_batches.)
"""

from __future__ import annotations

from typing import Callable

import numpy as np

import ray_tpu
from ray_tpu.llm.config import LLMConfig
from ray_tpu.llm.engine import SamplingParams


@ray_tpu.remote
class _EngineWorker:
    def __init__(self, llm_config_blob: bytes):
        from ray_tpu._private import serialization as ser

        from ray_tpu.llm.engine import TPUEngine
        from ray_tpu.llm.tokenizer import load_tokenizer

        llm_config = ser.loads(llm_config_blob)
        self.engine = TPUEngine.from_config(llm_config)
        self.tokenizer = load_tokenizer(llm_config.model_loading_config.tokenizer)

    def generate_batch(self, prompts: list, sampling: dict) -> list:
        from ray_tpu.llm.engine import _iter_request

        sp = SamplingParams(**sampling)
        reqs = [self.engine.submit(self.tokenizer.encode(p), sp) for p in prompts]
        return [self.tokenizer.decode(list(_iter_request(r))) for r in reqs]


class Processor:
    """(reference: batch/processor/processor.py Processor — callable over a
    Dataset; __call__ returns the transformed dataset.)"""

    def __init__(self, llm_config: LLMConfig, *, preprocess: Callable | None = None,
                 postprocess: Callable | None = None, concurrency: int = 1,
                 batch_size: int = 16, sampling_params: dict | None = None,
                 input_column: str = "prompt", output_column: str = "generated"):
        from ray_tpu._private import serialization as ser

        self.blob = ser.dumps(llm_config)
        self.preprocess = preprocess
        self.postprocess = postprocess
        self.concurrency = concurrency
        self.batch_size = batch_size
        self.sampling = sampling_params or {"max_tokens": 32, "temperature": 0.0}
        self.input_column = input_column
        self.output_column = output_column
        self._workers = None

    def _pool(self):
        if self._workers is None:
            self._workers = [_EngineWorker.remote(self.blob)
                             for _ in range(self.concurrency)]
        return self._workers

    def __call__(self, dataset):
        if self.preprocess is not None:
            dataset = dataset.map(self.preprocess)
        workers = self._pool()
        refs, metas = [], []
        for i, batch in enumerate(dataset.iter_batches(
                batch_size=self.batch_size, batch_format="numpy")):
            prompts = [str(p) for p in np.asarray(batch[self.input_column]).tolist()]
            w = workers[i % len(workers)]
            refs.append(w.generate_batch.remote(prompts, self.sampling))
            metas.append(batch)
        rows = []
        for ref, batch in zip(refs, metas):
            outs = ray_tpu.get(ref)
            keys = list(batch.keys())
            for j, text in enumerate(outs):
                row = {k: np.asarray(batch[k])[j] for k in keys}
                row[self.output_column] = text
                rows.append(row)
        import ray_tpu.data as rdata

        out = rdata.from_items(rows)
        if self.postprocess is not None:
            out = out.map(self.postprocess)
        return out

    def shutdown(self):
        for w in self._workers or []:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        self._workers = None


def build_llm_processor(llm_config: LLMConfig, **kwargs) -> Processor:
    """(reference: batch/processor/__init__.py build_llm_processor.)"""
    return Processor(llm_config, **kwargs)
