"""Param checkpoint IO: flat .npz with /-joined tree paths.

(reference capability: model loading from cloud/local storage,
llm/_internal/serve/... model_loading_config; orbax is available in the image
but a flat npz keeps checkpoints dependency-free and mmap-friendly.)
"""

from __future__ import annotations

import os

import jax
import numpy as np


def _flatten(params) -> dict:
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path)
        flat[key] = np.asarray(leaf)
    return flat


def save_params(params, path: str) -> str:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    np.savez(path if path.endswith(".npz") else path + ".npz", **_flatten(params))
    return path


def load_params(path: str):
    if not path.endswith(".npz"):
        path = path + ".npz"
    data = np.load(path)
    nested: dict = {}
    for key in data.files:
        parts = key.split("/")
        node = nested
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = data[key]
    return nested
