"""LLMConfig — the single config object for serve + batch LLM stacks.

(reference: llm/_internal/serve/core/configs/llm_config.py LLMConfig —
model_loading_config, engine_kwargs (tensor_parallel_size etc. forwarded to
vLLM at vllm_models.py:215,219), accelerator_type, deployment_config. Here
engine_kwargs drive the TPU engine and mesh axes instead of vLLM.)
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class ModelLoadingConfig:
    model_id: str = "tiny"  # a size key of the chosen model family
    # checkpoint directory (orbax/npz) or None → random init of `model_cfg`
    model_source: str | None = None
    tokenizer: str | None = "byte"


@dataclass
class LoraConfig:
    """(reference: llm/_internal/serve/core/configs/llm_config.py
    LoraConfig — dynamic_lora_loading_path + max_num_adapters_per_replica;
    adapters load on demand when a request's `model` names one.)"""

    dynamic_lora_loading_path: str = ""  # dir of <adapter_id>.npz files
    max_num_adapters_per_replica: int = 4
    lora_rank: int = 8


@dataclass
class PDConfig:
    """Prefill/decode disaggregation knobs (ray_tpu/llm/pd.py).

    (reference: serving_patterns/prefill_decode/pd_server.py — the proxy
    composes separately-sized prefill and decode pools; kv transfer config
    picks the handoff transport. Here the transport is the paged-KV shm
    plane — ray_tpu/llm/kv_transfer.py.)"""

    # KV handoff granularity in tokens; must divide the engine buckets, so
    # the prefill servers bump min_bucket up to it. Power of two.
    page_size: int = 64
    # per-page shm handoff timeout: a decode replica that never pulls (or
    # dies mid-pull) frees the prefill side's channel after this long
    transfer_timeout_s: float = 60.0
    # pages per transfer message — the in-flight prefetch window. >1
    # amortizes the seqlock handshake + pickle framing over several pages
    # at the cost of prefetch_depth*page_bytes of channel buffer per
    # in-flight transfer
    prefetch_depth: int = 2
    # route decode-side pulls through the shared BatchedKVPuller (one
    # polling thread for ALL in-flight transfers) + streamed slot
    # admission (pages adopted as they arrive). False restores the
    # pull-everything-then-admit path (debug/A-B escape hatch).
    batched_pull: bool = True
    # prefill-tier admission batching (pd.py PrefillCoalescer): concurrent
    # same-bucket prompts coalesce into ONE [B, T] prefill forward. The
    # window is how long the batch leader waits for stragglers; 0 batches
    # only what is already queued.
    prefill_batch_max: int = 4
    prefill_batch_window_s: float = 0.0015
    num_prefill_replicas: int = 1
    num_decode_replicas: int = 1


@dataclass
class LLMConfig:
    model_loading_config: ModelLoadingConfig = field(default_factory=ModelLoadingConfig)
    # TransformerConfig kwargs for the built-in families (gpt2/llama/mixtral)
    model_family: str = "llama"
    model_kwargs: dict = field(default_factory=dict)
    engine_kwargs: dict = field(default_factory=dict)  # max_slots, max_len, min_bucket,
                                                       # tensor_parallel_size, seed
    deployment_config: dict = field(default_factory=dict)  # serve options
    accelerator_type: str | None = "TPU"
    lora_config: LoraConfig | None = None
    # PD disaggregation (build_pd_openai_app); None → PDConfig() defaults
    pd_config: PDConfig | None = None

    def build_model(self):
        """Returns (TransformerConfig, params). Cited families live in
        ray_tpu/models; random init unless model_source points at a checkpoint."""
        import jax

        from ray_tpu import models

        factory = {"llama": models.llama_config, "gpt2": models.gpt2_config,
                   "mixtral": models.mixtral_config}[self.model_family]
        cfg = factory(self.model_loading_config.model_id, **self.model_kwargs)
        src = self.model_loading_config.model_source
        if src:
            from ray_tpu.llm import checkpoint_io

            params = checkpoint_io.load_params(src)
        else:
            params = models.transformer.init(jax.random.PRNGKey(0), cfg)
        return cfg, params
