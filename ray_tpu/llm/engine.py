"""TPUEngine: continuous-batching inference on one chip/mesh.

The scheduler thread owns the device state and runs the classic
continuous-batching loop (admit → prefill into a free slot → global
decode_step → emit/eject), all on static shapes:

- prompt lengths are padded to power-of-two buckets → a handful of prefill
  compilations, cached forever,
- the decode hot loop is ONE jitted fixed-shape program regardless of which
  rows are live — joins/leaves are slot bookkeeping, not recompiles,
- sampling is on-device; only the sampled token ids cross PCIe each step.

(reference capability: vLLM engine wrapped at
llm/_internal/serve/engines/vllm/vllm_engine.py:114; TPU design is
greenfield per SURVEY.md §7 — static-shape bucketing + slot cache instead of
paged CUDA kernels.)
"""

from __future__ import annotations

import dataclasses
import itertools
import queue
import threading
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.models import decoding
from ray_tpu.models.transformer import TransformerConfig


@dataclasses.dataclass
class SamplingParams:
    max_tokens: int = 64
    temperature: float = 0.0
    top_k: int = 0
    stop_token_ids: tuple = ()


@dataclasses.dataclass
class _Request:
    rid: int
    tokens: list
    params: SamplingParams
    out_queue: queue.SimpleQueue = dataclasses.field(default_factory=queue.SimpleQueue)
    slot: int = -1
    generated: int = 0
    kv_pack: dict | None = None  # prefilled elsewhere (PD disaggregation)


_SENTINEL = object()


class _EngineError:
    """End-of-stream marker carrying the scheduler's failure."""

    def __init__(self, exc: BaseException):
        self.exc = exc


def _iter_request(req: "_Request"):
    """Yield a request's tokens; raise if the engine died mid-stream."""
    while True:
        tok = req.out_queue.get()
        if tok is _SENTINEL:
            return
        if isinstance(tok, _EngineError):
            raise RuntimeError("engine scheduler died mid-generation") from tok.exc
        yield tok


def bucket_for(n: int, min_bucket: int, max_len: int) -> int:
    """Smallest power-of-two bucket ≥ n (starting at min_bucket, capped at
    max_len). Shared by the engine and the PD prefill server so the two can
    never disagree on padded shapes."""
    b = min_bucket
    while b < n and b < max_len:
        b *= 2
    return min(b, max_len)


class TPUEngine:
    def __init__(self, cfg: TransformerConfig, params: Any, *,
                 max_slots: int = 8, max_len: int | None = None,
                 min_bucket: int = 32, seed: int = 0):
        self.cfg = cfg
        self.params = params
        self.max_len = max_len or cfg.max_seq_len
        if self.max_len > cfg.max_seq_len:
            raise ValueError(
                f"engine max_len {self.max_len} exceeds the model's "
                f"max_seq_len {cfg.max_seq_len} (rope/pos tables are sized "
                "by the model config)")
        self.max_slots = max_slots
        self.buckets = []
        b = min_bucket
        while b < self.max_len:
            self.buckets.append(b)
            b *= 2
        self.buckets.append(self.max_len)
        self.state = decoding.init_decode_state(cfg, max_slots, self.max_len)
        self.key = jax.random.PRNGKey(seed)
        self._free = list(range(max_slots))
        self._by_slot: dict[int, _Request] = {}
        self._waiting: queue.SimpleQueue = queue.SimpleQueue()
        self._rid = itertools.count()
        self._work = threading.Event()
        self._stop = False
        self._error: BaseException | None = None
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="tpu-engine")
        self._thread.start()

    # ---------------------------------------------------------------- public

    @classmethod
    def from_config(cls, llm_config) -> "TPUEngine":
        """Single construction point for server/PD/batch paths."""
        cfg, params = llm_config.build_model()
        ek = dict(llm_config.engine_kwargs)
        return cls(cfg, params,
                   max_slots=ek.get("max_slots", 8),
                   max_len=ek.get("max_len", cfg.max_seq_len),
                   min_bucket=ek.get("min_bucket", 32),
                   seed=ek.get("seed", 0))

    def _check_alive(self):
        if self._error is not None:
            raise RuntimeError("engine scheduler died") from self._error
        if self._stop:
            raise RuntimeError("engine is shut down")

    def submit(self, token_ids: list, params: SamplingParams | None = None) -> _Request:
        self._check_alive()
        params = params or SamplingParams()
        token_ids = list(token_ids)
        if not token_ids:
            raise ValueError("empty prompt: at least one token is required")
        limit = self.max_len - params.max_tokens - 1
        if limit <= 0:
            raise ValueError("max_tokens leaves no room for the prompt")
        token_ids = token_ids[-limit:]
        req = _Request(next(self._rid), token_ids, params)
        self._waiting.put(req)
        self._work.set()
        return req

    def submit_prefilled(self, k, v, length: int, first_token: int,
                         params: SamplingParams | None = None) -> _Request:
        """Admit a sequence whose prefill ran elsewhere (PD disaggregation):
        k/v are [L, T, Hkv, Dh] host arrays for the prompt prefix."""
        self._check_alive()
        params = params or SamplingParams()
        if k.shape[1] > self.max_len:
            raise ValueError(
                f"transferred prefix bucket {k.shape[1]} exceeds engine "
                f"max_len {self.max_len}")
        if int(length) + params.max_tokens >= self.max_len:
            raise ValueError(
                f"prefix length {int(length)} + max_tokens {params.max_tokens} "
                f"does not fit engine max_len {self.max_len}")
        req = _Request(next(self._rid), [], params)
        req.kv_pack = {"k": k, "v": v, "length": int(length),
                       "first_token": int(first_token)}
        req.generated = 1  # the transferred first token counts
        self._waiting.put(req)
        self._work.set()
        return req

    def generate(self, token_ids: list, params: SamplingParams | None = None) -> list:
        """Blocking: returns the generated token ids."""
        return list(self.stream(token_ids, params))

    def stream(self, token_ids: list, params: SamplingParams | None = None):
        """Yields token ids as they are produced."""
        req = self.submit(token_ids, params)
        yield from _iter_request(req)

    def shutdown(self):
        self._stop = True
        self._work.set()
        self._thread.join(timeout=5.0)
        self._drain_all(None)

    def _drain_all(self, error: BaseException | None):
        """Unblock every waiting caller: end-of-stream, or the failure."""
        marker = _EngineError(error) if error is not None else _SENTINEL
        for req in list(self._by_slot.values()):
            req.out_queue.put(marker)
        while True:
            try:
                self._waiting.get_nowait().out_queue.put(marker)
            except queue.Empty:
                break

    # ------------------------------------------------------------- scheduler

    def _bucket(self, n: int) -> int:
        return bucket_for(n, self.buckets[0], self.max_len)

    def _admit(self):
        while self._free:
            try:
                req = self._waiting.get_nowait()
            except queue.Empty:
                return
            slot = self._free.pop()
            req.slot = slot
            if req.kv_pack is not None:
                if req.generated >= req.params.max_tokens:
                    # budget already spent by the transferred first token
                    self._free.append(slot)
                    req.out_queue.put(_SENTINEL)
                    continue
                # PD path: KV arrived from a prefill server over the host plane
                kv = {"k": jnp.asarray(req.kv_pack["k"], self.state["k"].dtype),
                      "v": jnp.asarray(req.kv_pack["v"], self.state["v"].dtype)}
                self.state = decoding.insert_sequence(
                    self.state, slot, kv, jnp.int32(req.kv_pack["length"]),
                    jnp.int32(req.kv_pack["first_token"]), self.cfg)
                self._by_slot[slot] = req
                continue
            n = len(req.tokens)
            bucket = self._bucket(n)
            padded = np.zeros((1, bucket), np.int32)
            padded[0, :n] = req.tokens
            logits, kv = decoding.prefill(self.params, jnp.asarray(padded),
                                          jnp.int32(n), self.cfg)
            self.key, sub = jax.random.split(self.key)
            first = decoding.sample(logits[None, :], sub,
                                    req.params.temperature, req.params.top_k)
            first_id = int(first[0])
            self.state = decoding.insert_sequence(
                self.state, slot, kv, jnp.int32(n), first[0], self.cfg)
            self._by_slot[slot] = req
            self._emit(req, first_id)

    def _emit(self, req: _Request, token_id: int):
        req.generated += 1
        stops = set(req.params.stop_token_ids)
        eos = token_id in stops
        if not eos:
            req.out_queue.put(token_id)
        if eos or req.generated >= req.params.max_tokens:
            self.state = decoding.release_slot(self.state, req.slot)
            self._free.append(req.slot)
            del self._by_slot[req.slot]
            req.out_queue.put(_SENTINEL)

    def _loop(self):
        try:
            self._loop_inner()
        except BaseException as e:  # noqa: BLE001 — engine death must unblock callers
            self._error = e
            self._drain_all(e)
            raise

    def _loop_inner(self):
        while not self._stop:
            if not self._by_slot and self._waiting.empty():
                self._work.wait(timeout=0.1)
                self._work.clear()
                continue
            self._admit()
            if not self._by_slot:
                continue
            self.state, logits = decoding.decode_step(self.params, self.state, self.cfg)
            self.key, sub = jax.random.split(self.key)
            # per-row sampling params, applied vectorized on device
            temps = np.zeros((self.max_slots,), np.float32)
            top_ks = np.zeros((self.max_slots,), np.int32)
            for slot, req in self._by_slot.items():
                temps[slot] = req.params.temperature
                top_ks[slot] = req.params.top_k
            toks = decoding.sample_per_row(logits, sub, jnp.asarray(temps),
                                           jnp.asarray(top_ks))
            self.state = decoding.commit_tokens(self.state, toks)
            toks_host = np.asarray(toks)
            for slot, req in list(self._by_slot.items()):
                self._emit(req, int(toks_host[slot]))

    # ---------------------------------------------------------------- stats

    def stats(self) -> dict:
        return {"free_slots": len(self._free), "active": len(self._by_slot),
                "waiting": self._waiting.qsize(), "max_slots": self.max_slots,
                "buckets": list(self.buckets)}
