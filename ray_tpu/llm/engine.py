"""TPUEngine: continuous-batching inference on one chip/mesh.

The scheduler thread owns the device state and runs the classic
continuous-batching loop (admit → prefill into a free slot → global
decode_step → emit/eject), all on static shapes:

- prompt lengths are padded to power-of-two buckets → a handful of prefill
  compilations, cached forever,
- the decode hot loop is ONE jitted fixed-shape program regardless of which
  rows are live — joins/leaves are slot bookkeeping, not recompiles,
- sampling is on-device; only the sampled token ids cross PCIe each step.

(reference capability: vLLM engine wrapped at
llm/_internal/serve/engines/vllm/vllm_engine.py:114; TPU design is
greenfield per SURVEY.md §7 — static-shape bucketing + slot cache instead of
paged CUDA kernels.)
"""

from __future__ import annotations

import dataclasses
import itertools
import logging
import queue
import threading
import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.exceptions import DeadlineExceededError, RequestCancelledError
from ray_tpu.models import decoding
from ray_tpu.models.transformer import TransformerConfig

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class SamplingParams:
    max_tokens: int = 64
    temperature: float = 0.0
    top_k: int = 0
    stop_token_ids: tuple = ()
    # constrained decoding: a llm.guided.GuidedFSM over token ids
    # (reference: guided_decoding passthrough to vLLM structured output,
    # vllm_engine_stage.py:278) — see ray_tpu/llm/guided.py
    guided: object | None = None


@dataclasses.dataclass
class _Request:
    rid: int
    tokens: list
    params: SamplingParams
    out_queue: queue.SimpleQueue = dataclasses.field(default_factory=queue.SimpleQueue)
    slot: int = -1
    generated: int = 0
    kv_pack: dict | None = None  # prefilled elsewhere (PD disaggregation)
    # streamed PD admission: pages adopted as they arrive off the transfer
    # plane (kv_transfer.KVPageStream protocol); length0 mirrors the
    # row's device length host-side so the ragged decode step can bound
    # its page sweep without a device readback
    kv_stream: object | None = None
    length0: int = 0
    # chunked-prefill progress (engine._prefill_step)
    pf_done: int = 0
    pf_pages: list | None = None
    pf_hashes: list | None = None
    # request-phase stamps (wall clock): submit → decode-slot bind is the
    # admission wait; _emit tracks the inter-token gap off last_emit_ts.
    # Read by llm/pd.py decode_stream to emit retroactive phase spans.
    submitted_ts: float = 0.0
    admitted_ts: float = 0.0
    last_emit_ts: float = 0.0
    # full token history (prompt + emitted) for the n-gram draft proposer,
    # plus an incremental index: trailing-ngram tuple → (latest, previous)
    # continuation-start positions, so proposal is O(1) per step instead of
    # rescanning the history (which is quadratic over a long generation)
    history: list = dataclasses.field(default_factory=list)
    ngram_index: dict | None = None
    # multi-LoRA: bank index this request decodes with (0 = base model)
    lora_idx: int = 0
    lora_released: bool = False
    # absolute wall-clock deadline (0 = none): the scheduler aborts the
    # row between steps once expired, and refuses admission for a request
    # whose queue-wait already spent the budget
    deadline_ts: float = 0.0

    def __iter__(self):
        """Yield generated tokens as they are produced (public surface for
        callers holding a submit() result — no private imports needed)."""
        return _iter_request(self)


_SENTINEL = object()


class _EngineError:
    """End-of-stream marker carrying the scheduler's failure."""

    def __init__(self, exc: BaseException):
        self.exc = exc


class _RequestError(_EngineError):
    """End-of-stream marker for a PER-REQUEST failure (e.g. the KV
    transfer feeding a streamed admission died): the carried exception is
    re-raised to this caller; the engine and every other request keep
    serving."""


def _iter_request(req: "_Request"):
    """Yield a request's tokens; raise if the engine died mid-stream."""
    while True:
        tok = req.out_queue.get()
        if tok is _SENTINEL:
            return
        if isinstance(tok, _RequestError):
            raise tok.exc
        if isinstance(tok, _EngineError):
            raise RuntimeError("engine scheduler died mid-generation") from tok.exc
        yield tok


def bucket_for(n: int, min_bucket: int, max_len: int) -> int:
    """Smallest power-of-two bucket ≥ n (starting at min_bucket, capped at
    max_len). Shared by the engine and the PD prefill server so the two can
    never disagree on padded shapes."""
    b = min_bucket
    while b < n and b < max_len:
        b *= 2
    return min(b, max_len)


def _shard_params_tp(params, mesh):
    """Tensor-parallel placement of the transformer parameter tree over a
    1-axis mesh: attention head dims and MLP hidden dims split, everything
    else replicated. XLA propagates + inserts the collectives."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    axis = mesh.axis_names[0]

    def spec_for(path, x):
        name = "/".join(str(getattr(p, "key", getattr(p, "name", p)))
                        for p in path)
        nd = x.ndim
        def pad(spec):
            return P(*(list(spec) + [None] * (nd - len(spec))))
        if "mlp" in name:
            # transformer.py MLP names: wi / wi_gate / wi_up [L, E, F],
            # wo [L, F, E], bi [L, F] — split the hidden (F) dim
            if "wi" in name:
                return pad([None, None, axis])
            if "wo" in name or name.endswith("bi"):
                return pad([None, axis])
            return P()
        if "wq" in name or "wk" in name or "wv" in name:
            # stacked [L, E, H, Dh] → split heads
            return pad([None, None, axis])
        if "wo" in name:
            # attention out [L, H, Dh, E] → split heads
            return pad([None, axis])
        if "bq" in name or "bk" in name or "bv" in name:
            return pad([None, axis])
        return P()  # replicate

    def place(path, x):
        import jax as _jax

        return _jax.device_put(x, NamedSharding(mesh, spec_for(path, x)))

    return jax.tree_util.tree_map_with_path(place, params)


def _shard_state_tp(state, mesh):
    """KV caches split on the kv-head dim; bookkeeping replicated."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    axis = mesh.axis_names[0]
    specs = {}
    for k, v in state.items():
        if k in ("k", "v"):          # [L, slots, S, Hkv, Dh]
            specs[k] = P(None, None, None, axis)
        elif k in ("kp", "vp"):      # [L, pages, P, Hkv, Dh]
            specs[k] = P(None, None, None, axis)
        else:
            specs[k] = P()
    return {k: jax.device_put(v, NamedSharding(mesh, specs[k]))
            for k, v in state.items()}


class TPUEngine:
    def __init__(self, cfg: TransformerConfig, params: Any, *,
                 max_slots: int = 8, max_len: int | None = None,
                 min_bucket: int = 32, seed: int = 0,
                 kv_layout: str = "slot", page_size: int = 64,
                 num_pages: int | None = None,
                 max_prefills_per_step: int = 2,
                 enable_prefix_cache: bool = False,
                 prefill_chunk: int | None = None,
                 speculative_k: int = 0, ngram_size: int = 2,
                 mesh=None, max_loras: int = 0, lora_rank: int = 8,
                 attn_impl: str = "auto"):
        self.cfg = cfg
        self.max_len = max_len or cfg.max_seq_len
        if self.max_len > cfg.max_seq_len:
            raise ValueError(
                f"engine max_len {self.max_len} exceeds the model's "
                f"max_seq_len {cfg.max_seq_len} (rope/pos tables are sized "
                "by the model config)")
        self.max_slots = max_slots
        if kv_layout not in ("slot", "paged"):
            raise ValueError(f"kv_layout must be 'slot' or 'paged', got {kv_layout!r}")
        self.kv_layout = kv_layout
        if kv_layout == "paged":
            if page_size <= 0 or (page_size & (page_size - 1)):
                raise ValueError("page_size must be a positive power of two")
            if self.max_len % page_size:
                raise ValueError(
                    f"max_len {self.max_len} must be a multiple of "
                    f"page_size {page_size} (buckets reshape into whole pages)")
            min_bucket = max(min_bucket, page_size)
            if min_bucket % page_size:
                raise ValueError(
                    f"min_bucket {min_bucket} must be a multiple of "
                    f"page_size {page_size} (every prompt bucket reshapes "
                    f"into whole pages)")
            if prefill_chunk is not None:
                if (prefill_chunk < min_bucket
                        or prefill_chunk % page_size
                        or bucket_for(prefill_chunk, min_bucket,
                                      max_len or cfg.max_seq_len)
                        != prefill_chunk):
                    raise ValueError(
                        f"prefill_chunk {prefill_chunk} must be one of the "
                        f"engine's bucket sizes (min_bucket {min_bucket} "
                        f"doublings) and a multiple of page_size "
                        f"{page_size} — a non-bucket chunk would pad past "
                        "its own page span and corrupt neighboring pages")
        self.buckets = []
        b = min_bucket
        while b < self.max_len:
            self.buckets.append(b)
            b *= 2
        self.buckets.append(self.max_len)
        # multi-chip serving: tensor-parallel sharding over a 1-axis mesh —
        # params' head/ff dims and the KV caches' kv-head dim are split
        # across chips; XLA inserts the collectives (reference capability:
        # vLLM tensor_parallel_size via PG bundles, vllm_models.py:215 —
        # here it's jax.sharding over ICI instead of NCCL)
        self.mesh = mesh
        if mesh is not None:
            params = _shard_params_tp(params, mesh)
        self.params = params
        if kv_layout == "paged":
            from ray_tpu.models import decoding_paged as dp

            self._dp = dp
            self.page_size = page_size
            self.max_pages_per_seq = -(-self.max_len // page_size)
            # default pool = full reservation (+1 scratch); pass num_pages
            # lower to oversubscribe HBM against short real sequences
            self.num_pages = num_pages or (max_slots * self.max_pages_per_seq + 1)
            self.state = dp.init_paged_state(
                cfg, max_slots, self.max_len, self.num_pages, page_size)
            self._free_pages = list(range(1, self.num_pages))  # 0 = scratch
            self._slot_pages: dict[int, list] = {}
            # hash-block prefix cache over the SAME page pool (reference
            # capability: vLLM automatic prefix caching): chain-hashed
            # full prompt blocks map to pages still resident in HBM; a
            # repeated prefix skips its share of prefill compute entirely.
            self.enable_prefix_cache = bool(enable_prefix_cache)
            import collections as _collections

            self._prefix_cache: _collections.OrderedDict = \
                _collections.OrderedDict()       # block-chain hash → page id
            self._page_refs: dict[int, int] = {}  # shared page → live users
            self._page_hash: dict[int, bytes] = {}  # reverse map (eviction)
            self._slot_shared: dict[int, list] = {}  # slot → shared pages
            self.prefix_hits = 0       # requests that reused ≥1 block
            self.prefix_misses = 0
            self.prefix_tokens_reused = 0
            # chunked prefill (reference capability: vLLM chunked prefill):
            # long prompts prefill in fixed chunks interleaved with decode
            # steps so running requests keep emitting during a long
            # admission instead of stalling a full prompt-bucket compile
            self.prefill_chunk = prefill_chunk
            self._prefilling: list = []  # requests mid-chunked-prefill
            self.prefill_chunks_run = 0
            # decode attention: "ragged" = one ragged-paged-attention
            # launch over the batch's live page tables (ops/
            # ragged_paged_attention.py — Pallas kernel on TPU, the
            # bit-consistent pure-JAX reference elsewhere); "gather" =
            # the legacy full-block-table gather + masked softmax
            if attn_impl == "auto":
                attn_impl = "ragged"
            if attn_impl not in ("ragged", "gather"):
                raise ValueError(
                    f"attn_impl must be 'auto', 'ragged' or 'gather', "
                    f"got {attn_impl!r}")
            self.attn_impl = attn_impl
            # the Pallas kernel needs an unsharded pool (the reference is
            # plain XLA ops, so tp-sharded states keep the ragged path)
            self._ragged_kernel = (attn_impl == "ragged" and mesh is None
                                   and jax.default_backend() == "tpu")
        else:
            self.attn_impl = "gather"
            self._ragged_kernel = False
            self.enable_prefix_cache = False
            self.prefill_chunk = None
            self._prefilling = []
            if enable_prefix_cache:
                raise ValueError(
                    "enable_prefix_cache requires kv_layout='paged'")
            if prefill_chunk is not None:
                raise ValueError("prefill_chunk requires kv_layout='paged'")
            self.state = decoding.init_decode_state(cfg, max_slots, self.max_len)
        if mesh is not None:
            self.state = _shard_state_tp(self.state, mesh)
        # speculative decoding (reference capability: vLLM prompt-lookup /
        # [ngram] speculation): propose `speculative_k` draft tokens per
        # row by matching the trailing n-gram against the request's own
        # history, verify all of them in ONE multi-token decode step
        # (models/decoding.py verify_step), emit the accepted prefix + one
        # corrected token. Model-free drafts; exact sampling semantics.
        self.speculative_k = int(speculative_k)
        self.ngram_size = max(1, int(ngram_size))
        if self.speculative_k:
            if kv_layout != "slot":
                raise ValueError(
                    "speculative_k requires kv_layout='slot' (the paged "
                    "verify kernel is not implemented)")
            if self.speculative_k < 1 or self.speculative_k > 16:
                raise ValueError("speculative_k must be in [1, 16]")
        # multi-LoRA serving (reference capability: LoRA adapters with
        # dynamic loading on serve multiplexing —
        # python/ray/llm/_internal/serve/utils/lora_serve_utils.py; here
        # adapters live in a device bank gathered per row inside the SAME
        # batched decode step — decoding.init_lora_bank)
        self.max_loras = int(max_loras)
        self.lora_rank = int(lora_rank)
        self.lora_bank = None
        if self.max_loras:
            if kv_layout != "slot":
                raise ValueError(
                    "max_loras requires kv_layout='slot' (the paged decode "
                    "kernel has no LoRA gather yet)")
            if self.speculative_k:
                raise ValueError(
                    "max_loras and speculative_k cannot be combined (the "
                    "verify kernel has no LoRA gather)")
            self.lora_bank = decoding.init_lora_bank(cfg, self.max_loras,
                                                     self.lora_rank)
            self._lora_free = list(range(1, self.max_loras + 1))
            self._lora_ids: dict[str, int] = {}   # name -> bank index
            self._lora_refs: dict[int, int] = {}  # index -> live requests
            self._slot_lora = jnp.zeros((max_slots,), jnp.int32)
            # serializes bank read-modify-write: concurrent loads from
            # replica threads must not lose each other's writes
            self._lora_lock = threading.Lock()
        self.decode_steps = 0
        self.decode_slot_steps = 0  # sum of active slots over decode steps
        self.spec_steps = 0
        self.spec_slot_steps = 0   # sum of active slots over verify steps
        self.spec_drafted = 0
        self.spec_accepted = 0
        # device-resident per-row sampling params: updated only on admit,
        # not rebuilt/re-uploaded every decode step
        self._temps = jnp.zeros((max_slots,), jnp.float32)
        self._topks = jnp.zeros((max_slots,), jnp.int32)
        # guided decoding: per-slot host-side FSM + current state; the only
        # per-step device traffic is the additive bias rows (llm/guided.py)
        self._guided_fsm: dict[int, object] = {}
        self._guided_state: dict[int, int] = {}
        self.max_prefills_per_step = max(1, int(max_prefills_per_step))
        self.key = jax.random.PRNGKey(seed)
        self._free = list(range(max_slots))
        self._by_slot: dict[int, _Request] = {}
        self._waiting: queue.SimpleQueue = queue.SimpleQueue()
        self._backlog: list = []  # paged: admitted-later queue (page pressure)
        self._streaming: list = []  # slot granted, pages still streaming in
        self._rid = itertools.count()
        self._work = threading.Event()
        self._stop = False
        self._error: BaseException | None = None
        # cancellation plane: abort_request() is called from request
        # threads; rids land here and the scheduler applies them at the
        # top of its next pass (slot + pages reclaimed in one step).
        # _abort_pending keeps rids whose request is still in _waiting
        # (a SimpleQueue can't be searched) until _admit pops them;
        # values are monotonic stamps so stale rids age out.
        self._abort_q: queue.SimpleQueue = queue.SimpleQueue()
        self._abort_pending: dict[int, float] = {}
        self.aborts = 0  # requests reclaimed via abort/deadline
        # serving-phase instrumentation (decode-slot admission wait,
        # inter-token gap): pre-bound histograms resolved ONCE per engine —
        # the per-token cost is one clock read + one lock-free observe.
        # None when RayConfig.serve_metrics is off (the bench A/B baseline).
        try:
            from ray_tpu.serve import request_context as _rc

            self._phase_admit = _rc.phase_observer(_rc.ENGINE_PHASE,
                                                   "admission_wait")
            self._phase_gap = _rc.phase_observer(_rc.ENGINE_PHASE,
                                                 "inter_token")
        except Exception:  # pragma: no cover — metrics must never gate boot
            self._phase_admit = self._phase_gap = None
        # per-decode-step wall time (device step + sampling sync) split by
        # attention impl: the ragged-vs-gather attribution the decode
        # microbench and dashboards key on
        self._step_obs = None
        try:
            from ray_tpu.serve import request_context as _rc2
            from ray_tpu.util import metrics as met

            if self.kv_layout == "paged" and _rc2.metrics_enabled():
                h = met.get_or_create(
                    met.Histogram, "ray_tpu_llm_decode_step_seconds",
                    "paged decode step wall time (device step + sampling "
                    "sync) by attention impl (ragged|gather)",
                    boundaries=[0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
                                0.05, 0.1, 0.25, 0.5, 1.0],
                    tag_keys=("impl",))
                self._step_obs = h.bind({"impl": self.attn_impl})
        except Exception:  # pragma: no cover — metrics must never gate boot
            self._step_obs = None
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="tpu-engine")
        self._thread.start()

    # ---------------------------------------------------------------- public

    @classmethod
    def from_config(cls, llm_config) -> "TPUEngine":
        """Single construction point for server/PD/batch paths."""
        cfg, params = llm_config.build_model()
        ek = dict(llm_config.engine_kwargs)
        lora_cfg = getattr(llm_config, "lora_config", None)
        return cls(cfg, params,
                   max_slots=ek.get("max_slots", 8),
                   max_len=ek.get("max_len", cfg.max_seq_len),
                   min_bucket=ek.get("min_bucket", 32),
                   seed=ek.get("seed", 0),
                   kv_layout=ek.get("kv_layout", "slot"),
                   page_size=ek.get("page_size", 64),
                   num_pages=ek.get("num_pages"),
                   max_prefills_per_step=ek.get("max_prefills_per_step", 2),
                   enable_prefix_cache=ek.get("enable_prefix_cache", False),
                   prefill_chunk=ek.get("prefill_chunk"),
                   speculative_k=ek.get("speculative_k", 0),
                   ngram_size=ek.get("ngram_size", 2),
                   attn_impl=ek.get("attn_impl", "auto"),
                   mesh=ek.get("mesh"),
                   max_loras=ek.get(
                       "max_loras",
                       lora_cfg.max_num_adapters_per_replica
                       if lora_cfg else 0),
                   lora_rank=ek.get(
                       "lora_rank",
                       lora_cfg.lora_rank if lora_cfg else 8))

    def _check_alive(self):
        if self._error is not None:
            raise RuntimeError("engine scheduler died") from self._error
        if self._stop:
            raise RuntimeError("engine is shut down")

    def load_lora(self, name: str, weights: dict, *,
                  alpha: float | None = None) -> None:
        """Load adapter `name` into a free bank slot. `weights` are
        layer-stacked host arrays {"A_q": [L, E, r], "B_q": [L, r, H, Dh],
        "A_v": [L, E, r], "B_v": [L, r, Hkv, Dh]} (missing targets stay
        zero). Scale defaults to alpha/r with alpha=r (i.e. 1.0)."""
        import numpy as _np

        if self.lora_bank is None:
            raise ValueError("engine built without max_loras")
        with self._lora_lock:
            if name in self._lora_ids:
                raise ValueError(f"lora {name!r} already loaded")
            if not self._lora_free:
                raise RuntimeError(
                    f"no free lora slots (max_loras={self.max_loras}); "
                    f"unload one of {sorted(self._lora_ids)}")
            idx = self._lora_free.pop()
            # shallow copy: writes below bind new arrays to the COPY, so a
            # mid-write failure (device OOM) leaves self.lora_bank the old,
            # fully-consistent bank — no partially-written slot
            bank = dict(self.lora_bank)
            # validate EVERY shape before writing any — a partial write
            # followed by a raise would leave stale weights in a slot the
            # free list hands to the next adapter
            for key in ("A_q", "B_q", "A_v", "B_v"):
                if key in weights:
                    want = bank[key].shape[0:1] + bank[key].shape[2:]
                    if _np.asarray(weights[key]).shape != want:
                        self._lora_free.append(idx)
                        raise ValueError(
                            f"lora {name!r} {key} shape "
                            f"{_np.asarray(weights[key]).shape} != {want} "
                            f"(rank {self.lora_rank}, layer-stacked)")
            try:
                for key in ("A_q", "B_q", "A_v", "B_v"):
                    if key in weights:
                        bank[key] = bank[key].at[:, idx].set(
                            jnp.asarray(_np.asarray(weights[key]),
                                        bank[key].dtype))
                scale = 1.0 if alpha is None else float(alpha) / self.lora_rank
                bank["scale"] = bank["scale"].at[idx].set(scale)
            except Exception:
                # device-side failure mid-write (e.g. HBM OOM): the slot must
                # go back on the free list or max_loras shrinks by one per
                # failure. The partial writes only touched the copy, so the
                # engine keeps decoding with the old consistent bank.
                self._lora_free.append(idx)
                raise
            self.lora_bank = bank
            self._lora_ids[name] = idx
            self._lora_refs[idx] = 0

    def unload_lora(self, name: str) -> None:
        """Free `name`'s bank slot. Refuses while requests using it are
        live (submitted and not yet finished)."""
        if self.lora_bank is None:
            raise KeyError(f"lora {name!r} not loaded")
        with self._lora_lock:
            if name not in self._lora_ids:
                raise KeyError(f"lora {name!r} not loaded")
            idx = self._lora_ids[name]
            if self._lora_refs.get(idx, 0) > 0:
                raise RuntimeError(
                    f"lora {name!r} has {self._lora_refs[idx]} live requests")
            # zero into a copy first: if a device write fails midway the
            # registry is untouched (same discipline as load_lora)
            bank = dict(self.lora_bank)
            for key in ("A_q", "B_q", "A_v", "B_v"):
                bank[key] = bank[key].at[:, idx].set(0.0)
            bank["scale"] = bank["scale"].at[idx].set(0.0)
            self.lora_bank = bank
            del self._lora_ids[name]
            self._lora_refs.pop(idx, None)
            self._lora_free.append(idx)

    def list_loras(self) -> list:
        return sorted(self._lora_ids) if self.lora_bank is not None else []

    def _lora_release(self, req: _Request) -> None:
        if req.lora_idx and not req.lora_released:
            req.lora_released = True
            with self._lora_lock:
                self._lora_refs[req.lora_idx] = max(
                    0, self._lora_refs.get(req.lora_idx, 1) - 1)

    def submit(self, token_ids: list, params: SamplingParams | None = None,
               *, lora: str | None = None,
               deadline_ts: float = 0.0) -> _Request:
        self._check_alive()
        params = params or SamplingParams()
        if params.guided is not None:
            if self.speculative_k:
                raise ValueError(
                    "guided decoding and speculative decoding cannot be "
                    "combined (drafts would have to be FSM-checked per "
                    "position; build the engine with speculative_k=0)")
            if params.guided.vocab_size != self.cfg.vocab_size:
                raise ValueError(
                    f"guided FSM vocab {params.guided.vocab_size} != model "
                    f"vocab {self.cfg.vocab_size}")
        token_ids = list(token_ids)
        if not token_ids:
            raise ValueError("empty prompt: at least one token is required")
        limit = self.max_len - params.max_tokens - 1
        if limit <= 0:
            raise ValueError("max_tokens leaves no room for the prompt")
        token_ids = token_ids[-limit:]
        if self.kv_layout == "paged":
            need = self._pages_needed(len(token_ids),
                                      self._bucket(len(token_ids)),
                                      params.max_tokens)
            if need > self.num_pages - 1:  # page 0 is scratch
                raise ValueError(
                    f"request needs {need} KV pages but the pool only has "
                    f"{self.num_pages - 1}; raise num_pages or shrink "
                    f"prompt/max_tokens")
        lora_idx = 0
        if lora is not None:
            if self.lora_bank is None:
                raise ValueError("engine built without max_loras")
            # resolve + take the reference atomically w.r.t. load/unload —
            # otherwise an eviction between the check and the increment
            # could reuse the bank index for a different adapter
            with self._lora_lock:
                if lora not in self._lora_ids:
                    raise KeyError(f"lora {lora!r} not loaded "
                                   f"(loaded: {sorted(self._lora_ids)})")
                lora_idx = self._lora_ids[lora]
                self._lora_refs[lora_idx] += 1
        req = _Request(next(self._rid), token_ids, params,
                       history=list(token_ids), lora_idx=lora_idx,
                       deadline_ts=float(deadline_ts or 0.0))
        req.submitted_ts = time.time()
        self._waiting.put(req)
        self._work.set()
        return req

    def submit_prefilled(self, k=None, v=None, length: int = 0,
                         first_token: int = 0,
                         params: SamplingParams | None = None, *,
                         k_pages: list | None = None,
                         v_pages: list | None = None,
                         kv_stream=None,
                         deadline_ts: float = 0.0) -> _Request:
        """Admit a sequence whose prefill ran elsewhere (PD disaggregation).

        Three forms:
        - whole-array: k/v are [L, T, Hkv, Dh] host arrays for the prompt
          prefix (the legacy object-plane handoff);
        - page-granular: k_pages/v_pages are ordered lists of
          [L, page_size, Hkv, Dh] pages (the shm transfer plane's unit).
          On a paged engine each page is adopted into the slot pool
          directly — no whole-bucket array is ever assembled;
        - streamed: kv_stream is a kv_transfer.KVPageStream the transfer
          plane is still feeding. The slot and its pages are granted NOW
          and each page is adopted the moment it arrives — the decode
          loop keeps stepping other slots while later pages stream in,
          and the row activates on the LAST page instead of waiting for
          pull-then-submit. A transfer failure surfaces as a per-request
          error; the slot and its granted pages are reclaimed.
        """
        self._check_alive()
        params = params or SamplingParams()
        paged_form = k_pages is not None or v_pages is not None
        if kv_stream is not None:
            if paged_form or k is not None or v is not None:
                raise ValueError(
                    "pass kv_stream alone, not with k/v or k_pages/v_pages")
            P = int(kv_stream.page_size)
            if self.kv_layout == "paged" and P != self.page_size:
                raise ValueError(
                    f"streamed page size {P} != engine page_size "
                    f"{self.page_size}: prefill and decode pools must agree")
            bucket = int(kv_stream.n_pages) * P
        elif paged_form:
            if k is not None or v is not None:
                raise ValueError(
                    "pass either k/v arrays or k_pages/v_pages, not both")
            if not k_pages or not v_pages or len(k_pages) != len(v_pages):
                raise ValueError(
                    "k_pages and v_pages must be equal-length non-empty "
                    "lists of [L, page_size, Hkv, Dh] pages")
            P = k_pages[0].shape[1]
            if any(p.shape[1] != P for p in list(k_pages) + list(v_pages)):
                raise ValueError("transferred pages have mixed page sizes")
            if self.kv_layout == "paged" and P != self.page_size:
                raise ValueError(
                    f"transferred page size {P} != engine page_size "
                    f"{self.page_size}: prefill and decode pools must agree")
            bucket = len(k_pages) * P
        else:
            if k is None or v is None:
                raise ValueError(
                    "submit_prefilled needs k/v arrays, k_pages/v_pages, "
                    "or kv_stream")
            bucket = k.shape[1]
        if bucket > self.max_len:
            raise ValueError(
                f"transferred prefix bucket {bucket} exceeds engine "
                f"max_len {self.max_len}")
        if self.kv_layout == "paged":
            if bucket % self.page_size:
                raise ValueError(
                    f"transferred prefix bucket {bucket} is not a "
                    f"multiple of page_size {self.page_size}: configure the "
                    f"prefill server with min_bucket >= page_size")
            need = self._pages_needed(int(length), bucket, params.max_tokens)
            if need > self.num_pages - 1:
                raise ValueError(
                    f"request needs {need} KV pages but the pool only has "
                    f"{self.num_pages - 1}")
        if int(length) + params.max_tokens > self.max_len:
            raise ValueError(
                f"prefix length {int(length)} + max_tokens {params.max_tokens} "
                f"does not fit engine max_len {self.max_len}")
        req = _Request(next(self._rid), [], params,
                       deadline_ts=float(deadline_ts or 0.0))
        req.submitted_ts = time.time()
        if kv_stream is not None:
            req.kv_stream = kv_stream
            req.kv_pack = {"length": int(length),
                           "first_token": int(first_token)}
            # feed()/finish()/fail() wake the scheduler so a parked loop
            # adopts new pages immediately instead of on its poll tick
            kv_stream._wake = self._work.set
        elif paged_form:
            req.kv_pack = {"k_pages": list(k_pages), "v_pages": list(v_pages),
                           "length": int(length),
                           "first_token": int(first_token)}
        else:
            req.kv_pack = {"k": k, "v": v, "length": int(length),
                           "first_token": int(first_token)}
        req.generated = 1  # the transferred first token counts
        self._waiting.put(req)
        self._work.set()
        return req

    def generate(self, token_ids: list, params: SamplingParams | None = None,
                 *, lora: str | None = None) -> list:
        """Blocking: returns the generated token ids."""
        return list(self.stream(token_ids, params, lora=lora))

    def stream(self, token_ids: list, params: SamplingParams | None = None,
               *, lora: str | None = None):
        """Yields token ids as they are produced."""
        req = self.submit(token_ids, params, lora=lora)
        yield from _iter_request(req)

    def abort_request(self, rid: int) -> None:
        """Cancel an in-flight request by rid: the scheduler reclaims its
        decode slot and every granted KV page at the top of its next pass
        (one decode step, not at max_tokens), and the caller's iterator
        raises RequestCancelledError. Thread-safe; a rid that already
        finished (or never existed) is a no-op that ages out."""
        self._abort_q.put(int(rid))
        self._work.set()

    def shutdown(self):
        self._stop = True
        self._work.set()
        self._thread.join(timeout=5.0)
        self._drain_all(None)

    def _drain_all(self, error: BaseException | None):
        """Unblock every waiting caller: end-of-stream, or the failure."""
        marker = _EngineError(error) if error is not None else _SENTINEL
        for req in list(self._by_slot.values()):
            self._lora_release(req)
            req.out_queue.put(marker)
        for req in self._backlog:
            self._lora_release(req)
            req.out_queue.put(marker)
        self._backlog.clear()
        for req in self._prefilling:
            self._lora_release(req)
            req.out_queue.put(marker)
        self._prefilling.clear()
        for req in self._streaming:
            self._lora_release(req)
            req.out_queue.put(marker)
        self._streaming.clear()
        while True:
            try:
                r = self._waiting.get_nowait()
                self._lora_release(r)
                r.out_queue.put(marker)
            except queue.Empty:
                break

    # ------------------------------------------------------------- scheduler

    def _bucket(self, n: int) -> int:
        return bucket_for(n, self.buckets[0], self.max_len)

    def _pages_needed(self, prompt_len: int, bucket: int, max_tokens: int) -> int:
        """All pages this sequence will EVER touch, granted up front (no
        mid-flight allocation → no page-starvation deadlock): the prompt
        bucket plus generated positions up to prompt_len + max_tokens."""
        last_pos = min(prompt_len + max_tokens, self.max_len - 1)
        return max(bucket // self.page_size, last_pos // self.page_size + 1)

    # ---------------------------------------------------- prefix cache (paged)

    def _block_hashes(self, tokens: list) -> list:
        """Chain hashes of the prompt's FULL page_size blocks: h_i commits
        to every token before the block too, so a hit means the whole
        prefix through block i is identical."""
        import hashlib

        out = []
        h = b""
        P = self.page_size
        for i in range(len(tokens) // P):
            blk = np.asarray(tokens[i * P:(i + 1) * P], np.int32).tobytes()
            h = hashlib.sha1(h + blk).digest()
            out.append(h)
        return out

    def _reclaimable_pages(self) -> int:
        # called from stats() on arbitrary threads while the scheduler
        # mutates the cache: snapshot first, tolerate a racing resize
        for _ in range(4):
            try:
                pages = list(self._prefix_cache.values())
                break
            except RuntimeError:
                continue
        else:
            return 0
        refs = self._page_refs
        return sum(1 for p in pages if refs.get(p, 0) == 0)

    def _available_pages(self) -> int:
        n = len(self._free_pages)
        if self.enable_prefix_cache:
            n += self._reclaimable_pages()
        return n

    def _alloc_pages(self, need: int) -> list | None:
        """Take pages from the free list, evicting zero-ref cached blocks
        (LRU first) when the list runs short. None = infeasible now."""
        if need > self._available_pages():
            return None
        if need > len(self._free_pages):
            for h in list(self._prefix_cache):
                if len(self._free_pages) >= need:
                    break
                p = self._prefix_cache[h]
                if self._page_refs.get(p, 0) == 0:
                    del self._prefix_cache[h]
                    self._page_refs.pop(p, None)
                    self._page_hash.pop(p, None)
                    self._free_pages.append(p)
        return [self._free_pages.pop() for _ in range(need)]

    def _match_prefix(self, tokens: list, hashes: list) -> int:
        """Longest run of leading cached blocks usable for reuse. The block
        holding the LAST prompt token is never reused — at least one real
        token must go through prefill to produce the sampling logits."""
        usable = (len(tokens) - 1) // self.page_size
        n_pre = 0
        for i in range(min(usable, len(hashes))):
            p = self._prefix_cache.get(hashes[i])
            if p is None:
                break
            self._prefix_cache.move_to_end(hashes[i])  # LRU touch
            n_pre += 1
        return n_pre

    def _register_blocks(self, slot: int, tokens: list, hashes: list,
                         n_pre: int, priv_pages: list) -> None:
        """Make this request's freshly-computed full blocks available to
        future prompts: their pages move from private (freed on release)
        to shared (ref-counted, cached)."""
        n = len(tokens)
        shared = self._slot_shared.setdefault(slot, [])
        still_private = list(priv_pages)
        for i in range(n_pre, n // self.page_size):
            if hashes[i] in self._prefix_cache:
                continue  # someone registered it first; keep ours private
            page = priv_pages[i - n_pre]
            self._prefix_cache[hashes[i]] = page
            self._page_hash[page] = hashes[i]
            self._page_refs[page] = self._page_refs.get(page, 0) + 1
            shared.append(page)
            still_private.remove(page)
        self._slot_pages[slot] = still_private

    def _release_shared(self, slot: int) -> None:
        for p in self._slot_shared.pop(slot, ()):
            left = self._page_refs.get(p, 0) - 1
            if left <= 0:
                self._page_refs[p] = 0  # reclaimable; stays cached until
                # eviction needs the page (or a new request re-refs it)
            else:
                self._page_refs[p] = left

    def _set_row_sampling(self, slot: int, params: SamplingParams):
        self._temps = self._temps.at[slot].set(params.temperature)
        self._topks = self._topks.at[slot].set(params.top_k)
        if params.guided is not None:
            self._guided_fsm[slot] = params.guided
            # the first token was already sampled under the START state's
            # mask (prefill path); its state advance happens in _emit
            self._guided_state[slot] = params.guided.start

    def _sample_first(self, req: _Request, logits, sub):
        """First-token sampling after a prefill, honoring the request's
        guided FSM start state (decode steps apply per-slot biases)."""
        if req.params.guided is not None:
            from ray_tpu.llm import guided as _g

            logits = logits + jnp.asarray(
                _g.bias_row(req.params.guided, req.params.guided.start,
                            remaining=req.params.max_tokens))
        return decoding.sample(logits[None, :], sub,
                               req.params.temperature, req.params.top_k)

    def _grant_pages(self, need: int) -> list | None:
        """Grant `need` pool pages (evicting zero-ref cached blocks when
        the prefix cache is on), or None when infeasible right now."""
        if self.enable_prefix_cache:
            return self._alloc_pages(need)
        if need > len(self._free_pages):
            return None
        return [self._free_pages.pop() for _ in range(need)]

    def _bind_slot(self, req: _Request, slot: int,
                   length: int | None = None) -> None:
        """The slot-activation bookkeeping shared by every admission path:
        device sampling params, LoRA row, request registry. `length` is
        the row's device length at activation — mirrored host-side so the
        ragged decode step can bound its page sweep without a readback."""
        if length is not None:
            req.length0 = int(length)
        self._set_row_sampling(slot, req.params)
        if self.lora_bank is not None:
            self._slot_lora = self._slot_lora.at[slot].set(req.lora_idx)
        self._by_slot[slot] = req
        req.admitted_ts = time.time()
        if self._phase_admit is not None and req.submitted_ts:
            # decode-slot admission wait: submit → slot bind, covering the
            # waiting queue, page-pressure backlog, and (PD) the page pull
            self._phase_admit.observe(req.admitted_ts - req.submitted_ts)

    def _insert(self, req: _Request, slot: int, kv, length: int, first_token):
        """Layout-dispatching sequence insertion. Returns False when the
        paged pool can't host the sequence right now (caller backlogs)."""
        if self.kv_layout == "paged":
            bucket = kv["k"].shape[1]
            need = self._pages_needed(length, bucket, req.params.max_tokens)
            pages = self._grant_pages(need)
            if pages is None:
                return False
            self._slot_pages[slot] = pages
            padded_pages = np.zeros((self.max_pages_per_seq,), np.int32)
            padded_pages[:need] = pages
            self.state = self._dp.insert_sequence_paged(
                self.state, slot, kv, jnp.int32(length),
                jnp.asarray(first_token, jnp.int32),
                jnp.asarray(padded_pages), self.cfg)
        else:
            self.state = decoding.insert_sequence(
                self.state, slot, kv, jnp.int32(length),
                jnp.asarray(first_token, jnp.int32), self.cfg)
        self._bind_slot(req, slot, length)
        return True

    def _insert_transferred(self, req: _Request, slot: int) -> bool:
        """PD admission: insert a kv_pack that arrived from a prefill
        server. Page-granular packs adopt pages straight into the paged
        pool; whole-array packs (or pages landing on a slot-layout engine)
        take the legacy _insert path. Returns False when the pool can't
        host the sequence right now (caller backlogs)."""
        pack = req.kv_pack
        if "k_pages" in pack:
            if self.kv_layout == "paged":
                return self._insert_pages(req, slot, pack)
            # slot layout has no page pool: stitch the bucket back together
            # (host copy — the paged decode pool is the production PD path)
            kv = {"k": np.concatenate([np.asarray(p)
                                       for p in pack["k_pages"]], axis=1),
                  "v": np.concatenate([np.asarray(p)
                                       for p in pack["v_pages"]], axis=1)}
        else:
            kv = {"k": pack["k"], "v": pack["v"]}
        ktmpl = self.state["k" if self.kv_layout == "slot" else "kp"]
        kv = {"k": jnp.asarray(kv["k"], ktmpl.dtype),
              "v": jnp.asarray(kv["v"], ktmpl.dtype)}
        return self._insert(req, slot, kv, pack["length"],
                            pack["first_token"])

    def _insert_pages(self, req: _Request, slot: int, pack: dict) -> bool:
        """Adopt transferred KV pages directly into the paged pool: one
        write_kv_pages scatter per page (a single [L, P, Hkv, Dh] compile
        serves every transfer), then activate the row. The whole-bucket
        [L, T, Hkv, Dh] array is never materialized on this path."""
        k_pages, v_pages = pack["k_pages"], pack["v_pages"]
        P = self.page_size
        length = pack["length"]
        need = self._pages_needed(length, len(k_pages) * P,
                                  req.params.max_tokens)
        pages = self._grant_pages(need)
        if pages is None:
            return False
        self._slot_pages[slot] = pages
        dt = self.state["kp"].dtype
        # prefix pages land in block-table order; the tail of `pages`
        # (granted up front, like every admission) hosts the generation
        for pid, kp, vp in zip(pages, k_pages, v_pages):
            self.state = self._dp.write_kv_pages(
                self.state,
                {"k": jnp.asarray(np.asarray(kp), dt),
                 "v": jnp.asarray(np.asarray(vp), dt)},
                jnp.asarray(np.asarray([pid], np.int32)))
        block_row = np.zeros((self.max_pages_per_seq,), np.int32)
        block_row[:need] = pages
        self.state = self._dp.activate_slot(
            self.state, slot, jnp.asarray(block_row), jnp.int32(length),
            jnp.asarray(pack["first_token"], jnp.int32))
        self._bind_slot(req, slot, length)
        return True

    # ------------------------------------------------- streamed admission

    def _admit_stream(self, req: _Request, slot: int) -> bool:
        """Streamed PD admission (tentpole: overlap transfer with decode):
        grant the slot and every page the sequence will EVER need now;
        pages are written into the pool as the transfer plane delivers
        them (_drain_streams) and the row activates on the LAST page —
        the decode loop keeps stepping other slots in between. Returns
        False when the page pool can't host the sequence yet (caller
        backlogs; arrived pages keep buffering host-side in the stream)."""
        st = req.kv_stream
        if self.kv_layout == "paged":
            need = self._pages_needed(req.kv_pack["length"],
                                      st.n_pages * self.page_size,
                                      req.params.max_tokens)
            pages = self._grant_pages(need)
            if pages is None:
                return False
            self._slot_pages[slot] = pages
        req.slot = slot
        req.pf_done = 0
        self._streaming.append(req)
        return True

    def _granted_block_row(self, slot: int) -> np.ndarray:
        """Zero-padded block-table row over the slot's granted pages —
        the activation layout shared by every page-granular admission."""
        granted = self._slot_pages[slot]
        row = np.zeros((self.max_pages_per_seq,), np.int32)
        row[:len(granted)] = granted
        return row

    def _fail_stream(self, req: _Request, err) -> None:
        """Reclaim a streamed admission whose transfer died: the slot was
        granted but never activated, so only host bookkeeping unwinds —
        a per-REQUEST error; every other request keeps serving."""
        if req in self._streaming:
            self._streaming.remove(req)
        if self.kv_layout == "paged":
            self._free_pages.extend(self._slot_pages.pop(req.slot, ()))
        self._free.append(req.slot)
        self._lora_release(req)
        if not isinstance(err, BaseException):
            err = RuntimeError(str(err))
        req.out_queue.put(_RequestError(err))

    def _drain_streams(self) -> bool:
        """Adopt every page that arrived since the last scheduler pass:
        page-granular write_kv_pages into the slot's granted pages, slot
        activation once all pages landed. Runs between decode steps, so
        running requests keep emitting while transfers stream in."""
        progressed = False
        for req in list(self._streaming):
            st = req.kv_stream
            err = st.take_error()
            if err is not None:
                self._fail_stream(req, err)
                progressed = True
                continue
            try:
                ready = st.take_ready()
                if ready:
                    progressed = True
                    if (self.kv_layout == "paged" and req.pf_done == 0
                            and len(ready) == st.n_pages):
                        # the whole transfer beat the scheduler here (fast
                        # sender / short prompt — the common case): write
                        # + activate in the ONE dispatch the non-streamed
                        # admission pays, instead of write_kv_pages +
                        # activate_slot
                        ready.sort(key=lambda t: t[0])
                        dt = self.state["kp"].dtype
                        block_row = self._granted_block_row(req.slot)
                        kv = {"k": jnp.asarray(np.concatenate(
                                  [np.asarray(t[1]) for t in ready],
                                  axis=1), dt),
                              "v": jnp.asarray(np.concatenate(
                                  [np.asarray(t[2]) for t in ready],
                                  axis=1), dt)}
                        length = req.kv_pack["length"]
                        self.state = self._dp.insert_sequence_paged(
                            self.state, req.slot, kv, jnp.int32(length),
                            jnp.asarray(req.kv_pack["first_token"],
                                        jnp.int32),
                            jnp.asarray(block_row), self.cfg)
                        self._streaming.remove(req)
                        self._bind_slot(req, req.slot, length)
                        continue
                    if self.kv_layout == "paged":
                        pages = self._slot_pages[req.slot]
                        dt = self.state["kp"].dtype
                        # consecutive arrivals collapse into ONE scatter
                        # per run (pages stream in order, so a whole
                        # prefetch window is usually one write); run
                        # lengths are bounded by the prefetch depth, so
                        # compile count stays small
                        ready.sort(key=lambda t: t[0])
                        runs: list = []
                        for i, kp, vp in ready:
                            if runs and runs[-1][0] + len(runs[-1][1]) == i:
                                runs[-1][1].append(kp)
                                runs[-1][2].append(vp)
                            else:
                                runs.append((i, [kp], [vp]))
                        for start, kps, vps in runs:
                            ids = pages[start:start + len(kps)]
                            kcat = np.concatenate(
                                [np.asarray(p) for p in kps], axis=1)
                            vcat = np.concatenate(
                                [np.asarray(p) for p in vps], axis=1)
                            self.state = self._dp.write_kv_pages(
                                self.state,
                                {"k": jnp.asarray(kcat, dt),
                                 "v": jnp.asarray(vcat, dt)},
                                jnp.asarray(np.asarray(ids, np.int32)))
                            req.pf_done += len(kps)
                    else:
                        # slot layout has no page pool: buffer, then take
                        # the stitch fallback at completion
                        kps = req.kv_pack.setdefault(
                            "k_pages", [None] * st.n_pages)
                        vps = req.kv_pack.setdefault(
                            "v_pages", [None] * st.n_pages)
                        for i, kp, vp in ready:
                            kps[i], vps[i] = kp, vp
                            req.pf_done += 1
                if req.pf_done >= st.n_pages:
                    self._streaming.remove(req)
                    if self.kv_layout == "paged":
                        length = req.kv_pack["length"]
                        block_row = self._granted_block_row(req.slot)
                        self.state = self._dp.activate_slot(
                            self.state, req.slot, jnp.asarray(block_row),
                            jnp.int32(length),
                            jnp.asarray(req.kv_pack["first_token"],
                                        jnp.int32))
                        self._bind_slot(req, req.slot, length)
                    else:
                        req.kv_stream = None
                        self._insert_transferred(req, req.slot)
                    progressed = True
            except Exception as e:  # noqa: BLE001 — a malformed page must
                # fail THIS request, not the scheduler (engine death would
                # drop every other in-flight request)
                self._fail_stream(req, e)
                progressed = True
        return progressed

    def _pages_bound(self) -> int:
        """Power-of-two bound on the batch's LIVE page span (host mirror
        of the device lengths): the ragged decode step sweeps only this
        many block-table columns, so attention FLOPs/HBM traffic track
        the longest RESIDENT row instead of max_len, and compile count
        stays O(log max_pages)."""
        P = self.page_size
        need = 1
        for req in self._by_slot.values():
            pos = req.length0 + max(0, req.generated - 1)
            need = max(need, pos // P + 1)
        b = 1
        while b < need:
            b *= 2
        return min(b, self.max_pages_per_seq)

    def _next_waiting(self):
        if self._backlog:
            return self._backlog.pop(0)
        try:
            return self._waiting.get_nowait()
        except queue.Empty:
            return None

    def _admit(self):
        admitted = 0
        while self._free and admitted < self.max_prefills_per_step:
            req = self._next_waiting()
            if req is None:
                return
            if self._cancel_at_admission(req):
                continue
            slot = self._free.pop()
            req.slot = slot
            if req.kv_pack is not None:
                if req.generated >= req.params.max_tokens:
                    # budget already spent by the transferred first token
                    self._free.append(slot)
                    self._lora_release(req)
                    req.out_queue.put(_SENTINEL)
                    continue
                if req.kv_stream is not None:
                    # streamed PD admission: slot + pages granted now,
                    # pages adopted as they arrive (_drain_streams). Pure
                    # bookkeeping — no prefill compute — so it does NOT
                    # count against the per-step prefill budget: a burst
                    # of transfers grabs every free slot in one round
                    if not self._admit_stream(req, slot):
                        self._free.append(slot)
                        self._backlog.append(req)
                        return  # page pressure: stop admitting this round
                    continue
                # PD path: KV arrived from a prefill server (shm pages or
                # legacy whole arrays)
                if not self._insert_transferred(req, slot):
                    self._free.append(slot)
                    self._backlog.append(req)
                    return  # page pressure: stop admitting this round
                admitted += 1
                continue
            if self.kv_layout == "paged" and (self.enable_prefix_cache
                                              or self.prefill_chunk):
                first_id = self._admit_cached(req, slot)
                if first_id is None:
                    self._free.append(slot)
                    self._backlog.append(req)
                    return  # page pressure: stop admitting this round
                admitted += 1
                if first_id != -1:  # -1 = staged for chunked prefill
                    self._emit(req, first_id)
                continue
            n = len(req.tokens)
            bucket = self._bucket(n)
            if self.kv_layout == "paged":
                # cheap feasibility check BEFORE paying for the prefill
                if (self._pages_needed(n, bucket, req.params.max_tokens)
                        > len(self._free_pages)):
                    self._free.append(slot)
                    self._backlog.append(req)
                    return
            padded = np.zeros((1, bucket), np.int32)
            padded[0, :n] = req.tokens
            if self.lora_bank is not None:
                logits, kv = decoding.prefill(
                    self.params, jnp.asarray(padded), jnp.int32(n), self.cfg,
                    self.lora_bank, jnp.int32(req.lora_idx))
            else:
                logits, kv = decoding.prefill(
                    self.params, jnp.asarray(padded), jnp.int32(n), self.cfg)
            self.key, sub = jax.random.split(self.key)
            first = self._sample_first(req, logits, sub)
            first_id = int(first[0])
            if not self._insert(req, slot, kv, n, first[0]):
                self._free.append(slot)
                self._backlog.append(req)
                return
            admitted += 1
            self._emit(req, first_id)

    def _admit_cached(self, req: _Request, slot: int):
        """Paged admission with hash-block prefix reuse. Returns the first
        sampled token id, or None when the page pool can't host the
        sequence right now (caller backlogs)."""
        tokens = req.tokens
        n = len(tokens)
        P = self.page_size
        hashes = self._block_hashes(tokens)
        n_pre = self._match_prefix(tokens, hashes)
        # shrink the reused prefix if suffix-bucket roundup would overflow
        # the static block table
        while n_pre > 0 and (n_pre + self._bucket(n - n_pre * P) // P
                             > self.max_pages_per_seq):
            n_pre -= 1
        pre_len = n_pre * P
        suffix = tokens[pre_len:]
        suf_bucket = self._bucket(len(suffix))
        last_pos = min(n + req.params.max_tokens, self.max_len - 1)
        total_pages = max(n_pre + suf_bucket // P, last_pos // P + 1)
        # pin the matched pages BEFORE allocating: _alloc_pages evicts
        # zero-ref cached blocks, and the ones we just matched must not be
        # among them
        pre_pages = [self._prefix_cache[hashes[i]] for i in range(n_pre)]
        chunk = self.prefill_chunk
        staged = chunk is not None and len(suffix) > chunk
        if staged:
            # long admission: stage for chunk-at-a-time prefill interleaved
            # with decode steps. Page need accounts for per-chunk bucket
            # spans (the final partial chunk pads to its own bucket). The
            # inflated count is committed ONLY if staging goes ahead — the
            # whole-prompt fallback must keep its own (table-fitting) need.
            rem = len(suffix) % chunk
            tail_bucket = self._bucket(rem) if rem else 0
            span = pre_len + (len(suffix) - rem) + tail_bucket
            staged_pages = max(span // P, total_pages)
            if staged_pages > self.max_pages_per_seq:
                staged = False  # bucket roundup overflow: whole-prompt path
            else:
                total_pages = staged_pages
        # pin matched blocks BEFORE allocating (eviction must not take them)
        for p in pre_pages:
            self._page_refs[p] = self._page_refs.get(p, 0) + 1
        priv = self._alloc_pages(total_pages - n_pre)
        if priv is None:
            for p in pre_pages:  # unpin; the request is backlogged
                self._page_refs[p] = self._page_refs.get(p, 1) - 1
            return None
        self._slot_shared[slot] = list(pre_pages)
        if self.enable_prefix_cache:
            if n_pre:
                self.prefix_hits += 1
                self.prefix_tokens_reused += pre_len
            else:
                self.prefix_misses += 1
        if staged:
            req.slot = slot
            req.pf_done = pre_len
            req.pf_pages = pre_pages + priv
            req.pf_hashes = hashes
            self._slot_pages[slot] = list(priv)
            self._prefilling.append(req)
            return -1  # staged: no first token yet
        padded = np.zeros((1, suf_bucket), np.int32)
        padded[0, :len(suffix)] = suffix
        if n_pre:
            # pad the shared-page id list to a power of two so compile
            # count stays O(log(max_pages) × buckets); tail ids point at
            # scratch page 0, masked out by prefix_len
            npad = 1
            while npad < n_pre:
                npad *= 2
            padded_ids = np.zeros((npad,), np.int32)
            padded_ids[:n_pre] = pre_pages
            k_pre, v_pre = self._dp.gather_prefix_pages(
                self.state["kp"], self.state["vp"], jnp.asarray(padded_ids))
            logits, kv = self._dp.prefill_with_prefix(
                self.params, jnp.asarray(padded), k_pre, v_pre,
                jnp.int32(pre_len), jnp.int32(len(suffix)), self.cfg)
        else:
            logits, kv = decoding.prefill(
                self.params, jnp.asarray(padded), jnp.int32(len(suffix)),
                self.cfg)
        self.key, sub = jax.random.split(self.key)
        first = self._sample_first(req, logits, sub)
        block_row = np.zeros((self.max_pages_per_seq,), np.int32)
        block_row[:n_pre] = pre_pages
        block_row[n_pre:n_pre + len(priv)] = priv
        suf_pages = np.asarray(priv[:suf_bucket // P], np.int32)
        self.state = self._dp.insert_sequence_paged_prefix(
            self.state, slot, kv, jnp.asarray(suf_pages),
            jnp.asarray(block_row), jnp.int32(n), first[0], self.cfg)
        self._bind_slot(req, slot, n)
        if self.enable_prefix_cache:
            self._register_blocks(slot, tokens, hashes, n_pre, priv)
        return int(first[0])

    def _prefill_step(self):
        """Run ONE chunk of the oldest staged prefill (called between
        decode steps, so running requests keep emitting during a long
        admission — reference capability: vLLM chunked prefill)."""
        req = self._prefilling[0]
        tokens = req.tokens
        P = self.page_size
        done = req.pf_done
        chunk_toks = tokens[done:done + self.prefill_chunk]
        is_last = done + len(chunk_toks) >= len(tokens)
        bucket = self._bucket(len(chunk_toks))
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :len(chunk_toks)] = chunk_toks
        chunk_pages = np.asarray(
            req.pf_pages[done // P:(done + bucket) // P], np.int32)
        if done == 0:
            logits, kv = decoding.prefill(
                self.params, jnp.asarray(padded),
                jnp.int32(len(chunk_toks)), self.cfg)
        else:
            npad = 1
            while npad < done // P:
                npad *= 2
            padded_ids = np.zeros((npad,), np.int32)
            padded_ids[:done // P] = req.pf_pages[:done // P]
            k_pre, v_pre = self._dp.gather_prefix_pages(
                self.state["kp"], self.state["vp"], jnp.asarray(padded_ids))
            logits, kv = self._dp.prefill_with_prefix(
                self.params, jnp.asarray(padded), k_pre, v_pre,
                jnp.int32(done), jnp.int32(len(chunk_toks)), self.cfg)
        self.state = self._dp.write_kv_pages(self.state, kv,
                                             jnp.asarray(chunk_pages))
        req.pf_done = done + len(chunk_toks)
        self.prefill_chunks_run += 1
        if not is_last:
            return
        self._prefilling.pop(0)
        n = len(tokens)
        self.key, sub = jax.random.split(self.key)
        first = self._sample_first(req, logits, sub)
        block_row = np.zeros((self.max_pages_per_seq,), np.int32)
        block_row[:len(req.pf_pages)] = req.pf_pages
        self.state = self._dp.activate_slot(
            self.state, req.slot, jnp.asarray(block_row), jnp.int32(n),
            first[0])
        self._bind_slot(req, req.slot, n)
        if self.enable_prefix_cache:
            n_shared = len(self._slot_shared.get(req.slot, ()))
            self._register_blocks(req.slot, tokens, req.pf_hashes, n_shared,
                                  self._slot_pages[req.slot])
        self._emit(req, int(first[0]))

    def _index_ngram_at(self, req: _Request, end: int):
        """Record the n-gram ENDING at history position end-1; its
        continuation starts at `end`."""
        n = self.ngram_size
        if end < n:
            return
        key = tuple(req.history[end - n:end])
        latest, _prev = req.ngram_index.get(key, (None, None))
        req.ngram_index[key] = (end, latest)

    def _propose_drafts(self, req: _Request) -> list:
        """Prompt-lookup drafts: continuation after the most recent earlier
        occurrence of the trailing n-gram in the request's own history.
        O(1) via the incremental index. No match → repeat the last token
        (a cheap guess; a wrong draft costs nothing beyond the verify
        FLOPs the step spends anyway)."""
        k = self.speculative_k
        h = req.history
        n = self.ngram_size
        if req.ngram_index is None:  # first proposal: index the prompt
            req.ngram_index = {}
            for end in range(n, len(h) + 1):
                self._index_ngram_at(req, end)
        if len(h) > n:
            key = tuple(h[-n:])
            latest, prev = req.ngram_index.get(key, (None, None))
            # `latest` is the trailing occurrence itself (continuation =
            # end of history); the draft source is the one before it
            cs = prev if latest == len(h) else latest
            if cs is not None:
                cont = h[cs:cs + k]
                if cont:
                    return (cont + [h[-1]] * (k - len(cont)))[:k]
        return [h[-1] if h else 0] * k

    def _speculative_step(self):
        """One multi-token decode: verify n-gram drafts for every active
        row, emit the accepted prefix plus one corrected token."""
        K = self.speculative_k + 1
        S = self.max_slots
        draft = np.zeros((S, self.speculative_k), np.int32)
        for slot, req in self._by_slot.items():
            draft[slot] = self._propose_drafts(req)
        self.state, logits = decoding.verify_step(
            self.params, self.state, jnp.asarray(draft), self.cfg, K)
        self.key, sub = jax.random.split(self.key)
        V = logits.shape[-1]
        toks = decoding.sample_per_row(
            logits.reshape(S * K, V), sub,
            jnp.repeat(self._temps, K), jnp.repeat(self._topks, K))
        toks_host = np.asarray(toks).reshape(S, K)
        counts = np.zeros((S,), np.int32)
        last = np.zeros((S,), np.int32)
        self.spec_steps += 1
        self.spec_slot_steps += len(self._by_slot)
        for slot, req in list(self._by_slot.items()):
            a = 0
            while (a < self.speculative_k
                   and toks_host[slot, a] == draft[slot, a]):
                a += 1
            self.spec_drafted += self.speculative_k
            self.spec_accepted += a
            counts[slot] = a + 1
            last[slot] = toks_host[slot, a]
            for j in range(a + 1):
                self._emit(req, int(toks_host[slot, j]))
                if slot not in self._by_slot:
                    break  # finished (EOS/max_tokens) mid-burst
        # release (inside _emit) precedes this commit: released rows are
        # inactive, so their length/last_token stay reset
        self.state = decoding.commit_accepted(
            self.state, jnp.asarray(last), jnp.asarray(counts))

    def _emit(self, req: _Request, token_id: int):
        if self._phase_gap is not None:
            now = time.time()
            last = req.last_emit_ts or req.admitted_ts
            if last:
                self._phase_gap.observe(now - last)
            req.last_emit_ts = now
        req.generated += 1
        req.history.append(token_id)
        if self.speculative_k and req.ngram_index is not None:
            self._index_ngram_at(req, len(req.history))
        fsm = self._guided_fsm.get(req.slot)
        if fsm is not None:
            self._guided_state[req.slot] = fsm.step(
                self._guided_state[req.slot], token_id)
        stops = set(req.params.stop_token_ids)
        eos = token_id in stops
        if not eos:
            req.out_queue.put(token_id)
        if eos or req.generated >= req.params.max_tokens:
            self._release_active(req)
            req.out_queue.put(_SENTINEL)

    def _release_active(self, req: _Request) -> None:
        """Return an ACTIVE row's slot, pages, LoRA ref and guided-FSM
        state to their pools — the one release path shared by normal
        completion (_emit) and mid-stream abort (_abort_one)."""
        if self.kv_layout == "paged":
            self.state = self._dp.release_slot_paged(self.state, req.slot)
            self._free_pages.extend(self._slot_pages.pop(req.slot, ()))
            if self.enable_prefix_cache:
                self._release_shared(req.slot)
        else:
            self.state = decoding.release_slot(self.state, req.slot)
        if self.lora_bank is not None:
            self._slot_lora = self._slot_lora.at[req.slot].set(0)
        self._lora_release(req)
        self._guided_fsm.pop(req.slot, None)
        self._guided_state.pop(req.slot, None)
        self._free.append(req.slot)
        del self._by_slot[req.slot]

    # -------------------------------------------------- cancellation plane

    def _count_cancel(self) -> None:
        self.aborts += 1
        try:
            from ray_tpu.serve import request_context as _rc

            _rc.count_cancellation("engine")
        except Exception as e:  # pragma: no cover — metrics must never
            # kill the scheduler (every in-flight request would die)
            logger.debug("cancellation metric failed: %r", e)

    def _abort_one(self, req: _Request, err: BaseException) -> bool:
        """Reclaim one request wherever it currently lives (active slot,
        streamed admission, staged chunked prefill, page-pressure backlog)
        and surface `err` to its caller. Scheduler thread only. Returns
        False when the request is in none of the searchable registries
        (still in _waiting, or already finished)."""
        if req.slot >= 0 and self._by_slot.get(req.slot) is req:
            self._release_active(req)
        elif req in self._streaming:
            # _fail_stream reclaims + puts its own _RequestError
            self._fail_stream(req, err)
            self._count_cancel()
            return True
        elif req in self._prefilling:
            self._prefilling.remove(req)
            if self.kv_layout == "paged":
                self._free_pages.extend(self._slot_pages.pop(req.slot, ()))
                self._release_shared(req.slot)
            self._free.append(req.slot)
            self._lora_release(req)
        elif req in self._backlog:
            self._backlog.remove(req)
            self._lora_release(req)
        else:
            return False
        req.out_queue.put(_RequestError(err))
        self._count_cancel()
        return True

    def _apply_aborts(self) -> None:
        """Drain abort_request() rids and reclaim their rows. Rids not yet
        admitted stay pending so _admit cancels them at pop time; stale
        ones (request already finished) age out after 120 s."""
        now = time.monotonic()
        while True:
            try:
                self._abort_pending.setdefault(self._abort_q.get_nowait(),
                                               now)
            except queue.Empty:
                break
        if not self._abort_pending:
            return
        for req in (list(self._by_slot.values()) + list(self._streaming)
                    + list(self._prefilling) + list(self._backlog)):
            if req.rid in self._abort_pending and self._abort_one(
                    req, RequestCancelledError(
                        f"request {req.rid} cancelled")):
                del self._abort_pending[req.rid]
        for rid, t in list(self._abort_pending.items()):
            if now - t > 120.0:
                del self._abort_pending[rid]

    def _expire_deadlines(self) -> None:
        """Abort every admitted request whose deadline passed — between
        decode steps, so an expired row never costs another step. Requests
        still in _waiting are checked at admission instead."""
        now = time.time()
        for reqs in (self._by_slot.values(), self._streaming,
                     self._prefilling, self._backlog):
            for req in list(reqs):
                if req.deadline_ts and now > req.deadline_ts:
                    self._abort_one(req, DeadlineExceededError(
                        f"request {req.rid} deadline exceeded "
                        f"({now - req.deadline_ts:.3f}s past)"))
                    self._abort_pending.pop(req.rid, None)

    def _cancel_at_admission(self, req: _Request) -> bool:
        """Refuse a popped waiting-queue request that was cancelled or
        whose queue-wait already spent its deadline budget — before any
        prefill compute or page grant."""
        if self._abort_pending.pop(req.rid, None) is not None:
            err: BaseException = RequestCancelledError(
                f"request {req.rid} cancelled before admission")
        elif req.deadline_ts and time.time() > req.deadline_ts:
            err = DeadlineExceededError(
                f"request {req.rid} deadline expired during queue wait")
        else:
            return False
        self._lora_release(req)
        req.out_queue.put(_RequestError(err))
        self._count_cancel()
        return True

    def _loop(self):
        try:
            self._loop_inner()
        except BaseException as e:  # noqa: BLE001 — engine death must unblock callers
            self._error = e
            self._drain_all(e)
            raise

    def _loop_inner(self):
        while not self._stop:
            # cancellation + deadline sweep first: an aborted/expired row's
            # slot and pages are back in the pool before this pass admits
            # or steps anything (reclaim within one decode step)
            self._apply_aborts()
            self._expire_deadlines()
            if (not self._by_slot and self._waiting.empty()
                    and not self._backlog and not self._prefilling
                    and not self._streaming):
                self._work.wait(timeout=0.1)
                self._work.clear()
                continue
            self._admit()
            stream_progress = (self._drain_streams() if self._streaming
                               else False)
            if self._prefilling:
                # one chunk per iteration: decode below keeps running
                # requests emitting while a long prompt streams in
                self._prefill_step()
            if not self._by_slot:
                if self._streaming and not stream_progress:
                    # nothing decodable and no new pages yet: park until
                    # the transfer plane's feed() wakes us
                    self._work.wait(timeout=0.005)
                    self._work.clear()
                continue
            if self.speculative_k:
                self._speculative_step()
                continue
            t_step = time.perf_counter()
            if self.kv_layout == "paged":
                if self.attn_impl == "ragged":
                    self.state, logits = self._dp.decode_step_paged_ragged(
                        self.params, self.state, self.cfg,
                        self._pages_bound(), self._ragged_kernel)
                else:
                    self.state, logits = self._dp.decode_step_paged(
                        self.params, self.state, self.cfg)
            elif self.lora_bank is not None:
                self.state, logits = decoding.decode_step(
                    self.params, self.state, self.cfg,
                    self.lora_bank, self._slot_lora)
            else:
                self.state, logits = decoding.decode_step(
                    self.params, self.state, self.cfg)
            self.key, sub = jax.random.split(self.key)
            if self._guided_fsm:
                # per-slot FSM masks as an additive bias; the sampling math
                # itself stays in the one jitted sample_per_row program.
                # `remaining` triggers the budget-aware closing mask so an
                # unbounded pattern completes before max_tokens.
                from ray_tpu.llm import guided as _g

                bias = np.zeros(logits.shape, np.float32)
                for slot, fsm in self._guided_fsm.items():
                    r = self._by_slot[slot]
                    bias[slot] = _g.bias_row(
                        fsm, self._guided_state[slot],
                        remaining=r.params.max_tokens - r.generated)
                logits = logits + jnp.asarray(bias)
            # sampling params live on device, updated only at admission
            toks = decoding.sample_per_row(logits, sub, self._temps, self._topks)
            self.state = decoding.commit_tokens(self.state, toks)
            toks_host = np.asarray(toks)
            self.decode_steps += 1
            self.decode_slot_steps += len(self._by_slot)
            if self._step_obs is not None:
                # device step + sampling sync: the ragged-vs-gather
                # attribution surface (LLM_BENCH decode_step row)
                self._step_obs.observe(time.perf_counter() - t_step)
            for slot, req in list(self._by_slot.items()):
                self._emit(req, int(toks_host[slot]))

    # ---------------------------------------------------------------- stats

    def stats(self) -> dict:
        out = {"free_slots": len(self._free), "active": len(self._by_slot),
               "waiting": self._waiting.qsize() + len(self._backlog),
               "streaming": len(self._streaming),
               "max_slots": self.max_slots, "buckets": list(self.buckets),
               "kv_layout": self.kv_layout, "attn_impl": self.attn_impl,
               "decode_steps": self.decode_steps,
               "aborts": self.aborts,
               "decode_occupancy": (self.decode_slot_steps
                                    / self.decode_steps
                                    if self.decode_steps else 0.0)}
        if self.speculative_k:
            drafted = self.spec_drafted
            out["speculative"] = {
                "k": self.speculative_k, "steps": self.spec_steps,
                "drafted": drafted, "accepted": self.spec_accepted,
                "acceptance_rate": (self.spec_accepted / drafted
                                    if drafted else 0.0),
                # per-SEQUENCE advance per verify step: each active slot
                # emits (accepted + 1) tokens per step
                "tokens_per_step": ((self.spec_accepted
                                     + self.spec_slot_steps)
                                    / self.spec_slot_steps
                                    if self.spec_slot_steps else 0.0),
            }
        if self.kv_layout == "paged":
            out["free_pages"] = len(self._free_pages)
            out["num_pages"] = self.num_pages
            out["page_size"] = self.page_size
            if self.prefill_chunk:
                out["prefill_chunk"] = self.prefill_chunk
                out["prefill_chunks_run"] = self.prefill_chunks_run
                out["prefilling"] = len(self._prefilling)
            if self.enable_prefix_cache:
                hits, misses = self.prefix_hits, self.prefix_misses
                out["prefix_cache"] = {
                    "hits": hits, "misses": misses,
                    "hit_rate": hits / (hits + misses) if hits + misses else 0.0,
                    "tokens_reused": self.prefix_tokens_reused,
                    "cached_blocks": len(self._prefix_cache),
                    "reclaimable_pages": self._reclaimable_pages(),
                }
        return out
