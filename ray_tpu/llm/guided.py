"""Guided (constrained) decoding: finite-state token masks.

Reference capability: ray.llm passes ``guided_decoding`` params
(choice / regex / json / grammar) through to vLLM's structured-output
machinery (llm/_internal/batch/stages/vllm_engine_stage.py:278, which
builds ``vllm.sampling_params.GuidedDecodingParams``). This framework owns
its engine, so the constraint machinery lives here.

TPU-first design: a guided request carries a finite-state machine over
TOKEN IDS — ``masks[S, V]`` (allowed tokens per state) and
``trans[S, V]`` (next state per token). Each decode step the engine adds
a per-slot ``-inf`` bias for disallowed tokens before sampling; the FSM
state advance is a host-side table lookup on the token that was emitted
anyway. The bias tensor is the only extra device traffic (slots × vocab
per step) and the sampling math stays inside the existing jitted
``sample_per_row`` — no data-dependent control flow enters the graph.

Builders:

- :meth:`GuidedFSM.from_choices` — output must be exactly one of N token
  sequences (the ``guided_choice`` feature): a token trie whose terminal
  state admits only EOS.
- :meth:`GuidedFSM.from_token_sets` — positional template: step i must
  draw from ``sets[i]`` (digits-only fields, enum slots, fixed-layout
  records), then EOS.
"""

from __future__ import annotations

import dataclasses

import numpy as np

NEG = np.float32(-1e9)


@dataclasses.dataclass
class GuidedFSM:
    """masks[S, V] bool (True = allowed), trans[S, V] int32, start state.

    ``eos_id`` (when ≥ 0) enables BUDGET-AWARE closing: per-state
    distance-to-accept is precomputed, and once a request's remaining
    max_tokens only just covers that distance the engine switches to a
    closing mask that admits only budget-decreasing tokens — an unbounded
    ``[a-z]+`` can then never overrun max_tokens mid-pattern."""

    masks: np.ndarray
    trans: np.ndarray
    start: int = 0
    eos_id: int = -1

    def __post_init__(self):
        if self.masks.shape != self.trans.shape:
            raise ValueError(
                f"masks {self.masks.shape} / trans {self.trans.shape} "
                "shape mismatch")
        if not (0 <= self.start < self.masks.shape[0]):
            raise ValueError(f"start state {self.start} out of range")
        # precomputed additive biases [S, V]: the decode hot loop indexes a
        # row per step instead of running a full-vocab np.where per slot
        self._biases = np.where(self.masks, np.float32(0.0), NEG)
        # distance-to-accept is computed LAZILY: a guided_choice request
        # builds a fresh FSM per request and (with max_tokens bumped past
        # the longest choice) never consults it — paying O(S*V) setup
        # there buys nothing
        self._dist: np.ndarray | None = None

    @property
    def dist(self) -> np.ndarray:
        """Per-state minimum tokens (excl. eos) to reach an accepting
        state; int32-max where acceptance is unreachable."""
        self._ensure_closing()
        return self._dist

    def _ensure_closing(self) -> None:
        if self._dist is not None:
            return
        S, V = self.masks.shape
        dist = np.full((S,), np.iinfo(np.int32).max, np.int64)
        if 0 <= self.eos_id < V:
            # reverse BFS from accepting states (eos admitted there)
            dist[self.masks[:, self.eos_id]] = 0
            frontier = list(np.nonzero(dist == 0)[0])
            radj: dict = {}
            for s in range(S):
                for t in np.nonzero(self.masks[s])[0]:
                    if t != self.eos_id:
                        radj.setdefault(int(self.trans[s, t]), []).append(s)
            d = 0
            while frontier:
                d += 1
                nxt = []
                for tgt in frontier:
                    for s in radj.get(int(tgt), ()):
                        if dist[s] > d:
                            dist[s] = d
                            nxt.append(s)
                frontier = nxt
        self._dist = dist

    @property
    def vocab_size(self) -> int:
        return self.masks.shape[1]

    def allowed(self, state: int) -> np.ndarray:
        return self.masks[state]

    def step(self, state: int, token: int) -> int:
        return int(self.trans[state, token])

    # ------------------------------------------------------------ builders

    @classmethod
    def from_choices(cls, choices: list, vocab_size: int,
                     eos_id: int) -> "GuidedFSM":
        """Token trie over ``choices`` (lists of token ids); at a complete
        choice only EOS is admitted (absorbing)."""
        if not choices:
            raise ValueError("from_choices needs at least one choice")
        # state 0 = root; assign states via trie insertion; final = EOS-only
        children: list[dict] = [{}]
        terminal: list[bool] = [False]
        for ch in choices:
            if not ch:
                raise ValueError("empty choice")
            s = 0
            for tok in ch:
                if not (0 <= tok < vocab_size):
                    raise ValueError(f"choice token {tok} outside vocab")
                nxt = children[s].get(tok)
                if nxt is None:
                    nxt = len(children)
                    children[s][tok] = nxt
                    children.append({})
                    terminal.append(False)
                s = nxt
            terminal[s] = True
        n = len(children) + 1  # + absorbing EOS-only state
        eos_state = n - 1
        masks = np.zeros((n, vocab_size), bool)
        trans = np.full((n, vocab_size), eos_state, np.int32)
        for s, kids in enumerate(children):
            for tok, nxt in kids.items():
                masks[s, tok] = True
                trans[s, tok] = nxt
            if terminal[s]:
                masks[s, eos_id] = True
                trans[s, eos_id] = eos_state
        masks[eos_state, eos_id] = True
        return cls(masks=masks, trans=trans, start=0, eos_id=eos_id)

    @classmethod
    def from_regex(cls, pattern: str, vocab_size: int, eos_id: int,
                   *, token_of: "callable | None" = None) -> "GuidedFSM":
        """Compile a regex SUBSET (literals, ``[...]`` classes incl.
        ranges/negation, ``.``, ``* + ?``, ``|``, ``( )``) to a DFA over
        token ids. ``token_of(char) -> token id`` maps symbols (default:
        ``ord`` — exact for byte-level tokenizers, where one token is one
        character; the ``guided_regex`` feature of the reference's
        structured-output stack). EOS is admitted exactly in accepting
        states."""
        nfa_start, nfa_accept = _regex_to_nfa(pattern)
        dfa = _nfa_to_dfa(nfa_start, nfa_accept)
        token_of = token_of or ord
        n = len(dfa.states) + 1
        eos_state = n - 1
        masks = np.zeros((n, vocab_size), bool)
        trans = np.full((n, vocab_size), eos_state, np.int32)
        for si, (edges, accepting) in enumerate(dfa.states):
            for ch, ti in edges.items():
                tok = token_of(ch)
                if not (0 <= tok < vocab_size):
                    raise ValueError(
                        f"regex symbol {ch!r} maps to token {tok} outside "
                        f"vocab {vocab_size}")
                masks[si, tok] = True
                trans[si, tok] = ti
            if accepting:
                masks[si, eos_id] = True
        masks[eos_state, eos_id] = True
        return cls(masks=masks, trans=trans, start=dfa.start,
                   eos_id=eos_id)

    @classmethod
    def from_token_sets(cls, sets: list, vocab_size: int,
                        eos_id: int) -> "GuidedFSM":
        """Positional template: position i draws from ``sets[i]``; after
        the last position only EOS is admitted."""
        n = len(sets) + 1
        eos_state = n - 1
        masks = np.zeros((n, vocab_size), bool)
        trans = np.full((n, vocab_size), eos_state, np.int32)
        for i, allowed in enumerate(sets):
            if not allowed:
                raise ValueError(f"position {i}: empty token set")
            for tok in allowed:
                if not (0 <= tok < vocab_size):
                    raise ValueError(f"token {tok} outside vocab")
                masks[i, tok] = True
                trans[i, tok] = i + 1
        masks[eos_state, eos_id] = True
        return cls(masks=masks, trans=trans, start=0, eos_id=eos_id)


def bias_row(fsm: GuidedFSM, state: int,
             remaining: int | None = None) -> np.ndarray:
    """Additive logit bias for one slot: 0 where allowed, -1e9 elsewhere
    (precomputed at FSM construction; this is a row view).

    With ``remaining`` (tokens of budget left incl. the one being sampled)
    the row is PER-TOKEN budget-feasible: token t stays allowed only if
    after taking it the leftover budget still covers the successor state's
    distance-to-accept plus the final EOS. This is inductive — a branch
    whose completion can't fit is masked BEFORE entering it (a state-level
    switch would fire too late for distance-INCREASING alternatives like
    'a|bcdef' at budget 3) — so outputs always complete within
    max_tokens."""
    if remaining is not None and fsm.eos_id >= 0:
        # S-1 bounds every finite distance: a budget beyond that can never
        # be tight, so the (lazy, cached) distance table isn't even built
        if remaining <= fsm.masks.shape[0]:
            fsm._ensure_closing()
            dist_next = fsm._dist[fsm.trans[state]]  # [V]
            feasible = fsm.masks[state] & (dist_next + 2 <= remaining)
            if fsm.masks[state, fsm.eos_id] and remaining >= 1:
                feasible = feasible.copy()
                feasible[fsm.eos_id] = True
            if feasible.any():
                return np.where(feasible, np.float32(0.0), NEG)
            # no feasible completion (caller under-budgeted below the
            # minimum): fall back to the plain mask — prefix-valid output
    return fsm._biases[state]


# ----------------------------------------------------- regex → NFA → DFA
# Thompson construction over an explicit character alphabet (printable
# ASCII by default): enough regex for the structured-output use cases
# (enums, numbers, identifiers, fixed-layout records) without importing a
# full engine. ``.`` and negated classes range over _ALPHABET.

_ALPHABET = [chr(c) for c in range(32, 127)]


class _NState:
    __slots__ = ("edges", "eps")

    def __init__(self):
        self.edges: dict = {}   # char -> _NState
        self.eps: list = []     # epsilon transitions


_SHORTHAND = {
    "d": set("0123456789"),
    "w": set("abcdefghijklmnopqrstuvwxyz"
             "ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_"),
    "s": set(" \t\n\r"),
}


def _read_symbol(pattern: str, i: int) -> tuple:
    """One class symbol at i; returns (char | shorthand-set, next_index).
    Unknown alphanumeric escapes raise — silently treating ``\\d`` as the
    letter 'd' would change the constraint without an error."""
    c = pattern[i]
    if c != "\\":
        return c, i + 1
    if i + 1 >= len(pattern):
        raise ValueError(f"dangling backslash in {pattern!r}")
    e = pattern[i + 1]
    if e in _SHORTHAND:
        return _SHORTHAND[e], i + 2
    if e.isalnum():
        raise ValueError(f"unsupported escape \\{e} in {pattern!r} "
                         "(supported: \\d \\w \\s and punctuation)")
    return e, i + 2


def _parse_class(pattern: str, i: int) -> tuple:
    """Parse ``[...]`` starting after '['; returns (chars, next_index)."""
    neg = i < len(pattern) and pattern[i] == "^"
    if neg:
        i += 1
    chars: set = set()
    while i < len(pattern) and pattern[i] != "]":
        sym, i = _read_symbol(pattern, i)
        if isinstance(sym, set):
            chars.update(sym)
            continue
        if (i + 1 < len(pattern) and pattern[i] == "-"
                and pattern[i + 1] != "]"):
            hi, i = _read_symbol(pattern, i + 1)
            if isinstance(hi, set):
                raise ValueError(
                    f"shorthand cannot end a range in {pattern!r}")
            if ord(hi) < ord(sym):
                raise ValueError(f"empty range {sym}-{hi} in {pattern!r}")
            chars.update(chr(x) for x in range(ord(sym), ord(hi) + 1))
        else:
            chars.add(sym)
    if i >= len(pattern):
        raise ValueError(f"unterminated character class in {pattern!r}")
    if neg:
        chars = set(_ALPHABET) - chars
    if not chars:
        raise ValueError(f"empty (or fully-negated) character class in "
                         f"{pattern!r}: it can never match")
    return sorted(chars), i + 1  # skip ']'


def _regex_to_nfa(pattern: str) -> tuple:
    """Recursive-descent Thompson construction. Returns (start, accept)."""

    def atom(i: int) -> tuple:
        """One atom; returns (start, end, next_i)."""
        if i >= len(pattern):
            raise ValueError(
                f"pattern ends where an atom was expected: {pattern!r}")
        c = pattern[i]
        if c == "(":
            s, e, i = alt(i + 1)
            if i >= len(pattern) or pattern[i] != ")":
                raise ValueError(f"unbalanced '(' in {pattern!r}")
            return s, e, i + 1
        if c == "[":
            chars, i = _parse_class(pattern, i + 1)
            s, e = _NState(), _NState()
            for ch in chars:
                s.edges.setdefault(ch, []).append(e)
            return s, e, i
        if c == ".":
            s, e = _NState(), _NState()
            for ch in _ALPHABET:
                s.edges.setdefault(ch, []).append(e)
            return s, e, i + 1
        if c == "\\":
            sym, i2 = _read_symbol(pattern, i)
            s, e = _NState(), _NState()
            for ch in (sym if isinstance(sym, set) else (sym,)):
                s.edges.setdefault(ch, []).append(e)
            return s, e, i2
        if c in ")|*+?":
            raise ValueError(f"unexpected {c!r} at {i} in {pattern!r}")
        s, e = _NState(), _NState()
        s.edges.setdefault(c, []).append(e)
        return s, e, i + 1

    def repeat(i: int) -> tuple:
        s, e, i = atom(i)
        while i < len(pattern) and pattern[i] in "*+?":
            op = pattern[i]
            ns, ne = _NState(), _NState()
            ns.eps.append(s)
            e.eps.append(ne)
            if op in "*?":
                ns.eps.append(ne)   # skip
            if op in "*+":
                e.eps.append(s)     # loop
            s, e, i = ns, ne, i + 1
        return s, e, i

    def concat(i: int) -> tuple:
        s, e, i = repeat(i)
        while i < len(pattern) and pattern[i] not in ")|":
            s2, e2, i = repeat(i)
            e.eps.append(s2)
            e = e2
        return s, e, i

    def alt(i: int) -> tuple:
        s, e, i = concat(i)
        while i < len(pattern) and pattern[i] == "|":
            s2, e2, i = concat(i + 1)
            ns, ne = _NState(), _NState()
            ns.eps.extend([s, s2])
            e.eps.append(ne)
            e2.eps.append(ne)
            s, e = ns, ne
        return s, e, i

    if not pattern:
        raise ValueError("empty regex")
    s, e, i = alt(0)
    if i != len(pattern):
        raise ValueError(f"trailing {pattern[i:]!r} in {pattern!r}")
    return s, e


class _Dfa:
    __slots__ = ("states", "start")

    def __init__(self, states, start):
        # states: list of (edges: {char: state_idx}, accepting: bool)
        self.states = states
        self.start = start


_MAX_DFA_STATES = 4096


def _nfa_to_dfa(start: "_NState", accept: "_NState") -> _Dfa:
    def closure(states: frozenset) -> frozenset:
        out = set(states)
        stack = list(states)
        while stack:
            st = stack.pop()
            for nxt in st.eps:
                if nxt not in out:
                    out.add(nxt)
                    stack.append(nxt)
        return frozenset(out)

    start_set = closure(frozenset([start]))
    index = {start_set: 0}
    worklist = [start_set]
    states: list = [({}, accept in start_set)]
    while worklist:
        cur = worklist.pop()
        ci = index[cur]
        by_char: dict = {}
        for st in cur:
            for ch, targets in st.edges.items():
                by_char.setdefault(ch, set()).update(targets)
        for ch, targets in by_char.items():
            nxt = closure(frozenset(targets))
            if nxt not in index:
                if len(states) >= _MAX_DFA_STATES:
                    # subset construction can blow up exponentially
                    # ((Σ)*aΣ^n forms); user-supplied patterns on the
                    # serving path must not be a memory/CPU DoS vector
                    raise ValueError(
                        f"regex compiles to more than {_MAX_DFA_STATES} "
                        "DFA states; simplify the pattern")
                index[nxt] = len(states)
                states.append(({}, accept in nxt))
                worklist.append(nxt)
            states[ci][0][ch] = index[nxt]
    return _Dfa(states, 0)
