"""Guided (constrained) decoding: finite-state token masks.

Reference capability: ray.llm passes ``guided_decoding`` params
(choice / regex / json / grammar) through to vLLM's structured-output
machinery (llm/_internal/batch/stages/vllm_engine_stage.py:278, which
builds ``vllm.sampling_params.GuidedDecodingParams``). This framework owns
its engine, so the constraint machinery lives here.

TPU-first design: a guided request carries a finite-state machine over
TOKEN IDS — ``masks[S, V]`` (allowed tokens per state) and
``trans[S, V]`` (next state per token). Each decode step the engine adds
a per-slot ``-inf`` bias for disallowed tokens before sampling; the FSM
state advance is a host-side table lookup on the token that was emitted
anyway. The bias tensor is the only extra device traffic (slots × vocab
per step) and the sampling math stays inside the existing jitted
``sample_per_row`` — no data-dependent control flow enters the graph.

Builders:

- :meth:`GuidedFSM.from_choices` — output must be exactly one of N token
  sequences (the ``guided_choice`` feature): a token trie whose terminal
  state admits only EOS.
- :meth:`GuidedFSM.from_token_sets` — positional template: step i must
  draw from ``sets[i]`` (digits-only fields, enum slots, fixed-layout
  records), then EOS.
"""

from __future__ import annotations

import dataclasses

import numpy as np

NEG = np.float32(-1e9)


@dataclasses.dataclass
class GuidedFSM:
    """masks[S, V] bool (True = allowed), trans[S, V] int32, start state."""

    masks: np.ndarray
    trans: np.ndarray
    start: int = 0

    def __post_init__(self):
        if self.masks.shape != self.trans.shape:
            raise ValueError(
                f"masks {self.masks.shape} / trans {self.trans.shape} "
                "shape mismatch")
        if not (0 <= self.start < self.masks.shape[0]):
            raise ValueError(f"start state {self.start} out of range")
        # precomputed additive biases [S, V]: the decode hot loop indexes a
        # row per step instead of running a full-vocab np.where per slot
        self._biases = np.where(self.masks, np.float32(0.0), NEG)

    @property
    def vocab_size(self) -> int:
        return self.masks.shape[1]

    def allowed(self, state: int) -> np.ndarray:
        return self.masks[state]

    def step(self, state: int, token: int) -> int:
        return int(self.trans[state, token])

    # ------------------------------------------------------------ builders

    @classmethod
    def from_choices(cls, choices: list, vocab_size: int,
                     eos_id: int) -> "GuidedFSM":
        """Token trie over ``choices`` (lists of token ids); at a complete
        choice only EOS is admitted (absorbing)."""
        if not choices:
            raise ValueError("from_choices needs at least one choice")
        # state 0 = root; assign states via trie insertion; final = EOS-only
        children: list[dict] = [{}]
        terminal: list[bool] = [False]
        for ch in choices:
            if not ch:
                raise ValueError("empty choice")
            s = 0
            for tok in ch:
                if not (0 <= tok < vocab_size):
                    raise ValueError(f"choice token {tok} outside vocab")
                nxt = children[s].get(tok)
                if nxt is None:
                    nxt = len(children)
                    children[s][tok] = nxt
                    children.append({})
                    terminal.append(False)
                s = nxt
            terminal[s] = True
        n = len(children) + 1  # + absorbing EOS-only state
        eos_state = n - 1
        masks = np.zeros((n, vocab_size), bool)
        trans = np.full((n, vocab_size), eos_state, np.int32)
        for s, kids in enumerate(children):
            for tok, nxt in kids.items():
                masks[s, tok] = True
                trans[s, tok] = nxt
            if terminal[s]:
                masks[s, eos_id] = True
                trans[s, eos_id] = eos_state
        masks[eos_state, eos_id] = True
        return cls(masks=masks, trans=trans, start=0)

    @classmethod
    def from_token_sets(cls, sets: list, vocab_size: int,
                        eos_id: int) -> "GuidedFSM":
        """Positional template: position i draws from ``sets[i]``; after
        the last position only EOS is admitted."""
        n = len(sets) + 1
        eos_state = n - 1
        masks = np.zeros((n, vocab_size), bool)
        trans = np.full((n, vocab_size), eos_state, np.int32)
        for i, allowed in enumerate(sets):
            if not allowed:
                raise ValueError(f"position {i}: empty token set")
            for tok in allowed:
                if not (0 <= tok < vocab_size):
                    raise ValueError(f"token {tok} outside vocab")
                masks[i, tok] = True
                trans[i, tok] = i + 1
        masks[eos_state, eos_id] = True
        return cls(masks=masks, trans=trans, start=0)


def bias_row(fsm: GuidedFSM, state: int) -> np.ndarray:
    """Additive logit bias for one slot: 0 where allowed, -1e9 elsewhere
    (precomputed at FSM construction; this is a row view)."""
    return fsm._biases[state]
