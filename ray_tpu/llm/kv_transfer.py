"""Paged-KV transfer plane for PD disaggregation.

The prefill→decode handoff moves the prefilled KV prefix at paged-KV
**page granularity** over `MutableShmChannel` — the compiled-DAG plane's
seqlock shm transport, reused — with a ticket/pull protocol:

- the prefill side computes the prompt KV, slices it into
  ``[L, page_size, Hkv, Dh]`` pages, and ``export()``s them: a per-ticket
  shm channel is created and a sender streams pages into it in messages
  of up to ``prefetch_pages`` pages (the seqlock write blocks until the
  reader consumed the previous message, so at most one message — the
  prefetch window — is in flight per transfer: natural backpressure, no
  buffering tier). A prefix that fits ONE message is written
  synchronously in ``export()`` itself ("sync" tickets — no sender
  thread at all; the reader retires the channel);
- the proxy only ever sees the **ticket** (a small dict: channel path,
  page count, shapes, first token) — it never materializes KV;
- the decode side attaches by path. The streamed-admission path
  registers the ticket with a ``BatchedKVPuller`` — ONE polling thread
  multiplexes every in-flight transfer, so N concurrent pulls cost one
  channel wake per cycle, not N — which feeds a ``KVPageStream`` the
  engine adopts pages from AS THEY ARRIVE (page-granular
  ``write_kv_pages``; the decode loop keeps stepping other slots while
  later pages stream). ``pull_pages()``/``pull_all()`` remain as the
  blocking single-ticket surface.

Page bytes cross the channel RAW (vectored writes + zero-copy read
views; pickle only frames the tiny per-message header), so a page costs
one memcpy per side.

Both ends must share one host (/dev/shm), which is the on-pod PD layout:
prefill and decode replicas co-locate per host and the proxy fans out
across hosts. Cross-host transfer is the ICI/RDMA follow-on.

(reference: llm/_internal/serve/serving_patterns/prefill_decode/pd_server.py
— the PDProxyServer + NIXL/LMCache KV-transfer pattern; here the transport
is the repo's own mutable-shm channel instead of RDMA, and the unit is the
paged-KV page so decode admission needs no reshape.)
"""

from __future__ import annotations

import logging
import struct
import threading
import uuid

import numpy as np

logger = logging.getLogger(__name__)

from ray_tpu.experimental.channel.channel import ChannelClosed
from ray_tpu.experimental.channel.mutable_shm import (MutableShmChannel,
                                                      create_mutable_channel)

# framing slack per page message (length prefix + pickled header); the
# payload itself is raw page bytes written vectored into the channel
_WIRE_SLACK = 8192

_LEN = struct.Struct("<q")


def _raw_bytes(a: np.ndarray):
    """Zero-copy byte view of a C-contiguous array. Routed through a
    uint8 reinterpret because extension dtypes (ml_dtypes bfloat16 —
    the TPU KV dtype) have no buffer protocol of their own."""
    return memoryview(a.view(np.uint8).reshape(-1))


def _pack_page_message(start: int, kps: list, vps: list) -> list:
    """Raw frame for one transfer message: [len][pickled tiny header]
    [k0][v0][k1][v1]... — page bytes go into the channel VECTORED
    (MutableShmChannel.write_vectored), never through pickle, so a page
    crosses the wire with exactly one memcpy per side."""
    import pickle

    hdr = pickle.dumps({"i": int(start), "n": len(kps),
                        "shape": tuple(kps[0].shape),
                        "dtype": kps[0].dtype},
                       protocol=pickle.HIGHEST_PROTOCOL)
    parts = [_LEN.pack(len(hdr)), hdr]
    for kp, vp in zip(kps, vps):
        parts.append(_raw_bytes(kp))
        parts.append(_raw_bytes(vp))
    return parts


def _unpack_page_view(view):
    """Parse one raw page message. The returned arrays VIEW the channel
    buffer — the caller must copy what it keeps BEFORE ack_read()."""
    import pickle

    (hlen,) = _LEN.unpack_from(view, 0)
    meta = pickle.loads(view[_LEN.size:_LEN.size + hlen])
    shape = meta["shape"]
    dt = np.dtype(meta["dtype"])
    count = 1
    for d in shape:
        count *= d
    nb = count * dt.itemsize
    off = _LEN.size + hlen
    kps, vps = [], []
    for _ in range(meta["n"]):
        kps.append(np.frombuffer(view, dt, count=count,
                                 offset=off).reshape(shape))
        off += nb
        vps.append(np.frombuffer(view, dt, count=count,
                                 offset=off).reshape(shape))
        off += nb
    return meta["i"], kps, vps


class KVTransferError(RuntimeError):
    """A KV handoff failed mid-flight: the per-REQUEST failure (the other
    transfers and both replica pools keep serving)."""


def _metrics():
    from ray_tpu.util import metrics as met

    return (
        met.get_or_create(
            met.Counter, "ray_tpu_llm_pd_transfer_bytes_total",
            "KV bytes moved prefill->decode over the shm transfer plane"),
        met.get_or_create(
            met.Counter, "ray_tpu_llm_pd_kv_pages_total",
            "KV pages moved prefill->decode over the shm transfer plane"),
    )


def _prefetch_metric():
    from ray_tpu.util import metrics as met

    return met.get_or_create(
        met.Counter, "ray_tpu_llm_pd_pages_prefetched_total",
        "KV pages pulled onto the decode host ahead of slot activation "
        "(streamed admission: batched puller + inline sync pulls)")


class _Transfer:
    __slots__ = ("ticket_id", "channel", "thread", "failed", "trace_ctx",
                 "created")

    def __init__(self, ticket_id: str, channel: MutableShmChannel,
                 trace_ctx: dict | None = None):
        import time as _time

        self.ticket_id = ticket_id
        self.channel = channel
        self.thread: threading.Thread | None = None  # None = sync transfer
        self.failed: str | None = None
        # sampled request's span context, captured at export: the sender
        # thread runs outside the request's contextvar scope
        self.trace_ctx = trace_ctx
        self.created = _time.monotonic()


class PagedKVExporter:
    """Prefill-side registry of in-flight page transfers.

    ``export()`` returns the ticket immediately. A prefix that fits one
    message ("sync") is written in the caller's thread — the reader
    retires the channel, and ``_reap_settled`` sweeps never-pulled ones.
    Larger transfers stream from a REUSED sender pool and retire their
    channel after a ``wait_drained`` barrier. A receiver that never
    attaches, or dies mid-pull, times the sender out after
    ``send_timeout_s`` — the channel is torn down either way, so
    /dev/shm can't accumulate segments.
    """

    def __init__(self, *, send_timeout_s: float = 60.0,
                 prefetch_pages: int = 2, page_interval_s: float = 0.0):
        self.send_timeout_s = float(send_timeout_s)
        # pages per channel message: the transfer's in-flight window. >1
        # amortizes the seqlock handshake + header framing over several
        # pages at the cost of prefetch_pages*page_bytes of channel buffer
        self.prefetch_pages = max(1, int(prefetch_pages))
        # pacing injection between messages (tests/benchmarks: a "slow
        # sender" proves decode keeps emitting under partial admission)
        self.page_interval_s = float(page_interval_s)
        self._live: dict[str, _Transfer] = {}
        self._lock = threading.Lock()
        # one self-rescheduling timer reaps never-pulled SYNC channels
        # even on an idle exporter (threaded senders time out on their
        # own thread; sync transfers have no thread to do it)
        self._reap_timer: threading.Timer | None = None
        self._torn_down = False
        self._m_bytes, self._m_pages = _metrics()
        self.failures = 0        # transfers that did not complete
        self.last_failure = ""   # "<ticket>: <reason>" for triage

    # ------------------------------------------------------------- export

    def export(self, k: np.ndarray, v: np.ndarray, length: int,
               first_token: int, page_size: int,
               trace_ctx: dict | None = None) -> dict:
        """Slice a bucketed prompt KV (``[L, T, Hkv, Dh]``, T a multiple of
        ``page_size``) into pages and start streaming them. Returns the
        ticket the proxy forwards to the decode pool. ``trace_ctx`` (a
        sampled request's span context) makes the sender emit a
        ``pd:kv_send`` span covering the whole transfer."""
        k = np.asarray(k)
        v = np.asarray(v)
        L, T = k.shape[0], k.shape[1]
        if page_size <= 0 or T % page_size:
            raise ValueError(
                f"prefill bucket {T} is not a multiple of page_size "
                f"{page_size}: configure the prefill server with "
                f"min_bucket >= page_size")
        n_pages = T // page_size
        depth = min(self.prefetch_pages, n_pages)
        page_bytes = (k.nbytes + v.nbytes) // n_pages
        tid = uuid.uuid4().hex[:16]
        self._reap_settled()
        ch = create_mutable_channel(depth * page_bytes + _WIRE_SLACK)
        # whole prefix in ONE message: write it NOW in the caller's thread
        # (a fresh channel can never block) and let the READER retire the
        # channel — no sender thread, no cross-thread handoff latency. The
        # reaper (`_reap_settled`) sweeps never-pulled sync channels.
        sync = n_pages <= depth and not self.page_interval_s
        try:
            tr = _Transfer(tid, ch, trace_ctx)
            if sync:
                import time as _time

                t_send0 = _time.time()
                kps = [np.ascontiguousarray(
                    k[:, i * page_size:(i + 1) * page_size])
                    for i in range(n_pages)]
                vps = [np.ascontiguousarray(
                    v[:, i * page_size:(i + 1) * page_size])
                    for i in range(n_pages)]
                ch.write_vectored(_pack_page_message(0, kps, vps), timeout=0)
                self._m_bytes.inc(sum(p.nbytes for p in kps)
                                  + sum(p.nbytes for p in vps))
                self._m_pages.inc(n_pages)
                with self._lock:
                    self._live[tid] = tr
                self._arm_reap_timer()
                if trace_ctx:
                    from ray_tpu.util import tracing

                    # the send happened right here (inline single-message
                    # write) — same span name the threaded sender emits
                    tracing.emit_span_for(
                        trace_ctx, "pd:kv_send", t_send0, _time.time(),
                        ok=True, ticket=tid, pages=n_pages, failed="",
                        sync=True)
            else:
                with self._lock:
                    self._live[tid] = tr
                tr.thread = threading.Thread(
                    target=self._send, args=(tr, k, v, page_size, n_pages),
                    daemon=True, name=f"pd-kv-send-{tid[:6]}")
                # ONE thread per threaded transfer (multi-message = long
                # prompt; spawn cost is noise next to the stream, and a
                # shared pool would let one dead-reader transfer
                # head-of-line-block every later export). Spawn can fail
                # (ulimit under load); until start() succeeds the
                # sender's finally owns nothing, so the segment (and the
                # ticket registration) must be rolled back here or
                # /dev/shm leaks one segment per failed export
                tr.thread.start()
        except BaseException:
            with self._lock:
                self._live.pop(tid, None)
            ch.close()
            ch.unlink()
            raise
        return {
            "ticket": tid,
            "path": ch.path,
            "capacity": ch.capacity,
            "n_pages": n_pages,
            "prefetch": depth,
            "sync": sync,
            "page_size": page_size,
            "length": int(length),
            "first_token": int(first_token),
            "bucket": T,
            "page_shape": (L, page_size, k.shape[2], k.shape[3]),
            "dtype": str(k.dtype),
        }

    def _send(self, tr: _Transfer, k, v, page_size: int, n_pages: int):
        import time as _time

        from ray_tpu.serve import request_context as rc

        ch = tr.channel
        depth = min(self.prefetch_pages, n_pages)
        t_send0 = _time.time()
        try:
            for start in range(0, n_pages, depth):
                m = min(depth, n_pages - start)
                kps = [np.ascontiguousarray(
                    k[:, (start + i) * page_size:(start + i + 1) * page_size])
                    for i in range(m)]
                vps = [np.ascontiguousarray(
                    v[:, (start + i) * page_size:(start + i + 1) * page_size])
                    for i in range(m)]
                if self.page_interval_s:
                    _time.sleep(self.page_interval_s)
                t_w = _time.perf_counter()
                ch.write_vectored(_pack_page_message(start, kps, vps),
                                  timeout=self.send_timeout_s)
                # per-message backpressure wait: the seqlock write blocks
                # until the reader consumed the previous message, so this
                # IS how long the handoff serialized on the decode side
                rc.observe_phase(rc.PD_PHASE, "transfer_send_wait",
                                 _time.perf_counter() - t_w)
                self._m_bytes.inc(sum(p.nbytes for p in kps)
                                  + sum(p.nbytes for p in vps))
                self._m_pages.inc(m)
            # the final page is published but possibly unread: wait for the
            # reader's ack before unlinking the segment
            ch.wait_drained(timeout=self.send_timeout_s)
        except ChannelClosed:
            tr.failed = "closed"  # teardown/abort raced the send: expected
        except TimeoutError:
            tr.failed = "timeout"  # receiver never attached or died mid-pull
            logger.warning("kv transfer %s: send timed out after %.1fs "
                           "(decode side never pulled, or died mid-pull)",
                           tr.ticket_id, self.send_timeout_s)
        except Exception as e:  # noqa: BLE001 — must never leak the segment
            tr.failed = f"{type(e).__name__}: {e}"
            logger.warning("kv transfer %s: sender failed: %s",
                           tr.ticket_id, tr.failed)
        finally:
            ch.close()
            ch.unlink()
            with self._lock:
                self._live.pop(tr.ticket_id, None)
                if tr.failed is not None:
                    self.failures += 1
                    self.last_failure = f"{tr.ticket_id}: {tr.failed}"
            if tr.trace_ctx:
                from ray_tpu.util import tracing

                tracing.emit_span_for(
                    tr.trace_ctx, "pd:kv_send", t_send0, _time.time(),
                    ok=tr.failed is None, ticket=tr.ticket_id,
                    pages=n_pages, failed=tr.failed or "")

    # ---------------------------------------------------------- lifecycle

    def _arm_reap_timer(self) -> None:
        """Ensure ONE timer is pending whenever sync transfers are live:
        a never-pulled sync channel (decode replica died before pulling)
        must retire after send_timeout_s even if this exporter never
        exports again — an idle replica cannot pin /dev/shm."""
        with self._lock:
            if self._torn_down or self._reap_timer is not None:
                return
            if not any(tr.thread is None for tr in self._live.values()):
                return
            t = threading.Timer(self.send_timeout_s + 1.0, self._reap_tick)
            t.daemon = True
            self._reap_timer = t
        t.start()

    def _reap_tick(self) -> None:
        with self._lock:
            self._reap_timer = None
        self._reap_settled()
        self._arm_reap_timer()  # re-arms iff sync transfers remain

    def _reap_settled(self) -> None:
        """Retire settled SYNC transfers: drained ones silently (the
        reader consumed the message and unlinked the name), expired
        never-pulled ones as failures. Threaded transfers own their
        retirement in the sender's finally. Called from export()/
        pending() and the reap timer — teardown sweeps whatever remains."""
        import time as _time

        now = _time.monotonic()
        done: list[_Transfer] = []
        with self._lock:
            for tr in list(self._live.values()):
                if tr.thread is not None:
                    continue
                drained = tr.channel.drained()
                expired = now - tr.created > self.send_timeout_s
                if drained or expired:
                    self._live.pop(tr.ticket_id, None)
                    if expired and not drained:
                        tr.failed = "timeout"
                        self.failures += 1
                        self.last_failure = f"{tr.ticket_id}: timeout " \
                                            "(decode side never pulled)"
                    done.append(tr)
        for tr in done:
            tr.channel.close()
            tr.channel.unlink()

    def pending(self) -> int:
        self._reap_settled()
        with self._lock:
            return len(self._live)

    def abort(self, ticket_id: str) -> None:
        """Kill one in-flight transfer (its puller observes ChannelClosed →
        KVTransferError). Used when the prefill replica is shutting down or
        the request was cancelled upstream."""
        with self._lock:
            tr = self._live.get(ticket_id)
        if tr is None:
            return
        if tr.thread is None:  # sync transfer: retire it here
            tr.channel.close()
            tr.channel.unlink()
            with self._lock:
                self._live.pop(ticket_id, None)
            return
        tr.channel.close()
        tr.thread.join(timeout=5.0)

    def teardown(self) -> None:
        """Close every live channel, join the senders, unlink the
        segments. Safe to call twice; after it returns /dev/shm holds none
        of this exporter's ``rtpu_chan_*`` files."""
        with self._lock:
            self._torn_down = True
            timer, self._reap_timer = self._reap_timer, None
            live = list(self._live.values())
        if timer is not None:
            timer.cancel()
        for tr in live:
            tr.channel.close()
        for tr in live:
            if tr.thread is not None:
                tr.thread.join(timeout=5.0)
            tr.channel.unlink()  # sync transfers retire here too
        with self._lock:
            for tr in live:
                self._live.pop(tr.ticket_id, None)


# ----------------------------------------------------------------- receiver


def pull_pages(ticket: dict, timeout_s: float = 60.0):
    """Decode-side pull: attach to the ticket's channel and yield
    ``(index, k_page, v_page)`` in order (each ``[L, page_size, Hkv, Dh]``).
    Every failure mode surfaces as KVTransferError naming the ticket — the
    per-request error contract."""
    import time as _time

    from ray_tpu.serve import request_context as rc

    tid = ticket.get("ticket", "?")
    try:
        ch = MutableShmChannel(ticket["path"], ticket["capacity"])
    except FileNotFoundError:
        raise KVTransferError(
            f"kv transfer {tid}: channel {ticket['path']} not found — the "
            "prefill replica died (or retired the ticket), or prefill and "
            "decode are not co-hosted (shm transfer is same-host)") from None
    i = 0
    try:
        while i < ticket["n_pages"]:
            t_r = _time.perf_counter()
            try:
                view = ch.read_view(timeout=timeout_s)
            except ChannelClosed:
                raise KVTransferError(
                    f"kv transfer {tid}: prefill side closed after "
                    f"{i}/{ticket['n_pages']} pages (replica death or "
                    "abort mid-transfer)") from None
            except TimeoutError:
                raise KVTransferError(
                    f"kv transfer {tid}: timed out waiting for page {i} of "
                    f"{ticket['n_pages']} after {timeout_s}s") from None
            # per-message channel wait: how long decode admission stalled
            # on the transfer plane for this prefetch window
            rc.observe_phase(rc.PD_PHASE, "transfer_wait",
                             _time.perf_counter() - t_r)
            start, kviews, vviews = _unpack_page_view(view)
            # copy BEFORE acking: the writer may overwrite after the ack
            pages = [(start + off, np.array(kv), np.array(vv))
                     for off, (kv, vv) in enumerate(zip(kviews, vviews))]
            del kviews, vviews, view
            ch.ack_read()
            yield from pages
            i += len(pages)
        if ticket.get("sync"):
            # sync transfer fully consumed: the READER retires the
            # channel (the exporter never spawned a sender to do it)
            ch.close()
            ch.unlink()
    finally:
        ch.close_mapping()


def pull_all(ticket: dict, timeout_s: float = 60.0):
    """Pull the whole transfer: ``(k_pages, v_pages)`` as ordered lists of
    per-page arrays, ready for ``TPUEngine.submit_prefilled(k_pages=...)``."""
    k_pages: list = [None] * ticket["n_pages"]
    v_pages: list = [None] * ticket["n_pages"]
    for i, kp, vp in pull_pages(ticket, timeout_s):
        k_pages[i] = kp
        v_pages[i] = vp
    return k_pages, v_pages


# -------------------------------------------------------- streamed admission


class KVPageStream:
    """Thread-safe hand-off between the transfer plane and the engine.

    The puller (or an inline sync pull) ``feed()``s pages as they come
    off the channel; the engine scheduler ``take_ready()``s them between
    decode steps and adopts each into the paged pool
    (``TPUEngine.submit_prefilled(kv_stream=...)``), activating the slot
    once all ``n_pages`` landed. ``fail()`` turns the in-flight request
    into a per-request error — the engine reclaims the slot and its
    granted pages.
    """

    def __init__(self, n_pages: int, page_size: int):
        self.n_pages = int(n_pages)
        self.page_size = int(page_size)
        self._lock = threading.Lock()
        self._ready: list = []
        self._error: BaseException | None = None
        self.fed = 0
        self.finished_ts: float | None = None
        # set by the engine at submit: wakes the scheduler so a parked
        # (no-active-slot) loop adopts new pages immediately
        self._wake = None

    # ---------------------------------------------------- transfer side

    def feed(self, index: int, k_page, v_page) -> None:
        with self._lock:
            self._ready.append((int(index), k_page, v_page))
            self.fed += 1
        wake = self._wake
        if wake is not None:
            wake()

    def finish(self) -> None:
        import time as _time

        self.finished_ts = _time.time()
        wake = self._wake
        if wake is not None:
            wake()

    def fail(self, exc: BaseException) -> None:
        with self._lock:
            self._error = exc
        wake = self._wake
        if wake is not None:
            wake()

    # ------------------------------------------------------ engine side

    def take_ready(self) -> list:
        with self._lock:
            out, self._ready = self._ready, []
            return out

    def take_error(self) -> BaseException | None:
        with self._lock:
            return self._error


class _DiscardSink:
    """Drain-only sink: the prefix-cache warm path (decode budget already
    spent by the transferred token) still has to consume the channel so
    the prefill side retires it, but adopts nothing."""

    #: pull paths skip the copy-out-of-shm entirely for sinks that drop
    #: the pages — a long-prompt drain costs acks, not memcpys
    keeps_pages = False

    def feed(self, index, k_page, v_page) -> None:
        pass

    def finish(self) -> None:
        pass

    def fail(self, exc) -> None:
        pass


def pull_sync(ticket: dict, sink) -> bool:
    """Inline pull for single-message ('sync') tickets.

    A sync ticket's message was published BEFORE the ticket was returned,
    so the decode-side caller consumes it right here — no puller
    registration, no cross-thread wake; on a loaded host that hop costs
    more than the copy. Feeds ``sink`` like the puller would (feed per
    page, then finish) and retires the channel (reader-side ownership).
    Returns False when the ticket is not sync — register it with the
    BatchedKVPuller instead."""
    if not ticket.get("sync"):
        return False
    tid = ticket.get("ticket", "?")
    try:
        ch = MutableShmChannel(ticket["path"], ticket["capacity"])
    except FileNotFoundError:
        raise KVTransferError(
            f"kv transfer {tid}: channel {ticket['path']} not found — the "
            "prefill replica died (or retired the ticket), or prefill and "
            "decode are not co-hosted (shm transfer is same-host)") from None
    try:
        try:
            view = ch.read_view(timeout=0)
        except (ChannelClosed, TimeoutError):
            raise KVTransferError(
                f"kv transfer {tid}: sync message missing (aborted or "
                "reaped before the pull)") from None
        start, kviews, vviews = _unpack_page_view(view)
        if getattr(sink, "keeps_pages", True):
            # copy BEFORE acking: the writer side may reap/reuse after
            pages = [(start + off, np.array(kv), np.array(vv))
                     for off, (kv, vv) in enumerate(zip(kviews, vviews))]
        else:
            pages = []  # drain-only sink: ack without paying the memcpy
        n_fed = len(kviews)
        del kviews, vviews, view
        ch.ack_read()
        # fully consumed: the READER retires the channel (the exporter
        # never spawned a sender to do it)
        ch.close()
        ch.unlink()
    finally:
        ch.close_mapping()
    _prefetch_metric().inc(n_fed)
    for idx, kp, vp in pages:
        sink.feed(idx, kp, vp)
    sink.finish()
    return True


class _Pull:
    __slots__ = ("ticket_id", "channel", "sink", "n_pages", "next_i",
                 "timeout_s", "last_progress", "aborted")

    def __init__(self, ticket_id, channel, sink, n_pages, timeout_s, now):
        self.ticket_id = ticket_id
        self.channel = channel
        self.sink = sink
        self.n_pages = n_pages
        self.next_i = 0
        self.timeout_s = timeout_s
        self.last_progress = now
        self.aborted = False  # abort(): finished by the polling thread


class BatchedKVPuller:
    """One polling thread multiplexes EVERY in-flight ticket pull.

    The per-ticket ``pull_pages`` loop parks one thread per transfer in
    the seqlock wait — at concurrency N the decode host pays N wake-ups
    (and N spinning waiters) per page interval. Here a single thread
    sweeps all registered channels per cycle with non-blocking ``poll()``
    reads, so N concurrent transfers cost ONE wake, and pages flow into
    their ``KVPageStream`` sinks the moment the sender publishes them.
    Single-message ("sync") tickets bypass the thread entirely — consumed
    inline at ``pull()``.
    """

    def __init__(self, *, name: str = "pd-kv-pull"):
        self._lock = threading.Lock()
        self._pulls: list[_Pull] = []
        self._work = threading.Event()
        self._stop = False
        self._thread: threading.Thread | None = None
        self._name = name
        self._m_prefetched = _prefetch_metric()

    # ------------------------------------------------------ registration

    def pull(self, ticket: dict, sink, timeout_s: float = 60.0) -> None:
        """Register one transfer; returns immediately. ``sink`` receives
        ``feed(i, k_page, v_page)`` per page in order, then ``finish()``
        — or ``fail(KVTransferError)`` on death/timeout. Raises
        KVTransferError synchronously when the channel is already gone
        (prefill replica died or retired the ticket)."""
        import time as _time

        tid = ticket.get("ticket", "?")
        if self._stop:
            raise KVTransferError(
                f"kv transfer {tid}: puller is torn down")
        if pull_sync(ticket, sink):
            # single-message ticket consumed inline on the caller's
            # thread — no registration, no polling-thread wake
            return
        try:
            ch = MutableShmChannel(ticket["path"], ticket["capacity"])
        except FileNotFoundError:
            raise KVTransferError(
                f"kv transfer {tid}: channel {ticket['path']} not found — "
                "the prefill replica died (or retired the ticket), or "
                "prefill and decode are not co-hosted (shm transfer is "
                "same-host)") from None
        p = _Pull(tid, ch, sink, int(ticket["n_pages"]), float(timeout_s),
                  _time.monotonic())
        with self._lock:
            # re-check under the lock: teardown() flips _stop and sweeps
            # _pulls under this lock, so a pull racing it must not
            # register a _Pull nobody will ever service
            if self._stop:
                ch.close_mapping()
                raise KVTransferError(
                    f"kv transfer {tid}: puller is torn down")
            self._pulls.append(p)
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._loop, daemon=True, name=self._name)
                self._thread.start()
        self._work.set()

    def drain(self, ticket: dict, timeout_s: float = 60.0) -> None:
        """Consume a ticket's pages without adopting them (warm path:
        the transferred first token already spent the decode budget).
        Non-blocking for threaded tickets — the sender retires its
        channel once drained; sync tickets are consumed inline."""
        self.pull(ticket, _DiscardSink(), timeout_s)

    def pending(self) -> int:
        with self._lock:
            return len(self._pulls)

    def abort(self, ticket_id: str) -> bool:
        """Cancel an in-flight registered pull (decode-tier ticket abort:
        the request was cancelled downstream). The polling thread — the
        only channel reader — closes the channel (the flipped shared flag
        stops the sender's stream in one write) and fails the sink on its
        next cycle, so no page read races the teardown. Thread-safe; a
        ticket already finished (or consumed inline by pull_sync) returns
        False."""
        with self._lock:
            for p in self._pulls:
                if p.ticket_id == ticket_id:
                    p.aborted = True
                    self._work.set()
                    return True
        return False

    # ------------------------------------------------------------- loop

    def _finish(self, p: _Pull, exc: BaseException | None) -> None:
        # only threaded (multi-message) tickets ever register here — sync
        # tickets are consumed inline by pull_sync, which also retires
        # their channel — so the sender side owns channel retirement
        p.channel.close_mapping()
        with self._lock:
            if p in self._pulls:
                self._pulls.remove(p)
        if exc is None:
            p.sink.finish()
        else:
            logger.warning("kv transfer %s: pull failed: %s",
                           p.ticket_id, exc)
            p.sink.fail(exc)

    def _sweep_one(self, p: _Pull, now: float) -> bool:
        """Drain every message currently ready on one channel; returns
        True if any page moved."""
        import time as _time

        from ray_tpu.serve import request_context as rc

        progressed = False
        while p.channel.poll():
            view = p.channel.read_view(timeout=0)
            # per-message wait: how long the decode side had this
            # transfer stalled before the window arrived
            rc.observe_phase(rc.PD_PHASE, "transfer_wait",
                             _time.monotonic() - p.last_progress)
            start, kviews, vviews = _unpack_page_view(view)
            if getattr(p.sink, "keeps_pages", True):
                # copy out BEFORE acking (the writer may overwrite after),
                # then feed — the sink keeps the copies
                pages = [(start + off, np.array(kv), np.array(vv))
                         for off, (kv, vv) in enumerate(zip(kviews, vviews))]
            else:
                pages = []  # drain-only sink: ack without the memcpy
            n = len(kviews)
            del kviews, vviews, view
            p.channel.ack_read()
            for idx, kp, vp in pages:
                p.sink.feed(idx, kp, vp)
            p.next_i += n
            self._m_prefetched.inc(n)
            p.last_progress = _time.monotonic()
            progressed = True
            if p.next_i >= p.n_pages:
                self._finish(p, None)
                return True
        if not progressed:
            if p.channel.closed():
                # abort/replica death: poll() drained whatever was already
                # published above, so a flipped flag here means the stream
                # ended incomplete
                self._finish(p, KVTransferError(
                    f"kv transfer {p.ticket_id}: prefill side closed after "
                    f"{p.next_i}/{p.n_pages} pages (replica death or abort "
                    "mid-transfer)"))
            elif now - p.last_progress > p.timeout_s:
                self._finish(p, KVTransferError(
                    f"kv transfer {p.ticket_id}: timed out waiting for page "
                    f"{p.next_i} of {p.n_pages} after {p.timeout_s}s"))
        return progressed

    def _loop(self) -> None:
        import time as _time

        quiet_since: float | None = None
        while not self._stop:
            with self._lock:
                pulls = list(self._pulls)
            if not pulls:
                self._work.wait(timeout=0.1)
                self._work.clear()
                quiet_since = None
                continue
            progressed = False
            for p in pulls:
                try:
                    if p.aborted:
                        # reader-side close: the shared flag stops the
                        # sender's stream at its next write, then the sink
                        # fails so the engine reclaims the granted slot
                        p.channel.close()
                        self._finish(p, KVTransferError(
                            f"kv transfer {p.ticket_id}: cancelled by the "
                            f"decode side after {p.next_i}/{p.n_pages} "
                            "pages (request aborted)"))
                        progressed = True
                        continue
                    progressed |= self._sweep_one(p, _time.monotonic())
                except ChannelClosed:
                    self._finish(p, KVTransferError(
                        f"kv transfer {p.ticket_id}: prefill side closed "
                        f"after {p.next_i}/{p.n_pages} pages (replica "
                        "death or abort mid-transfer)"))
                except KVTransferError as e:
                    self._finish(p, e)
                except Exception as e:  # noqa: BLE001 — one bad channel
                    # must not take down the other transfers' pull loop
                    self._finish(p, KVTransferError(
                        f"kv transfer {p.ticket_id}: pull failed: "
                        f"{type(e).__name__}: {e}"))
            if progressed:
                quiet_since = None
                continue
            # nothing ready on ANY channel: one escalating WAITABLE sleep
            # covers the whole set — the "one wake, not N" part; a new
            # pull() registration interrupts it (threaded tickets can
            # publish their first message at any moment)
            now = _time.monotonic()
            if quiet_since is None:
                quiet_since = now
            quiet = now - quiet_since
            if quiet < 0.002:
                _time.sleep(50e-6)
            else:
                self._work.wait(timeout=200e-6 if quiet < 0.02 else 1e-3)
                self._work.clear()

    def teardown(self) -> None:
        """Stop the thread and fail every outstanding pull. Safe to call
        twice; after it returns no mapping of this puller's remains."""
        with self._lock:
            self._stop = True
            t = self._thread
        self._work.set()
        if t is not None:
            t.join(timeout=5.0)
        with self._lock:
            pulls, self._pulls = list(self._pulls), []
        for p in pulls:
            p.channel.close_mapping()
            p.sink.fail(KVTransferError(
                f"kv transfer {p.ticket_id}: puller torn down mid-pull"))
