"""Paged-KV transfer plane for PD disaggregation.

The prefill→decode handoff moves the prefilled KV prefix at paged-KV
**page granularity** over `MutableShmChannel` — the compiled-DAG plane's
seqlock shm transport, reused — with a ticket/pull protocol:

- the prefill side computes the prompt KV, slices it into
  ``[L, page_size, Hkv, Dh]`` pages, and ``export()``s them: a per-ticket
  shm channel is created and a sender thread starts streaming pages into
  it (the seqlock write blocks until the reader consumed the previous
  page, so at most ONE page is in flight per transfer — natural
  backpressure, no buffering tier);
- the proxy only ever sees the **ticket** (a small dict: channel path,
  page count, shapes, first token) — it never materializes KV;
- the decode side attaches to the channel by path and ``pull_pages()``
  them straight into its paged slot pool (engine ``submit_prefilled``
  adopts pages without reshaping).

Both ends must share one host (/dev/shm), which is the on-pod PD layout:
prefill and decode replicas co-locate per host and the proxy fans out
across hosts. Cross-host transfer is the ICI/RDMA follow-on.

(reference: llm/_internal/serve/serving_patterns/prefill_decode/pd_server.py
— the PDProxyServer + NIXL/LMCache KV-transfer pattern; here the transport
is the repo's own mutable-shm channel instead of RDMA, and the unit is the
paged-KV page so decode admission needs no reshape.)
"""

from __future__ import annotations

import logging
import threading
import uuid

import numpy as np

logger = logging.getLogger(__name__)

from ray_tpu.experimental.channel.channel import ChannelClosed
from ray_tpu.experimental.channel.mutable_shm import (MutableShmChannel,
                                                      create_mutable_channel)

# serialization slack per page message (pickle framing + dict keys); the
# payload itself is the two out-of-band numpy buffers
_WIRE_SLACK = 8192


class KVTransferError(RuntimeError):
    """A KV handoff failed mid-flight: the per-REQUEST failure (the other
    transfers and both replica pools keep serving)."""


def _metrics():
    from ray_tpu.util import metrics as met

    return (
        met.get_or_create(
            met.Counter, "ray_tpu_llm_pd_transfer_bytes_total",
            "KV bytes moved prefill->decode over the shm transfer plane"),
        met.get_or_create(
            met.Counter, "ray_tpu_llm_pd_kv_pages_total",
            "KV pages moved prefill->decode over the shm transfer plane"),
    )


class _Transfer:
    __slots__ = ("ticket_id", "channel", "thread", "failed", "trace_ctx")

    def __init__(self, ticket_id: str, channel: MutableShmChannel,
                 trace_ctx: dict | None = None):
        self.ticket_id = ticket_id
        self.channel = channel
        self.thread: threading.Thread | None = None
        self.failed: str | None = None
        # sampled request's span context, captured at export: the sender
        # thread runs outside the request's contextvar scope
        self.trace_ctx = trace_ctx


class PagedKVExporter:
    """Prefill-side registry of in-flight page transfers.

    ``export()`` returns the ticket immediately; a sender thread streams
    the pages and retires the channel (close → unlink) once the reader
    drained the last one. A receiver that never attaches, or dies
    mid-pull, times the sender out after ``send_timeout_s`` — the channel
    is torn down either way, so /dev/shm can't accumulate segments.
    """

    def __init__(self, *, send_timeout_s: float = 60.0):
        self.send_timeout_s = float(send_timeout_s)
        self._live: dict[str, _Transfer] = {}
        self._lock = threading.Lock()
        self._m_bytes, self._m_pages = _metrics()
        self.failures = 0        # transfers that did not complete
        self.last_failure = ""   # "<ticket>: <reason>" for triage

    # ------------------------------------------------------------- export

    def export(self, k: np.ndarray, v: np.ndarray, length: int,
               first_token: int, page_size: int,
               trace_ctx: dict | None = None) -> dict:
        """Slice a bucketed prompt KV (``[L, T, Hkv, Dh]``, T a multiple of
        ``page_size``) into pages and start streaming them. Returns the
        ticket the proxy forwards to the decode pool. ``trace_ctx`` (a
        sampled request's span context) makes the sender emit a
        ``pd:kv_send`` span covering the whole transfer."""
        k = np.asarray(k)
        v = np.asarray(v)
        L, T = k.shape[0], k.shape[1]
        if page_size <= 0 or T % page_size:
            raise ValueError(
                f"prefill bucket {T} is not a multiple of page_size "
                f"{page_size}: configure the prefill server with "
                f"min_bucket >= page_size")
        n_pages = T // page_size
        page_bytes = (k.nbytes + v.nbytes) // n_pages
        tid = uuid.uuid4().hex[:16]
        ch = create_mutable_channel(page_bytes + _WIRE_SLACK)
        try:
            tr = _Transfer(tid, ch, trace_ctx)
            with self._lock:
                self._live[tid] = tr
            tr.thread = threading.Thread(
                target=self._send, args=(tr, k, v, page_size, n_pages),
                daemon=True, name=f"pd-kv-send-{tid[:6]}")
            # thread spawn can fail (ulimit/fragmentation under load);
            # until start() succeeds the sender's finally owns nothing, so
            # the segment (and the ticket registration) must be rolled
            # back here or /dev/shm leaks one segment per failed export
            tr.thread.start()
        except BaseException:
            with self._lock:
                self._live.pop(tid, None)
            ch.close()
            ch.unlink()
            raise
        return {
            "ticket": tid,
            "path": ch.path,
            "capacity": ch.capacity,
            "n_pages": n_pages,
            "page_size": page_size,
            "length": int(length),
            "first_token": int(first_token),
            "bucket": T,
            "page_shape": (L, page_size, k.shape[2], k.shape[3]),
            "dtype": str(k.dtype),
        }

    def _send(self, tr: _Transfer, k, v, page_size: int, n_pages: int):
        import time as _time

        from ray_tpu.serve import request_context as rc

        ch = tr.channel
        t_send0 = _time.time()
        try:
            for i in range(n_pages):
                sl = slice(i * page_size, (i + 1) * page_size)
                kp = np.ascontiguousarray(k[:, sl])
                vp = np.ascontiguousarray(v[:, sl])
                t_w = _time.perf_counter()
                ch.write({"i": i, "k": kp, "v": vp},
                         timeout=self.send_timeout_s)
                # per-page backpressure wait: the seqlock write blocks
                # until the reader consumed the previous page, so this IS
                # how long the handoff serialized on the decode side
                rc.observe_phase(rc.PD_PHASE, "transfer_send_wait",
                                 _time.perf_counter() - t_w)
                self._m_bytes.inc(kp.nbytes + vp.nbytes)
                self._m_pages.inc()
            # the final page is published but possibly unread: wait for the
            # reader's ack before unlinking the segment
            ch.wait_drained(timeout=self.send_timeout_s)
        except ChannelClosed:
            tr.failed = "closed"  # teardown/abort raced the send: expected
        except TimeoutError:
            tr.failed = "timeout"  # receiver never attached or died mid-pull
            logger.warning("kv transfer %s: send timed out after %.1fs "
                           "(decode side never pulled, or died mid-pull)",
                           tr.ticket_id, self.send_timeout_s)
        except Exception as e:  # noqa: BLE001 — must never leak the segment
            tr.failed = f"{type(e).__name__}: {e}"
            logger.warning("kv transfer %s: sender failed: %s",
                           tr.ticket_id, tr.failed)
        finally:
            ch.close()
            ch.unlink()
            with self._lock:
                self._live.pop(tr.ticket_id, None)
                if tr.failed is not None:
                    self.failures += 1
                    self.last_failure = f"{tr.ticket_id}: {tr.failed}"
            if tr.trace_ctx:
                from ray_tpu.util import tracing

                tracing.emit_span_for(
                    tr.trace_ctx, "pd:kv_send", t_send0, _time.time(),
                    ok=tr.failed is None, ticket=tr.ticket_id,
                    pages=n_pages, failed=tr.failed or "")

    # ---------------------------------------------------------- lifecycle

    def pending(self) -> int:
        with self._lock:
            return len(self._live)

    def abort(self, ticket_id: str) -> None:
        """Kill one in-flight transfer (its puller observes ChannelClosed →
        KVTransferError). Used when the prefill replica is shutting down or
        the request was cancelled upstream."""
        with self._lock:
            tr = self._live.get(ticket_id)
        if tr is None:
            return
        tr.channel.close()
        if tr.thread is not None:
            tr.thread.join(timeout=5.0)

    def teardown(self) -> None:
        """Close every live channel, join the senders, unlink the segments.
        Safe to call twice; after it returns /dev/shm holds none of this
        exporter's ``rtpu_chan_*`` files."""
        with self._lock:
            live = list(self._live.values())
        for tr in live:
            tr.channel.close()
        for tr in live:
            if tr.thread is not None:
                tr.thread.join(timeout=5.0)
            tr.channel.unlink()
        with self._lock:
            for tr in live:
                self._live.pop(tr.ticket_id, None)


# ----------------------------------------------------------------- receiver


def pull_pages(ticket: dict, timeout_s: float = 60.0):
    """Decode-side pull: attach to the ticket's channel and yield
    ``(index, k_page, v_page)`` in order (each ``[L, page_size, Hkv, Dh]``).
    Every failure mode surfaces as KVTransferError naming the ticket — the
    per-request error contract."""
    import time as _time

    from ray_tpu.serve import request_context as rc

    tid = ticket.get("ticket", "?")
    try:
        ch = MutableShmChannel(ticket["path"], ticket["capacity"])
    except FileNotFoundError:
        raise KVTransferError(
            f"kv transfer {tid}: channel {ticket['path']} not found — the "
            "prefill replica died (or retired the ticket), or prefill and "
            "decode are not co-hosted (shm transfer is same-host)") from None
    try:
        for i in range(ticket["n_pages"]):
            t_r = _time.perf_counter()
            try:
                msg = ch.read(timeout=timeout_s)
            except ChannelClosed:
                raise KVTransferError(
                    f"kv transfer {tid}: prefill side closed after "
                    f"{i}/{ticket['n_pages']} pages (replica death or "
                    "abort mid-transfer)") from None
            except TimeoutError:
                raise KVTransferError(
                    f"kv transfer {tid}: timed out waiting for page {i} of "
                    f"{ticket['n_pages']} after {timeout_s}s") from None
            # per-page channel wait: how long decode admission stalled on
            # the transfer plane for this page
            rc.observe_phase(rc.PD_PHASE, "transfer_wait",
                             _time.perf_counter() - t_r)
            yield msg["i"], msg["k"], msg["v"]
    finally:
        ch.close_mapping()


def pull_all(ticket: dict, timeout_s: float = 60.0):
    """Pull the whole transfer: ``(k_pages, v_pages)`` as ordered lists of
    per-page arrays, ready for ``TPUEngine.submit_prefilled(k_pages=...)``."""
    k_pages: list = [None] * ticket["n_pages"]
    v_pages: list = [None] * ticket["n_pages"]
    for i, kp, vp in pull_pages(ticket, timeout_s):
        k_pages[i] = kp
        v_pages[i] = vp
    return k_pages, v_pages
