"""Prefill/decode disaggregation.

(reference: llm/_internal/serve/serving_patterns/prefill_decode/pd_server.py
— a PDProxyServer sends each request to a prefill deployment, transfers the
KV cache to a decode deployment (NIXL/LMCache over RDMA in the reference),
and streams tokens from the decoder. TPU mapping: prefill replicas own
prefill-shaped meshes, decode replicas own the slot cache; KV crosses via the
host object plane here (ICI remote-DMA is the on-pod fast path).)
"""

from __future__ import annotations

import numpy as np

from ray_tpu import serve
from ray_tpu.llm.config import LLMConfig
from ray_tpu.llm.engine import SamplingParams
from ray_tpu.llm.tokenizer import load_tokenizer


@serve.deployment(max_ongoing_requests=8)
class PrefillServer:
    """Prompt-only forward: returns the packed KV + the first sampled token."""

    def __init__(self, llm_config: LLMConfig):
        import jax

        from ray_tpu.models import decoding

        self.cfg, self.params = llm_config.build_model()
        self._decoding = decoding
        self._jax = jax
        ek = llm_config.engine_kwargs
        self.min_bucket = ek.get("min_bucket", 32)
        self.max_len = ek.get("max_len", self.cfg.max_seq_len)
        self.key = jax.random.PRNGKey(ek.get("seed", 0))

    def prefill(self, token_ids: list, temperature: float = 0.0) -> dict:
        from ray_tpu.llm.engine import bucket_for

        jax, decoding = self._jax, self._decoding
        import jax.numpy as jnp

        n = len(token_ids)
        bucket = bucket_for(n, self.min_bucket, self.max_len)
        if n > bucket:
            raise ValueError(f"prompt of {n} tokens exceeds max_len {self.max_len}")
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :n] = token_ids
        logits, kv = decoding.prefill(self.params, jnp.asarray(padded),
                                      jnp.int32(n), self.cfg)
        self.key, sub = jax.random.split(self.key)
        first = int(decoding.sample(logits[None, :], sub, temperature)[0])
        return {"k": np.asarray(kv["k"]), "v": np.asarray(kv["v"]),
                "length": n, "first_token": first}


@serve.deployment(max_ongoing_requests=8)
class DecodeServer:
    """Continues generation from a transferred KV prefix."""

    def __init__(self, llm_config: LLMConfig):
        from ray_tpu.llm.engine import TPUEngine

        self.engine = TPUEngine.from_config(llm_config)

    def decode(self, kv_pack: dict, params: dict | None = None) -> list:
        sp = SamplingParams(**(params or {}))
        from ray_tpu.llm.engine import _iter_request

        req = self.engine.submit_prefilled(
            kv_pack["k"], kv_pack["v"], kv_pack["length"],
            kv_pack["first_token"], sp)
        out = [kv_pack["first_token"]]
        out.extend(_iter_request(req))
        return out


@serve.deployment
class PDProxyServer:
    """(reference: pd_server.py PDProxyServer — composes the two pools.)"""

    def __init__(self, prefill_handle, decode_handle, tokenizer_spec="byte"):
        self.prefill = prefill_handle
        self.decode = decode_handle
        self.tokenizer = load_tokenizer(tokenizer_spec)

    def __call__(self, request: dict) -> dict:
        body = request.get("body") or request
        ids = self.tokenizer.encode(body.get("prompt", ""))
        kv = self.prefill.prefill.remote(
            ids, float(body.get("temperature", 0.0))).result(timeout_s=120)
        out_ids = self.decode.decode.remote(
            kv, {"max_tokens": int(body.get("max_tokens", 32)),
                 "temperature": float(body.get("temperature", 0.0))}
        ).result(timeout_s=120)
        return {"choices": [{"text": self.tokenizer.decode(out_ids)}],
                "usage": {"prompt_tokens": len(ids),
                          "completion_tokens": len(out_ids)}}


def build_pd_openai_app(llm_config: LLMConfig) -> serve.Application:
    return PDProxyServer.bind(PrefillServer.bind(llm_config),
                              DecodeServer.bind(llm_config),
                              llm_config.model_loading_config.tokenizer or "byte")
