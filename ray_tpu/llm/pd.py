"""Prefill/decode disaggregation over the paged-KV shm transfer plane.

(reference: llm/_internal/serve/serving_patterns/prefill_decode/pd_server.py
— a PDProxyServer sends each request to a prefill deployment, transfers the
KV cache to a decode deployment (NIXL/LMCache over RDMA in the reference),
and streams tokens from the decoder.)

TPU mapping here:

- **PrefillServer** runs the prompt-only forward on a prefill-shaped mesh
  and exports the resulting KV as paged-KV **pages** through
  `ray_tpu/llm/kv_transfer.py` (per-ticket MutableShmChannel + sender
  thread). Its reply is a small **ticket** — the proxy never materializes
  KV.
- **DecodeServer** runs STREAMED admission: the ticket registers with the
  replica's shared `BatchedKVPuller` (one polling thread for every
  in-flight transfer) and the engine adopts pages into the paged pool AS
  THEY ARRIVE (`submit_prefilled(kv_stream=...)`) — the decode loop keeps
  stepping other slots while later pages stream, and the row activates on
  the last page. Tokens stream out as they are produced.
- **PDProxyServer** composes the two pools and **streams**: the decode
  call is a serve streaming handle, so the proxy forwards tokens as they
  arrive instead of blocking on the full completion, and reports
  first-token latency separately from completion latency.

Prefill and decode replicas must share a host (/dev/shm) — the on-pod PD
layout. ICI remote-DMA is the cross-host follow-on.
"""

from __future__ import annotations

import dataclasses
import logging
import time

import numpy as np

from ray_tpu import serve
from ray_tpu.exceptions import DeadlineExceededError
from ray_tpu.serve import replica as _replica
from ray_tpu.llm.config import LLMConfig, PDConfig
from ray_tpu.llm.engine import SamplingParams, bucket_for
from ray_tpu.llm.kv_transfer import (BatchedKVPuller, KVPageStream,
                                     PagedKVExporter, pull_all)
from ray_tpu.llm.tokenizer import load_tokenizer
from ray_tpu.serve import request_context as _rc
from ray_tpu.util import tracing as _tracing

logger = logging.getLogger(__name__)

_TTFT_BOUNDS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                1.0, 2.5, 5.0, 10.0)


def _ttft_histogram():
    from ray_tpu.util import metrics as met

    return met.get_or_create(
        met.Histogram, "ray_tpu_llm_pd_ttft_seconds",
        "PD time-to-first-token split by phase (prefill: request->ticket; "
        "decode: dispatch->first decode-produced token)",
        boundaries=list(_TTFT_BOUNDS), tag_keys=("phase",))


def _pd_engine_kwargs(llm_config: LLMConfig) -> dict:
    """One normalization of engine_kwargs shared by BOTH pools, so prefill
    bucketing and the decode page pool can never disagree on shapes: PD
    defaults to the paged layout with pd_config.page_size, and min_bucket
    is bumped so every prompt bucket slices into whole pages."""
    pd = llm_config.pd_config or PDConfig()
    ek = dict(llm_config.engine_kwargs)
    ek.setdefault("kv_layout", "paged")
    ek.setdefault("page_size", pd.page_size)
    if ek["kv_layout"] == "paged":
        ek["min_bucket"] = max(ek.get("min_bucket", 32), ek["page_size"])
    return ek


class _PrefillJob:
    __slots__ = ("ids", "n", "bucket", "event", "logits", "k", "v", "error")

    def __init__(self, ids, n, bucket):
        import threading

        self.ids = ids
        self.n = n
        self.bucket = bucket
        self.event = threading.Event()
        self.logits = self.k = self.v = None
        self.error: BaseException | None = None


class PrefillCoalescer:
    """Admission batching for the dedicated prefill tier.

    Concurrent same-bucket prompts coalesce into ONE ``[B, T]``
    ``decoding.prefill_batch`` forward — the structural advantage of
    disaggregation the monolithic engine cannot copy: its prefills
    interleave with decode steps one prompt at a time. Baton-passing
    combiner, no dedicated thread: the first waiting caller becomes the
    leader, runs ONE batch (everything same-bucket queued at that
    moment, including its own job), releases leadership, and waiting
    callers promote themselves — batching emerges from bursts without
    adding a scheduling hop or an artificial wait (``window_s`` can add
    one for sparse arrivals). Each row's logits/KV are bit-identical to
    a solo ``[1, T]`` prefill — causality keeps rows independent."""

    def __init__(self, params, cfg, *, min_bucket: int, max_len: int,
                 max_batch: int = 4, window_s: float = 0.0):
        import threading

        self.params = params
        self.cfg = cfg
        self.min_bucket = min_bucket
        self.max_len = max_len
        self.max_batch = max(1, int(max_batch))
        self.window_s = float(window_s)
        self._cond = threading.Condition()
        self._pending: list = []
        self._leader_active = False
        self._stop = False
        self.batches = 0   # forwards run
        self.jobs = 0      # prompts served (jobs/batches = mean batch)

    def _run(self, batch: list) -> None:
        import jax.numpy as jnp

        from ray_tpu.models import decoding

        try:
            T = batch[0].bucket
            # prefill() floors the batch take to a power of two, so the
            # row count here is always one of O(log max_batch) shapes —
            # no pad rows, no wasted forward FLOPs
            tb = np.zeros((len(batch), T), np.int32)
            lens = np.zeros((len(batch),), np.int32)
            for b, j in enumerate(batch):
                tb[b, :j.n] = j.ids
                lens[b] = j.n
            logits, kv = decoding.prefill_batch(
                self.params, jnp.asarray(tb), jnp.asarray(lens), self.cfg)
            for b, j in enumerate(batch):
                j.logits = logits[b]
                j.k = kv["k"][:, b]
                j.v = kv["v"][:, b]
            self.batches += 1
            self.jobs += len(batch)
        except BaseException as e:  # noqa: BLE001 — the waiters MUST be
            # released with the failure, or every straggler hangs forever
            for j in batch:
                j.error = e
        finally:
            for j in batch:
                j.event.set()

    def prefill(self, token_ids: list):
        """Blocking: returns (logits_at_last [V], k [L, T, Hkv, Dh],
        v [L, T, Hkv, Dh], bucket) for this prompt, computed inside
        whichever coalesced forward picked the job up."""
        n = len(token_ids)
        job = _PrefillJob(token_ids, n, bucket_for(n, self.min_bucket,
                                                   self.max_len))
        with self._cond:
            if self._stop:
                raise RuntimeError("prefill coalescer is torn down")
            self._pending.append(job)
        while not job.event.is_set():
            with self._cond:
                while (not job.event.is_set() and self._leader_active
                       and not self._stop):
                    self._cond.wait(timeout=0.5)
                if job.event.is_set():
                    break
                if self._stop:
                    job.error = RuntimeError(
                        "prefill coalescer torn down mid-batch")
                    break
                self._leader_active = True
            try:
                if self.window_s:
                    time.sleep(self.window_s)  # sparse arrivals: wait a beat
                with self._cond:
                    batch = []
                    if self._pending:
                        bucket = self._pending[0].bucket  # FIFO fairness
                        group = [j for j in self._pending
                                 if j.bucket == bucket][:self.max_batch]
                        # floor power of two: prefill_batch compiles per
                        # pow2 row count, and padding 3→4 or 5→8 would
                        # BURN the rows batching is supposed to save —
                        # leftovers catch the next baton immediately
                        take = 1 << (len(group).bit_length() - 1)
                        batch = group[:take]
                        for j in batch:
                            self._pending.remove(j)
                if batch:
                    self._run(batch)
            finally:
                with self._cond:
                    self._leader_active = False
                    self._cond.notify_all()
        if job.error is not None:
            raise job.error
        return job.logits, job.k, job.v, job.bucket

    def teardown(self) -> None:
        """Fail queued jobs and refuse new ones. Safe to call twice."""
        with self._cond:
            self._stop = True
            pending, self._pending = self._pending, []
            self._cond.notify_all()
        for j in pending:
            j.error = RuntimeError("prefill coalescer torn down")
            j.event.set()


@serve.deployment(max_ongoing_requests=8)
class PrefillServer:
    """Prompt-only forward: pages the prefilled KV into the transfer plane
    and returns the ticket + the first sampled token."""

    def __init__(self, llm_config: LLMConfig):
        import jax

        from ray_tpu.models import decoding

        self.cfg, self.params = llm_config.build_model()
        self._decoding = decoding
        self._jax = jax
        ek = _pd_engine_kwargs(llm_config)
        pd = llm_config.pd_config or PDConfig()
        self.page_size = ek["page_size"]
        self.min_bucket = max(ek.get("min_bucket", 32), self.page_size)
        self.max_len = ek.get("max_len", self.cfg.max_seq_len)
        import threading

        self.key = jax.random.PRNGKey(ek.get("seed", 0))
        # replica methods run on several threads, and the coalescer wakes
        # a whole batch of them at once: the read-split-write of the
        # shared key must be atomic or concurrent requests sample with
        # the SAME subkey (correlated first tokens)
        self._key_lock = threading.Lock()
        self.exporter = PagedKVExporter(
            send_timeout_s=pd.transfer_timeout_s,
            prefetch_pages=pd.prefetch_depth)
        # admission batching: concurrent prompts share one [B, T] forward
        self.coalescer = PrefillCoalescer(
            self.params, self.cfg, min_bucket=self.min_bucket,
            max_len=self.max_len, max_batch=pd.prefill_batch_max,
            window_s=pd.prefill_batch_window_s)

    def prefill(self, token_ids: list, temperature: float = 0.0) -> dict:
        """Returns the transfer TICKET (kv_transfer.py) — the KV itself
        streams page-by-page to whichever decode replica pulls it.
        Concurrent calls coalesce into one batched forward
        (PrefillCoalescer) before each row exports its own ticket."""
        jax, decoding = self._jax, self._decoding

        n = len(token_ids)
        bucket = bucket_for(n, self.min_bucket, self.max_len)
        if n > bucket:
            raise ValueError(f"prompt of {n} tokens exceeds max_len {self.max_len}")
        t0 = time.time()
        logits, k, v, bucket = self.coalescer.prefill(list(token_ids))
        with self._key_lock:
            self.key, sub = jax.random.split(self.key)
        first = int(decoding.sample(logits[None, :], sub, temperature)[0])
        _tracing.emit_child_span("pd:prefill_forward", t0, time.time(),
                                 tokens=n, bucket=bucket)
        # sampled requests: the sender thread runs outside the request's
        # contextvar scope, so its pd:kv_send span context rides the ticket
        return self.exporter.export(np.asarray(k), np.asarray(v),
                                    n, first, self.page_size,
                                    trace_ctx=_tracing.inject())

    def abort_transfer(self, ticket_id: str) -> None:
        """Best-effort: retire an exported ticket whose consumer went away
        (client disconnect before/while the decode side pulled) so the
        sender thread stops now instead of at its send timeout. A ticket
        another replica exported — or one already settled — is a no-op."""
        self.exporter.abort(ticket_id)

    def transfer_stats(self) -> dict:
        return {"pending_transfers": self.exporter.pending(),
                "failed_transfers": self.exporter.failures,
                "last_failure": self.exporter.last_failure,
                "page_size": self.page_size,
                "prefill_batches": self.coalescer.batches,
                "prefill_jobs": self.coalescer.jobs}

    def __del__(self):
        try:
            self.coalescer.teardown()
            self.exporter.teardown()
        except Exception:
            pass


@serve.deployment(max_ongoing_requests=8)
class DecodeServer:
    """Continues generation from a transferred paged-KV prefix, admitting
    pulled pages straight into the engine's continuous-batching slots."""

    def __init__(self, llm_config: LLMConfig):
        from ray_tpu.llm.engine import TPUEngine

        pd = llm_config.pd_config or PDConfig()
        cfg = dataclasses.replace(llm_config,
                                  engine_kwargs=_pd_engine_kwargs(llm_config))
        self.engine = TPUEngine.from_config(cfg)
        self.pull_timeout_s = pd.transfer_timeout_s
        # ONE polling thread multiplexes every in-flight transfer on this
        # replica (streamed admission); None = legacy pull-then-admit
        self.puller = BatchedKVPuller() if pd.batched_pull else None

    def decode_stream(self, ticket: dict, params: dict | None = None):
        """Generator over generated token ids: the transferred first token
        immediately (TTFT is not gated on the page transfer), then the
        engine's tokens as the decode loop produces them. The default
        path STREAMS admission: the ticket registers with the replica's
        batched puller and the engine adopts pages as they arrive, so
        decode of other slots overlaps this request's transfer and the
        slot activates on the last page. Transfer failures raise
        KVTransferError — a clean per-request error; the engine and the
        other in-flight requests keep serving.

        Sampled requests emit the decode-side phase spans here:
        ``pd:kv_transfer`` (the page pull), ``pd:admission`` (submit →
        slot bind, retroactive from the engine's request stamps) and
        ``pd:decode`` (first engine token → stream end)."""
        from ray_tpu.llm.engine import _iter_request
        from ray_tpu.llm.kv_transfer import pull_pages

        # capture: the generator body runs across many __next__ calls but
        # always on the activated task's thread — the captured context is
        # the one stable handle for retroactive span emission
        ctx = _tracing.current_context()
        sp = SamplingParams(**(params or {}))
        yield ticket["first_token"]
        if sp.max_tokens <= 1:
            # budget spent by the transferred token: drain the channel so
            # the prefill side retires it, but skip slot admission — via
            # the SAME batched puller (one wake serves this drain and
            # every live transfer), never the whole prefix in host memory
            if self.puller is not None:
                self.puller.drain(ticket, timeout_s=self.pull_timeout_s)
            else:
                for _ in pull_pages(ticket, timeout_s=self.pull_timeout_s):
                    pass
            return
        t_pull = time.time()
        deadline_ts = _replica.request_deadline() or 0.0
        if self.puller is not None:
            stream = KVPageStream(ticket["n_pages"], ticket["page_size"])
            self.puller.pull(ticket, stream, timeout_s=self.pull_timeout_s)
            req = self.engine.submit_prefilled(
                length=ticket["length"], first_token=ticket["first_token"],
                params=sp, kv_stream=stream, deadline_ts=deadline_ts)
        else:
            stream = None
            k_pages, v_pages = pull_all(ticket, timeout_s=self.pull_timeout_s)
            _tracing.emit_span_for(ctx, "pd:kv_transfer", t_pull, time.time(),
                                   ticket=ticket.get("ticket", ""),
                                   pages=ticket["n_pages"])
            req = self.engine.submit_prefilled(
                length=ticket["length"], first_token=ticket["first_token"],
                params=sp, k_pages=k_pages, v_pages=v_pages,
                deadline_ts=deadline_ts)

        fin = {"done": False}

        def _abort():
            """Reclaim BOTH planes mid-stream: the decode slot + granted
            KV pages (engine abort) and the in-flight page transfer
            (puller abort closes the channel, which also makes the
            prefill-side sender retire its ticket). Idempotent: finished
            requests no-op in both registries."""
            if fin["done"]:
                return
            try:
                self.engine.abort_request(req.rid)
                if self.puller is not None:
                    self.puller.abort(ticket.get("ticket", ""))
            finally:
                _rc.count_cancellation("pd")

        # serve-plane cancel (client disconnect seen by the proxy, explicit
        # cancel(), timed-out caller) lands here via the replica's holder
        _replica.on_cancel(_abort)
        n = 0
        t_dec = time.time()
        try:
            it = _iter_request(req)
            for tok in it:
                if n == 0 and ctx is not None:
                    if stream is not None:
                        # streamed path: the transfer overlapped decode;
                        # its span closes at the stream's last page
                        _tracing.emit_span_for(
                            ctx, "pd:kv_transfer", t_pull,
                            stream.finished_ts or time.time(),
                            ticket=ticket.get("ticket", ""),
                            pages=ticket["n_pages"])
                    if req.admitted_ts:
                        # the engine stamped the slot bind: emit the
                        # admission wait retroactively now that it is known
                        _tracing.emit_span_for(ctx, "pd:admission",
                                               req.submitted_ts,
                                               req.admitted_ts)
                n += 1
                yield tok
            fin["done"] = True
        finally:
            if not fin["done"]:
                # consumer abandoned the stream (GeneratorExit from the
                # replica's close()) or it failed mid-decode: reclaim now
                _abort()
            if ctx is not None:
                _tracing.emit_span_for(ctx, "pd:decode", t_dec, time.time(),
                                       tokens=n)

    def decode(self, ticket: dict, params: dict | None = None) -> list:
        """Blocking form (compat surface for non-streaming callers)."""
        return list(self.decode_stream(ticket, params))

    def engine_stats(self) -> dict:
        st = self.engine.stats()
        if self.puller is not None:
            st["pulls_in_flight"] = self.puller.pending()
        return st

    def __del__(self):
        try:
            if self.puller is not None:
                self.puller.teardown()
            self.engine.shutdown()
        except Exception:
            pass


@serve.deployment
class PDProxyServer:
    """(reference: pd_server.py PDProxyServer — composes the two pools.)

    The decode leg is a serve STREAMING handle: tokens forward as they are
    produced, first-token latency is measured (and exported per phase via
    ray_tpu_llm_pd_ttft_seconds) instead of being buried in one blocking
    result() call."""

    def __init__(self, prefill_handle, decode_handle, tokenizer_spec="byte",
                 request_timeout_s: float = 120.0):
        self.prefill = prefill_handle
        self.decode = decode_handle
        self.tokenizer = load_tokenizer(tokenizer_spec)
        self.request_timeout_s = request_timeout_s
        self._m_ttft = _ttft_histogram()

    def _pump(self, body: dict, timing: dict):
        """Drive one request through both pools, yielding token ids as they
        arrive; `timing` is filled with the latency split for `usage`."""
        ids = self.tokenizer.encode(body.get("prompt", ""))
        timing["prompt_tokens"] = len(ids)
        t0 = time.monotonic()
        w0 = time.time()
        # the proxy's own request deadline (set by the HTTP ingress) rides
        # into both pools; each leg's blocking wait is clamped to the
        # remaining budget so a queued prefill can't eat the decode's time
        deadline_ts = _replica.request_deadline()
        budget_s = self.request_timeout_s
        if deadline_ts:
            rem = _rc.deadline_remaining(deadline_ts)
            if rem is not None:
                if rem <= 0:
                    _rc.count_cancellation("pd")
                    raise DeadlineExceededError(
                        "pd proxy: deadline expired before prefill dispatch")
                budget_s = min(budget_s, rem)
        ticket = self.prefill.prefill.remote(
            ids, float(body.get("temperature", 0.0)),
            _deadline_ts=deadline_ts,
        ).result(timeout_s=budget_s)
        # the first token is sampled BY prefill and rides the ticket: its
        # arrival is the request's time-to-first-token
        timing["ttft_s"] = time.monotonic() - t0
        self._m_ttft.observe(timing["ttft_s"], tags={"phase": "prefill"})
        _tracing.emit_child_span("pd:prefill", w0, w0 + timing["ttft_s"],
                                 prompt_tokens=len(ids))
        t1 = time.monotonic()
        w1 = time.time()
        stream = self.decode.options(
            stream=True, stream_item_timeout_s=self.request_timeout_s,
        ).decode_stream.remote(
            ticket, {"max_tokens": int(body.get("max_tokens", 32)),
                     "temperature": float(body.get("temperature", 0.0))},
            _deadline_ts=deadline_ts)
        finished = False
        try:
            for i, tok in enumerate(stream):
                if i == 1:
                    # first DECODE-produced token: page pull + slot admission
                    # + one decode step — the decode half of the TTFT split
                    decode_ttft = time.monotonic() - t1
                    timing["decode_ttft_s"] = decode_ttft
                    self._m_ttft.observe(decode_ttft, tags={"phase": "decode"})
                    _tracing.emit_child_span("pd:decode_first_token", w1,
                                             w1 + decode_ttft)
                yield tok
            finished = True
        finally:
            if not finished:
                # abandoned mid-decode (client gone) or failed: cancel the
                # decode replica's stream (which aborts the engine request
                # and the page pull) and best-effort retire the exported
                # ticket on the prefill tier so its sender stops too
                cancel = getattr(stream, "cancel", None)
                if cancel is not None:
                    try:
                        cancel()
                    except Exception as e:  # noqa: BLE001 — best-effort
                        logger.debug("pd decode-stream cancel failed: %r", e)
                try:
                    self.prefill.abort_transfer.remote(
                        ticket.get("ticket", ""))
                except Exception as e:  # noqa: BLE001 — best-effort
                    logger.debug("pd prefill abort_transfer failed: %r", e)
        timing["total_time_s"] = time.monotonic() - t0

    def _usage(self, timing: dict, n_out: int) -> dict:
        return {"prompt_tokens": timing.get("prompt_tokens", 0),
                "completion_tokens": n_out,
                # first-token latency reported SEPARATELY from completion
                "ttft_s": round(timing.get("ttft_s", 0.0), 4),
                "total_time_s": round(timing.get("total_time_s", 0.0), 4)}

    def _record(self, request: dict, timing: dict, t0: float,
                n_out: int, status) -> None:
        """PD-phase flight-recorder entry: richer than the HTTP proxy's
        (prefill vs decode TTFT split), same ring/GCS log."""
        rec = {"request_id": request.get("request_id") or _rc.new_request_id(),
               "component": "pd_proxy", "ts": time.time(),
               "phases": {"prefill": round(timing.get("ttft_s", 0.0), 6),
                          "decode_first_token": round(
                              timing.get("decode_ttft_s", 0.0), 6)},
               "completion_tokens": n_out}
        _rc.record_request(rec, t0, status=status)

    def __call__(self, request: dict) -> dict:
        body = request.get("body") or request
        timing: dict = {}
        t0 = time.perf_counter()
        status = "error"
        out_ids: list = []
        try:
            out_ids = list(self._pump(body, timing))
            status = 200
        finally:
            # failed requests (KVTransferError, replica death) are exactly
            # the ones the flight recorder must explain — record either way
            self._record(request, timing, t0, len(out_ids), status)
        return {"choices": [{"index": 0,
                             "text": self.tokenizer.decode(out_ids),
                             "finish_reason": "stop"}],
                "usage": self._usage(timing, len(out_ids))}

    def stream_request(self, request: dict):
        """Streaming HTTP entry (SSE through the proxy): one chunk per
        token, then a final usage-bearing chunk — parity with
        LLMServer.stream_request."""
        body = request.get("body") or request
        timing: dict = {}
        n = 0
        t0 = time.perf_counter()
        status = "aborted"  # GeneratorExit (client gone) or mid-stream error
        gen = self._pump(body, timing)
        try:
            for tok in gen:
                n += 1
                yield {"object": "text_completion.chunk",
                       "choices": [{"index": 0,
                                    "text": self.tokenizer.decode([tok]),
                                    "finish_reason": None}]}
            status = "stream"
        finally:
            # explicit close: on abandonment the pump's finally must run
            # NOW (cancel the decode stream, retire the prefill ticket),
            # not whenever the suspended frame gets collected
            gen.close()
            self._record(request, timing, t0, n, status)
        yield {"object": "text_completion.chunk",
               "choices": [{"index": 0, "text": "", "finish_reason": "stop"}],
               "usage": self._usage(timing, n)}


def build_pd_openai_app(llm_config: LLMConfig) -> serve.Application:
    pd = llm_config.pd_config or PDConfig()
    prefill = PrefillServer.options(
        num_replicas=pd.num_prefill_replicas).bind(llm_config)
    decode = DecodeServer.options(
        num_replicas=pd.num_decode_replicas).bind(llm_config)
    return PDProxyServer.bind(
        prefill, decode,
        llm_config.model_loading_config.tokenizer or "byte")
