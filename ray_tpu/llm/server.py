"""LLMServer deployment + OpenAI-compatible ingress.

(reference: llm/_internal/serve/core/server/llm_server.py:97 LLMServer wraps
the engine as a Serve deployment; core/ingress/ provides the OpenAI-style
/v1/completions + /v1/chat/completions routes; build_openai_app composes
them. Same layering here over the TPU engine.)
"""

from __future__ import annotations

import time

from ray_tpu import serve
from ray_tpu.llm.config import LLMConfig
from ray_tpu.llm.engine import SamplingParams, TPUEngine
from ray_tpu.llm.tokenizer import load_tokenizer


@serve.deployment(max_ongoing_requests=16)
class LLMServer:
    """One engine per replica; requests ride replica threads and park on the
    engine's continuous-batching queue."""

    def __init__(self, llm_config: LLMConfig):
        self.config = llm_config
        self.engine = TPUEngine.from_config(llm_config)
        self.tokenizer = load_tokenizer(llm_config.model_loading_config.tokenizer)

    def _params(self, body: dict) -> SamplingParams:
        eos = getattr(self.tokenizer, "eos_token_id", None)
        return SamplingParams(
            max_tokens=int(body.get("max_tokens", 64)),
            temperature=float(body.get("temperature", 0.0)),
            top_k=int(body.get("top_k", 0)),
            stop_token_ids=(eos,) if eos is not None else (),
        )

    def completions(self, body: dict) -> dict:
        prompt = body.get("prompt", "")
        t0 = time.monotonic()
        ids = self.tokenizer.encode(prompt)
        out_ids = self.engine.generate(ids, self._params(body))
        dt = time.monotonic() - t0
        return {
            "object": "text_completion",
            "model": self.config.model_loading_config.model_id,
            "choices": [{"index": 0, "text": self.tokenizer.decode(out_ids),
                         "finish_reason": "stop"}],
            "usage": {"prompt_tokens": len(ids),
                      "completion_tokens": len(out_ids),
                      "total_time_s": round(dt, 4)},
        }

    def chat(self, body: dict) -> dict:
        msgs = body.get("messages", [])
        prompt = "".join(f"<{m.get('role', 'user')}>{m.get('content', '')}\n"
                         for m in msgs) + "<assistant>"
        out = self.completions({**body, "prompt": prompt})
        out["object"] = "chat.completion"
        out["choices"] = [{"index": 0, "finish_reason": "stop",
                           "message": {"role": "assistant",
                                       "content": out["choices"][0]["text"]}}]
        return out

    def engine_stats(self) -> dict:
        return self.engine.stats()

    def completions_stream(self, body: dict):
        """Token-by-token SSE chunks, OpenAI text_completion.chunk shape
        (reference: llm serve streams engine tokens through the replica —
        llm_server.py + proxy streaming)."""
        prompt = body.get("prompt", "")
        model = self.config.model_loading_config.model_id
        ids = self.tokenizer.encode(prompt)
        for tok in self.engine.stream(ids, self._params(body)):
            yield {
                "object": "text_completion.chunk",
                "model": model,
                "choices": [{"index": 0, "text": self.tokenizer.decode([tok]),
                             "finish_reason": None}],
            }
        yield {"object": "text_completion.chunk", "model": model,
               "choices": [{"index": 0, "text": "", "finish_reason": "stop"}]}

    def chat_stream(self, body: dict):
        msgs = body.get("messages", [])
        prompt = "".join(f"<{m.get('role', 'user')}>{m.get('content', '')}\n"
                         for m in msgs) + "<assistant>"
        for chunk in self.completions_stream({**body, "prompt": prompt}):
            text = chunk["choices"][0].pop("text")
            chunk["object"] = "chat.completion.chunk"
            chunk["choices"][0]["delta"] = {"content": text}
            yield chunk

    def stream_request(self, request: dict):
        """Streaming HTTP entry (SSE through the proxy)."""
        path = request.get("path", "")
        body = request.get("body") or {}
        if path.endswith("/chat/completions"):
            yield from self.chat_stream(body)
        else:
            yield from self.completions_stream(body)

    def __call__(self, request: dict) -> dict:
        """HTTP entry: route by path suffix (OpenAI wire shapes)."""
        path = request.get("path", "")
        body = request.get("body") or {}
        if path.endswith("/chat/completions"):
            return self.chat(body)
        if path.endswith("/stats"):
            # engine observability: slots/pages plus the prefix-cache and
            # speculative sections when those features are enabled
            return self.engine_stats()
        return self.completions(body)


def build_openai_app(llm_config: LLMConfig) -> serve.Application:
    """(reference: llm serve builds an ingress app from LLMConfig —
    serve/core/ingress; deployment options come from deployment_config.)"""
    dep = LLMServer
    opts = dict(llm_config.deployment_config)
    # LLM serving defaults to prefix-aware routing: requests sharing a prompt
    # prefix hit the same replica for KV reuse (reference: llm request_router/
    # prefix_aware/prefix_tree.py)
    opts.setdefault("request_router", "prefix_aware")
    dep = dep.options(**opts)
    return dep.bind(llm_config)
