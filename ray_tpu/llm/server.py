"""LLMServer deployment + OpenAI-compatible ingress.

(reference: llm/_internal/serve/core/server/llm_server.py:97 LLMServer wraps
the engine as a Serve deployment; core/ingress/ provides the OpenAI-style
/v1/completions + /v1/chat/completions routes; build_openai_app composes
them. Same layering here over the TPU engine.)
"""

from __future__ import annotations

import os
import time

from ray_tpu import serve
from ray_tpu.serve import replica as _replica
from ray_tpu.llm.config import LLMConfig
from ray_tpu.llm.engine import SamplingParams, TPUEngine
from ray_tpu.llm.tokenizer import load_tokenizer


class _AdapterHandle:
    """The multiplex cache entry for a loaded adapter: eviction from the
    LRU calls __del__, which frees the engine's bank slot (unless requests
    are mid-flight — then the slot frees on the next load's eviction pass).
    ensure() re-loads the adapter if the engine-side eviction pass freed
    its bank slot while this cache entry stayed live."""

    def __init__(self, engine: TPUEngine, loading_path: str,
                 adapter_id: str):
        self.engine = engine
        self.loading_path = loading_path
        self.adapter_id = adapter_id
        self._evicted = False

    def ensure(self) -> None:
        if self.adapter_id not in self.engine.list_loras():
            _load_weights(self.engine, self.loading_path, self.adapter_id)

    def __del__(self):
        # multiplex eviction calls __del__ explicitly AND the interpreter
        # calls it again at GC time — without the guard the second call
        # could unload an adapter that was RELOADED after eviction
        if self._evicted:
            return
        self._evicted = True
        try:
            self.engine.unload_lora(self.adapter_id)
        except Exception:
            pass  # in use or already gone: next load's eviction retries


def _load_weights(engine: TPUEngine, loading_path: str,
                  adapter_id: str) -> None:
    """Read <loading_path>/<adapter_id>.npz (A_q/B_q/A_v/B_v layer-stacked,
    optional scalar alpha) into the engine bank, evicting an idle adapter
    if the bank is full (reference: lora_serve_utils.py downloads adapter
    weights by model id and hands them to the engine)."""
    import numpy as np

    path = os.path.join(loading_path, f"{adapter_id}.npz")
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"no adapter {adapter_id!r} under {loading_path!r}")
    z = np.load(path)
    weights = {k: z[k] for k in ("A_q", "B_q", "A_v", "B_v") if k in z.files}
    alpha = float(z["alpha"]) if "alpha" in z.files else None
    try:
        engine.load_lora(adapter_id, weights, alpha=alpha)
    except ValueError as e:
        if "already loaded" in str(e):
            return  # a concurrent ensure() won the race: done
        raise
    except RuntimeError:
        # bank full: evict an idle adapter (multiplex eviction may have
        # been unable to free it while requests were live)
        for name in engine.list_loras():
            try:
                engine.unload_lora(name)
                break
            except RuntimeError:
                continue  # live requests: try the next one
            except KeyError:
                # a concurrent thread unloaded it between list and unload —
                # that freed a slot, which is all this loop is after
                break
        try:
            engine.load_lora(adapter_id, weights, alpha=alpha)
        except ValueError as e:
            if "already loaded" not in str(e):
                raise


def _load_adapter_into_engine(engine: TPUEngine, loading_path: str,
                              adapter_id: str) -> _AdapterHandle:
    if adapter_id not in engine.list_loras():
        _load_weights(engine, loading_path, adapter_id)
    return _AdapterHandle(engine, loading_path, adapter_id)


@serve.deployment(max_ongoing_requests=16)
class LLMServer:
    """One engine per replica; requests ride replica threads and park on the
    engine's continuous-batching queue."""

    def __init__(self, llm_config: LLMConfig):
        self.config = llm_config
        self.engine = TPUEngine.from_config(llm_config)
        self.tokenizer = load_tokenizer(llm_config.model_loading_config.tokenizer)
        self._get_adapter = None
        lc = getattr(llm_config, "lora_config", None)
        if lc is not None:
            from ray_tpu.serve.multiplex import multiplexed

            engine, path = self.engine, lc.dynamic_lora_loading_path

            @multiplexed(
                max_num_models_per_replica=lc.max_num_adapters_per_replica)
            def _get(adapter_id: str):
                return _load_adapter_into_engine(engine, path, adapter_id)

            self._get_adapter = _get

    def _maybe_lora(self, body: dict) -> str | None:
        """A request whose `model` names something other than the base
        model is a LoRA adapter request (reference: serve LLM treats
        model_id as the multiplexed adapter id — lora_serve_utils.py)."""
        model = body.get("model")
        if (self._get_adapter is None or not model
                or model == self.config.model_loading_config.model_id):
            return None
        handle = self._get_adapter(model)  # load or LRU-refresh (mux cache)
        handle.ensure()  # heal a cache hit whose bank slot was evicted
        return model

    def _params(self, body: dict) -> SamplingParams:
        eos = getattr(self.tokenizer, "eos_token_id", None)
        guided = None
        choices = body.get("guided_choice")
        if choices:
            # structured output, choice flavor (reference: guided_decoding
            # params passed through the OpenAI surface to the engine —
            # vllm_engine_stage.py:278): output must be exactly one of the
            # given strings, enforced token-by-token in the decode step
            from ray_tpu.llm.guided import GuidedFSM

            if eos is None:
                raise ValueError(
                    "guided_choice requires a tokenizer with an EOS token")
            encoded = [self._encode_continuation(c) for c in choices]
            guided = GuidedFSM.from_choices(
                encoded, self.engine.cfg.vocab_size, eos)
            # the guided contract is "exactly one of the choices": never
            # let max_tokens cut the FSM off mid-choice
            body = {**body, "max_tokens": max(
                int(body.get("max_tokens", 64)),
                max(len(e) for e in encoded) + 1)}
        elif body.get("guided_regex"):
            # regex flavor: exact for byte-level tokenizers, where one
            # token is one character (reference: guided_decoding regex)
            from ray_tpu.llm.guided import GuidedFSM

            if eos is None:
                raise ValueError(
                    "guided_regex requires a tokenizer with an EOS token")
            if not getattr(self.tokenizer, "byte_level", False):
                raise ValueError(
                    "guided_regex needs a byte-level tokenizer (one token "
                    "per character); use guided_choice for subword models")
            if len(body["guided_regex"]) > 1024:
                raise ValueError("guided_regex longer than 1024 chars")
            guided = GuidedFSM.from_regex(
                body["guided_regex"], self.engine.cfg.vocab_size, eos)
            # a budget below the pattern's minimum length could only ever
            # return a truncated non-match: bump like guided_choice does
            min_len = int(guided.dist[guided.start])
            if min_len < 2 ** 31 - 1:
                body = {**body, "max_tokens": max(
                    int(body.get("max_tokens", 64)), min_len + 1)}
        return SamplingParams(
            max_tokens=int(body.get("max_tokens", 64)),
            temperature=float(body.get("temperature", 0.0)),
            top_k=int(body.get("top_k", 0)),
            stop_token_ids=(eos,) if eos is not None else (),
            guided=guided,
        )

    def _encode_continuation(self, text: str) -> list:
        """Tokenize a guided choice as a CONTINUATION: BOS/special tokens
        would otherwise be baked into the FSM and forced into the output."""
        try:
            return self.tokenizer.encode(text, add_bos=False)
        except TypeError:
            pass
        try:
            return self.tokenizer.encode(text, add_special_tokens=False)
        except TypeError:
            return self.tokenizer.encode(text)

    def _submit_retry(self, ids: list, params, lora: str | None):
        """Submit with one evicted-adapter reload retry: multiplex churn can
        evict the adapter between ensure() and submit. One shared path for
        blocking and streaming completions; returns the engine request
        (iterable over generated tokens)."""
        deadline_ts = _replica.request_deadline() or 0.0
        try:
            req = self.engine.submit(ids, params, lora=lora,
                                     deadline_ts=deadline_ts)
        except KeyError:
            if lora is None:
                raise
            self._get_adapter(lora).ensure()
            req = self.engine.submit(ids, params, lora=lora,
                                     deadline_ts=deadline_ts)
        # a cancel observed by the serve plane (client disconnect, explicit
        # cancel(), timed-out caller) reclaims this request's decode slot
        # and KV pages in one step instead of decoding to max_tokens
        _replica.on_cancel(lambda: self.engine.abort_request(req.rid))
        return req

    def completions(self, body: dict) -> dict:
        prompt = body.get("prompt", "")
        t0 = time.monotonic()
        lora = self._maybe_lora(body)
        ids = self.tokenizer.encode(prompt)
        out_ids = list(self._submit_retry(ids, self._params(body), lora))
        dt = time.monotonic() - t0
        return {
            "object": "text_completion",
            "model": lora or self.config.model_loading_config.model_id,
            "choices": [{"index": 0, "text": self.tokenizer.decode(out_ids),
                         "finish_reason": "stop"}],
            "usage": {"prompt_tokens": len(ids),
                      "completion_tokens": len(out_ids),
                      "total_time_s": round(dt, 4)},
        }

    def chat(self, body: dict) -> dict:
        msgs = body.get("messages", [])
        prompt = "".join(f"<{m.get('role', 'user')}>{m.get('content', '')}\n"
                         for m in msgs) + "<assistant>"
        out = self.completions({**body, "prompt": prompt})
        out["object"] = "chat.completion"
        out["choices"] = [{"index": 0, "finish_reason": "stop",
                           "message": {"role": "assistant",
                                       "content": out["choices"][0]["text"]}}]
        return out

    def engine_stats(self) -> dict:
        return self.engine.stats()

    def completions_stream(self, body: dict):
        """Token-by-token SSE chunks, OpenAI text_completion.chunk shape
        (reference: llm serve streams engine tokens through the replica —
        llm_server.py + proxy streaming)."""
        prompt = body.get("prompt", "")
        lora = self._maybe_lora(body)
        model = lora or self.config.model_loading_config.model_id
        ids = self.tokenizer.encode(prompt)
        req = self._submit_retry(ids, self._params(body), lora)
        for tok in req:
            yield {
                "object": "text_completion.chunk",
                "model": model,
                "choices": [{"index": 0, "text": self.tokenizer.decode([tok]),
                             "finish_reason": None}],
            }
        yield {"object": "text_completion.chunk", "model": model,
               "choices": [{"index": 0, "text": "", "finish_reason": "stop"}]}

    def chat_stream(self, body: dict):
        msgs = body.get("messages", [])
        prompt = "".join(f"<{m.get('role', 'user')}>{m.get('content', '')}\n"
                         for m in msgs) + "<assistant>"
        for chunk in self.completions_stream({**body, "prompt": prompt}):
            text = chunk["choices"][0].pop("text")
            chunk["object"] = "chat.completion.chunk"
            chunk["choices"][0]["delta"] = {"content": text}
            yield chunk

    def stream_request(self, request: dict):
        """Streaming HTTP entry (SSE through the proxy)."""
        path = request.get("path", "")
        body = request.get("body") or {}
        if path.endswith("/chat/completions"):
            yield from self.chat_stream(body)
        else:
            yield from self.completions_stream(body)

    def __call__(self, request: dict) -> dict:
        """HTTP entry: route by path suffix (OpenAI wire shapes)."""
        path = request.get("path", "")
        body = request.get("body") or {}
        if path.endswith("/chat/completions"):
            return self.chat(body)
        if path.endswith("/stats"):
            # engine observability: slots/pages plus the prefix-cache and
            # speculative sections when those features are enabled
            return self.engine_stats()
        return self.completions(body)


def build_openai_app(llm_config: LLMConfig) -> serve.Application:
    """(reference: llm serve builds an ingress app from LLMConfig —
    serve/core/ingress; deployment options come from deployment_config.)"""
    dep = LLMServer
    opts = dict(llm_config.deployment_config)
    # LLM serving defaults to prefix-aware routing: requests sharing a prompt
    # prefix hit the same replica for KV reuse (reference: llm request_router/
    # prefix_aware/prefix_tree.py)
    opts.setdefault("request_router", "prefix_aware")
    dep = dep.options(**opts)
    return dep.bind(llm_config)
