"""Tokenizers for the LLM stack.

ByteTokenizer is the built-in default (self-contained, vocab 259). HF
tokenizers (transformers is in the image) load from a local path when given —
remote downloads are not assumed.
(reference: the LLM stack tokenizes via the model's HF tokenizer inside vLLM;
llm/_internal/batch/stages/ tokenize stages.)
"""

from __future__ import annotations


class ByteTokenizer:
    """UTF-8 bytes + BOS/EOS/PAD. vocab = 256 + 3 specials."""

    PAD = 256
    BOS = 257
    EOS = 258
    vocab_size = 259
    # one token per byte: character-level FSMs (guided_regex) are exact
    byte_level = True

    @property
    def eos_token_id(self) -> int:
        return self.EOS

    def encode(self, text: str, *, add_bos: bool = True) -> list[int]:
        ids = list(text.encode("utf-8"))
        return ([self.BOS] + ids) if add_bos else ids

    def decode(self, ids) -> str:
        data = bytes(i for i in ids if i < 256)
        return data.decode("utf-8", errors="replace")


def load_tokenizer(spec: str | None):
    if spec is None or spec == "byte":
        return ByteTokenizer()
    # local HF tokenizer directory
    from transformers import AutoTokenizer

    return AutoTokenizer.from_pretrained(spec)
