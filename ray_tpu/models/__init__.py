from ray_tpu.models import transformer, vit
from ray_tpu.models.gpt2 import gpt2_config
from ray_tpu.models.llama import llama_config
from ray_tpu.models.mixtral import mixtral_config
from ray_tpu.models.transformer import MoEConfig, TransformerConfig
from ray_tpu.models.vit import ViTConfig, vit_config

__all__ = [
    "MoEConfig",
    "TransformerConfig",
    "ViTConfig",
    "gpt2_config",
    "llama_config",
    "mixtral_config",
    "transformer",
    "vit",
    "vit_config",
]
