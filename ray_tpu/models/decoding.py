"""KV-cache decoding for the shared transformer core: the TPU inference path.

Design (JetStream-style, XLA-first — everything static-shape):
- one global decode state of `max_slots` rows; each row is an independent
  sequence with its own length counter (continuous batching = rows join and
  leave between jitted `decode_step` calls, no recompilation),
- `prefill` runs the prompt at a bucketed length and returns per-layer KV to
  be inserted into a free row (`insert_sequence`, donated buffers → in-place
  dynamic-update-slice in HBM),
- `decode_step` advances ALL rows one token with per-row masks; inactive rows
  are masked out, so the hot loop is one fixed-shape program on the MXU.

The reference delegates all of this to vLLM (paged attention, CUDA);
(reference: python/ray/llm/_internal/serve/engines/vllm/vllm_engine.py:114 —
capability parity target, not a design source). A contiguous [slots, max_len]
cache replaces vLLM's paged KV: XLA prefers static dense layouts, and HBM
capacity planning is done by slot count instead of page tables.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp

from ray_tpu import ops
from ray_tpu.models.transformer import TransformerConfig, _dense_mlp, _moe_mlp, _norm


def init_decode_state(cfg: TransformerConfig, max_slots: int, max_len: int) -> dict:
    """Allocate the global decode state: per-layer KV + per-row bookkeeping."""
    L, Hkv, Dh = cfg.n_layers, cfg.kv_heads, cfg.head_dim
    return {
        "k": jnp.zeros((L, max_slots, max_len, Hkv, Dh), cfg.dtype),
        "v": jnp.zeros((L, max_slots, max_len, Hkv, Dh), cfg.dtype),
        "length": jnp.zeros((max_slots,), jnp.int32),     # tokens in cache
        "last_token": jnp.zeros((max_slots,), jnp.int32),  # next input per row
        "active": jnp.zeros((max_slots,), jnp.bool_),
    }


def _rope(cfg):
    if cfg.pos == "rope":
        return ops.rope_frequencies(cfg.head_dim, cfg.max_seq_len, theta=cfg.rope_theta)
    return None, None


def init_lora_bank(cfg: TransformerConfig, num_adapters: int,
                   rank: int) -> dict:
    """Device-resident multi-LoRA bank for batched per-slot adapters
    (reference capability: multi-LoRA serving —
    python/ray/llm/_internal/serve/utils/lora_serve_utils.py loads adapters
    onto vLLM's punica kernels; here the bank is plain stacked tensors the
    jitted forward gathers per row — S-LoRA-style, XLA does the batching).

    Adapter slot 0 is the NULL adapter and stays all-zero: a row with
    index 0 computes base + 0, bit-identical to the base model. Banks are
    LAYER-major ([L, N+1, ...]) so lax.scan consumes them directly.
    Targets q and v projections (the standard LoRA target set)."""
    L, E = cfg.n_layers, cfg.d_model
    H, Hkv, Dh = cfg.n_heads, cfg.kv_heads, cfg.head_dim
    N = num_adapters + 1
    return {
        "A_q": jnp.zeros((L, N, E, rank), cfg.dtype),
        "B_q": jnp.zeros((L, N, rank, H, Dh), cfg.dtype),
        "A_v": jnp.zeros((L, N, E, rank), cfg.dtype),
        "B_v": jnp.zeros((L, N, rank, Hkv, Dh), cfg.dtype),
        "scale": jnp.zeros((N,), jnp.float32),
    }


def _attn_qkv(x, p, cfg, lora_l=None, lora_idx=None, lora_scale=None):
    """QKV projections; when a LoRA layer-slice is given, adds the per-row
    low-rank q/v deltas. `lora_idx` is [B] (per decode row) or a scalar
    (single-sequence prefill); `lora_scale` the matching alpha/r gather."""
    dt = cfg.dtype
    q = jnp.einsum("bte,ehd->bthd", x, p["wq"].astype(dt))
    k = jnp.einsum("bte,ehd->bthd", x, p["wk"].astype(dt))
    v = jnp.einsum("bte,ehd->bthd", x, p["wv"].astype(dt))
    if lora_l is not None:
        aq, bq, av, bv = lora_l
        if lora_idx.ndim == 0:  # one sequence: scalar gather
            dq = jnp.einsum("bte,er->btr", x, aq[lora_idx].astype(dt))
            dq = jnp.einsum("btr,rhd->bthd", dq, bq[lora_idx].astype(dt))
            dv = jnp.einsum("bte,er->btr", x, av[lora_idx].astype(dt))
            dv = jnp.einsum("btr,rhd->bthd", dv, bv[lora_idx].astype(dt))
            s = lora_scale.astype(dt)
            q = q + dq * s
            v = v + dv * s
        else:  # per-row adapters: batched gather + matmul
            dq = jnp.einsum("bte,ber->btr", x, aq[lora_idx].astype(dt))
            dq = jnp.einsum("btr,brhd->bthd", dq, bq[lora_idx].astype(dt))
            dv = jnp.einsum("bte,ber->btr", x, av[lora_idx].astype(dt))
            dv = jnp.einsum("btr,brhd->bthd", dv, bv[lora_idx].astype(dt))
            s = lora_scale.astype(dt)[:, None, None, None]
            q = q + dq * s
            v = v + dv * s
    if cfg.bias:
        q = q + p["bq"].astype(dt)
        k = k + p["bk"].astype(dt)
        v = v + p["bv"].astype(dt)
    return q, k, v


def _mlp_block(normed, layer_p, cfg):
    if cfg.moe:
        delta, _aux = _moe_mlp(normed, layer_p["mlp"], cfg)
        return delta
    return _dense_mlp(normed, layer_p["mlp"], cfg)


@functools.partial(jax.jit, static_argnames=("cfg",))
def prefill(params, tokens, length, cfg: TransformerConfig,
            lora_bank=None, lora_idx=None):
    """Run one prompt [1, T] (T = bucket size, padded; true length `length`).

    Returns (logits_at_last [V], kv {k,v: [L, T, Hkv, Dh]}).
    With `lora_bank` + scalar `lora_idx`, applies that adapter's q/v
    deltas (init_lora_bank; idx 0 = null adapter = exact base model).
    """
    dt = cfg.dtype
    B, T = tokens.shape
    x = params["embed"].astype(dt)[tokens]
    if cfg.pos == "learned":
        x = x + params["pos_embed"][:T].astype(dt)
    cos, sin = _rope(cfg)
    lscale = None if lora_bank is None else lora_bank["scale"][lora_idx]

    def block(h, layer_in):
        if lora_bank is None:
            layer_p, lora_l = layer_in, None
        else:
            layer_p, aq, bq, av, bv = layer_in
            lora_l = (aq, bq, av, bv)
        normed = _norm(h, layer_p["norm1"], cfg)
        q, k, v = _attn_qkv(normed, layer_p["attn"], cfg, lora_l, lora_idx,
                            lscale)
        if cfg.pos == "rope":
            q = ops.apply_rope(q, cos, sin)
            k = ops.apply_rope(k, cos, sin)
        out = ops.attention(q, k, v, causal=True)
        out = jnp.einsum("bthd,hde->bte", out, layer_p["attn"]["wo"].astype(dt))
        if cfg.bias:
            out = out + layer_p["attn"]["bo"].astype(dt)
        h = h + out
        h = h + _mlp_block(_norm(h, layer_p["norm2"], cfg), layer_p, cfg)
        return h, (k[0], v[0])

    xs = (params["layers"] if lora_bank is None
          else (params["layers"], lora_bank["A_q"], lora_bank["B_q"],
                lora_bank["A_v"], lora_bank["B_v"]))
    x, kv = jax.lax.scan(block, x, xs)
    x = _norm(x, params["final_norm"], cfg)
    last = x[0, length - 1]
    if cfg.tie_embeddings:
        logits = last @ params["embed"].astype(dt).T
    else:
        logits = last @ params["lm_head"].astype(dt)
    return logits.astype(jnp.float32), {"k": kv[0], "v": kv[1]}


@functools.partial(jax.jit, static_argnames=("cfg",))
def prefill_batch(params, tokens, lengths, cfg: TransformerConfig):
    """Batched prompt prefill: [B, T] (one shared bucket, padded; true
    per-row lengths in `lengths` [B]).

    Returns (logits_at_last [B, V], kv {k, v: [L, B, T, Hkv, Dh]}).

    The PD prefill tier's admission batching: several queued prompts
    share ONE forward instead of B sequential [1, T] calls — the
    dedicated tier can coalesce because it never interleaves with decode
    steps (llm/pd.py PrefillCoalescer). Causality keeps rows independent:
    positions past a row's length only produce KV that the consumer
    masks by length, exactly as in the single-prompt path."""
    dt = cfg.dtype
    B, T = tokens.shape
    x = params["embed"].astype(dt)[tokens]
    if cfg.pos == "learned":
        x = x + params["pos_embed"][:T].astype(dt)
    cos, sin = _rope(cfg)

    def block(h, layer_p):
        normed = _norm(h, layer_p["norm1"], cfg)
        q, k, v = _attn_qkv(normed, layer_p["attn"], cfg)
        if cfg.pos == "rope":
            q = ops.apply_rope(q, cos, sin)
            k = ops.apply_rope(k, cos, sin)
        out = ops.attention(q, k, v, causal=True)
        out = jnp.einsum("bthd,hde->bte", out, layer_p["attn"]["wo"].astype(dt))
        if cfg.bias:
            out = out + layer_p["attn"]["bo"].astype(dt)
        h = h + out
        h = h + _mlp_block(_norm(h, layer_p["norm2"], cfg), layer_p, cfg)
        return h, (k, v)

    x, kv = jax.lax.scan(block, x, params["layers"])
    x = _norm(x, params["final_norm"], cfg)
    last = jnp.take_along_axis(
        x, (lengths - 1)[:, None, None], axis=1)[:, 0]        # [B, E]
    if cfg.tie_embeddings:
        logits = last @ params["embed"].astype(dt).T
    else:
        logits = last @ params["lm_head"].astype(dt)
    return logits.astype(jnp.float32), {"k": kv[0], "v": kv[1]}


@functools.partial(jax.jit, donate_argnames=("state",), static_argnames=("cfg",))
def insert_sequence(state, slot, kv, length, first_token, cfg: TransformerConfig):
    """Graft a prefilled sequence into decode row `slot` (in place: donated)."""
    T = kv["k"].shape[1]
    pad = state["k"].shape[2] - T
    k_new = jnp.pad(kv["k"], ((0, 0), (0, pad), (0, 0), (0, 0)))[:, None]
    v_new = jnp.pad(kv["v"], ((0, 0), (0, pad), (0, 0), (0, 0)))[:, None]
    state = dict(state)
    state["k"] = jax.lax.dynamic_update_slice_in_dim(state["k"], k_new.astype(state["k"].dtype), slot, axis=1)
    state["v"] = jax.lax.dynamic_update_slice_in_dim(state["v"], v_new.astype(state["v"].dtype), slot, axis=1)
    state["length"] = state["length"].at[slot].set(length)
    state["last_token"] = state["last_token"].at[slot].set(first_token)
    state["active"] = state["active"].at[slot].set(True)
    return state


@functools.partial(jax.jit, donate_argnames=("state",), static_argnames=("cfg",))
def decode_step(params, state, cfg: TransformerConfig,
                lora_bank=None, slot_lora=None):
    """Advance every active row one token. Returns (state, logits [slots, V]).
    With `lora_bank` + `slot_lora` [B], each row adds its own adapter's
    q/v deltas in the SAME batched step (idx 0 = null = base model)."""
    dt = cfg.dtype
    S = state["k"].shape[2]
    B = state["length"].shape[0]
    tokens = state["last_token"][:, None]                      # [B, 1]
    pos = state["length"]                                      # [B]
    x = params["embed"].astype(dt)[tokens]
    if cfg.pos == "learned":
        x = x + params["pos_embed"].astype(dt)[pos][:, None]
    cos, sin = _rope(cfg)
    lscale = None if lora_bank is None else lora_bank["scale"][slot_lora]

    def block(carry, layer_in):
        h, = carry
        if lora_bank is None:
            layer_p, k_cache, v_cache = layer_in               # caches [B, S, Hkv, Dh]
            lora_l = None
        else:
            layer_p, k_cache, v_cache, aq, bq, av, bv = layer_in
            lora_l = (aq, bq, av, bv)
        normed = _norm(h, layer_p["norm1"], cfg)
        q, k, v = _attn_qkv(normed, layer_p["attn"], cfg, lora_l, slot_lora,
                            lscale)                            # [B, 1, H, Dh]
        if cfg.pos == "rope":
            q = ops.apply_rope(q, cos, sin, positions=pos[:, None])
            k = ops.apply_rope(k, cos, sin, positions=pos[:, None])
        # write this step's K/V at each row's position
        onehot = jax.nn.one_hot(pos, S, dtype=dt)              # [B, S]
        k_cache = k_cache * (1 - onehot)[..., None, None] + onehot[..., None, None] * k[:, 0][:, None]
        v_cache = v_cache * (1 - onehot)[..., None, None] + onehot[..., None, None] * v[:, 0][:, None]
        # grouped-query attention against the cache
        G = cfg.n_heads // cfg.kv_heads
        qh = q[:, 0].reshape(B, cfg.kv_heads, G, cfg.head_dim)
        scores = jnp.einsum("bkgd,bskd->bkgs", qh, k_cache.astype(dt)) / (cfg.head_dim ** 0.5)
        mask = jnp.arange(S)[None, :] <= pos[:, None]          # [B, S]
        scores = jnp.where(mask[:, None, None, :], scores.astype(jnp.float32), -1e30)
        w = jax.nn.softmax(scores, axis=-1).astype(dt)
        out = jnp.einsum("bkgs,bskd->bkgd", w, v_cache.astype(dt))
        out = out.reshape(B, 1, cfg.n_heads, cfg.head_dim)
        out = jnp.einsum("bthd,hde->bte", out, layer_p["attn"]["wo"].astype(dt))
        if cfg.bias:
            out = out + layer_p["attn"]["bo"].astype(dt)
        h = h + out
        h = h + _mlp_block(_norm(h, layer_p["norm2"], cfg), layer_p, cfg)
        return (h,), (k_cache, v_cache)

    xs = ((params["layers"], state["k"], state["v"]) if lora_bank is None
          else (params["layers"], state["k"], state["v"],
                lora_bank["A_q"], lora_bank["B_q"],
                lora_bank["A_v"], lora_bank["B_v"]))
    (x,), (k_new, v_new) = jax.lax.scan(block, (x,), xs)
    x = _norm(x, params["final_norm"], cfg)
    if cfg.tie_embeddings:
        logits = x[:, 0] @ params["embed"].astype(dt).T
    else:
        logits = x[:, 0] @ params["lm_head"].astype(dt)
    state = dict(state)
    state["k"], state["v"] = k_new, v_new
    state["length"] = jnp.where(state["active"], state["length"] + 1, state["length"])
    return state, logits.astype(jnp.float32)


@functools.partial(jax.jit, donate_argnames=("state",),
                   static_argnames=("cfg", "K"))
def verify_step(params, state, draft, cfg: TransformerConfig, K: int):
    """Speculative verification: advance every active row K tokens at once.

    Inputs per row are [last_token, draft_0 .. draft_{K-2}] at positions
    len .. len+K-1; returns (state, logits [slots, K, V]) where logits[:, j]
    is the next-token distribution AFTER input j. KV is written for all K
    inputs; `length`/`last_token` are NOT advanced — the host decides how
    many drafts were accepted and calls commit_accepted. Rejected inputs'
    KV rows sit beyond the committed length, where the attention mask
    already ignores them, so no rollback is needed (the memory-bound
    decode step has idle MXU headroom — verifying K tokens costs barely
    more than one, which is the whole speculative-decoding bet).

    (reference capability: vLLM speculative decoding / prompt-lookup;
    rebuilt as one fixed-shape XLA program like decode_step.)
    """
    dt = cfg.dtype
    S = state["k"].shape[2]
    B = state["length"].shape[0]
    tokens = jnp.concatenate([state["last_token"][:, None], draft], axis=1)
    pos = state["length"][:, None] + jnp.arange(K)[None, :]    # [B, K]
    x = params["embed"].astype(dt)[tokens]
    if cfg.pos == "learned":
        x = x + params["pos_embed"].astype(dt)[pos]
    cos, sin = _rope(cfg)

    def block(carry, layer_in):
        h, = carry
        layer_p, k_cache, v_cache = layer_in                   # [B, S, Hkv, Dh]
        normed = _norm(h, layer_p["norm1"], cfg)
        q, k, v = _attn_qkv(normed, layer_p["attn"], cfg)      # [B, K, H, Dh]
        if cfg.pos == "rope":
            q = ops.apply_rope(q, cos, sin, positions=pos)
            k = ops.apply_rope(k, cos, sin, positions=pos)
        # scatter the K new K/V rows (positions are distinct per row)
        oh = jax.nn.one_hot(pos, S, dtype=dt)                  # [B, K, S]
        any_mask = oh.sum(axis=1)                              # [B, S]
        k_cache = (k_cache * (1 - any_mask)[..., None, None]
                   + jnp.einsum("bks,bkhd->bshd", oh, k))
        v_cache = (v_cache * (1 - any_mask)[..., None, None]
                   + jnp.einsum("bks,bkhd->bshd", oh, v))
        G = cfg.n_heads // cfg.kv_heads
        qh = q.reshape(B, K, cfg.kv_heads, G, cfg.head_dim)
        scores = jnp.einsum("bkhgd,bshd->bhgks", qh,
                            k_cache.astype(dt)) / (cfg.head_dim ** 0.5)
        # causal within the window + full view of the committed cache
        mask = jnp.arange(S)[None, None, :] <= pos[:, :, None]  # [B, K, S]
        scores = jnp.where(mask[:, None, None, :, :],
                           scores.astype(jnp.float32), -1e30)
        w = jax.nn.softmax(scores, axis=-1).astype(dt)
        out = jnp.einsum("bhgks,bshd->bkhgd", w, v_cache.astype(dt))
        out = out.reshape(B, K, cfg.n_heads, cfg.head_dim)
        out = jnp.einsum("bthd,hde->bte", out, layer_p["attn"]["wo"].astype(dt))
        if cfg.bias:
            out = out + layer_p["attn"]["bo"].astype(dt)
        h = h + out
        h = h + _mlp_block(_norm(h, layer_p["norm2"], cfg), layer_p, cfg)
        return (h,), (k_cache, v_cache)

    (x,), (k_new, v_new) = jax.lax.scan(
        block, (x,), (params["layers"], state["k"], state["v"]))
    x = _norm(x, params["final_norm"], cfg)
    if cfg.tie_embeddings:
        logits = x @ params["embed"].astype(dt).T
    else:
        logits = x @ params["lm_head"].astype(dt)
    state = dict(state)
    state["k"], state["v"] = k_new, v_new
    return state, logits.astype(jnp.float32)


@functools.partial(jax.jit, donate_argnames=("state",))
def commit_accepted(state, new_last, counts):
    """Advance each active row by its accepted-token count (1 + accepted
    drafts) and set the new last (unverified) token."""
    state = dict(state)
    act = state["active"]
    state["length"] = jnp.where(act, state["length"] + counts,
                                state["length"])
    state["last_token"] = jnp.where(act, new_last, state["last_token"])
    return state


@functools.partial(jax.jit, donate_argnames=("state",))
def commit_tokens(state, next_tokens):
    """Record sampled tokens as the next decode inputs (active rows only)."""
    state = dict(state)
    state["last_token"] = jnp.where(state["active"], next_tokens, state["last_token"])
    return state


@functools.partial(jax.jit, donate_argnames=("state",))
def release_slot(state, slot):
    state = dict(state)
    state["active"] = state["active"].at[slot].set(False)
    state["length"] = state["length"].at[slot].set(0)
    return state


@jax.jit
def sample_per_row(logits, key, temperatures, top_ks):
    """Row-wise temperature + top-k sampling for the decode hot loop.
    logits [B, V], temperatures [B] (0 → greedy), top_ks [B] int32 (0 → off)."""
    V = logits.shape[-1]
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits / jnp.maximum(temperatures, 1e-6)[:, None]
    # per-row k-th largest as the cutoff (k=0 → cutoff -inf, i.e. no cut)
    sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]
    idx = jnp.clip(top_ks - 1, 0, V - 1)
    kth = jnp.take_along_axis(sorted_desc, idx[:, None], axis=-1)
    kth = jnp.where(top_ks[:, None] > 0, kth, -jnp.inf)
    scaled = jnp.where(scaled < kth, -1e30, scaled)
    sampled = jax.random.categorical(key, scaled, axis=-1).astype(jnp.int32)
    return jnp.where(temperatures <= 0.0, greedy, sampled)


@functools.partial(jax.jit, static_argnames=("top_k",))
def sample(logits, key, temperature: float, top_k: int = 0):
    """Greedy when temperature == 0, else (top-k) categorical. [B, V] → [B]."""
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    t = jnp.maximum(temperature, 1e-6)
    scaled = logits / t
    if top_k and top_k > 0:
        kth = jnp.sort(scaled, axis=-1)[:, -top_k][:, None]
        scaled = jnp.where(scaled < kth, -1e30, scaled)
    sampled = jax.random.categorical(key, scaled, axis=-1).astype(jnp.int32)
    return jnp.where(temperature <= 0.0, greedy, sampled)
