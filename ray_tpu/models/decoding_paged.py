"""Paged KV-cache decoding: block-table attention with static shapes.

The contiguous backend (models/decoding.py) reserves `max_len` tokens of KV
per slot — fine for uniform sequence lengths, wasteful for mixed ones. This
backend carves HBM into a shared **page pool**; each slot owns just the
pages its sequence actually needs, tracked in a block table, so the same
HBM serves many more concurrent sequences at typical length distributions.

All shapes stay static (XLA-first, like everything here): the pool is
[L, num_pages, page, Hkv, Dh]; per-step writes are scatters at
(page_id, offset) and attention gathers each row's pages with a take along
the page axis. Page allocation/free is host-side bookkeeping in the engine
(a free list), mirroring how vLLM's scheduler owns its block tables.

(reference capability: vLLM paged attention behind
llm/_internal/serve/engines/vllm/vllm_engine.py:114; design here is
TPU-native — dense static gathers, no custom CUDA.)
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ray_tpu.models.decoding import _attn_qkv, _mlp_block, _rope
from ray_tpu.models.transformer import TransformerConfig, _norm
from ray_tpu import ops


def init_paged_state(cfg: TransformerConfig, max_slots: int, max_len: int,
                     num_pages: int, page_size: int) -> dict:
    """Page pool + block tables. `num_pages * page_size` is the total token
    capacity shared by all slots (oversubscribable vs max_slots*max_len)."""
    L, Hkv, Dh = cfg.n_layers, cfg.kv_heads, cfg.head_dim
    max_pages_per_seq = (max_len + page_size - 1) // page_size
    return {
        "kp": jnp.zeros((L, num_pages, page_size, Hkv, Dh), cfg.dtype),
        "vp": jnp.zeros((L, num_pages, page_size, Hkv, Dh), cfg.dtype),
        # page ids per slot; unused entries point at page 0 (masked anyway)
        "block": jnp.zeros((max_slots, max_pages_per_seq), jnp.int32),
        "length": jnp.zeros((max_slots,), jnp.int32),
        "last_token": jnp.zeros((max_slots,), jnp.int32),
        "active": jnp.zeros((max_slots,), jnp.bool_),
    }


@functools.partial(jax.jit, donate_argnames=("state",), static_argnames=("cfg",))
def insert_sequence_paged(state, slot, kv, length, first_token, pages,
                          cfg: TransformerConfig):
    """Write a prefilled [L, T, Hkv, Dh] KV into the first T/page_size of
    this slot's `pages` (int32 [max_pages_per_seq], padded with 0 — the
    engine grants ALL pages the sequence will ever need up front, so no
    mid-flight allocation) and activate the row."""
    P = state["kp"].shape[2]
    L, T = kv["k"].shape[0], kv["k"].shape[1]
    n = T // P  # static: T is the prompt bucket
    k_pages = kv["k"].reshape(L, n, P, kv["k"].shape[2], kv["k"].shape[3])
    v_pages = kv["v"].reshape(L, n, P, kv["v"].shape[2], kv["v"].shape[3])
    state = dict(state)
    state["kp"] = state["kp"].at[:, pages[:n]].set(k_pages.astype(state["kp"].dtype))
    state["vp"] = state["vp"].at[:, pages[:n]].set(v_pages.astype(state["vp"].dtype))
    state["block"] = jax.lax.dynamic_update_slice_in_dim(
        state["block"], pages[None], slot, axis=0)
    state["length"] = state["length"].at[slot].set(length)
    state["last_token"] = state["last_token"].at[slot].set(first_token)
    state["active"] = state["active"].at[slot].set(True)
    return state


@functools.partial(jax.jit, donate_argnames=("state",), static_argnames=("cfg",))
def decode_step_paged(params, state, cfg: TransformerConfig):
    """Advance every active row one token against its paged cache."""
    dt = cfg.dtype
    B, MP = state["block"].shape
    P = state["kp"].shape[2]
    S = MP * P
    tokens = state["last_token"][:, None]
    pos = state["length"]                                      # [B]
    page_ids = jnp.take_along_axis(state["block"],
                                   (pos // P)[:, None], axis=1)[:, 0]  # [B]
    # inactive rows scatter into page 0 — RESERVED as scratch (the engine's
    # allocator never hands out page 0), so they can't corrupt live pages
    page_ids = jnp.where(state["active"], page_ids, 0)
    offsets = pos % P                                          # [B]
    x = params["embed"].astype(dt)[tokens]
    if cfg.pos == "learned":
        x = x + params["pos_embed"].astype(dt)[pos][:, None]
    cos, sin = _rope(cfg)

    def block(carry, layer_in):
        h, = carry
        layer_p, kp, vp = layer_in               # pools [num_pages, P, Hkv, Dh]
        normed = _norm(h, layer_p["norm1"], cfg)
        q, k, v = _attn_qkv(normed, layer_p["attn"], cfg)      # [B, 1, H, Dh]
        if cfg.pos == "rope":
            q = ops.apply_rope(q, cos, sin, positions=pos[:, None])
            k = ops.apply_rope(k, cos, sin, positions=pos[:, None])
        # scatter this step's K/V at (page, offset) per row
        kp = kp.at[page_ids, offsets].set(k[:, 0].astype(kp.dtype))
        vp = vp.at[page_ids, offsets].set(v[:, 0].astype(vp.dtype))
        # gather each row's pages → a contiguous [B, S] view for attention
        k_cache = kp[state["block"]].reshape(B, S, cfg.kv_heads, cfg.head_dim)
        v_cache = vp[state["block"]].reshape(B, S, cfg.kv_heads, cfg.head_dim)
        G = cfg.n_heads // cfg.kv_heads
        qh = q[:, 0].reshape(B, cfg.kv_heads, G, cfg.head_dim)
        scores = jnp.einsum("bkgd,bskd->bkgs", qh, k_cache.astype(dt)) / (cfg.head_dim ** 0.5)
        mask = jnp.arange(S)[None, :] <= pos[:, None]
        scores = jnp.where(mask[:, None, None, :], scores.astype(jnp.float32), -1e30)
        w = jax.nn.softmax(scores, axis=-1).astype(dt)
        out = jnp.einsum("bkgs,bskd->bkgd", w, v_cache.astype(dt))
        out = out.reshape(B, 1, cfg.n_heads, cfg.head_dim)
        out = jnp.einsum("bthd,hde->bte", out, layer_p["attn"]["wo"].astype(dt))
        if cfg.bias:
            out = out + layer_p["attn"]["bo"].astype(dt)
        h = h + out
        h = h + _mlp_block(_norm(h, layer_p["norm2"], cfg), layer_p, cfg)
        return (h,), (kp, vp)

    (x,), (kp_new, vp_new) = jax.lax.scan(
        block, (x,), (params["layers"], state["kp"], state["vp"]))
    x = _norm(x, params["final_norm"], cfg)
    if cfg.tie_embeddings:
        logits = x[:, 0] @ params["embed"].astype(dt).T
    else:
        logits = x[:, 0] @ params["lm_head"].astype(dt)
    state = dict(state)
    state["kp"], state["vp"] = kp_new, vp_new
    state["length"] = jnp.where(state["active"], state["length"] + 1, state["length"])
    return state, logits.astype(jnp.float32)


@functools.partial(jax.jit, donate_argnames=("state",),
                   static_argnames=("cfg", "pages_bound", "kernel"))
def decode_step_paged_ragged(params, state, cfg: TransformerConfig,
                             pages_bound: int, kernel: bool = False):
    """Advance every active row one token — ragged paged attention.

    Same per-step scatter as decode_step_paged, but the attention core is
    ONE ragged launch over the batch's block tables (ops/
    ragged_paged_attention.py): no [B, max_pages*page] gather, and the
    sweep stops at `pages_bound` — the engine's host-side bound on the
    batch's LIVE page count (power of two, so compile count stays
    O(log(max_pages))). `kernel=True` runs the Pallas TPU kernel;
    False runs the bit-consistent pure-JAX reference (the CPU path).
    """
    from ray_tpu.ops.ragged_paged_attention import ragged_decode_attention

    dt = cfg.dtype
    B, MP = state["block"].shape
    P = state["kp"].shape[2]
    tokens = state["last_token"][:, None]
    pos = state["length"]                                      # [B]
    page_ids = jnp.take_along_axis(state["block"],
                                   (pos // P)[:, None], axis=1)[:, 0]  # [B]
    page_ids = jnp.where(state["active"], page_ids, 0)
    offsets = pos % P                                          # [B]
    # the ragged sweep only walks the batch's live prefix of each table;
    # positions past a row's `pos` inside that prefix are masked in-kernel
    tbl = state["block"][:, :pages_bound]
    x = params["embed"].astype(dt)[tokens]
    if cfg.pos == "learned":
        x = x + params["pos_embed"].astype(dt)[pos][:, None]
    cos, sin = _rope(cfg)
    G = cfg.n_heads // cfg.kv_heads

    def block(carry, layer_in):
        h, = carry
        layer_p, kp, vp = layer_in               # pools [num_pages, P, Hkv, Dh]
        normed = _norm(h, layer_p["norm1"], cfg)
        q, k, v = _attn_qkv(normed, layer_p["attn"], cfg)      # [B, 1, H, Dh]
        if cfg.pos == "rope":
            q = ops.apply_rope(q, cos, sin, positions=pos[:, None])
            k = ops.apply_rope(k, cos, sin, positions=pos[:, None])
        kp = kp.at[page_ids, offsets].set(k[:, 0].astype(kp.dtype))
        vp = vp.at[page_ids, offsets].set(v[:, 0].astype(vp.dtype))
        qh = q[:, 0].reshape(B, cfg.kv_heads, G, cfg.head_dim)
        out = ragged_decode_attention(
            qh, kp, vp, tbl, pos, scale=cfg.head_dim ** -0.5,
            impl="kernel" if kernel else "reference")
        out = out.reshape(B, 1, cfg.n_heads, cfg.head_dim).astype(dt)
        out = jnp.einsum("bthd,hde->bte", out, layer_p["attn"]["wo"].astype(dt))
        if cfg.bias:
            out = out + layer_p["attn"]["bo"].astype(dt)
        h = h + out
        h = h + _mlp_block(_norm(h, layer_p["norm2"], cfg), layer_p, cfg)
        return (h,), (kp, vp)

    (x,), (kp_new, vp_new) = jax.lax.scan(
        block, (x,), (params["layers"], state["kp"], state["vp"]))
    x = _norm(x, params["final_norm"], cfg)
    if cfg.tie_embeddings:
        logits = x[:, 0] @ params["embed"].astype(dt).T
    else:
        logits = x[:, 0] @ params["lm_head"].astype(dt)
    state = dict(state)
    state["kp"], state["vp"] = kp_new, vp_new
    state["length"] = jnp.where(state["active"], state["length"] + 1, state["length"])
    return state, logits.astype(jnp.float32)


@functools.partial(jax.jit, donate_argnames=("state",))
def release_slot_paged(state, slot):
    state = dict(state)
    state["active"] = state["active"].at[slot].set(False)
    state["length"] = state["length"].at[slot].set(0)
    return state


# --------------------------------------------------- prefix-cache support
# (reference capability: vLLM automatic prefix caching / hash-block reuse;
# TPU design: cached blocks stay IN the page pool and are gathered into a
# dense bucketed array for the continuation prefill — static shapes, no
# custom kernels.)


@jax.jit
def gather_prefix_pages(kp, vp, page_ids):
    """Collect cached prefix KV out of the page pool: page_ids [n] →
    k, v [L, n*P, Hkv, Dh] (n static via the id vector's shape; unused
    tail ids point at scratch page 0 and are masked by prefix_len)."""
    L, _, P, Hkv, Dh = kp.shape
    n = page_ids.shape[0]
    k = kp[:, page_ids].reshape(L, n * P, Hkv, Dh)
    v = vp[:, page_ids].reshape(L, n * P, Hkv, Dh)
    return k, v


@functools.partial(jax.jit, static_argnames=("cfg",))
def prefill_with_prefix(params, tokens, prefix_k, prefix_v, prefix_len,
                        length, cfg: TransformerConfig):
    """Continuation prefill: run ONLY the suffix tokens [1, Ts] (padded
    bucket; true count `length`) attending over a cached prefix KV
    [L, Tp, Hkv, Dh] (valid first `prefix_len` positions — cached K is
    already roped at its absolute positions) plus the causal suffix.

    Returns (logits at the last suffix token [V],
             suffix kv {k, v: [L, Ts, Hkv, Dh]}).
    Compilation count is bounded by #prefix_buckets × #suffix_buckets.
    """
    dt = cfg.dtype
    B, Ts = tokens.shape
    Tp = prefix_k.shape[1]
    x = params["embed"].astype(dt)[tokens]
    pos_suffix = prefix_len + jnp.arange(Ts)                     # [Ts]
    if cfg.pos == "learned":
        x = x + params["pos_embed"].astype(dt)[pos_suffix][None]
    cos, sin = _rope(cfg)

    # [Ts, Tp + Ts]: every suffix query sees the real prefix positions and
    # its causal suffix slice
    prefix_mask = jnp.broadcast_to(
        jnp.arange(Tp)[None, :] < prefix_len, (Ts, Tp))
    causal = jnp.arange(Ts)[:, None] >= jnp.arange(Ts)[None, :]
    mask = jnp.concatenate([prefix_mask, causal], axis=1)

    def block(h, layer_in):
        layer_p, pk, pv = layer_in                    # [Tp, Hkv, Dh] each
        normed = _norm(h, layer_p["norm1"], cfg)
        q, k, v = _attn_qkv(normed, layer_p["attn"], cfg)  # [1, Ts, H, Dh]
        if cfg.pos == "rope":
            q = ops.apply_rope(q, cos, sin, positions=pos_suffix)
            k = ops.apply_rope(k, cos, sin, positions=pos_suffix)
        k_all = jnp.concatenate([pk[None].astype(dt), k], axis=1)
        v_all = jnp.concatenate([pv[None].astype(dt), v], axis=1)
        G = cfg.n_heads // cfg.kv_heads
        qh = q.reshape(B, Ts, cfg.kv_heads, G, cfg.head_dim)
        scores = jnp.einsum("btkgd,bskd->btkgs", qh,
                            k_all.astype(dt)) / (cfg.head_dim ** 0.5)
        scores = jnp.where(mask[None, :, None, None, :],
                           scores.astype(jnp.float32), -1e30)
        w = jax.nn.softmax(scores, axis=-1).astype(dt)
        out = jnp.einsum("btkgs,bskd->btkgd", w, v_all.astype(dt))
        out = out.reshape(B, Ts, cfg.n_heads, cfg.head_dim)
        out = jnp.einsum("bthd,hde->bte", out, layer_p["attn"]["wo"].astype(dt))
        if cfg.bias:
            out = out + layer_p["attn"]["bo"].astype(dt)
        h = h + out
        h = h + _mlp_block(_norm(h, layer_p["norm2"], cfg), layer_p, cfg)
        return h, (k[0], v[0])

    x, kv = jax.lax.scan(block, x, (params["layers"], prefix_k, prefix_v))
    x = _norm(x, params["final_norm"], cfg)
    last = x[0, length - 1]
    if cfg.tie_embeddings:
        logits = last @ params["embed"].astype(dt).T
    else:
        logits = last @ params["lm_head"].astype(dt)
    return logits.astype(jnp.float32), {"k": kv[0], "v": kv[1]}


@functools.partial(jax.jit, donate_argnames=("state",))
def write_kv_pages(state, kv, pages):
    """Write a bucketed [L, T, Hkv, Dh] KV into `pages` (T/page_size ids)
    WITHOUT touching the row bookkeeping — the chunked-prefill building
    block: chunks accumulate into the pool page by page, and the row only
    activates once the whole prompt is resident (activate_slot)."""
    P = state["kp"].shape[2]
    L, T = kv["k"].shape[0], kv["k"].shape[1]
    n = T // P
    Hkv, Dh = kv["k"].shape[2], kv["k"].shape[3]
    state = dict(state)
    state["kp"] = state["kp"].at[:, pages[:n]].set(
        kv["k"].reshape(L, n, P, Hkv, Dh).astype(state["kp"].dtype))
    state["vp"] = state["vp"].at[:, pages[:n]].set(
        kv["v"].reshape(L, n, P, Hkv, Dh).astype(state["vp"].dtype))
    return state


@functools.partial(jax.jit, donate_argnames=("state",))
def activate_slot(state, slot, block_row, length, first_token):
    """Turn a fully-prefilled slot live for decode (the bookkeeping half
    of insert_sequence_paged, after write_kv_pages staged the KV)."""
    state = dict(state)
    state["block"] = jax.lax.dynamic_update_slice_in_dim(
        state["block"], block_row[None], slot, axis=0)
    state["length"] = state["length"].at[slot].set(length)
    state["last_token"] = state["last_token"].at[slot].set(first_token)
    state["active"] = state["active"].at[slot].set(True)
    return state


@functools.partial(jax.jit, donate_argnames=("state",), static_argnames=("cfg",))
def insert_sequence_paged_prefix(state, slot, kv, suffix_pages, block_row,
                                 length, first_token, cfg: TransformerConfig):
    """Like insert_sequence_paged, but only the SUFFIX KV is written (the
    prefix already lives in shared cache pages): `suffix_pages` [ns] are
    the pages receiving the suffix bucket, `block_row`
    [max_pages_per_seq] is the full table (shared prefix ids + private
    ids + 0-padding)."""
    P = state["kp"].shape[2]
    L, T = kv["k"].shape[0], kv["k"].shape[1]
    n = T // P  # static: T is the suffix bucket
    Hkv, Dh = kv["k"].shape[2], kv["k"].shape[3]
    k_pages = kv["k"].reshape(L, n, P, Hkv, Dh)
    v_pages = kv["v"].reshape(L, n, P, Hkv, Dh)
    state = dict(state)
    state["kp"] = state["kp"].at[:, suffix_pages[:n]].set(
        k_pages.astype(state["kp"].dtype))
    state["vp"] = state["vp"].at[:, suffix_pages[:n]].set(
        v_pages.astype(state["vp"].dtype))
    state["block"] = jax.lax.dynamic_update_slice_in_dim(
        state["block"], block_row[None], slot, axis=0)
    state["length"] = state["length"].at[slot].set(length)
    state["last_token"] = state["last_token"].at[slot].set(first_token)
    state["active"] = state["active"].at[slot].set(True)
    return state
