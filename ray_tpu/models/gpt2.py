"""GPT-2 family (BASELINE.md config 1: 124M DDP smoke)."""

from __future__ import annotations

import jax.numpy as jnp

from ray_tpu.models.transformer import TransformerConfig

SIZES = {
    "124m": dict(d_model=768, n_layers=12, n_heads=12, d_ff=3072),
    "350m": dict(d_model=1024, n_layers=24, n_heads=16, d_ff=4096),
    "774m": dict(d_model=1280, n_layers=36, n_heads=20, d_ff=5120),
    "1.5b": dict(d_model=1600, n_layers=48, n_heads=25, d_ff=6400),
}


def gpt2_config(size: str = "124m", *, vocab_size: int = 50257,
                max_seq_len: int = 1024, dtype=jnp.bfloat16, **overrides) -> TransformerConfig:
    base = dict(SIZES[size])
    base.update(
        vocab_size=vocab_size,
        max_seq_len=max_seq_len,
        norm="ln",
        act="gelu",
        pos="learned",
        bias=True,
        tie_embeddings=True,
        dtype=dtype,
    )
    base.update(overrides)
    return TransformerConfig(**base)
