"""Llama-3 family (BASELINE.md config 2: 8B on a v5e-8 slice)."""

from __future__ import annotations

import jax.numpy as jnp

from ray_tpu.models.transformer import TransformerConfig

SIZES = {
    # (d_model, layers, heads, kv_heads, d_ff)
    "tiny": dict(d_model=256, n_layers=4, n_heads=8, n_kv_heads=4, d_ff=688),
    "1b": dict(d_model=2048, n_layers=16, n_heads=32, n_kv_heads=8, d_ff=8192),
    "3b": dict(d_model=3072, n_layers=28, n_heads=24, n_kv_heads=8, d_ff=8192),
    "8b": dict(d_model=4096, n_layers=32, n_heads=32, n_kv_heads=8, d_ff=14336),
    "70b": dict(d_model=8192, n_layers=80, n_heads=64, n_kv_heads=8, d_ff=28672),
}


def llama_config(size: str = "8b", *, vocab_size: int = 128256,
                 max_seq_len: int = 8192, dtype=jnp.bfloat16, **overrides) -> TransformerConfig:
    base = dict(SIZES[size])
    base.update(
        vocab_size=vocab_size,
        max_seq_len=max_seq_len,
        norm="rms",
        act="swiglu",
        pos="rope",
        rope_theta=500000.0,
        bias=False,
        tie_embeddings=False,
        dtype=dtype,
    )
    base.update(overrides)
    return TransformerConfig(**base)
