"""Mixtral MoE family (BASELINE.md config 4: 8x7B expert-parallel)."""

from __future__ import annotations

import jax.numpy as jnp

from ray_tpu.models.transformer import MoEConfig, TransformerConfig

SIZES = {
    "tiny": dict(d_model=256, n_layers=4, n_heads=8, n_kv_heads=4, d_ff=512),
    "8x7b": dict(d_model=4096, n_layers=32, n_heads=32, n_kv_heads=8, d_ff=14336),
    "8x22b": dict(d_model=6144, n_layers=56, n_heads=48, n_kv_heads=8, d_ff=16384),
}


def mixtral_config(size: str = "8x7b", *, vocab_size: int = 32000,
                   max_seq_len: int = 8192, num_experts: int = 8, top_k: int = 2,
                   dtype=jnp.bfloat16, **overrides) -> TransformerConfig:
    base = dict(SIZES[size])
    base.update(
        vocab_size=vocab_size,
        max_seq_len=max_seq_len,
        norm="rms",
        act="swiglu",
        pos="rope",
        rope_theta=1000000.0,
        bias=False,
        tie_embeddings=False,
        moe=MoEConfig(num_experts=num_experts, top_k=top_k),
        dtype=dtype,
    )
    base.update(overrides)
    return TransformerConfig(**base)
