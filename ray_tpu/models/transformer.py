"""Decoder-only transformer core shared by the GPT-2 / Llama / Mixtral
families. Pure-functional: params are pytrees (layers stacked on a leading
dim and consumed by lax.scan — compile-fast and pipeline-ready), logical axis
trees drive mesh sharding, compute runs in bf16 with f32 accumulators.

The reference framework contains no model code (models live in user code /
vLLM); these families exist so the framework's train/serve/bench paths are
self-contained (BASELINE.md configs 1, 2, 4).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any

import jax
import jax.numpy as jnp

from ray_tpu import ops


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    aux_coef: float = 0.01


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_layers: int = 4
    n_heads: int = 8
    n_kv_heads: int | None = None          # None → MHA
    d_head: int | None = None              # None → d_model // n_heads
    d_ff: int = 2048
    norm: str = "rms"                      # "rms" | "ln"
    act: str = "swiglu"                    # "swiglu" | "gelu"
    pos: str = "rope"                      # "rope" | "learned"
    rope_theta: float = 10000.0
    max_seq_len: int = 2048
    tie_embeddings: bool = False
    bias: bool = False                     # attn/mlp biases (GPT-2 style)
    moe: MoEConfig | None = None
    remat: bool = True                     # checkpoint each layer (HBM for FLOPs)
    remat_policy: str = "nothing"          # "nothing" | "dots" (save matmul outputs)
                                           # | "pairs" (checkpoint every other layer)
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads or self.n_heads

    @property
    def head_dim(self) -> int:
        return self.d_head or self.d_model // self.n_heads

    def num_params(self) -> int:
        leaves = jax.tree.leaves(jax.eval_shape(lambda: init(jax.random.PRNGKey(0), self)))
        return sum(math.prod(l.shape) for l in leaves)


# ------------------------------------------------------------------ init

def _norm_params(cfg, key):
    p = {"w": jnp.ones((cfg.d_model,), cfg.param_dtype)}
    if cfg.norm == "ln":
        p["b"] = jnp.zeros((cfg.d_model,), cfg.param_dtype)
    return p


def _dense_mlp_params(cfg, key):
    E, F = cfg.d_model, cfg.d_ff
    k1, k2, k3 = jax.random.split(key, 3)
    std = 0.02
    out_std = 0.02 / math.sqrt(2 * cfg.n_layers)
    if cfg.act == "swiglu":
        p = {
            "wi_gate": jax.random.normal(k1, (E, F), cfg.param_dtype) * std,
            "wi_up": jax.random.normal(k2, (E, F), cfg.param_dtype) * std,
            "wo": jax.random.normal(k3, (F, E), cfg.param_dtype) * out_std,
        }
    else:
        p = {
            "wi": jax.random.normal(k1, (E, F), cfg.param_dtype) * std,
            "wo": jax.random.normal(k3, (F, E), cfg.param_dtype) * out_std,
        }
        if cfg.bias:
            p["bi"] = jnp.zeros((F,), cfg.param_dtype)
            p["bo"] = jnp.zeros((E,), cfg.param_dtype)
    return p


def _moe_params(cfg, key):
    E, F, X = cfg.d_model, cfg.d_ff, cfg.moe.num_experts
    k0, k1, k2, k3 = jax.random.split(key, 4)
    std = 0.02
    out_std = 0.02 / math.sqrt(2 * cfg.n_layers)
    return {
        "router": jax.random.normal(k0, (E, X), cfg.param_dtype) * std,
        "gate": jax.random.normal(k1, (X, E, F), cfg.param_dtype) * std,
        "up": jax.random.normal(k2, (X, E, F), cfg.param_dtype) * std,
        "down": jax.random.normal(k3, (X, F, E), cfg.param_dtype) * out_std,
    }


def _layer_params(cfg, key):
    E, H, Hkv, Dh = cfg.d_model, cfg.n_heads, cfg.kv_heads, cfg.head_dim
    ks = jax.random.split(key, 6)
    std = 0.02
    out_std = 0.02 / math.sqrt(2 * cfg.n_layers)
    attn = {
        "wq": jax.random.normal(ks[0], (E, H, Dh), cfg.param_dtype) * std,
        "wk": jax.random.normal(ks[1], (E, Hkv, Dh), cfg.param_dtype) * std,
        "wv": jax.random.normal(ks[2], (E, Hkv, Dh), cfg.param_dtype) * std,
        "wo": jax.random.normal(ks[3], (H, Dh, E), cfg.param_dtype) * out_std,
    }
    if cfg.bias:
        attn["bq"] = jnp.zeros((H, Dh), cfg.param_dtype)
        attn["bk"] = jnp.zeros((Hkv, Dh), cfg.param_dtype)
        attn["bv"] = jnp.zeros((Hkv, Dh), cfg.param_dtype)
        attn["bo"] = jnp.zeros((E,), cfg.param_dtype)
    layer = {
        "norm1": _norm_params(cfg, ks[4]),
        "attn": attn,
        "norm2": _norm_params(cfg, ks[4]),
        "mlp": _moe_params(cfg, ks[5]) if cfg.moe else _dense_mlp_params(cfg, ks[5]),
    }
    return layer


def init(key, cfg: TransformerConfig):
    k_emb, k_pos, k_layers, k_head = jax.random.split(key, 4)
    params = {
        "embed": jax.random.normal(k_emb, (cfg.vocab_size, cfg.d_model), cfg.param_dtype) * 0.02,
        "layers": jax.vmap(lambda k: _layer_params(cfg, k))(jax.random.split(k_layers, cfg.n_layers)),
        "final_norm": _norm_params(cfg, k_head),
    }
    if cfg.pos == "learned":
        params["pos_embed"] = jax.random.normal(k_pos, (cfg.max_seq_len, cfg.d_model), cfg.param_dtype) * 0.02
    if not cfg.tie_embeddings:
        params["lm_head"] = jax.random.normal(k_head, (cfg.d_model, cfg.vocab_size), cfg.param_dtype) * 0.02
    return params


def logical_axes(cfg: TransformerConfig):
    """Same tree shape as init(), leaves = tuples of logical dim names.
    Stacked layer params get a leading 'layers' dim."""
    norm = {"w": ("embed",)} if cfg.norm == "rms" else {"w": ("embed",), "b": ("embed",)}
    attn = {
        "wq": ("embed", "heads", "head_dim"),
        "wk": ("embed", "kv_heads", "head_dim"),
        "wv": ("embed", "kv_heads", "head_dim"),
        "wo": ("heads", "head_dim", "embed"),
    }
    if cfg.bias:
        attn.update({"bq": ("heads", "head_dim"), "bk": ("kv_heads", "head_dim"),
                     "bv": ("kv_heads", "head_dim"), "bo": ("embed",)})
    if cfg.moe:
        mlp = {"router": ("embed", None), "gate": ("expert", "embed", "mlp"),
               "up": ("expert", "embed", "mlp"), "down": ("expert", "mlp", "embed")}
    elif cfg.act == "swiglu":
        mlp = {"wi_gate": ("embed", "mlp"), "wi_up": ("embed", "mlp"), "wo": ("mlp", "embed")}
    else:
        mlp = {"wi": ("embed", "mlp"), "wo": ("mlp", "embed")}
        if cfg.bias:
            mlp.update({"bi": ("mlp",), "bo": ("embed",)})
    layer = {"norm1": norm, "attn": attn, "norm2": norm, "mlp": mlp}
    stacked = jax.tree.map(lambda t: ("layers",) + t, layer, is_leaf=lambda x: isinstance(x, tuple))
    out = {
        "embed": ("vocab", "embed"),
        "layers": stacked,
        "final_norm": norm,
    }
    if cfg.pos == "learned":
        out["pos_embed"] = (None, "embed")
    if not cfg.tie_embeddings:
        out["lm_head"] = ("embed", "vocab")
    return out


# ----------------------------------------------------------------- apply

def _norm(x, p, cfg):
    if cfg.norm == "rms":
        return ops.rms_norm(x, p["w"])
    return ops.layer_norm(x, p["w"], p.get("b"))


def _attn_block(x, p, cfg, cos, sin, sp_axis, attn_impl):
    dt = cfg.dtype
    q = jnp.einsum("bte,ehd->bthd", x, p["wq"].astype(dt))
    k = jnp.einsum("bte,ehd->bthd", x, p["wk"].astype(dt))
    v = jnp.einsum("bte,ehd->bthd", x, p["wv"].astype(dt))
    if cfg.bias:
        q = q + p["bq"].astype(dt)
        k = k + p["bk"].astype(dt)
        v = v + p["bv"].astype(dt)
    if cfg.pos == "rope":
        if sp_axis is not None:
            # sequence-sharded: offset positions by this shard's start
            idx = jax.lax.axis_index(sp_axis)
            T = x.shape[1]
            positions = idx * T + jnp.arange(T)
            q = ops.apply_rope(q, cos, sin, positions=positions)
            k = ops.apply_rope(k, cos, sin, positions=positions)
        else:
            q = ops.apply_rope(q, cos, sin)
            k = ops.apply_rope(k, cos, sin)
    out = ops.attention(q, k, v, causal=True, sp_axis=sp_axis, impl=attn_impl)
    out = jnp.einsum("bthd,hde->bte", out, p["wo"].astype(dt))
    if cfg.bias:
        out = out + p["bo"].astype(dt)
    return out


def _dense_mlp(x, p, cfg):
    dt = cfg.dtype
    if cfg.act == "swiglu":
        h = ops.swiglu(x @ p["wi_gate"].astype(dt), x @ p["wi_up"].astype(dt))
        return h @ p["wo"].astype(dt)
    h = x @ p["wi"].astype(dt)
    if cfg.bias:
        h = h + p["bi"].astype(dt)
    h = ops.gelu(h)
    out = h @ p["wo"].astype(dt)
    if cfg.bias:
        out = out + p["bo"].astype(dt)
    return out


def _moe_mlp(x, p, cfg):
    dt = cfg.dtype
    B, T, E = x.shape
    xf = x.reshape(B * T, E)
    router_logits = (xf @ p["router"].astype(dt)).astype(jnp.float32)
    routing = ops.topk_routing(router_logits, num_experts=cfg.moe.num_experts,
                               k=cfg.moe.top_k, capacity_factor=cfg.moe.capacity_factor)

    def expert_fn(pe, xe):
        h = ops.swiglu(xe @ pe["gate"].astype(dt), xe @ pe["up"].astype(dt))
        return h @ pe["down"].astype(dt)

    expert_params = {"gate": p["gate"], "up": p["up"], "down": p["down"]}
    y = ops.moe_apply(xf, routing, expert_fn, expert_params)
    return y.reshape(B, T, E), routing.aux_loss


def forward(params, tokens, cfg: TransformerConfig, *, sp_axis: str | None = None,
            attn_impl: str | None = None, return_hidden: bool = False):
    """tokens [B, T] int32 → logits [B, T, V] (cfg.dtype). Returns
    (logits, aux_loss); with return_hidden=True, returns the pre-head hidden
    states [B, T, E] instead of logits."""
    dt = cfg.dtype
    x = params["embed"].astype(dt)[tokens]
    if cfg.pos == "learned":
        T = tokens.shape[1]
        if sp_axis is not None:
            idx = jax.lax.axis_index(sp_axis)
            pos = jax.lax.dynamic_slice_in_dim(params["pos_embed"], idx * T, T)
        else:
            pos = params["pos_embed"][:T]
        x = x + pos.astype(dt)
    cos = sin = None
    if cfg.pos == "rope":
        cos, sin = ops.rope_frequencies(cfg.head_dim, cfg.max_seq_len, theta=cfg.rope_theta)

    aux_total = jnp.zeros((), jnp.float32)

    def block(carry, layer_p):
        h, aux = carry
        h = h + _attn_block(_norm(h, layer_p["norm1"], cfg), layer_p["attn"], cfg,
                            cos, sin, sp_axis, attn_impl)
        normed = _norm(h, layer_p["norm2"], cfg)
        if cfg.moe:
            delta, layer_aux = _moe_mlp(normed, layer_p["mlp"], cfg)
            aux = aux + layer_aux
        else:
            delta = _dense_mlp(normed, layer_p["mlp"], cfg)
        return (h + delta, aux), None

    if cfg.remat and cfg.remat_policy == "pairs" and (cfg.n_layers % 2
                                                      or cfg.moe):
        raise ValueError(
            "remat_policy='pairs' needs an even n_layers and a dense (non-"
            "MoE) stack; falling back silently would misattribute benchmark "
            "results to selective remat")
    if cfg.remat and cfg.remat_policy == "pairs":
        # selective remat: scan over layer PAIRS, checkpointing only the
        # first of each pair. Backward recomputes half the layers (full
        # per-layer remat recomputes all of them — a 4-pass step with an
        # MFU ceiling of 0.75), at the cost of keeping one layer's
        # activations per pair live. Picked by on-hardware sweeps.
        ck = jax.checkpoint(block, policy=jax.checkpoint_policies.nothing_saveable)

        def pair(carry, pair_p):
            a = jax.tree.map(lambda t: t[0], pair_p)
            b = jax.tree.map(lambda t: t[1], pair_p)
            carry, _ = ck(carry, a)
            carry, _ = block(carry, b)
            return carry, None

        stacked = jax.tree.map(
            lambda t: t.reshape(t.shape[0] // 2, 2, *t.shape[1:]),
            params["layers"])
        (x, aux_total), _ = jax.lax.scan(pair, (x, aux_total), stacked)
    else:
        if cfg.remat:
            policy = (jax.checkpoint_policies.dots_with_no_batch_dims_saveable
                      if cfg.remat_policy == "dots"
                      else jax.checkpoint_policies.nothing_saveable)
            block = jax.checkpoint(block, policy=policy)
        (x, aux_total), _ = jax.lax.scan(block, (x, aux_total), params["layers"])
    x = _norm(x, params["final_norm"], cfg)
    if return_hidden:
        return x, aux_total
    if cfg.tie_embeddings:
        logits = x @ params["embed"].astype(dt).T
    else:
        logits = x @ params["lm_head"].astype(dt)
    return logits, aux_total


def loss_fn(params, tokens, cfg: TransformerConfig, *, sp_axis: str | None = None,
            attn_impl: str | None = None, fused_ce: bool | None = None,
            logits_spec=None, ce_chunk: int | None = None):
    """Next-token LM loss on tokens [B, T]; positions with label -100 ignored.

    fused_ce (default: on for vocab >= 8192) streams the lm_head matmul into
    a chunked cross-entropy so [B,T,V] logits are never materialized.
    logits_spec optionally shards the per-chunk head-matmul output over the
    mesh (vocab dim on tp — see ops.fused_head_cross_entropy)."""
    if fused_ce is None:
        fused_ce = cfg.vocab_size >= 8192
    fused_ce = fused_ce and not cfg.tie_embeddings  # fused path needs lm_head
    if logits_spec is not None and not fused_ce:
        raise ValueError(
            "logits_spec requires the fused-CE path (untied embeddings and "
            "fused_ce enabled); the unfused path would silently materialize "
            "replicated [B,T,V] logits")
    labels = tokens[:, 1:]
    if fused_ce:
        hidden, aux = forward(params, tokens[:, :-1], cfg, sp_axis=sp_axis,
                              attn_impl=attn_impl, return_hidden=True)
        B, T, E = hidden.shape
        loss, _ = ops.fused_head_cross_entropy(
            hidden.reshape(B * T, E), params["lm_head"], labels.reshape(B * T),
            logits_spec=logits_spec, chunk=ce_chunk or 2048)
    else:
        logits, aux = forward(params, tokens[:, :-1], cfg, sp_axis=sp_axis, attn_impl=attn_impl)
        loss, _ = ops.softmax_cross_entropy(logits, labels)
    if cfg.moe:
        loss = loss + cfg.moe.aux_coef * aux / cfg.n_layers
    return loss
