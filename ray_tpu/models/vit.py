"""Vision Transformer (BASELINE.md config 3: ViT-L/16 image pipeline)."""

from __future__ import annotations

import dataclasses
import math
from typing import Any

import jax
import jax.numpy as jnp

from ray_tpu import ops


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    num_classes: int = 1000
    d_model: int = 1024
    n_layers: int = 24
    n_heads: int = 16
    d_ff: int = 4096
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


SIZES = {
    "s16": dict(d_model=384, n_layers=12, n_heads=6, d_ff=1536),
    "b16": dict(d_model=768, n_layers=12, n_heads=12, d_ff=3072),
    "l16": dict(d_model=1024, n_layers=24, n_heads=16, d_ff=4096),
}


def vit_config(size: str = "l16", **overrides) -> ViTConfig:
    base = dict(SIZES[size])
    base.update(overrides)
    return ViTConfig(**base)


def init(key, cfg: ViTConfig):
    ks = jax.random.split(key, 8)
    E, H, Dh, F = cfg.d_model, cfg.n_heads, cfg.head_dim, cfg.d_ff
    patch_dim = 3 * cfg.patch_size ** 2
    std = 0.02
    out_std = 0.02 / math.sqrt(2 * cfg.n_layers)

    def layer(k):
        kk = jax.random.split(k, 6)
        return {
            "norm1": {"w": jnp.ones((E,), cfg.param_dtype), "b": jnp.zeros((E,), cfg.param_dtype)},
            "attn": {
                "wq": jax.random.normal(kk[0], (E, H, Dh), cfg.param_dtype) * std,
                "wk": jax.random.normal(kk[1], (E, H, Dh), cfg.param_dtype) * std,
                "wv": jax.random.normal(kk[2], (E, H, Dh), cfg.param_dtype) * std,
                "wo": jax.random.normal(kk[3], (H, Dh, E), cfg.param_dtype) * out_std,
            },
            "norm2": {"w": jnp.ones((E,), cfg.param_dtype), "b": jnp.zeros((E,), cfg.param_dtype)},
            "mlp": {
                "wi": jax.random.normal(kk[4], (E, F), cfg.param_dtype) * std,
                "bi": jnp.zeros((F,), cfg.param_dtype),
                "wo": jax.random.normal(kk[5], (F, E), cfg.param_dtype) * out_std,
                "bo": jnp.zeros((E,), cfg.param_dtype),
            },
        }

    return {
        "patch_embed": jax.random.normal(ks[0], (patch_dim, E), cfg.param_dtype) * std,
        "patch_bias": jnp.zeros((E,), cfg.param_dtype),
        "cls_token": jax.random.normal(ks[1], (1, 1, E), cfg.param_dtype) * std,
        "pos_embed": jax.random.normal(ks[2], (cfg.n_patches + 1, E), cfg.param_dtype) * std,
        "layers": jax.vmap(layer)(jax.random.split(ks[3], cfg.n_layers)),
        "final_norm": {"w": jnp.ones((E,), cfg.param_dtype), "b": jnp.zeros((E,), cfg.param_dtype)},
        "head": jax.random.normal(ks[4], (E, cfg.num_classes), cfg.param_dtype) * std,
    }


def logical_axes(cfg: ViTConfig):
    norm = {"w": ("embed",), "b": ("embed",)}
    layer = {
        "norm1": norm,
        "attn": {"wq": ("embed", "heads", "head_dim"), "wk": ("embed", "heads", "head_dim"),
                 "wv": ("embed", "heads", "head_dim"), "wo": ("heads", "head_dim", "embed")},
        "norm2": norm,
        "mlp": {"wi": ("embed", "mlp"), "bi": ("mlp",), "wo": ("mlp", "embed"), "bo": ("embed",)},
    }
    stacked = jax.tree.map(lambda t: ("layers",) + t, layer, is_leaf=lambda x: isinstance(x, tuple))
    return {
        "patch_embed": (None, "embed"),
        "patch_bias": ("embed",),
        "cls_token": (None, None, "embed"),
        "pos_embed": (None, "embed"),
        "layers": stacked,
        "final_norm": norm,
        "head": ("embed", None),
    }


def patchify(images, patch_size: int):
    """[B, H, W, 3] → [B, n_patches, 3*p*p]."""
    B, H, W, C = images.shape
    p = patch_size
    x = images.reshape(B, H // p, p, W // p, p, C)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(B, (H // p) * (W // p), p * p * C)


def forward(params, images, cfg: ViTConfig):
    """images [B, H, W, 3] float → logits [B, num_classes]."""
    dt = cfg.dtype
    x = patchify(images.astype(dt), cfg.patch_size)
    x = x @ params["patch_embed"].astype(dt) + params["patch_bias"].astype(dt)
    B = x.shape[0]
    cls = jnp.broadcast_to(params["cls_token"].astype(dt), (B, 1, cfg.d_model))
    x = jnp.concatenate([cls, x], axis=1)
    x = x + params["pos_embed"].astype(dt)

    def block(h, p):
        hn = ops.layer_norm(h, p["norm1"]["w"], p["norm1"]["b"])
        q = jnp.einsum("bte,ehd->bthd", hn, p["attn"]["wq"].astype(dt))
        k = jnp.einsum("bte,ehd->bthd", hn, p["attn"]["wk"].astype(dt))
        v = jnp.einsum("bte,ehd->bthd", hn, p["attn"]["wv"].astype(dt))
        a = ops.attention(q, k, v, causal=False)
        h = h + jnp.einsum("bthd,hde->bte", a, p["attn"]["wo"].astype(dt))
        hn = ops.layer_norm(h, p["norm2"]["w"], p["norm2"]["b"])
        m = ops.gelu(hn @ p["mlp"]["wi"].astype(dt) + p["mlp"]["bi"].astype(dt))
        h = h + (m @ p["mlp"]["wo"].astype(dt) + p["mlp"]["bo"].astype(dt))
        return h, None

    x, _ = jax.lax.scan(block, x, params["layers"])
    x = ops.layer_norm(x, params["final_norm"]["w"], params["final_norm"]["b"])
    return (x[:, 0] @ params["head"].astype(dt)).astype(jnp.float32)


def loss_fn(params, batch, cfg: ViTConfig):
    images, labels = batch
    logits = forward(params, images, cfg)
    loss, _ = ops.softmax_cross_entropy(logits, labels)
    return loss
