from ray_tpu.ops.activations import geglu, gelu, swiglu
from ray_tpu.ops.attention import attention, repeat_kv
from ray_tpu.ops.flash_attention import flash_attention, flash_attention_forward
from ray_tpu.ops.losses import fused_head_cross_entropy, softmax_cross_entropy
from ray_tpu.ops.moe import RoutingInfo, moe_apply, topk_routing
from ray_tpu.ops.norms import layer_norm, rms_norm
from ray_tpu.ops.ragged_paged_attention import (
    ragged_decode_attention, ragged_decode_attention_reference)
from ray_tpu.ops.rope import apply_rope, rope_frequencies

__all__ = [
    "RoutingInfo",
    "apply_rope",
    "attention",
    "flash_attention",
    "flash_attention_forward",
    "fused_head_cross_entropy",
    "geglu",
    "gelu",
    "layer_norm",
    "moe_apply",
    "ragged_decode_attention",
    "ragged_decode_attention_reference",
    "repeat_kv",
    "rms_norm",
    "rope_frequencies",
    "softmax_cross_entropy",
    "swiglu",
]
