"""Gated activations used by the model families."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def swiglu(gate, up):
    return jax.nn.silu(gate) * up


def geglu(gate, up):
    return jax.nn.gelu(gate) * up


def gelu(x):
    return jax.nn.gelu(x, approximate=True)
