"""Attention dispatcher: picks the best implementation for the platform.

Models call `attention(q, k, v, ...)` with [B, T, H, D] activations (GQA
allowed: fewer KV heads). On TPU the Pallas flash kernel runs; elsewhere (or
for odd shapes) the XLA reference path does — same numerics, so tests on the
CPU mesh validate the model code that the TPU executes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ray_tpu.ops.flash_attention import flash_attention
from ray_tpu.parallel.ring_attention import reference_attention, ring_attention


def repeat_kv(k, *, n_rep: int):
    """[B, T, Hkv, D] → [B, T, Hkv*n_rep, D] by repeating each kv head."""
    if n_rep == 1:
        return k
    B, T, Hkv, D = k.shape
    return jnp.repeat(k, n_rep, axis=2)


def _flash_ok(q) -> bool:
    if q.shape[1] % 256 != 0:  # seq must tile into flash blocks
        return False
    # measured on v5e (benchmarks/attn_bench.py, b8 h16 d128): the Pallas
    # kernel wins from seq 1024 up once fwd AND bwd are kernels — 2.4x at
    # s2048 (12.96 vs 31.22 ms fwd+bwd) — and is the only path that runs at
    # s4096+ (XLA's quadratic score tensor OOMs HBM)
    return jax.default_backend() == "tpu" and q.shape[1] >= 1024


def attention(q, k, v, *, causal: bool = True, scale: float | None = None,
              sp_axis: str | None = None, impl: str | None = None):
    """q: [B, T, H, D]; k, v: [B, T, Hkv, D]. Returns [B, T, H, D].

    impl: None=auto, "flash", "reference". sp_axis: when set, runs ring
    attention over that mesh axis (inputs must be sequence-sharded and the
    call made inside shard_map).
    """
    H, Hkv = q.shape[2], k.shape[2]
    if H % Hkv != 0:
        raise ValueError(f"q heads {H} not a multiple of kv heads {Hkv}")
    k = repeat_kv(k, n_rep=H // Hkv)
    v = repeat_kv(v, n_rep=H // Hkv)

    if sp_axis is not None:
        return ring_attention(q, k, v, axis_name=sp_axis, causal=causal, scale=scale)

    use_flash = impl == "flash" or (impl is None and _flash_ok(q))
    if use_flash:
        T = q.shape[1]
        # best measured block size (benchmarks/attn_bench.py), falling back
        # to 256 for seqs that don't tile into 512
        blk = 512 if T % 512 == 0 else min(256, T)
        qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
        out = flash_attention(qt, kt, vt, causal, scale, blk, blk)
        return out.transpose(0, 2, 1, 3)
    return reference_attention(q, k, v, causal=causal, scale=scale)
