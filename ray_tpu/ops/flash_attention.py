"""Flash attention forward kernel for TPU (Pallas), with recompute backward.

Blocked online-softmax attention: grid (B, H, nq, nk) with the kv dimension
innermost so the f32 accumulators live in VMEM scratch across kv steps and
the MXU sees [block_q, D] x [D, block_k] matmuls. Causal blocks above the
diagonal are skipped via predication. (The reference framework has no
attention kernels at all — attention lives in vLLM/torch; this is the
TPU-native compute path that replaces it.)

Backward is recompute-based (jax.vjp over the reference formulation under
remat) — a dedicated Pallas backward kernel is a later optimization.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu only resolves on TPU builds; tests run the kernel via interpret
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

_NEG_INF = -1e30
DEFAULT_BLOCK_Q = 256
DEFAULT_BLOCK_K = 256


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *,
                scale: float, causal: bool, block_q: int, block_k: int):
    i = pl.program_id(2)
    j = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # causal: kv block j is live iff its first key position <= last q position
    live = (j * block_k <= (i + 1) * block_q - 1) if causal else True

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)          # [block_q, D]
        k = k_ref[0, 0].astype(jnp.float32)          # [block_k, D]
        v = v_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale                                     # [block_q, block_k]
        if causal:
            q_pos = i * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_pos = j * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_prev = m_scr[:, :1]                         # [block_q, 1]
        l_prev = l_scr[:, :1]
        m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_new = l_prev * corr + p.sum(axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * corr + jnp.dot(
            p, v, preferred_element_type=jnp.float32
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(j == nk - 1)
    def _finalize():
        l = l_scr[:, :1]
        o_ref[0, 0] = (acc_scr[:] / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def flash_attention_forward(q, k, v, *, causal: bool = True,
                            scale: float | None = None,
                            block_q: int = DEFAULT_BLOCK_Q,
                            block_k: int = DEFAULT_BLOCK_K,
                            interpret: bool = False):
    """q,k,v: [B, H, T, D] (heads-major). Returns [B, H, T, D]."""
    B, H, T, D = q.shape
    if scale is None:
        scale = D ** -0.5
    block_q = min(block_q, T)
    block_k = min(block_k, T)
    if T % block_q or T % block_k:
        raise ValueError(f"T={T} must be divisible by block sizes {block_q},{block_k}")
    nq, nk = T // block_q, T // block_k
    grid = (B, H, nq, nk)

    def qo_map(b, h, i, j):
        return (b, h, i, 0)

    def kv_map(b, h, i, j):
        return (b, h, j, 0)

    kwargs = dict(memory_space=_VMEM) if (_VMEM is not None and not interpret) else {}
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               block_q=block_q, block_k=block_k)
    if pltpu is None:  # pragma: no cover — dispatcher routes to reference instead
        raise RuntimeError("pallas TPU backend unavailable; use the reference attention path")
    scratch = [
        pltpu.VMEM((block_q, 128), jnp.float32),
        pltpu.VMEM((block_q, 128), jnp.float32),
        pltpu.VMEM((block_q, D), jnp.float32),
    ]
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((B, H, T, D), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D), qo_map, **kwargs),
            pl.BlockSpec((1, 1, block_k, D), kv_map, **kwargs),
            pl.BlockSpec((1, 1, block_k, D), kv_map, **kwargs),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, D), qo_map, **kwargs),
        scratch_shapes=scratch,
        interpret=interpret,
    )(q, k, v)


def _reference_bhtd(q, k, v, *, causal: bool, scale: float):
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)) * scale
    if causal:
        T = q.shape[2]
        mask = jnp.tril(jnp.ones((T, T), dtype=bool))
        s = jnp.where(mask[None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention(q, k, v, causal: bool = True, scale: float | None = None):
    """Differentiable flash attention, [B,H,T,D]. Forward = Pallas kernel on
    TPU; backward recomputes attention under the reference formulation."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    return flash_attention_forward(q, k, v, causal=causal, scale=scale)


def _fa_fwd(q, k, v, causal, scale):
    if scale is None:
        scale = q.shape[-1] ** -0.5
    out = flash_attention_forward(q, k, v, causal=causal, scale=scale)
    return out, (q, k, v)


def _fa_bwd(causal, scale, res, g):
    q, k, v = res
    if scale is None:
        scale = q.shape[-1] ** -0.5
    _, vjp = jax.vjp(lambda q, k, v: _reference_bhtd(q, k, v, causal=causal, scale=scale), q, k, v)
    return vjp(g)


flash_attention.defvjp(_fa_fwd, _fa_bwd)
