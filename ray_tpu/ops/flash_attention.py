"""Flash attention forward + backward kernels for TPU (Pallas).

Blocked online-softmax attention: forward grid (B, H, nq, nk) with the kv
dimension innermost so the f32 accumulators live in VMEM scratch across kv
steps and the MXU sees [block_q, D] x [D, block_k] matmuls. Causal blocks
above the diagonal are skipped via predication. The forward also emits the
per-row logsumexp so the backward never rebuilds the softmax normalizer.

Backward is the standard two-kernel flash decomposition (no [T, T] score
tensor is ever materialized):
  - dkv kernel, grid (B, H, nk, nq): for a fixed kv block, sweep q blocks
    accumulating dv += p^T dO and dk += ds^T q in VMEM scratch.
  - dq kernel, grid (B, H, nq, nk): for a fixed q block, sweep kv blocks
    accumulating dq += ds k.
where p = exp(s - lse) is recomputed blockwise from the saved logsumexp and
delta = rowsum(dO * O) folds the softmax Jacobian into ds = p * (dp - delta).

(The reference framework has no attention kernels at all — attention lives in
vLLM/torch; this is the TPU-native compute path that replaces it.)
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu only resolves on TPU builds; tests run the kernel via interpret
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

_NEG_INF = -1e30
DEFAULT_BLOCK_Q = 256
DEFAULT_BLOCK_K = 256


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr, *,
                scale: float, causal: bool, block_q: int, block_k: int):
    i = pl.program_id(2)
    j = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # causal: kv block j is live iff its first key position <= last q position
    live = (j * block_k <= (i + 1) * block_q - 1) if causal else True

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)          # [block_q, D]
        k = k_ref[0, 0].astype(jnp.float32)          # [block_k, D]
        v = v_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale                                     # [block_q, block_k]
        if causal:
            q_pos = i * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_pos = j * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_prev = m_scr[:, :1]                         # [block_q, 1]
        l_prev = l_scr[:, :1]
        m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_new = l_prev * corr + p.sum(axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * corr + jnp.dot(
            p, v, preferred_element_type=jnp.float32
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(j == nk - 1)
    def _finalize():
        l = jnp.maximum(l_scr[:, :1], 1e-30)
        o_ref[0, 0] = (acc_scr[:] / l).astype(o_ref.dtype)
        lse_ref[0, 0] = m_scr[:, :1] + jnp.log(l)


def _fwd_call(q, k, v, *, causal: bool, scale: float, block_q: int,
              block_k: int, interpret: bool):
    B, H, T, D = q.shape
    block_q = min(block_q, T)
    block_k = min(block_k, T)
    if T % block_q or T % block_k:
        raise ValueError(f"T={T} must be divisible by block sizes {block_q},{block_k}")
    nq, nk = T // block_q, T // block_k
    grid = (B, H, nq, nk)

    def qo_map(b, h, i, j):
        return (b, h, i, 0)

    def kv_map(b, h, i, j):
        return (b, h, j, 0)

    def lse_map(b, h, i, j):
        return (b, h, i, 0)

    kwargs = dict(memory_space=_VMEM) if (_VMEM is not None and not interpret) else {}
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               block_q=block_q, block_k=block_k)
    if pltpu is None:  # pragma: no cover
        raise RuntimeError("pallas TPU backend unavailable; use the reference attention path")
    scratch = [
        pltpu.VMEM((block_q, 128), jnp.float32),
        pltpu.VMEM((block_q, 128), jnp.float32),
        pltpu.VMEM((block_q, D), jnp.float32),
    ]
    return pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((B, H, T, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, T, 1), jnp.float32),
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D), qo_map, **kwargs),
            pl.BlockSpec((1, 1, block_k, D), kv_map, **kwargs),
            pl.BlockSpec((1, 1, block_k, D), kv_map, **kwargs),
        ],
        out_specs=(
            pl.BlockSpec((1, 1, block_q, D), qo_map, **kwargs),
            pl.BlockSpec((1, 1, block_q, 1), lse_map, **kwargs),
        ),
        scratch_shapes=scratch,
        interpret=interpret,
    )(q, k, v)


def flash_attention_forward(q, k, v, *, causal: bool = True,
                            scale: float | None = None,
                            block_q: int = DEFAULT_BLOCK_Q,
                            block_k: int = DEFAULT_BLOCK_K,
                            interpret: bool = False):
    """q,k,v: [B, H, T, D] (heads-major). Returns [B, H, T, D]."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    out, _ = _fwd_call(q, k, v, causal=causal, scale=scale,
                       block_q=block_q, block_k=block_k, interpret=interpret)
    return out


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_scr, dv_scr, *,
                scale: float, causal: bool, block_q: int, block_k: int):
    j = pl.program_id(2)   # kv block (outer)
    i = pl.program_id(3)   # q block (inner sweep)
    nq = pl.num_programs(3)

    @pl.when(i == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    live = ((i + 1) * block_q - 1 >= j * block_k) if causal else True

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)            # [bq, D]
        k = k_ref[0, 0].astype(jnp.float32)            # [bk, D]
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)          # [bq, D]
        lse = lse_ref[0, 0]                            # [bq, 1]
        delta = delta_ref[0, 0]                        # [bq, 1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale                                       # [bq, bk]
        if causal:
            q_pos = i * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_pos = j * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        p = jnp.exp(s - lse)                            # [bq, bk]
        # dv += p^T dO
        dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale                   # [bq, bk]
        dk_scr[:] = dk_scr[:] + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(i == nq - 1)
    def _finalize():
        dk_ref[0, 0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[:].astype(dv_ref.dtype)


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
               dq_ref, dq_scr, *,
               scale: float, causal: bool, block_q: int, block_k: int):
    i = pl.program_id(2)   # q block (outer)
    j = pl.program_id(3)   # kv block (inner sweep)
    nk = pl.num_programs(3)

    @pl.when(j == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    live = (j * block_k <= (i + 1) * block_q - 1) if causal else True

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0]
        delta = delta_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if causal:
            q_pos = i * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_pos = j * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale                   # [bq, bk]
        dq_scr[:] = dq_scr[:] + jnp.dot(
            ds, k, preferred_element_type=jnp.float32)

    @pl.when(j == nk - 1)
    def _finalize():
        dq_ref[0, 0] = dq_scr[:].astype(dq_ref.dtype)


def flash_attention_backward(q, k, v, o, lse, do, *, causal: bool,
                             scale: float,
                             block_q: int = DEFAULT_BLOCK_Q,
                             block_k: int = DEFAULT_BLOCK_K,
                             interpret: bool = False):
    """Gradients (dq, dk, dv) for [B,H,T,D] flash attention."""
    B, H, T, D = q.shape
    block_q = min(block_q, T)
    block_k = min(block_k, T)
    nq, nk = T // block_q, T // block_k
    # delta_t = sum_d dO * O — folds the softmax Jacobian; tiny elementwise op,
    # XLA fuses it, no need for a kernel.
    delta = (do.astype(jnp.float32) * o.astype(jnp.float32)).sum(-1, keepdims=True)  # [B,H,T,1]

    kwargs = dict(memory_space=_VMEM) if (_VMEM is not None and not interpret) else {}

    # both backward grids are (B, H, outer, inner): blocks swept by the inner
    # loop index with `inner`, blocks fixed per outer step index with `o_idx`
    def inner_map(b, h, o_idx, inner):
        return (b, h, inner, 0)

    def outer_map(b, h, o_idx, inner):
        return (b, h, o_idx, 0)

    dkv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k),
        out_shape=(
            jax.ShapeDtypeStruct((B, H, T, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, T, D), q.dtype),
        ),
        grid=(B, H, nk, nq),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D), inner_map, **kwargs),
            pl.BlockSpec((1, 1, block_k, D), outer_map, **kwargs),
            pl.BlockSpec((1, 1, block_k, D), outer_map, **kwargs),
            pl.BlockSpec((1, 1, block_q, D), inner_map, **kwargs),
            pl.BlockSpec((1, 1, block_q, 1), inner_map, **kwargs),
            pl.BlockSpec((1, 1, block_q, 1), inner_map, **kwargs),
        ],
        out_specs=(
            pl.BlockSpec((1, 1, block_k, D), outer_map, **kwargs),
            pl.BlockSpec((1, 1, block_k, D), outer_map, **kwargs),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ] if pltpu is not None else [],
        interpret=interpret,
    )
    dk, dv = dkv(q, k, v, do, lse, delta)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k),
        out_shape=jax.ShapeDtypeStruct((B, H, T, D), q.dtype),
        grid=(B, H, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D), outer_map, **kwargs),
            pl.BlockSpec((1, 1, block_k, D), inner_map, **kwargs),
            pl.BlockSpec((1, 1, block_k, D), inner_map, **kwargs),
            pl.BlockSpec((1, 1, block_q, D), outer_map, **kwargs),
            pl.BlockSpec((1, 1, block_q, 1), outer_map, **kwargs),
            pl.BlockSpec((1, 1, block_q, 1), outer_map, **kwargs),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, D), outer_map, **kwargs),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)] if pltpu is not None else [],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


def _reference_bhtd(q, k, v, *, causal: bool, scale: float):
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)) * scale
    if causal:
        T = q.shape[2]
        mask = jnp.tril(jnp.ones((T, T), dtype=bool))
        s = jnp.where(mask[None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q, k, v, causal: bool = True, scale: float | None = None,
                    block_q: int = DEFAULT_BLOCK_Q, block_k: int = DEFAULT_BLOCK_K,
                    interpret: bool = False):
    """Differentiable flash attention, [B,H,T,D]. Forward and backward are
    Pallas kernels on TPU; neither materializes the [T,T] score tensor."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    return flash_attention_forward(q, k, v, causal=causal, scale=scale,
                                   block_q=block_q, block_k=block_k,
                                   interpret=interpret)


def _fa_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    if scale is None:
        scale = q.shape[-1] ** -0.5
    out, lse = _fwd_call(q, k, v, causal=causal, scale=scale,
                         block_q=block_q, block_k=block_k, interpret=interpret)
    return out, (q, k, v, out, lse)


def _fa_bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v, o, lse = res
    if scale is None:
        scale = q.shape[-1] ** -0.5
    return flash_attention_backward(q, k, v, o, lse, g, causal=causal,
                                    scale=scale, block_q=block_q,
                                    block_k=block_k, interpret=interpret)


flash_attention.defvjp(_fa_fwd, _fa_bwd)
