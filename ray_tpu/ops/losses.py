"""Loss ops: cross-entropy with optional z-loss, computed stably in f32."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def softmax_cross_entropy(logits, labels, *, ignore_index: int = -100,
                          z_loss: float = 0.0):
    """logits [..., V] f32/bf16, labels [...] int32. Returns (mean_loss, n_valid).

    Mean is over valid (non-ignored) positions. z_loss penalizes log(Z)^2
    (PaLM-style) to keep logits from drifting — cheap on TPU, fused by XLA.
    """
    lf = logits.astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(lf, axis=-1)
    label_safe = jnp.where(labels == ignore_index, 0, labels)
    picked = jnp.take_along_axis(lf, label_safe[..., None], axis=-1)[..., 0]
    nll = lse - picked
    if z_loss > 0.0:
        nll = nll + z_loss * jnp.square(lse)
    valid = (labels != ignore_index).astype(jnp.float32)
    n_valid = jnp.maximum(valid.sum(), 1.0)
    return (nll * valid).sum() / n_valid, n_valid
