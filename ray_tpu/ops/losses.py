"""Loss ops: cross-entropy with optional z-loss, computed stably in f32."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def softmax_cross_entropy(logits, labels, *, ignore_index: int = -100,
                          z_loss: float = 0.0):
    """logits [..., V] f32/bf16, labels [...] int32. Returns (mean_loss, n_valid).

    Mean is over valid (non-ignored) positions. z_loss penalizes log(Z)^2
    (PaLM-style) to keep logits from drifting — cheap on TPU, fused by XLA.
    """
    lf = logits.astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(lf, axis=-1)
    label_safe = jnp.where(labels == ignore_index, 0, labels)
    picked = jnp.take_along_axis(lf, label_safe[..., None], axis=-1)[..., 0]
    nll = lse - picked
    if z_loss > 0.0:
        nll = nll + z_loss * jnp.square(lse)
    valid = (labels != ignore_index).astype(jnp.float32)
    n_valid = jnp.maximum(valid.sum(), 1.0)
    return (nll * valid).sum() / n_valid, n_valid


def fused_head_cross_entropy(hidden, head_w, labels, *, ignore_index: int = -100,
                             z_loss: float = 0.0, chunk: int = 2048,
                             logits_spec=None):
    """CE( hidden @ head_w, labels ) without materializing full logits.

    hidden [N, E] (any float dtype), head_w [E, V], labels [N]. The [N, V]
    logits tensor never exists at once: lax.map runs the head matmul + lse
    per chunk and the VJP replays per chunk too. Saves ~2×N×V×4 bytes of HBM
    on big-vocab models, which is what caps batch size on one chip.

    `logits_spec` (a PartitionSpec over [chunk, V]) constrains the per-chunk
    logits sharding under a mesh: with the vocab dim on the tp axis each
    chip computes its vocab slice of the head matmul + partial lse and XLA
    reduces — the vocab-matmul output sharding lever for multi-chip
    training (scaling-book output-sharded final projection)."""
    N, E = hidden.shape
    pad = (-N) % chunk
    if pad:
        hidden = jnp.concatenate([hidden, jnp.zeros((pad, E), hidden.dtype)])
        labels = jnp.concatenate([labels, jnp.full((pad,), ignore_index, labels.dtype)])
    n_chunks = hidden.shape[0] // chunk
    hidden = hidden.reshape(n_chunks, chunk, E)
    labels_c = labels.reshape(n_chunks, chunk)

    @jax.checkpoint
    def one(args):
        h, lab = args
        logits = (h @ head_w.astype(h.dtype)).astype(jnp.float32)
        if logits_spec is not None:
            logits = jax.lax.with_sharding_constraint(logits, logits_spec)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        safe = jnp.where(lab == ignore_index, 0, lab)
        picked = jnp.take_along_axis(logits, safe[:, None], axis=-1)[:, 0]
        nll = lse - picked
        if z_loss > 0.0:
            nll = nll + z_loss * jnp.square(lse)
        valid = (lab != ignore_index).astype(jnp.float32)
        return (nll * valid).sum(), valid.sum()

    sums, counts = jax.lax.map(one, (hidden, labels_c))
    n_valid = jnp.maximum(counts.sum(), 1.0)
    return sums.sum() / n_valid, n_valid
