"""Mixture-of-experts routing: token-choice top-k with capacity (GShard-style).

Everything is dense einsum over one-hot dispatch tensors — static shapes, no
gather/scatter with data-dependent sizes, so XLA tiles it onto the MXU and
the `expert` dimension shards cleanly over the `ep` mesh axis. (The reference
has no in-repo EP — SURVEY.md §2.6 — it passes knobs to vLLM; this is the
TPU-native implementation.)
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class RoutingInfo(NamedTuple):
    dispatch: jax.Array       # [N, E, C] one-hot dispatch mask
    combine: jax.Array        # [N, E, C] combine weights (softmax-scaled)
    aux_loss: jax.Array       # load-balancing loss (scalar)


def topk_routing(router_logits, *, num_experts: int, k: int,
                 capacity_factor: float = 1.25) -> RoutingInfo:
    """router_logits: [N, E] (N = flattened tokens). Top-k token-choice routing
    with per-expert capacity C = ceil(k * N / E * capacity_factor); tokens over
    capacity are dropped (their combine weights are zero)."""
    N, E = router_logits.shape
    assert E == num_experts
    capacity = int(max(k * N / E * capacity_factor, 1.0) + 0.9999)

    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)  # [N, E]
    gate_vals, expert_idx = jax.lax.top_k(probs, k)                     # [N, k]
    # renormalize the selected gates (Mixtral convention)
    gate_vals = gate_vals / jnp.maximum(gate_vals.sum(-1, keepdims=True), 1e-9)

    # position of each (token, choice) in its expert's queue
    onehot = jax.nn.one_hot(expert_idx, E, dtype=jnp.int32)             # [N, k, E]
    flat = onehot.reshape(N * k, E)
    # order: token-major, choice-major — earlier tokens win capacity
    pos_in_expert = jnp.cumsum(flat, axis=0) - flat                     # [N*k, E]
    pos = (pos_in_expert * flat).sum(-1).reshape(N, k)                  # [N, k]
    within_cap = pos < capacity

    slot_onehot = jax.nn.one_hot(pos, capacity, dtype=jnp.float32)      # [N, k, C]
    keep = within_cap.astype(jnp.float32)                               # [N, k]
    # accumulate per choice: peak memory stays at the [N, E, C] output size
    # instead of materializing a [N, k, E, C] intermediate
    dispatch = jnp.zeros((N, E, capacity), jnp.float32)
    combine = jnp.zeros((N, E, capacity), jnp.float32)
    for c in range(k):
        d = (onehot[:, c].astype(jnp.float32)[:, :, None]
             * slot_onehot[:, c][:, None, :]
             * keep[:, c][:, None, None])                               # [N, E, C]
        dispatch = dispatch + d
        combine = combine + d * gate_vals[:, c][:, None, None]

    # Switch-style load-balance aux loss
    frac_tokens = jnp.mean(onehot[:, 0].astype(jnp.float32), axis=0)    # top-1 assignment share
    frac_probs = jnp.mean(probs, axis=0)
    aux = E * jnp.sum(frac_tokens * frac_probs)
    return RoutingInfo(dispatch=dispatch, combine=combine, aux_loss=aux)


def moe_apply(x, routing: RoutingInfo, expert_fn, expert_params):
    """x: [N, D]; expert_fn(params_e, xe) applied per expert via vmap.

    expert_params leaves have leading dim E (shardable over 'ep')."""
    xe = jnp.einsum("nd,nec->ecd", x, routing.dispatch.astype(x.dtype))  # [E, C, D]
    ye = jax.vmap(expert_fn)(expert_params, xe)                          # [E, C, D]
    return jnp.einsum("ecd,nec->nd", ye, routing.combine.astype(x.dtype))
