"""Normalization ops. Computed in f32 regardless of input dtype (TPU bf16
training convention); XLA fuses these into neighboring matmuls."""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def rms_norm(x, weight, *, eps: float = 1e-6):
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    y = xf * lax.rsqrt(var + eps)
    return (y * weight.astype(jnp.float32)).astype(x.dtype)


def layer_norm(x, weight, bias, *, eps: float = 1e-5):
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=-1, keepdims=True)
    y = (xf - mean) * lax.rsqrt(var + eps)
    out = y * weight.astype(jnp.float32)
    if bias is not None:
        out = out + bias.astype(jnp.float32)
    return out.astype(x.dtype)
