"""Ragged paged attention for the decode step (Pallas TPU + reference).

One launch covers the WHOLE continuous batch against its paged KV: each
row attends over exactly the pages its block table names, up to its own
length — no per-slot gather of the full [max_pages, page] span, no
padding compute for short rows (arXiv 2604.15464, Ragged Paged Attention;
PAPERS.md). The previous decode step gathered every row's full block
table (`kp[state["block"]]` → [B, max_pages*page, Hkv, Dh]) and masked —
HBM traffic and FLOPs scale with the LONGEST POSSIBLE sequence for every
row, not with the tokens actually resident.

Two implementations with ONE accumulation order so they agree bitwise:

- ``_ragged_kernel`` — Pallas TPU kernel, grid (batch, page); the block
  table and per-row positions ride scalar prefetch so the page BlockSpec
  index map gathers each row's next page straight out of the HBM pool,
  and ``pl.when`` skips pages past the row's length (the ragged part —
  dead pages cost neither FLOPs nor VMEM bandwidth). Online-softmax
  accumulators live in VMEM scratch across the page sweep, like
  flash_attention.py.
- ``ragged_decode_attention_reference`` — pure JAX mirror of the same
  per-page online-softmax math (fori_loop over pages, f32 accumulators,
  identical op order), so tier-1 on ``JAX_PLATFORMS=cpu`` asserts the
  kernel (interpret mode) is bit-consistent with the path the CPU engine
  actually decodes with.

The engine bounds the page sweep host-side (`pages_bound` in
models/decoding_paged.py decode_step_paged_ragged): the block table is
sliced to the batch's live maximum before either impl runs, so even the
reference does work proportional to the longest RESIDENT row.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu only resolves on TPU builds; tests run the kernel via interpret
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

_NEG_INF = -1e30


def _ragged_kernel(tbl_ref, pos_ref, q_ref, kp_ref, vp_ref, o_ref,
                   m_scr, l_scr, acc_scr, *, scale: float, page_size: int,
                   kv_heads: int, q_per_kv: int):
    b = pl.program_id(0)
    j = pl.program_id(1)
    nj = pl.num_programs(1)
    H = kv_heads * q_per_kv

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    p0 = pos_ref[b]
    # page j holds cache positions [j*P, (j+1)*P); live iff its first
    # position is attendable (<= the row's current position) — dead pages
    # are skipped entirely, which is what makes the sweep ragged
    live = j * page_size <= p0

    @pl.when(live)
    def _compute():
        q = q_ref[0].astype(jnp.float32)              # [Hkv, G, Dh]
        k = kp_ref[0].astype(jnp.float32)             # [P, Hkv, Dh]
        v = vp_ref[0].astype(jnp.float32)
        s = jnp.einsum("kgd,pkd->kgp", q, k,
                       preferred_element_type=jnp.float32) * scale
        kpos = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 2)
        s = jnp.where(kpos <= p0, s, _NEG_INF)
        sf = s.reshape(H, page_size)
        m_prev = m_scr[:, :1]                         # [H, 1]
        l_prev = l_scr[:, :1]
        m_new = jnp.maximum(m_prev, sf.max(axis=-1, keepdims=True))
        p = jnp.exp(sf - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_new = l_prev * corr + p.sum(axis=-1, keepdims=True)
        pv = jnp.einsum("kgp,pkd->kgd",
                        p.reshape(kv_heads, q_per_kv, page_size), v,
                        preferred_element_type=jnp.float32)
        acc_scr[:] = acc_scr[:] * corr + pv.reshape(H, -1)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(j == nj - 1)
    def _finalize():
        l = jnp.maximum(l_scr[:, :1], 1e-30)
        out = (acc_scr[:] / l).reshape(kv_heads, q_per_kv, -1)
        o_ref[0] = out.astype(o_ref.dtype)


def _ragged_kernel_call(q, kp, vp, block_table, pos, *, scale: float,
                        interpret: bool):
    B, Hkv, G, Dh = q.shape
    P = kp.shape[1]
    nb = block_table.shape[1]
    H = Hkv * G
    if pltpu is None:  # pragma: no cover — CPU wheels lack the TPU backend
        raise RuntimeError(
            "pallas TPU backend unavailable; use impl='reference'")

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # block_table, pos
        grid=(B, nb),
        in_specs=[
            pl.BlockSpec((1, Hkv, G, Dh), lambda b, j, tbl, pos: (b, 0, 0, 0)),
            # the ragged gather: page j of row b streams in from wherever
            # the block table says it lives in the pool
            pl.BlockSpec((1, P, Hkv, Dh),
                         lambda b, j, tbl, pos: (tbl[b, j], 0, 0, 0)),
            pl.BlockSpec((1, P, Hkv, Dh),
                         lambda b, j, tbl, pos: (tbl[b, j], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, Hkv, G, Dh),
                               lambda b, j, tbl, pos: (b, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H, 128), jnp.float32),
            pltpu.VMEM((H, 128), jnp.float32),
            pltpu.VMEM((H, Dh), jnp.float32),
        ],
    )
    kernel = functools.partial(_ragged_kernel, scale=scale, page_size=P,
                               kv_heads=Hkv, q_per_kv=G)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((B, Hkv, G, Dh), q.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )(block_table, pos, q, kp, vp)


def ragged_decode_attention_reference(q, kp, vp, block_table, pos, *,
                                      scale: float):
    """Pure-JAX mirror of the kernel: fori_loop over pages with the SAME
    f32 online-softmax accumulation per page, so the two are
    bit-consistent (asserted in tier-1). Dead pages keep the previous
    accumulators untouched — the where() twin of the kernel's pl.when."""
    B, Hkv, G, Dh = q.shape
    P = kp.shape[1]
    nb = block_table.shape[1]
    H = Hkv * G
    qf = q.astype(jnp.float32)

    def body(j, carry):
        m, l, acc = carry
        pid = block_table[:, j]                        # [B]
        k = kp[pid].astype(jnp.float32)                # [B, P, Hkv, Dh]
        v = vp[pid].astype(jnp.float32)
        s = jnp.einsum("bkgd,bpkd->bkgp", qf, k,
                       preferred_element_type=jnp.float32) * scale
        kpos = j * P + jax.lax.broadcasted_iota(jnp.int32, s.shape, 3)
        s = jnp.where(kpos <= pos[:, None, None, None], s, _NEG_INF)
        sf = s.reshape(B, H, P)
        m_new = jnp.maximum(m, sf.max(axis=-1, keepdims=True))
        p = jnp.exp(sf - m_new)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(axis=-1, keepdims=True)
        pv = jnp.einsum("bkgp,bpkd->bkgd",
                        p.reshape(B, Hkv, G, P), v,
                        preferred_element_type=jnp.float32)
        acc_new = acc * corr + pv.reshape(B, H, Dh)
        live = (j * P <= pos)[:, None, None]           # [B, 1, 1]
        return (jnp.where(live, m_new, m), jnp.where(live, l_new, l),
                jnp.where(live, acc_new, acc))

    m0 = jnp.full((B, H, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, 1), jnp.float32)
    a0 = jnp.zeros((B, H, Dh), jnp.float32)
    _m, l, acc = jax.lax.fori_loop(0, nb, body, (m0, l0, a0))
    out = acc / jnp.maximum(l, 1e-30)
    return out.reshape(B, Hkv, G, Dh).astype(q.dtype)


def ragged_decode_attention(q, kp, vp, block_table, pos, *,
                            scale: float | None = None,
                            impl: str = "reference",
                            interpret: bool = False):
    """One decode-attention launch over the whole continuous batch.

    q: [B, Hkv, G, Dh] — this step's queries (one token per row, grouped
    by kv head); kp/vp: [num_pages, P, Hkv, Dh] — one layer's page pool;
    block_table: [B, nb] int32 page ids (pre-sliced to the batch's live
    page bound); pos: [B] int32 — row b attends cache positions <= pos[b].
    Returns [B, Hkv, G, Dh] in q's dtype.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if impl == "kernel":
        return _ragged_kernel_call(q, kp, vp, block_table, pos,
                                   scale=scale, interpret=interpret)
    if impl != "reference":
        raise ValueError(f"impl must be 'kernel' or 'reference', got {impl!r}")
    return ragged_decode_attention_reference(q, kp, vp, block_table, pos,
                                             scale=scale)
