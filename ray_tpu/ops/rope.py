"""Rotary position embeddings (RoPE), half-rotation convention (Llama-style)."""

from __future__ import annotations

import jax.numpy as jnp


def rope_frequencies(head_dim: int, max_len: int, *, theta: float = 10000.0,
                     dtype=jnp.float32):
    """[max_len, head_dim//2] cos/sin tables."""
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    t = jnp.arange(max_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)
    return jnp.cos(freqs).astype(dtype), jnp.sin(freqs).astype(dtype)


def apply_rope(x, cos, sin, *, positions=None):
    """x: [B, T, H, D]; cos/sin: [max_len, D//2]; positions: [B, T] or [T]."""
    B, T, H, D = x.shape
    if positions is None:
        c = cos[:T][None, :, None, :]
        s = sin[:T][None, :, None, :]
    else:
        c = cos[positions]
        s = sin[positions]
        if c.ndim == 2:  # [T, D/2] → [1, T, 1, D/2]
            c, s = c[None, :, None, :], s[None, :, None, :]
        else:            # [B, T, D/2] → [B, T, 1, D/2]
            c, s = c[:, :, None, :], s[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * c - x2 * s, x1 * s + x2 * c], axis=-1)
    return out.astype(x.dtype)
