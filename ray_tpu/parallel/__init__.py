# shard_map's home moved across jax releases (top-level on new jax, under
# jax.experimental on the 0.4.x line this image ships), and the replication-
# check kwarg was renamed check_rep → check_vma along the way. Resolve both
# ONCE here; library code and tests import shard_map from ray_tpu.parallel
# instead of jax.
try:
    from jax import shard_map as _sm  # newer jax: function (or module)
    _sm = getattr(_sm, "shard_map", _sm)
except ImportError:  # jax 0.4.x
    from jax.experimental.shard_map import shard_map as _sm


def shard_map(f, /, *args, **kwargs):
    import inspect

    if "check_vma" in kwargs and \
            "check_vma" not in inspect.signature(_sm).parameters:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    return _sm(f, *args, **kwargs)

from ray_tpu.parallel.mesh import (
    AXES,
    DEFAULT_RULES,
    MeshSpec,
    ShardingRules,
    act_sharding,
    constrain,
    hybrid_mesh,
    param_shardings,
    sharding_for,
)
from ray_tpu.parallel import collectives
from ray_tpu.parallel.pipeline import pipeline_apply, stack_stage_params
from ray_tpu.parallel.ring_attention import reference_attention, ring_attention

__all__ = [
    "AXES",
    "DEFAULT_RULES",
    "MeshSpec",
    "hybrid_mesh",
    "ShardingRules",
    "act_sharding",
    "collectives",
    "constrain",
    "param_shardings",
    "pipeline_apply",
    "reference_attention",
    "ring_attention",
    "shard_map",
    "sharding_for",
    "stack_stage_params",
]
