from ray_tpu.parallel.mesh import (
    AXES,
    DEFAULT_RULES,
    MeshSpec,
    ShardingRules,
    act_sharding,
    constrain,
    hybrid_mesh,
    param_shardings,
    sharding_for,
)
from ray_tpu.parallel import collectives
from ray_tpu.parallel.pipeline import pipeline_apply, stack_stage_params
from ray_tpu.parallel.ring_attention import reference_attention, ring_attention

__all__ = [
    "AXES",
    "DEFAULT_RULES",
    "MeshSpec",
    "hybrid_mesh",
    "ShardingRules",
    "act_sharding",
    "collectives",
    "constrain",
    "param_shardings",
    "pipeline_apply",
    "reference_attention",
    "ring_attention",
    "sharding_for",
    "stack_stage_params",
]
