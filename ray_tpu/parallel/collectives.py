"""Device collectives: thin names over XLA's, usable inside shard_map/jit.

TPU-native replacement for the reference's actor-attached NCCL collectives
(reference: python/ray/util/collective/collective.py:325-738 — allreduce/
reduce/broadcast/allgather/reducescatter/send/recv/barrier over NCCL).
Here the collectives are *in-program*: XLA schedules them on ICI, overlapped
with compute. Host-side (CPU tensor) collectives over actor groups live in
ray_tpu.util.collective instead.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def pvary(x, axes):
    """Compat shim: mark x as varying over `axes` (jax pcast/pvary rename).
    jax 0.4.x predates vma typing entirely — there it's an identity."""
    pcast = getattr(lax, "pcast", None)
    if pcast is not None:
        return pcast(x, axes, to="varying")
    pv = getattr(lax, "pvary", None)
    if pv is not None:
        return pv(x, axes)
    return x


def zeros_varying_like(shape, dtype, ref):
    """Zeros of `shape` carrying `ref`'s varying-manual-axes type (vma), so
    scan carries initialized from constants type-check under shard_map."""
    return jnp.zeros(shape, dtype) + (ref.ravel()[0] * 0).astype(dtype)


def allreduce(x, axis_name: str):
    return lax.psum(x, axis_name)


def allreduce_mean(x, axis_name: str):
    return lax.pmean(x, axis_name)


def reducescatter(x, axis_name: str, *, scatter_dimension: int = 0, tiled: bool = True):
    return lax.psum_scatter(x, axis_name, scatter_dimension=scatter_dimension, tiled=tiled)


def allgather(x, axis_name: str, *, axis: int = 0, tiled: bool = True):
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def broadcast(x, axis_name: str, *, root: int = 0):
    """Every member gets root's value (select + psum keeps it one collective)."""
    idx = lax.axis_index(axis_name)
    masked = jnp.where(idx == root, x, jnp.zeros_like(x))
    return lax.psum(masked, axis_name)


def ring_permute(x, axis_name: str, *, shift: int = 1):
    n = axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


def all_to_all(x, axis_name: str, *, split_axis: int, concat_axis: int, tiled: bool = True):
    return lax.all_to_all(x, axis_name, split_axis=split_axis, concat_axis=concat_axis, tiled=tiled)


def axis_index(axis_name: str):
    return lax.axis_index(axis_name)


def axis_size(axis_name: str):
    """STATIC size of a named mesh axis from inside shard_map. jax 0.4.x has
    no lax.axis_size; there the axis env frame carries the size directly."""
    fn = getattr(lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    from jax._src.core import axis_frame

    fr = axis_frame(axis_name)
    return fr if isinstance(fr, int) else fr.size
