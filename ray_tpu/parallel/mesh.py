"""Device mesh + sharding vocabulary — the TPU-native parallelism substrate.

Where the reference delegates TP/PP/EP to engines and does DP via NCCL
process groups (SURVEY.md §2.6), here every strategy is a named axis of one
`jax.sharding.Mesh` and parallelism is expressed as shardings over it; XLA
inserts the ICI/DCN collectives. Axes:

  dp    data parallel (gradient psum)
  fsdp  fully-sharded data parallel (params sharded, batch also split here)
  ep    expert parallel (MoE experts)
  pp    pipeline parallel (layer stages)
  sp    sequence/context parallel (ring attention)
  tp    tensor parallel (heads / mlp / vocab)

Axis order is outermost→innermost: tp is innermost so its collectives ride
the shortest ICI hops (scaling-book recipe: mesh → annotate → let XLA insert
collectives).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Mapping, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu._private.constants import (MESH_AXES, MESH_AXIS_DP,
                                        MESH_AXIS_EP, MESH_AXIS_FSDP,
                                        MESH_AXIS_PP, MESH_AXIS_SP,
                                        MESH_AXIS_TP)

# the vocabulary lives in _private/constants.py so every axis string in
# the tree resolves against ONE spelling (spmd-consistency enforces it);
# AXES stays exported as this module's public name for it
AXES = MESH_AXES


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    dp: int = 1
    fsdp: int = 1
    ep: int = 1
    pp: int = 1
    sp: int = 1
    tp: int = 1

    @property
    def shape(self) -> tuple[int, ...]:
        return (self.dp, self.fsdp, self.ep, self.pp, self.sp, self.tp)

    def size(self) -> int:
        return math.prod(self.shape)

    @classmethod
    def auto(cls, n_devices: int | None = None, *, fsdp: int = 1, ep: int = 1,
             pp: int = 1, sp: int = 1, tp: int = 1) -> "MeshSpec":
        """Fill dp with whatever devices remain after the explicit axes."""
        n = n_devices if n_devices is not None else len(jax.devices())
        rest = fsdp * ep * pp * sp * tp
        if n % rest != 0:
            raise ValueError(f"{n} devices not divisible by fsdp*ep*pp*sp*tp={rest}")
        return cls(dp=n // rest, fsdp=fsdp, ep=ep, pp=pp, sp=sp, tp=tp)

    def build(self, devices: Sequence[Any] | None = None) -> Mesh:
        devices = list(devices) if devices is not None else jax.devices()
        n = self.size()
        if len(devices) < n:
            raise ValueError(f"mesh needs {n} devices, have {len(devices)}")
        devices = devices[:n]
        if n > 1 and devices[0].platform == "tpu":
            # respects ICI torus adjacency when assigning mesh coordinates
            from jax.experimental import mesh_utils

            dev_array = mesh_utils.create_device_mesh(self.shape, devices=devices)
        else:
            dev_array = np.asarray(devices).reshape(self.shape)
        return Mesh(dev_array, AXES)


def hybrid_mesh(*, dcn_dp: int | None = None, fsdp: int = 1, ep: int = 1,
                pp: int = 1, sp: int = 1, tp: int = 1,
                devices: Sequence[Any] | None = None) -> Mesh:
    """Multi-slice mesh: data parallelism over DCN between slices, the other
    axes inside each slice over ICI (the scaling-book recipe — gradients
    cross the slow inter-slice network once per step, everything
    bandwidth-hungry stays on the torus).

    dcn_dp defaults to the number of slices (one data shard per slice).
    Under jax.distributed this uses mesh_utils.create_hybrid_device_mesh so
    the leading axis maps exactly to slice boundaries; off-TPU (tests) it
    reshapes process-major device order, which has the same property on the
    virtual CPU mesh. (reference capability: multislice DCN training —
    SURVEY §2.6/§2.7; jax mesh_utils.create_hybrid_device_mesh.)"""
    devices = list(devices) if devices is not None else jax.devices()
    n_slices = len({getattr(d, "slice_index", getattr(d, "process_index", 0))
                    for d in devices})
    dcn_dp = dcn_dp if dcn_dp is not None else max(1, n_slices)
    if len(devices) % dcn_dp != 0:
        raise ValueError(
            f"{len(devices)} devices not divisible by dcn_dp={dcn_dp}")
    per_slice = len(devices) // dcn_dp
    ici = fsdp * ep * pp * sp * tp
    if per_slice % ici != 0:
        raise ValueError(
            f"{per_slice} per-slice devices not divisible by "
            f"fsdp*ep*pp*sp*tp={ici}")
    ici_dp = per_slice // ici
    ici_shape = (ici_dp, fsdp, ep, pp, sp, tp)
    if devices[0].platform == "tpu" and dcn_dp > 1:
        from jax.experimental import mesh_utils

        dev_array = mesh_utils.create_hybrid_device_mesh(
            ici_shape, (dcn_dp, 1, 1, 1, 1, 1), devices=devices)
        # hybrid mesh returns shape (dcn*ici_dp, fsdp, ...): dp leads
        dev_array = dev_array.reshape((dcn_dp * ici_dp, fsdp, ep, pp, sp, tp))
    elif devices[0].platform == "tpu":
        # single slice: keep torus-adjacency-aware assignment
        return MeshSpec(dp=ici_dp, fsdp=fsdp, ep=ep, pp=pp, sp=sp,
                        tp=tp).build(devices)
    else:
        order = sorted(devices, key=lambda d: (getattr(d, "process_index", 0),
                                               d.id))
        dev_array = np.asarray(order[:dcn_dp * per_slice]).reshape(
            (dcn_dp * ici_dp, fsdp, ep, pp, sp, tp))
    return Mesh(dev_array, AXES)


# ---------------------------------------------------------------- rules

# Logical dimension names used by models; rules map them to mesh axes.
# Separate tables for parameters vs activations (t5x-style): e.g. "embed" is
# sharded over fsdp in parameters (ZeRO-3) but replicated in activations.
@dataclasses.dataclass(frozen=True)
class ShardingRules:
    params: Mapping[str, Any]
    acts: Mapping[str, Any]

    def param_spec(self, logical: Sequence[str | None]) -> P:
        return P(*(self.params.get(d) if d is not None else None for d in logical))

    def act_spec(self, logical: Sequence[str | None]) -> P:
        return P(*(self.acts.get(d) if d is not None else None for d in logical))


DEFAULT_RULES = ShardingRules(
    params={
        "vocab": MESH_AXIS_TP,
        "embed": MESH_AXIS_FSDP,  # ZeRO-3-style weight shard; all-gathered by XLA at use
        "heads": MESH_AXIS_TP,
        "kv_heads": MESH_AXIS_TP,
        "head_dim": None,
        "mlp": MESH_AXIS_TP,
        "expert": MESH_AXIS_EP,
        "layers": None,
        "stage": MESH_AXIS_PP,
    },
    acts={
        "batch": (MESH_AXIS_DP, MESH_AXIS_FSDP),  # global batch over both data axes
        "seq": MESH_AXIS_SP,
        "embed": None,
        "heads": MESH_AXIS_TP,
        "kv_heads": MESH_AXIS_TP,
        "head_dim": None,
        "mlp": MESH_AXIS_TP,
        "vocab": MESH_AXIS_TP,
        "expert": MESH_AXIS_EP,
        "stage": MESH_AXIS_PP,
    },
)


def sharding_for(mesh: Mesh, spec: P) -> NamedSharding:
    return NamedSharding(mesh, spec)


def param_shardings(mesh: Mesh, logical_tree, rules: ShardingRules = DEFAULT_RULES):
    """Map a pytree of logical-axis tuples to NamedShardings."""
    return jax.tree.map(
        lambda logical: NamedSharding(mesh, rules.param_spec(logical)),
        logical_tree,
        is_leaf=lambda x: isinstance(x, tuple),
    )


def act_sharding(mesh: Mesh, *logical: str | None,
                 rules: ShardingRules = DEFAULT_RULES) -> NamedSharding:
    return NamedSharding(mesh, rules.act_spec(logical))


def constrain(x, mesh: Mesh, *logical: str | None, rules: ShardingRules = DEFAULT_RULES):
    """jax.lax.with_sharding_constraint with logical names."""
    return jax.lax.with_sharding_constraint(x, act_sharding(mesh, *logical, rules=rules))


def local_mesh_devices(platform: str = "cpu", n: int | None = None):
    devs = [d for d in jax.devices() if d.platform == platform] or jax.devices()
    return devs if n is None else devs[:n]
