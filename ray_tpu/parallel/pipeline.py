"""Pipeline parallelism: GPipe-style microbatch schedule over the `pp` axis.

The reference expresses PP only by passing `pipeline_parallel_size` to vLLM
or by hand-building compiled DAGs with overlapped stages
(reference: dag/compiled_dag_node.py:2002 _build_execution_schedule). Here PP
is a compiled construct: one jitted program per device, activations hop
stage→stage over ICI via ppermute inside a lax.scan — the schedule is static,
exactly what XLA wants.

Called INSIDE shard_map over the 'pp' axis. Layer params are stacked on a
leading `stage` dim and sharded over pp, so each device holds its stage's
slice; within a stage, layers run under an inner lax.scan.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu._private.constants import MESH_AXIS_PP
from ray_tpu.parallel.collectives import axis_size, pvary as _pvary, zeros_varying_like


def pipeline_apply(
    stage_fn: Callable,
    stage_params,
    x,                      # [n_micro, micro_batch, ...] same on every stage
    *,
    axis_name: str = MESH_AXIS_PP,
):
    """Run microbatches through the pipeline; returns [n_micro, ...] outputs
    (valid on every device — the final outputs are broadcast over the axis).

    stage_fn(stage_params, h) -> h', applied by each stage to each microbatch.
    """
    n_stages = axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    x = _pvary(x, (axis_name,))  # replicated input enters the varying world
    n_micro = x.shape[0]
    n_ticks = n_micro + n_stages - 1
    out_shape = jax.eval_shape(stage_fn, stage_params, x[0])
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def tick(carry, t):
        recv, outputs = carry
        # stage 0 feeds from the input stream; others from the previous stage
        mb_idx = jnp.clip(t, 0, n_micro - 1)
        inp = jnp.where(stage == 0, lax.dynamic_index_in_dim(x, mb_idx, 0, keepdims=False), recv)
        h = stage_fn(stage_params, inp)
        # last stage banks its result for microbatch t - (n_stages - 1)
        out_idx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
        is_valid = jnp.logical_and(stage == n_stages - 1, t >= n_stages - 1)
        outputs = jnp.where(
            is_valid,
            lax.dynamic_update_index_in_dim(outputs, h.astype(outputs.dtype), out_idx, 0),
            outputs,
        )
        nxt = lax.ppermute(h, axis_name, perm)
        return (nxt, outputs), None

    # carries must hold the union vma of x and the stage params
    ref = x.ravel()[0] * 0 + jax.tree.leaves(stage_params)[0].ravel()[0] * 0
    recv0 = zeros_varying_like(out_shape.shape, out_shape.dtype, ref[None])
    outs0 = zeros_varying_like((n_micro,) + out_shape.shape, out_shape.dtype, ref[None])
    (_, outputs), _ = lax.scan(tick, (recv0, outs0), jnp.arange(n_ticks))
    # broadcast final outputs from the last stage to every stage
    outputs = jnp.where(stage == n_stages - 1, outputs, jnp.zeros_like(outputs))
    return lax.psum(outputs, axis_name)


def stack_stage_params(params_per_layer, n_stages: int):
    """[L, ...] stacked layer params → [pp, L//pp, ...] for sharding over pp."""
    def reshape(p):
        L = p.shape[0]
        if L % n_stages != 0:
            raise ValueError(f"{L} layers not divisible by {n_stages} stages")
        return p.reshape(n_stages, L // n_stages, *p.shape[1:])

    return jax.tree.map(reshape, params_per_layer)
