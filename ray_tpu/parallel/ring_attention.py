"""Ring attention: exact attention over a sequence-sharded axis.

Greenfield for this framework (the reference has NO sequence/context
parallelism — SURVEY.md §2.6: ring/Ulysses absent, delegated to engines).
Design follows the ring-attention construction (blockwise attention with
online softmax; KV blocks rotate around the `sp` mesh axis via ppermute so
each hop rides one ICI link while the local block matmul hides the transfer).

All functions are called INSIDE shard_map with q/k/v already sharded on the
sequence dimension; shapes are per-shard [B, T_local, H, D].
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.parallel.collectives import axis_size, pvary as _pvary, zeros_varying_like

_NEG_INF = -1e30


def _block_attend(q, k, v, m_prev, l_prev, o_prev, mask, scale):
    """One flash-attention-style accumulation step.

    q: [B,Tq,H,D]  k,v: [B,Tk,H,D]  mask: [Tq,Tk] bool (True = attend)
    m,l: [B,H,Tq]  o: [B,Tq,H,D]
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    s = jnp.where(mask[None, None, :, :], s, _NEG_INF)
    m_new = jnp.maximum(m_prev, s.max(axis=-1))
    p = jnp.exp(s - m_new[..., None])
    # rows fully masked in this block contribute exp(-1e30 - m) ≈ 0 naturally
    correction = jnp.exp(m_prev - m_new)
    l_new = l_prev * correction + p.sum(axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    o_new = o_prev * correction.transpose(0, 2, 1)[..., None] + pv
    return m_new, l_new, o_new


def ring_attention(q, k, v, *, axis_name: str, causal: bool = True,
                   scale: float | None = None):
    """Exact (optionally causal) attention with KV rotating around `axis_name`.

    Per-shard inputs [B, T, H, D]; K/V heads must already match Q heads
    (repeat GQA KV heads before sharding). Returns per-shard [B, T, H, D].
    """
    B, T, H, D = q.shape
    if scale is None:
        scale = D ** -0.5
    n = axis_size(axis_name)
    my = lax.axis_index(axis_name)
    q_pos = my * T + jnp.arange(T)

    # init accumulators carrying q's full vma (not just the ring axis) so the
    # scan carry types line up with the per-shard outputs under shard_map
    qf = q.astype(jnp.float32)
    m0 = zeros_varying_like((B, H, T), jnp.float32, qf) + _NEG_INF
    l0 = zeros_varying_like((B, H, T), jnp.float32, qf)
    o0 = zeros_varying_like((B, T, H, D), jnp.float32, qf)

    def step(carry, idx):
        k_cur, v_cur, m, l, o = carry
        src = (my - idx) % n  # which shard's KV block we currently hold
        k_pos = src * T + jnp.arange(T)
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]
        else:
            mask = jnp.ones((T, T), dtype=bool)
        m, l, o = _block_attend(qf, k_cur.astype(jnp.float32),
                                v_cur.astype(jnp.float32), m, l, o, mask, scale)
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (k_nxt, v_nxt, m, l, o), None

    (_k, _v, m, l, o), _ = _scan_steps(step, (k, v, m0, l0, o0), n)
    out = o / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def _scan_steps(step, carry, n):
    return lax.scan(step, carry, jnp.arange(n))


def reference_attention(q, k, v, *, causal: bool = True, scale: float | None = None):
    """Unsharded reference used by tests and by the single-device path."""
    B, T, H, D = q.shape
    if scale is None:
        scale = D ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)) * scale
    if causal:
        mask = jnp.tril(jnp.ones((T, T), dtype=bool))
        s = jnp.where(mask[None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return o.astype(q.dtype)
