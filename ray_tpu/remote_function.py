"""RemoteFunction: the @ray_tpu.remote wrapper for functions.

(reference: python/ray/remote_function.py:41 — options plumbing mirrors
_remote at remote_function.py:313.)
"""

from __future__ import annotations

import functools
from typing import Any

from ray_tpu._private import serialization as ser

_UNSET = object()


def _build_resources(num_cpus, num_tpus, resources) -> dict:
    out = {"CPU": 1.0 if num_cpus is None else float(num_cpus)}
    if num_tpus:
        from ray_tpu._private.accelerators import validate_num_tpus

        validate_num_tpus(num_tpus)
        out["TPU"] = float(num_tpus)
    if resources:
        out.update({k: float(v) for k, v in resources.items()})
    if out.get("CPU") == 0.0:
        out.pop("CPU")
    return out


class RemoteFunction:
    def __init__(self, func, *, num_cpus=None, num_tpus=None, resources=None,
                 num_returns=1, max_retries=None, scheduling_strategy=None,
                 runtime_env=None):
        self._func = func
        self._num_returns = num_returns
        if max_retries is None:
            from ray_tpu._private.ray_config import RayConfig

            max_retries = RayConfig.get("default_max_retries")
        self._max_retries = max_retries
        self._opts = {"num_cpus": num_cpus, "num_tpus": num_tpus, "resources": resources}
        self._resources = _build_resources(num_cpus, num_tpus, resources)
        self._strategy = scheduling_strategy
        self._runtime_env = runtime_env
        self._blob: bytes | None = None
        self._blob_sha: str | None = None
        functools.update_wrapper(self, func)

    def _get_blob(self) -> bytes:
        if self._blob is None:
            import hashlib

            blob = ser.dumps(self._func)
            # sha assigned BEFORE _blob: a racing reader seeing _blob set is
            # then guaranteed to see the sha too
            self._blob_sha = hashlib.sha1(blob).hexdigest()[:20]
            self._blob = blob
        return self._blob

    def options(self, *, num_cpus=None, num_tpus=None, resources=None,
                num_returns=None, max_retries=None, scheduling_strategy=_UNSET,
                runtime_env=_UNSET, **_ignored) -> "RemoteFunction":
        rf = RemoteFunction(
            self._func,
            num_cpus=self._opts["num_cpus"] if num_cpus is None else num_cpus,
            num_tpus=self._opts["num_tpus"] if num_tpus is None else num_tpus,
            resources=self._opts["resources"] if resources is None else resources,
            num_returns=self._num_returns if num_returns is None else num_returns,
            max_retries=self._max_retries if max_retries is None else max_retries,
            scheduling_strategy=(self._strategy if scheduling_strategy is _UNSET
                                 else scheduling_strategy),
            runtime_env=(self._runtime_env if runtime_env is _UNSET
                         else runtime_env),
        )
        rf._blob = self._blob
        rf._blob_sha = self._blob_sha
        return rf

    def remote(self, *args, **kwargs):
        from ray_tpu._private.api import _get_worker
        from ray_tpu.util.scheduling_strategies import strategy_to_spec

        worker = _get_worker()
        refs = worker.submit_task(
            self._get_blob() if worker.kind != "local" else self._func,
            args,
            kwargs,
            func_sha=self._blob_sha,
            num_returns=self._num_returns,
            resources=self._resources,
            max_retries=self._max_retries,
            name=getattr(self._func, "__name__", "task"),
            strategy=strategy_to_spec(self._strategy),
            runtime_env=self._runtime_env,
        )
        if self._num_returns == "streaming":
            return refs  # an ObjectRefGenerator (reference: _raylet.pyx:299)
        return refs[0] if self._num_returns == 1 else refs

    def __call__(self, *args, **kwargs):
        raise TypeError(
            "Remote functions cannot be called directly; use .remote() "
            "(or access the original function via .func)."
        )

    @property
    def func(self):
        return self._func
