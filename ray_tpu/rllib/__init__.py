"""ray_tpu.rllib — reinforcement learning.

(reference: rllib/ — Algorithm/AlgorithmConfig, EnvRunnerGroup rollout
actors, Learner SGD; PPO first (rllib/algorithms/ppo/). The learner update
is a jitted XLA program that scales by mesh sharding instead of torch DDP.)
"""

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.algorithms.appo import APPO, APPOConfig
from ray_tpu.rllib.algorithms.bc import BC, BCConfig
from ray_tpu.rllib.algorithms.cql import CQL, CQLConfig
from ray_tpu.rllib.algorithms.dqn import DQN, DQNConfig
from ray_tpu.rllib.algorithms.dreamerv3 import DreamerV3, DreamerV3Config
from ray_tpu.rllib.algorithms.impala import IMPALA, IMPALAConfig
from ray_tpu.rllib.algorithms.iql import IQL, IQLConfig
from ray_tpu.rllib.algorithms.marwil import MARWIL, MARWILConfig
from ray_tpu.rllib.algorithms.ppo import PPO, PPOConfig
from ray_tpu.rllib.algorithms.sac import SAC, SACConfig
from ray_tpu.rllib.replay import ReplayBuffer
from ray_tpu.rllib.env import (CartPoleVecEnv, PendulumVecEnv, VectorEnv,
                               make_vec_env)
from ray_tpu.rllib.env_runner import EnvRunner, EnvRunnerGroup
from ray_tpu.rllib.learner import Learner, compute_gae
from ray_tpu.rllib.multi_agent_env import (CoordinationGameVecEnv,
                                           MultiAgentCartPoleVecEnv,
                                           MultiAgentVecEnv,
                                           make_multi_agent_env)
from ray_tpu.rllib.multi_agent_runner import (MultiAgentEnvRunner,
                                              MultiAgentEnvRunnerGroup)
from ray_tpu.rllib.multi_rl_module import (MultiRLModuleSpec, RLModuleSpec,
                                           init_multi)

__all__ = [
    "Algorithm",
    "AlgorithmConfig",
    "APPO",
    "APPOConfig",
    "BC",
    "BCConfig",
    "CQL",
    "CQLConfig",
    "DQN",
    "DQNConfig",
    "DreamerV3",
    "DreamerV3Config",
    "IMPALA",
    "IMPALAConfig",
    "IQL",
    "IQLConfig",
    "MARWIL",
    "MARWILConfig",
    "ReplayBuffer",
    "CartPoleVecEnv",
    "PendulumVecEnv",
    "EnvRunner",
    "EnvRunnerGroup",
    "Learner",
    "PPO",
    "PPOConfig",
    "SAC",
    "SACConfig",
    "VectorEnv",
    "CoordinationGameVecEnv",
    "MultiAgentCartPoleVecEnv",
    "MultiAgentVecEnv",
    "MultiAgentEnvRunner",
    "MultiAgentEnvRunnerGroup",
    "MultiRLModuleSpec",
    "RLModuleSpec",
    "compute_gae",
    "init_multi",
    "make_multi_agent_env",
    "make_vec_env",
]
