"""AlgorithmConfig builder + Algorithm base.

(reference: rllib/algorithms/algorithm_config.py — the fluent
.environment()/.env_runners()/.training() builder; algorithm.py:213
Algorithm with train() → result dict and save/restore via Checkpointable.)
"""

from __future__ import annotations

import os
from typing import Any

import numpy as np


class AlgorithmConfig:
    def __init__(self):
        self.env_id: Any = "CartPole-v1"
        self.num_env_runners = 2
        self.num_envs_per_runner = 8
        self.rollout_fragment_length = 64
        self.lr = 3e-4
        self.gamma = 0.99
        self.lam = 0.95
        self.minibatch_size = 256
        self.num_epochs = 4
        self.clip_param = 0.2
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.01
        self.model_hidden = (64, 64)
        self.seed = 0
        self.env_config: dict = {}
        # multi-agent (reference: algorithm_config.py multi_agent() —
        # policies + policy_mapping_fn switch the whole stack to the
        # MultiAgentEnvRunner / per-policy learner path)
        self.policies: dict | None = None
        self.policy_mapping_fn = None

    def environment(self, env=None, *, env_config=None,
                    **_ignored) -> "AlgorithmConfig":
        if env is not None:
            self.env_id = env
        if env_config is not None:
            self.env_config = dict(env_config)
        return self

    def multi_agent(self, *, policies=None, policy_mapping_fn=None,
                    **_ignored) -> "AlgorithmConfig":
        """(reference: algorithm_config.py:multi_agent — `policies` names
        the module ids (dict id -> RLModuleSpec-or-None, or an iterable of
        ids with specs inferred from the env), `policy_mapping_fn`
        (agent_id) -> policy_id decides which module serves which agent.)"""
        if policies is not None:
            if isinstance(policies, dict):
                self.policies = dict(policies)
            else:
                self.policies = {p: None for p in policies}
        if policy_mapping_fn is not None:
            self.policy_mapping_fn = policy_mapping_fn
        return self

    def env_runners(self, *, num_env_runners=None, num_envs_per_env_runner=None,
                    rollout_fragment_length=None, **_ignored) -> "AlgorithmConfig":
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if num_envs_per_env_runner is not None:
            self.num_envs_per_runner = num_envs_per_env_runner
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, *, lr=None, gamma=None, lambda_=None, minibatch_size=None,
                 num_epochs=None, clip_param=None, vf_loss_coeff=None,
                 entropy_coeff=None, model=None, **_ignored) -> "AlgorithmConfig":
        for name, v in (("lr", lr), ("gamma", gamma), ("lam", lambda_),
                        ("minibatch_size", minibatch_size),
                        ("num_epochs", num_epochs), ("clip_param", clip_param),
                        ("vf_loss_coeff", vf_loss_coeff),
                        ("entropy_coeff", entropy_coeff)):
            if v is not None:
                setattr(self, name, v)
        if model and "fcnet_hiddens" in model:
            self.model_hidden = tuple(model["fcnet_hiddens"])
        return self

    def debugging(self, *, seed=None, **_ignored) -> "AlgorithmConfig":
        if seed is not None:
            self.seed = seed
        return self

    def build(self) -> "Algorithm":
        return self.algo_class(self)


class Algorithm:
    """(reference: rllib/algorithms/algorithm.py:213 — iteration =
    training_step(); results carry env_runners/learner metric trees.)"""

    def __init__(self, config: AlgorithmConfig):
        self.config = config
        self.iteration = 0
        self._episode_returns: list[float] = []
        self.rng = np.random.default_rng(config.seed)
        self._setup()

    def _setup(self):
        raise NotImplementedError

    def training_step(self) -> dict:
        raise NotImplementedError

    def train(self) -> dict:
        self.iteration += 1
        metrics = self.training_step()
        recent = self._episode_returns[-100:]
        out = {
            "training_iteration": self.iteration,
            "env_runners": {
                "episode_return_mean": float(np.mean(recent)) if recent else float("nan"),
                "num_episodes": len(self._episode_returns),
            },
            "learners": metrics,
        }
        # multi-agent: per-agent return means alongside the aggregate
        # (reference: result dicts carry env_runners/module_... subtrees)
        agent_returns = getattr(self, "_agent_episode_returns", None)
        if agent_returns:
            out["env_runners"]["agent_episode_returns"] = {
                a: float(np.mean(v[-100:])) if v else float("nan")
                for a, v in agent_returns.items()
            }
        return out

    def save(self, path: str) -> str:
        from ray_tpu.llm import checkpoint_io

        os.makedirs(path, exist_ok=True)
        learners = getattr(self, "learners", None)
        if learners is not None:  # multi-agent: one subdir per module id
            for mid, lrn in learners.items():
                checkpoint_io.save_params(lrn.params,
                                          os.path.join(path, "module", mid))
        else:
            checkpoint_io.save_params(self.learner.params,
                                      os.path.join(path, "module"))
        return path

    def restore(self, path: str) -> None:
        import jax

        from ray_tpu.llm import checkpoint_io

        def _merge(old, new):
            return jax.tree.map(
                lambda o, n: n.astype(o.dtype) if hasattr(o, "dtype") else n,
                old, new)

        learners = getattr(self, "learners", None)
        if learners is not None:
            for mid, lrn in learners.items():
                loaded = checkpoint_io.load_params(
                    os.path.join(path, "module", mid))
                lrn.params = _merge(lrn.params, loaded)
        else:
            loaded = checkpoint_io.load_params(os.path.join(path, "module"))
            self.learner.params = _merge(self.learner.params, loaded)

    def stop(self):
        if hasattr(self, "runner_group"):
            self.runner_group.shutdown()
