from ray_tpu.rllib.algorithms.impala import IMPALA, IMPALAConfig
from ray_tpu.rllib.algorithms.ppo import PPO, PPOConfig

__all__ = ["IMPALA", "IMPALAConfig", "PPO", "PPOConfig"]
