from ray_tpu.rllib.algorithms.ppo import PPO, PPOConfig

__all__ = ["PPO", "PPOConfig"]
