"""APPO: asynchronous PPO — IMPALA's decoupled actor-learner machinery with
a clipped surrogate objective and a periodically-refreshed target policy.

(reference: rllib/algorithms/appo/ — APPO = IMPALA architecture + PPO
surrogate; the target network anchors the update so stale rollouts can't
blow it up, and V-trace still corrects the off-policy value targets. The
learner subclasses ImpalaLearner and overrides ONLY the loss + the
target-refresh hook; runners, streams, restart-on-death and async weight
pushes are inherited unchanged.)
"""

from __future__ import annotations

from ray_tpu.rllib.algorithms.impala import IMPALA, IMPALAConfig, ImpalaLearner


class APPOConfig(IMPALAConfig):
    algo_class = None  # set below

    def __init__(self):
        super().__init__()
        self.clip_param = 0.2
        self.kl_coeff = 0.1
        self.target_update_frequency = 4  # learner updates between refreshes

    def training(self, *, kl_coeff=None,
                 target_update_frequency=None, **kwargs) -> "APPOConfig":
        super().training(**kwargs)  # clip_param rides the base handler
        for name, val in (("kl_coeff", kl_coeff),
                          ("target_update_frequency", target_update_frequency)):
            if val is not None:
                setattr(self, name, val)
        return self


class AppoLearner(ImpalaLearner):
    """V-trace advantages + PPO clipped surrogate + target-policy KL."""

    def __init__(self, *args, clip_param: float = 0.2, kl_coeff: float = 0.1,
                 target_update_frequency: int = 4, **kwargs):
        # loss hyperparams must exist before super().__init__ jits _loss
        self.clip_param = clip_param
        self.kl_coeff = kl_coeff
        self.target_update_frequency = max(1, int(target_update_frequency))
        super().__init__(*args, **kwargs)

    def _loss(self, p, target_params, batch):
        import jax
        import jax.numpy as jnp

        target_logp, logp_all, values, vs, pg_adv = self._policy_terms(
            p, batch)
        adv = (pg_adv - pg_adv.mean()) / (pg_adv.std() + 1e-8)
        # clipped surrogate on the behavior-policy ratio
        ratio = jnp.exp(target_logp - batch["behavior_logp"])
        surr = jnp.minimum(
            ratio * adv,
            jnp.clip(ratio, 1 - self.clip_param, 1 + self.clip_param) * adv)
        pg_loss = -jnp.mean(surr)
        vf_loss = 0.5 * jnp.mean((vs - values) ** 2)
        ent = -jnp.mean(jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
        # KL(target || current) anchors the update across async staleness
        # (reference: appo's lagging target network, not last-iter weights,
        # because rollouts arrive at arbitrary lag)
        T, N = batch["rewards"].shape
        obs = batch["obs"].reshape(T * N, -1)
        t_logits, _ = self._rl.forward(target_params, obs)
        t_logp_all = jax.nn.log_softmax(t_logits)
        kl = jnp.mean(jnp.sum(
            jnp.exp(t_logp_all) * (t_logp_all - logp_all), axis=-1))
        loss = (pg_loss + self.vf_coef * vf_loss - self.ent_coef * ent
                + self.kl_coeff * kl)
        return loss, {"pg_loss": pg_loss, "vf_loss": vf_loss, "entropy": ent,
                      "kl": kl}

    def _post_update(self):
        if self.version % self.target_update_frequency == 0:
            self.target_params = self.params


class APPO(IMPALA):
    """IMPALA's runner/stream/restart machinery with the APPO learner."""

    def _setup(self):
        cfg = self.config
        from ray_tpu.rllib.env import make_vec_env

        probe = make_vec_env(cfg.env_id, 1, cfg.seed)
        self.learner = AppoLearner(
            probe.obs_dim, probe.num_actions, lr=cfg.lr,
            hidden=cfg.model_hidden, vf_coef=cfg.vf_loss_coeff,
            ent_coef=cfg.entropy_coeff, gamma=cfg.gamma,
            rho_bar=getattr(cfg, "rho_bar", 1.0),
            c_bar=getattr(cfg, "c_bar", 1.0),
            clip_param=cfg.clip_param, kl_coeff=cfg.kl_coeff,
            target_update_frequency=cfg.target_update_frequency,
            seed=cfg.seed)
        self._streams = []
        self._runners = []
        for i in range(cfg.num_env_runners):
            self._start_runner(i)


APPOConfig.algo_class = APPO
