"""BC — behavior cloning: offline RL on a ray_tpu.data Dataset.

(reference: rllib/algorithms/bc/ + the offline-RL pipeline on Ray Data,
rllib/offline/ — trains a policy by supervised imitation of logged
(obs, action) pairs streamed from a dataset.)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib import rl_module
from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig


class BCConfig(AlgorithmConfig):
    algo_class = None  # set below

    def __init__(self):
        super().__init__()
        self.offline_data = None       # ray_tpu.data Dataset | list[dict]
        self.obs_dim = None            # required (no env probe offline)
        self.num_actions = None
        self.train_batch_size = 256

    def offline(self, *, offline_data=None, obs_dim=None, num_actions=None,
                train_batch_size=None, **_ignored) -> "BCConfig":
        if offline_data is not None:
            self.offline_data = offline_data
        if obs_dim is not None:
            self.obs_dim = obs_dim
        if num_actions is not None:
            self.num_actions = num_actions
        if train_batch_size is not None:
            self.train_batch_size = train_batch_size
        return self


def make_bc_update(optimizer):
    @jax.jit
    def update(params, opt_state, batch):
        def loss_fn(p):
            logits, _ = rl_module.forward(p, batch["obs"])
            logp = jax.nn.log_softmax(logits)
            nll = -jnp.take_along_axis(logp, batch["actions"][:, None],
                                       axis=1)[:, 0]
            loss = jnp.mean(nll)
            acc = jnp.mean((jnp.argmax(logits, axis=-1)
                            == batch["actions"]).astype(jnp.float32))
            return loss, {"imitation_accuracy": acc}

        (loss, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        metrics["total_loss"] = loss
        return params, opt_state, metrics

    return update


class BC(Algorithm):
    def _setup(self):
        cfg = self.config
        if cfg.offline_data is None or cfg.obs_dim is None or cfg.num_actions is None:
            raise ValueError(
                "BC needs .offline(offline_data=..., obs_dim=..., "
                "num_actions=...)")
        self.params = rl_module.init(jax.random.PRNGKey(cfg.seed),
                                     cfg.obs_dim, cfg.num_actions,
                                     cfg.model_hidden)
        self.optimizer = optax.adam(cfg.lr)
        self.opt_state = self.optimizer.init(self.params)
        self._update = make_bc_update(self.optimizer)

    def _batches(self):
        """Stream (obs, actions) batches from the configured source: a
        ray_tpu.data Dataset of {'obs': ..., 'action': ...} rows, or an
        in-memory list of such dicts."""
        cfg = self.config
        data = cfg.offline_data
        bs = cfg.train_batch_size
        rows_iter = (data.iter_rows() if hasattr(data, "iter_rows")
                     else iter(data))
        obs, acts = [], []
        for row in rows_iter:
            obs.append(np.asarray(row["obs"], np.float32))
            acts.append(int(row["action"]))
            if len(obs) >= bs:
                yield {"obs": jnp.asarray(np.stack(obs)),
                       "actions": jnp.asarray(np.asarray(acts, np.int32))}
                obs, acts = [], []
        if obs:
            yield {"obs": jnp.asarray(np.stack(obs)),
                   "actions": jnp.asarray(np.asarray(acts, np.int32))}

    def training_step(self) -> dict:
        metrics: dict = {}
        n = 0
        for batch in self._batches():
            self.params, self.opt_state, m = self._update(
                self.params, self.opt_state, batch)
            n += int(batch["actions"].shape[0])
            metrics = {k: float(v) for k, v in m.items()}
        metrics["num_samples_trained"] = n
        return metrics

    def predict(self, obs) -> np.ndarray:
        return np.asarray(rl_module.forward_inference(
            self.params, jnp.asarray(obs, jnp.float32)))


BCConfig.algo_class = BC
