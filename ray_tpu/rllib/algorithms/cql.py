"""CQL — conservative Q-learning for offline continuous control.

(reference: rllib/algorithms/cql/ — CQLConfig/CQL layers the conservative
regularizer of Kumar et al. 2020 on top of the SAC losses: in addition to
the soft Bellman backup, each critic is penalized by
``logsumexp_a Q(s,a) - Q(s, a_data)`` so Q-values on out-of-distribution
actions are pushed DOWN, which is what keeps a policy trained purely from
a static dataset from exploiting Q-function extrapolation errors. The
logsumexp is estimated from uniform-random and current-policy action
samples with importance correction, as in the paper's CQL(H) variant.)

Reuses the SAC networks/optimizers (sac.py); there are no env runners —
the data source is a static dataset of {obs, action, reward, next_obs,
done} transitions.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.algorithms.sac import (actor_mean, actor_sample,
                                          init_sac_params, q_value)


class CQLConfig(AlgorithmConfig):
    algo_class = None  # set below

    def __init__(self):
        super().__init__()
        self.offline_data = None
        self.obs_dim = None
        self.action_dim = None
        self.action_scale = 1.0
        self.train_batch_size = 256
        self.num_updates_per_step = 200
        self.tau = 0.005
        self.initial_alpha = 0.1
        self.autotune_alpha = True
        self.target_entropy = None
        self.cql_alpha = 1.0           # weight of the conservative penalty
        self.num_cql_actions = 8       # sampled actions per logsumexp term

    def offline(self, *, offline_data=None, obs_dim=None, action_dim=None,
                action_scale=None, train_batch_size=None,
                num_updates_per_step=None, cql_alpha=None,
                num_cql_actions=None, initial_alpha=None, tau=None,
                **_ignored) -> "CQLConfig":
        for name, val in (("offline_data", offline_data),
                          ("obs_dim", obs_dim), ("action_dim", action_dim),
                          ("action_scale", action_scale),
                          ("train_batch_size", train_batch_size),
                          ("num_updates_per_step", num_updates_per_step),
                          ("cql_alpha", cql_alpha),
                          ("num_cql_actions", num_cql_actions),
                          ("initial_alpha", initial_alpha), ("tau", tau)):
            if val is not None:
                setattr(self, name, val)
        return self


def make_cql_update(actor_opt, q_opt, alpha_opt, *, gamma: float, tau: float,
                    action_scale: float, target_entropy: float,
                    autotune: bool, cql_alpha: float, n_actions: int):
    def _conservative_penalty(q_params, params, batch, key):
        """CQL(H): logsumexp over sampled actions minus the data action's Q,
        per critic. Uniform samples are importance-corrected by the uniform
        density; policy samples by their log-prob."""
        B = batch["obs"].shape[0]
        ku, kp, kn = jax.random.split(key, 3)
        unif = jax.random.uniform(
            ku, (n_actions, B, batch["actions"].shape[-1]),
            minval=-action_scale, maxval=action_scale)
        log_unif_density = -jnp.log(2.0 * action_scale) * unif.shape[-1]

        def stacked_q(qp, acts, obs):
            return jax.vmap(lambda a: q_value(qp, obs, a))(acts)  # [n, B]

        pi_cur, logp_cur = jax.vmap(
            lambda k: actor_sample(params["actor"], batch["obs"], k,
                                   action_scale))(jax.random.split(kp, n_actions))
        pi_nxt, logp_nxt = jax.vmap(
            lambda k: actor_sample(params["actor"], batch["next_obs"], k,
                                   action_scale))(jax.random.split(kn, n_actions))
        # actor_sample's logp is the density of tanh(u) on [-1,1]^d; the
        # action it returns lives on [-scale, scale]^d — add the
        # change-of-variables term so policy rows are commensurate with the
        # uniform rows in the logsumexp
        d = batch["actions"].shape[-1]
        scale_corr = d * jnp.log(action_scale)
        pi_cur = jax.lax.stop_gradient(pi_cur)
        pi_nxt = jax.lax.stop_gradient(pi_nxt)
        logp_cur = jax.lax.stop_gradient(logp_cur) - scale_corr
        logp_nxt = jax.lax.stop_gradient(logp_nxt) - scale_corr

        pen = 0.0
        for name in ("q1", "q2"):
            qp = q_params[name]
            cat = jnp.concatenate([
                stacked_q(qp, unif, batch["obs"]) - log_unif_density,
                stacked_q(qp, pi_cur, batch["obs"]) - logp_cur,
                stacked_q(qp, pi_nxt, batch["obs"]) - logp_nxt,
            ], axis=0)                                         # [3n, B]
            lse = jax.scipy.special.logsumexp(cat, axis=0) - jnp.log(3 * n_actions)
            q_data = q_value(qp, batch["obs"], batch["actions"])
            pen = pen + jnp.mean(lse - q_data)
        return pen

    @jax.jit
    def update(params, target_q, opt_states, batch, key):
        k1, k2, k3 = jax.random.split(key, 3)

        def q_loss_fn(q_params):
            a_next, logp_next = actor_sample(params["actor"],
                                             batch["next_obs"], k1,
                                             action_scale)
            tq1 = q_value(target_q["q1"], batch["next_obs"], a_next)
            tq2 = q_value(target_q["q2"], batch["next_obs"], a_next)
            alpha = jnp.exp(params["log_alpha"])
            soft_q = jnp.minimum(tq1, tq2) - alpha * logp_next
            nonterminal = 1.0 - batch["dones"].astype(jnp.float32)
            target = jax.lax.stop_gradient(
                batch["rewards"] + gamma * nonterminal * soft_q)
            q1 = q_value(q_params["q1"], batch["obs"], batch["actions"])
            q2 = q_value(q_params["q2"], batch["obs"], batch["actions"])
            bellman = jnp.mean((q1 - target) ** 2) + jnp.mean((q2 - target) ** 2)
            penalty = _conservative_penalty(q_params, params, batch, k3)
            return bellman + cql_alpha * penalty, (jnp.mean(q1), penalty)

        q_params = {"q1": params["q1"], "q2": params["q2"]}
        (q_loss, (q_mean, penalty)), q_grads = jax.value_and_grad(
            q_loss_fn, has_aux=True)(q_params)
        q_updates, q_state = q_opt.update(q_grads, opt_states["q"], q_params)
        q_params = optax.apply_updates(q_params, q_updates)

        def pi_loss_fn(actor_params):
            a, logp = actor_sample(actor_params, batch["obs"], k2,
                                   action_scale)
            q1 = q_value(q_params["q1"], batch["obs"], a)
            q2 = q_value(q_params["q2"], batch["obs"], a)
            alpha = jax.lax.stop_gradient(jnp.exp(params["log_alpha"]))
            return jnp.mean(alpha * logp - jnp.minimum(q1, q2)), logp

        (pi_loss, logp), pi_grads = jax.value_and_grad(
            pi_loss_fn, has_aux=True)(params["actor"])
        pi_updates, pi_state = actor_opt.update(pi_grads, opt_states["actor"],
                                                params["actor"])
        actor_params = optax.apply_updates(params["actor"], pi_updates)

        def alpha_loss_fn(log_alpha):
            return -jnp.mean(jnp.exp(log_alpha)
                             * jax.lax.stop_gradient(logp + target_entropy))

        if autotune:
            a_loss, a_grad = jax.value_and_grad(alpha_loss_fn)(
                params["log_alpha"])
            a_updates, a_state = alpha_opt.update(
                a_grad, opt_states["alpha"], params["log_alpha"])
            log_alpha = optax.apply_updates(params["log_alpha"], a_updates)
        else:
            a_loss = jnp.float32(0)
            a_state = opt_states["alpha"]
            log_alpha = params["log_alpha"]

        new_params = {"actor": actor_params, "q1": q_params["q1"],
                      "q2": q_params["q2"], "log_alpha": log_alpha}
        new_target = jax.tree.map(lambda t, o: (1 - tau) * t + tau * o,
                                  target_q, q_params)
        metrics = {"q_loss": q_loss, "pi_loss": pi_loss, "alpha_loss": a_loss,
                   "cql_penalty": penalty, "alpha": jnp.exp(log_alpha),
                   "q_mean": q_mean, "entropy": -jnp.mean(logp)}
        return (new_params, new_target,
                {"q": q_state, "actor": pi_state, "alpha": a_state}, metrics)

    return update


def load_transitions(offline_data) -> dict:
    """Materialize a transition dataset ({obs, action, reward, next_obs,
    done} rows) into stacked float32 numpy arrays."""
    rows_iter = (offline_data.iter_rows()
                 if hasattr(offline_data, "iter_rows") else iter(offline_data))
    obs, acts, rews, nxt, dones = [], [], [], [], []
    for row in rows_iter:
        obs.append(np.asarray(row["obs"], np.float32))
        acts.append(np.asarray(row["action"], np.float32).reshape(-1))
        rews.append(float(row["reward"]))
        nxt.append(np.asarray(row["next_obs"], np.float32))
        dones.append(bool(row.get("done", False)))
    return {"obs": np.stack(obs), "actions": np.stack(acts),
            "rewards": np.asarray(rews, np.float32),
            "next_obs": np.stack(nxt), "dones": np.asarray(dones, bool)}


class CQL(Algorithm):
    def _setup(self):
        cfg = self.config
        if cfg.offline_data is None or cfg.obs_dim is None or cfg.action_dim is None:
            raise ValueError(
                "CQL needs .offline(offline_data=..., obs_dim=..., "
                "action_dim=...)")
        self._data = load_transitions(cfg.offline_data)
        target_entropy = (cfg.target_entropy if cfg.target_entropy is not None
                          else -float(cfg.action_dim))
        self.params = init_sac_params(
            jax.random.PRNGKey(cfg.seed), cfg.obs_dim, cfg.action_dim,
            hidden=cfg.model_hidden, initial_alpha=cfg.initial_alpha)
        self.target_q = {"q1": self.params["q1"], "q2": self.params["q2"]}
        self.actor_opt = optax.adam(cfg.lr)
        self.q_opt = optax.adam(cfg.lr)
        self.alpha_opt = optax.adam(cfg.lr)
        self.opt_states = {
            "actor": self.actor_opt.init(self.params["actor"]),
            "q": self.q_opt.init({"q1": self.params["q1"],
                                  "q2": self.params["q2"]}),
            "alpha": self.alpha_opt.init(self.params["log_alpha"]),
        }
        self._update = make_cql_update(
            self.actor_opt, self.q_opt, self.alpha_opt, gamma=cfg.gamma,
            tau=cfg.tau, action_scale=cfg.action_scale,
            target_entropy=target_entropy, autotune=cfg.autotune_alpha,
            cql_alpha=cfg.cql_alpha, n_actions=cfg.num_cql_actions)
        self.key = jax.random.PRNGKey(cfg.seed + 7)
        self._rng = np.random.default_rng(cfg.seed)
        self._num_updates = 0

    def training_step(self) -> dict:
        cfg = self.config
        n = len(self._data["rewards"])
        m: dict = {}
        for _ in range(cfg.num_updates_per_step):
            sel = self._rng.integers(0, n, cfg.train_batch_size)
            batch = {k: jnp.asarray(v[sel]) for k, v in self._data.items()}
            self.key, sub = jax.random.split(self.key)
            self.params, self.target_q, self.opt_states, m = self._update(
                self.params, self.target_q, self.opt_states, batch, sub)
            self._num_updates += 1
        out = {k: float(v) for k, v in m.items()}
        out["num_updates"] = self._num_updates
        return out

    def compute_single_action(self, obs) -> np.ndarray:
        return np.asarray(actor_mean(self.params["actor"],
                                     jnp.asarray(obs)[None],
                                     self.config.action_scale))[0]


CQLConfig.algo_class = CQL
