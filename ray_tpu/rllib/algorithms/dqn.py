"""DQN: double Q-learning with a target network and replay buffer.

(reference: rllib/algorithms/dqn/ — DQNConfig/DQN with replay + target-net
sync + double-Q; Rainbow extensions out of scope. The TD update is one
jitted XLA program; rollout exploration is epsilon-greedy on the runners.)
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib import rl_module
from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.env import make_vec_env
from ray_tpu.rllib.env_runner import EnvRunnerGroup
from ray_tpu.rllib.replay import ReplayBuffer


class DQNConfig(AlgorithmConfig):
    algo_class = None  # set below

    def __init__(self):
        super().__init__()
        self.buffer_size = 50_000
        self.train_batch_size = 64
        self.target_update_freq = 200     # updates between target syncs
        self.num_updates_per_step = 8
        self.learning_starts = 500        # min transitions before updates
        self.epsilon_initial = 1.0
        self.epsilon_final = 0.05
        self.epsilon_decay_steps = 5_000  # env steps to anneal over
        self.double_q = True

    def training(self, *, buffer_size=None, train_batch_size=None,
                 target_update_freq=None, num_updates_per_step=None,
                 learning_starts=None, epsilon_initial=None,
                 epsilon_final=None, epsilon_decay_steps=None,
                 double_q=None, **kwargs) -> "DQNConfig":
        super().training(**kwargs)
        for name, val in (("buffer_size", buffer_size),
                          ("train_batch_size", train_batch_size),
                          ("target_update_freq", target_update_freq),
                          ("num_updates_per_step", num_updates_per_step),
                          ("learning_starts", learning_starts),
                          ("epsilon_initial", epsilon_initial),
                          ("epsilon_final", epsilon_final),
                          ("epsilon_decay_steps", epsilon_decay_steps),
                          ("double_q", double_q)):
            if val is not None:
                setattr(self, name, val)
        return self


def make_dqn_update(optimizer, *, gamma: float, double_q: bool):
    @jax.jit
    def update(params, target_params, opt_state, batch):
        def loss_fn(p):
            q_all, _ = rl_module.forward(p, batch["obs"])      # [B, A]
            q = jnp.take_along_axis(q_all, batch["actions"][:, None],
                                    axis=1)[:, 0]
            qt_all, _ = rl_module.forward(target_params, batch["next_obs"])
            if double_q:
                qo_all, _ = rl_module.forward(p, batch["next_obs"])
                a_star = jnp.argmax(qo_all, axis=-1)
                q_next = jnp.take_along_axis(qt_all, a_star[:, None],
                                             axis=1)[:, 0]
            else:
                q_next = jnp.max(qt_all, axis=-1)
            q_next = jax.lax.stop_gradient(q_next)
            nonterminal = 1.0 - batch["dones"].astype(jnp.float32)
            target = batch["rewards"] + gamma * nonterminal * q_next
            td = q - target
            loss = jnp.mean(optax.huber_loss(td))
            return loss, {"td_error_mean": jnp.mean(jnp.abs(td)),
                          "q_mean": jnp.mean(q)}

        (loss, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        metrics["total_loss"] = loss
        return params, opt_state, metrics

    return update


class DQN(Algorithm):
    def _setup(self):
        cfg = self.config
        probe = make_vec_env(cfg.env_id, 1, cfg.seed)
        self.obs_dim, self.num_actions = probe.obs_dim, probe.num_actions
        self.params = rl_module.init(jax.random.PRNGKey(cfg.seed),
                                     self.obs_dim, self.num_actions,
                                     cfg.model_hidden)
        self.target_params = self.params
        self.optimizer = optax.adam(cfg.lr)
        self.opt_state = self.optimizer.init(self.params)
        self._update = make_dqn_update(self.optimizer, gamma=cfg.gamma,
                                       double_q=cfg.double_q)
        self.buffer = ReplayBuffer(cfg.buffer_size, self.obs_dim,
                                   seed=cfg.seed)
        self.runner_group = EnvRunnerGroup(
            cfg.env_id, num_runners=cfg.num_env_runners,
            num_envs_per_runner=cfg.num_envs_per_runner, seed=cfg.seed)
        self._env_steps = 0
        self._num_updates = 0

    def _epsilon(self) -> float:
        cfg = self.config
        frac = min(1.0, self._env_steps / max(1, cfg.epsilon_decay_steps))
        return cfg.epsilon_initial + frac * (cfg.epsilon_final
                                             - cfg.epsilon_initial)

    def training_step(self) -> dict:
        cfg = self.config
        from ray_tpu._private import serialization as ser

        blob = ser.dumps(jax.device_get(self.params))
        samples = self.runner_group.sample_epsilon_greedy(
            blob, cfg.rollout_fragment_length, self._epsilon())
        for s in samples:
            T, N = s["rewards"].shape
            self.buffer.add_batch(
                s["obs"].reshape(T * N, -1), s["actions"].reshape(T * N),
                s["rewards"].reshape(T * N),
                s["next_obs"].reshape(T * N, -1), s["dones"].reshape(T * N))
            self._env_steps += T * N
            self._episode_returns.extend(s["episode_returns"])
        metrics: dict = {"epsilon": self._epsilon(),
                         "buffer_size": len(self.buffer)}
        if len(self.buffer) < cfg.learning_starts:
            return metrics
        m: dict = {}
        for _ in range(cfg.num_updates_per_step):
            batch = {k: jnp.asarray(v)
                     for k, v in self.buffer.sample(cfg.train_batch_size).items()}
            self.params, self.opt_state, m = self._update(
                self.params, self.target_params, self.opt_state, batch)
            self._num_updates += 1
            if self._num_updates % cfg.target_update_freq == 0:
                self.target_params = self.params
        metrics.update({k: float(v) for k, v in m.items()})
        metrics["num_updates"] = self._num_updates
        return metrics


    def save(self, path: str) -> str:
        import os

        from ray_tpu.llm import checkpoint_io

        os.makedirs(path, exist_ok=True)
        checkpoint_io.save_params(self.params, os.path.join(path, "module"))
        return path

    def restore(self, path: str) -> None:
        import os

        from ray_tpu.llm import checkpoint_io

        loaded = checkpoint_io.load_params(os.path.join(path, "module"))
        self.params = jax.tree.map(
            lambda old, new: new.astype(old.dtype) if hasattr(old, "dtype") else new,
            self.params, loaded)
        self.target_params = self.params
        self.opt_state = self.optimizer.init(self.params)


DQNConfig.algo_class = DQN
