"""DreamerV3 — model-based RL: learn a latent world model, act in dreams.

(reference: rllib/algorithms/dreamerv3/ — DreamerV3Config/DreamerV3 per
Hafner et al. 2023. Three jointly-trained pieces:
  1. WORLD MODEL: an RSSM with a deterministic GRU path h_t and a
     categorical stochastic state z_t (straight-through gradients),
     trained on replayed sequences by reconstruction + reward + continue
     prediction and the two KL terms (dynamics vs representation) with
     free bits,
  2. CRITIC: regresses symlog lambda-returns computed over imagined
     rollouts, with a slow EMA target for bootstrapping,
  3. ACTOR: REINFORCE on imagined trajectories with advantages normalized
     by an EMA of the return percentile range, plus an entropy bonus.
The reference implementation is TF2; this one is a jitted JAX program —
the world-model update and the imagination phase are each a single XLA
program built from lax.scan over time, which is the TPU-native shape for
recurrent models.)

Scaled to the built-in vector envs (MLP encoder/decoder, small RSSM); the
architecture, loss structure, and training loop match the paper.

Alignment convention: the RSSM consumes the PREVIOUS action at every step
(training and acting identically; is_first masks it at episode starts).
rewards[t]/dones[t] are the outcome of the action taken at t, and the
lambda-return indexing matches that; the reward/continue heads therefore
predict outcome-at-t marginalized over the current action (exact for
state-determined rewards, a small bias otherwise — the auto-resetting
vector envs drop the terminal observation, which rules out the paper's
arrival-indexed storage).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax

import ray_tpu
from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.env import make_vec_env


def symlog(x):
    return jnp.sign(x) * jnp.log1p(jnp.abs(x))


def symexp(x):
    return jnp.sign(x) * (jnp.exp(jnp.abs(x)) - 1.0)


class DreamerV3Config(AlgorithmConfig):
    algo_class = None  # set below

    def __init__(self):
        super().__init__()
        self.model_hidden = (128,)
        self.deter_dim = 128           # GRU (deterministic) state
        self.stoch_classes = 8         # categorical classes per latent
        self.stoch_dims = 8            # number of categorical latents
        self.embed_dim = 64
        self.batch_size_B = 16         # sequences per world-model batch
        self.batch_length_T = 32       # timesteps per sequence
        self.horizon_H = 15            # imagination horizon
        self.buffer_size = 50_000
        self.num_updates_per_step = 8
        self.learning_starts = 1_000
        self.gae_lambda = 0.95
        self.entropy_scale = 3e-3
        self.critic_ema_decay = 0.98
        self.free_bits = 1.0
        self.kl_dyn_scale = 0.5
        self.kl_rep_scale = 0.1
        self.world_lr = 6e-4
        self.actor_lr = 3e-4
        self.critic_lr = 3e-4

    def training(self, *, batch_size_B=None, batch_length_T=None,
                 horizon_H=None, num_updates_per_step=None,
                 learning_starts=None, entropy_scale=None, world_lr=None,
                 actor_lr=None, critic_lr=None, **kwargs) -> "DreamerV3Config":
        super().training(**kwargs)
        for name, val in (("batch_size_B", batch_size_B),
                          ("batch_length_T", batch_length_T),
                          ("horizon_H", horizon_H),
                          ("num_updates_per_step", num_updates_per_step),
                          ("learning_starts", learning_starts),
                          ("entropy_scale", entropy_scale),
                          ("world_lr", world_lr), ("actor_lr", actor_lr),
                          ("critic_lr", critic_lr)):
            if val is not None:
                setattr(self, name, val)
        return self


# ------------------------------------------------------------------ modules


def _dense_init(key, sizes):
    params = {}
    keys = jax.random.split(key, len(sizes) - 1)
    for i in range(len(sizes) - 1):
        params[str(i)] = {
            "w": jax.random.normal(keys[i], (sizes[i], sizes[i + 1]))
            * jnp.sqrt(2.0 / sizes[i]),
            "b": jnp.zeros((sizes[i + 1],)),
        }
    return params


def _dense(params, x, act=jax.nn.silu, final_linear=True):
    n = len(params)
    for i in range(n):
        layer = params[str(i)]
        x = x @ layer["w"] + layer["b"]
        if i < n - 1 or not final_linear:
            x = act(x)
    return x


def _gru_init(key, in_dim: int, hidden: int) -> dict:
    k1, k2 = jax.random.split(key)
    scale = jnp.sqrt(1.0 / (in_dim + hidden))
    return {"wi": jax.random.normal(k1, (in_dim, 3 * hidden)) * scale,
            "wh": jax.random.normal(k2, (hidden, 3 * hidden)) * scale,
            "b": jnp.zeros((3 * hidden,))}


def _gru(params, x, h):
    gates = x @ params["wi"] + h @ params["wh"] + params["b"]
    r, u, c = jnp.split(gates, 3, axis=-1)
    r, u = jax.nn.sigmoid(r), jax.nn.sigmoid(u)
    cand = jnp.tanh(r * c)
    return u * h + (1.0 - u) * cand


def init_dreamer_params(key, obs_dim: int, num_actions: int,
                        cfg: DreamerV3Config) -> dict:
    S, C = cfg.stoch_dims, cfg.stoch_classes
    z_dim = S * C
    feat = cfg.deter_dim + z_dim
    ks = jax.random.split(key, 9)
    hid = cfg.model_hidden
    return {
        "encoder": _dense_init(ks[0], (obs_dim, *hid, cfg.embed_dim)),
        "gru": _gru_init(ks[1], z_dim + num_actions, cfg.deter_dim),
        "prior": _dense_init(ks[2], (cfg.deter_dim, *hid, z_dim)),
        "posterior": _dense_init(ks[3], (cfg.deter_dim + cfg.embed_dim,
                                         *hid, z_dim)),
        "decoder": _dense_init(ks[4], (feat, *hid, obs_dim)),
        "reward": _dense_init(ks[5], (feat, *hid, 1)),
        "continue": _dense_init(ks[6], (feat, *hid, 1)),
        "actor": _dense_init(ks[7], (feat, *hid, num_actions)),
        "critic": _dense_init(ks[8], (feat, *hid, 1)),
    }


def _sample_z(logits, key, S: int, C: int):
    """Straight-through categorical sample: one-hot forward, probs grad."""
    lg = logits.reshape(*logits.shape[:-1], S, C)
    # unimix (paper): 1% uniform smoothing keeps log-probs finite
    probs = 0.99 * jax.nn.softmax(lg) + 0.01 / C
    lg = jnp.log(probs)
    idx = jax.random.categorical(key, lg)
    onehot = jax.nn.one_hot(idx, C, dtype=lg.dtype)
    st = onehot + probs - jax.lax.stop_gradient(probs)
    return st.reshape(*logits.shape[:-1], S * C), lg


def _kl_cat(lg_p, lg_q):
    """KL(p || q) for stacked categorical logits [.., S, C], summed over S."""
    p = jnp.exp(lg_p)
    return jnp.sum(p * (lg_p - lg_q), axis=(-2, -1))


# ------------------------------------------------------------- world model


def make_world_model_update(opt, cfg: DreamerV3Config, num_actions: int):
    S, C = cfg.stoch_dims, cfg.stoch_classes

    def rssm_observe(params, obs_seq, act_seq, is_first, key):
        """Teacher-forced posterior roll: obs/act [T, B, .] → features,
        prior/posterior logits. is_first resets the recurrent state."""
        T, B = obs_seq.shape[:2]
        embed = _dense(params["encoder"], obs_seq)
        keys = jax.random.split(key, T)

        def step(carry, inp):
            h, z = carry
            e_t, a_t, first_t, k_t = inp
            mask = (1.0 - first_t)[:, None]
            h, z = h * mask, z * mask
            a_t = a_t * mask
            h = _gru(params["gru"], jnp.concatenate([z, a_t], -1), h)
            prior_lg = _dense(params["prior"], h)
            post_lg = _dense(params["posterior"],
                             jnp.concatenate([h, e_t], -1))
            z, post_lgn = _sample_z(post_lg, k_t, S, C)
            _, prior_lgn = _sample_z(prior_lg, k_t, S, C)
            return (h, z), (h, z, prior_lgn, post_lgn)

        h0 = jnp.zeros((B, cfg.deter_dim))
        z0 = jnp.zeros((B, S * C))
        (_, _), (hs, zs, prior_lg, post_lg) = jax.lax.scan(
            step, (h0, z0), (embed, act_seq, is_first, keys))
        feats = jnp.concatenate([hs, zs], -1)
        return feats, prior_lg, post_lg

    @jax.jit
    def update(wm_params, opt_state, batch, key):
        """wm_params: ONLY the world-model subtree (encoder/gru/prior/
        posterior/decoder/reward/continue) — the optimizer state is built
        over exactly this tree, and the loss touches nothing else."""

        def loss_fn(p):
            feats, prior_lg, post_lg = rssm_observe(
                p, batch["obs"], batch["actions_onehot"],
                batch["is_first"], key)
            recon = _dense(p["decoder"], feats)
            recon_loss = jnp.mean(jnp.sum(
                (recon - symlog(batch["obs"])) ** 2, -1))
            rew_pred = _dense(p["reward"], feats)[..., 0]
            rew_loss = jnp.mean((rew_pred - symlog(batch["rewards"])) ** 2)
            cont_logit = _dense(p["continue"], feats)[..., 0]
            cont = 1.0 - batch["dones"].astype(jnp.float32)
            cont_loss = jnp.mean(optax.sigmoid_binary_cross_entropy(
                cont_logit, cont))
            dyn_kl = _kl_cat(jax.lax.stop_gradient(post_lg), prior_lg)
            rep_kl = _kl_cat(post_lg, jax.lax.stop_gradient(prior_lg))
            kl_loss = (cfg.kl_dyn_scale
                       * jnp.mean(jnp.maximum(cfg.free_bits, dyn_kl))
                       + cfg.kl_rep_scale
                       * jnp.mean(jnp.maximum(cfg.free_bits, rep_kl)))
            loss = recon_loss + rew_loss + cont_loss + kl_loss
            metrics = {"wm_recon": recon_loss, "wm_reward": rew_loss,
                       "wm_continue": cont_loss,
                       "wm_kl": jnp.mean(dyn_kl)}
            return loss, (feats, metrics)

        (loss, (feats, metrics)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(wm_params)
        grads = jax.tree.map(lambda g: jnp.clip(g, -100.0, 100.0), grads)
        updates, opt_state = opt.update(grads, opt_state, wm_params)
        wm_params = optax.apply_updates(wm_params, updates)
        metrics["wm_loss"] = loss
        return wm_params, opt_state, jax.lax.stop_gradient(feats), metrics

    return update


# ------------------------------------------------------- imagination phase


def make_dream_update(actor_opt, critic_opt, cfg: DreamerV3Config,
                      num_actions: int):
    S, C = cfg.stoch_dims, cfg.stoch_classes

    def imagine(params, feats0, key):
        """Roll the dynamics forward H steps from real posterior states,
        actions sampled from the actor. feats0 [N, feat]."""
        N = feats0.shape[0]
        h0 = feats0[:, :cfg.deter_dim]
        z0 = feats0[:, cfg.deter_dim:]
        keys = jax.random.split(key, cfg.horizon_H)

        def step(carry, k_t):
            h, z = carry
            feat = jnp.concatenate([h, z], -1)
            a_lg = jax.nn.log_softmax(_dense(params["actor"], feat))
            ka, kz = jax.random.split(k_t)
            a = jax.random.categorical(ka, a_lg)
            a_1h = jax.nn.one_hot(a, num_actions)
            h = _gru(params["gru"], jnp.concatenate([z, a_1h], -1), h)
            prior_lg = _dense(params["prior"], h)
            z, _ = _sample_z(prior_lg, kz, S, C)
            logp = jnp.take_along_axis(a_lg, a[:, None], 1)[:, 0]
            ent = -jnp.sum(jnp.exp(a_lg) * a_lg, -1)
            return (h, z), (feat, logp, ent)

        (_, _), (feats, logps, ents) = jax.lax.scan(
            step, (h0, z0), keys)
        return feats, logps, ents  # [H, N, .]

    @jax.jit
    def update(params, slow_critic, opt_states, ret_ema, feats0, key):
        # ---- imagine with gradients flowing ONLY into the actor (the
        # world model is frozen in this phase, per the paper)
        frozen = jax.lax.stop_gradient(
            {k: params[k] for k in ("gru", "prior")})

        def actor_loss_fn(actor_params):
            p = {**params, **frozen, "actor": actor_params}
            feats, logps, ents = imagine(p, feats0, key)
            rew = symexp(_dense(params["reward"], feats)[..., 0])
            cont = jax.nn.sigmoid(_dense(params["continue"], feats)[..., 0])
            vals = symexp(_dense(slow_critic, feats)[..., 0])
            disc = cont * cfg.gamma

            # lambda-returns, backward scan
            def lam_step(nxt, inp):
                r_t, d_t, v_next = inp
                ret = r_t + d_t * ((1 - cfg.gae_lambda) * v_next
                                   + cfg.gae_lambda * nxt)
                return ret, ret

            last_v = vals[-1]
            _, rets = jax.lax.scan(
                lam_step, last_v,
                (rew[:-1], disc[:-1], vals[1:]), reverse=True)
            # normalize advantages by an EMA of the return spread (paper:
            # 95th-5th percentile, floored at 1)
            lo = jnp.percentile(rets, 5.0)
            hi = jnp.percentile(rets, 95.0)
            spread = jnp.maximum(1.0, hi - lo)
            adv = jax.lax.stop_gradient(rets - vals[:-1]) / \
                jax.lax.stop_gradient(jnp.maximum(1.0, ret_ema))
            # discount-weight trajectories by survival probability
            weight = jax.lax.stop_gradient(
                jnp.cumprod(jnp.concatenate(
                    [jnp.ones((1,) + disc.shape[1:]), disc[:-1]], 0), 0))[:-1]
            pg = -jnp.mean(weight * adv * logps[:-1])
            ent_bonus = -cfg.entropy_scale * jnp.mean(weight * ents[:-1])
            return pg + ent_bonus, (rets, feats, weight,
                                    jnp.mean(ents), spread)

        (a_loss, (rets, feats, weight, ent_mean, spread)), a_grads = \
            jax.value_and_grad(actor_loss_fn, has_aux=True)(params["actor"])
        a_updates, a_state = actor_opt.update(
            a_grads, opt_states["actor"], params["actor"])
        actor_params = optax.apply_updates(params["actor"], a_updates)

        # ---- critic: symlog regression toward the lambda-returns
        feats_sg = jax.lax.stop_gradient(feats[:-1])
        target = jax.lax.stop_gradient(symlog(rets))

        def critic_loss_fn(critic_params):
            v = _dense(critic_params, feats_sg)[..., 0]
            return jnp.mean(weight * (v - target) ** 2)

        c_loss, c_grads = jax.value_and_grad(critic_loss_fn)(params["critic"])
        c_updates, c_state = critic_opt.update(
            c_grads, opt_states["critic"], params["critic"])
        critic_params = optax.apply_updates(params["critic"], c_updates)

        new_slow = jax.tree.map(
            lambda s, o: cfg.critic_ema_decay * s
            + (1 - cfg.critic_ema_decay) * o,
            slow_critic, critic_params)
        new_params = {**params, "actor": actor_params,
                      "critic": critic_params}
        new_ema = 0.99 * ret_ema + 0.01 * spread
        metrics = {"actor_loss": a_loss, "critic_loss": c_loss,
                   "dream_return": jnp.mean(rets),
                   "actor_entropy": ent_mean}
        return (new_params, new_slow,
                {"actor": a_state, "critic": c_state}, new_ema, metrics)

    return update


# --------------------------------------------------------------- env runner


@ray_tpu.remote
class _DreamerRunner:
    """Remote rollout actor carrying the recurrent (h, z) policy state
    across sample() calls; the world-model + actor params are shipped
    per call like the other off-policy runners."""

    def __init__(self, env_id, num_envs: int, cfg_blob: bytes,
                 seed: int = 0):
        from ray_tpu._private import serialization as ser

        self.cfg = ser.loads(cfg_blob)
        self.env = make_vec_env(env_id, num_envs, seed)
        self.obs = self.env.reset(seed)
        self.num_actions = self.env.num_actions
        self.key = jax.random.PRNGKey(seed)
        cfg = self.cfg
        N = num_envs
        self.h = np.zeros((N, cfg.deter_dim), np.float32)
        self.z = np.zeros((N, cfg.stoch_dims * cfg.stoch_classes),
                          np.float32)
        self.prev_action = np.zeros((N,), np.int64)
        self.first = np.ones((N,), np.float32)

        S, C = cfg.stoch_dims, cfg.stoch_classes

        @jax.jit
        def policy(params, h, z, obs, prev_a, first, key):
            kz, ka = jax.random.split(key)
            mask = (1.0 - first)[:, None]
            h, z = h * mask, z * mask
            a_1h = jax.nn.one_hot(prev_a, self.num_actions) * mask
            e = _dense(params["encoder"], obs)
            h = _gru(params["gru"], jnp.concatenate([z, a_1h], -1), h)
            post_lg = _dense(params["posterior"],
                             jnp.concatenate([h, e], -1))
            z, _ = _sample_z(post_lg, kz, S, C)
            feat = jnp.concatenate([h, z], -1)
            logits = _dense(params["actor"], feat)
            a = jax.random.categorical(ka, logits)
            return h, z, a

        self._policy = policy

    def sample(self, params_blob: bytes, num_steps: int,
               random_actions: bool = False) -> dict:
        from ray_tpu._private import serialization as ser

        params = None if random_actions else ser.loads(params_blob)
        N = self.env.num_envs
        obs_l, act_l, prev_l, rew_l, done_l, first_l = [], [], [], [], [], []
        for _ in range(num_steps):
            self.key, sub = jax.random.split(self.key)
            # prev_actions[t] = action taken BEFORE observing obs_t — the
            # exact input the acting policy's GRU consumed, so training
            # sequences reproduce the same action alignment (is_first
            # masks it at episode starts)
            prev_l.append(self.prev_action.copy())
            if random_actions:
                a = np.asarray(jax.random.randint(
                    sub, (N,), 0, self.num_actions))
            else:
                h, z, a = self._policy(
                    params, jnp.asarray(self.h), jnp.asarray(self.z),
                    jnp.asarray(self.obs), jnp.asarray(self.prev_action),
                    jnp.asarray(self.first), sub)
                self.h, self.z = np.asarray(h), np.asarray(z)
                a = np.asarray(a)
            obs_l.append(self.obs.copy())
            first_l.append(self.first.copy())
            nxt, r, d, _ = self.env.step(a)
            act_l.append(a)
            rew_l.append(r)
            done_l.append(d)
            self.obs = nxt
            self.prev_action = a
            self.first = d.astype(np.float32)
        return {
            "obs": np.stack(obs_l, 1),        # [N, T, obs]
            "actions": np.stack(act_l, 1),
            "prev_actions": np.stack(prev_l, 1),
            "rewards": np.stack(rew_l, 1),
            "dones": np.stack(done_l, 1),
            "is_first": np.stack(first_l, 1),
            "episode_returns": self.env.drain_episode_returns(),
        }


class _SequenceBuffer:
    """Stores per-env streams; samples [B, T] windows uniformly."""

    def __init__(self, capacity_steps: int, obs_dim: int, seed: int = 0):
        self.capacity = capacity_steps
        self.obs_dim = obs_dim
        self.streams: list[dict] = []
        self.rng = np.random.default_rng(seed)
        self.size = 0

    def add_rollout(self, batch: dict):
        N = batch["obs"].shape[0]
        for i in range(N):
            self.streams.append({
                "obs": batch["obs"][i], "actions": batch["actions"][i],
                "prev_actions": batch["prev_actions"][i],
                "rewards": batch["rewards"][i], "dones": batch["dones"][i],
                "is_first": batch["is_first"][i]})
            self.size += batch["obs"].shape[1]
        while self.size > self.capacity and len(self.streams) > 1:
            dead = self.streams.pop(0)
            self.size -= len(dead["rewards"])

    def sample(self, B: int, T: int) -> dict | None:
        eligible = [s for s in self.streams if len(s["rewards"]) >= T]
        if not eligible:
            return None
        out = {k: [] for k in ("obs", "actions", "prev_actions", "rewards",
                               "dones", "is_first")}
        for _ in range(B):
            s = eligible[self.rng.integers(0, len(eligible))]
            lo = self.rng.integers(0, len(s["rewards"]) - T + 1)
            for k in out:
                out[k].append(s[k][lo:lo + T])
        return {k: np.stack(v) for k, v in out.items()}  # [B, T, ...]


class DreamerV3(Algorithm):
    def _setup(self):
        cfg = self.config
        probe = make_vec_env(cfg.env_id, 1, cfg.seed)
        if probe.num_actions < 1:
            raise ValueError("DreamerV3 here supports discrete-action envs")
        self.obs_dim = probe.obs_dim
        self.num_actions = probe.num_actions
        self.params = init_dreamer_params(
            jax.random.PRNGKey(cfg.seed), self.obs_dim, self.num_actions, cfg)
        self.slow_critic = self.params["critic"]
        wm_keys = ("encoder", "gru", "prior", "posterior", "decoder",
                   "reward", "continue")
        self.world_opt = optax.adam(cfg.world_lr)
        self.actor_opt = optax.adam(cfg.actor_lr)
        self.critic_opt = optax.adam(cfg.critic_lr)
        self._wm_keys = wm_keys
        self.opt_states = {
            "world": self.world_opt.init(
                {k: self.params[k] for k in wm_keys}),
            "actor": self.actor_opt.init(self.params["actor"]),
            "critic": self.critic_opt.init(self.params["critic"]),
        }
        self.ret_ema = jnp.float32(1.0)
        self._wm_update = self._make_wm_wrapper()
        self._dream_update = make_dream_update(
            self.actor_opt, self.critic_opt, cfg, self.num_actions)
        from ray_tpu._private import serialization as ser

        cfg_blob = ser.dumps(cfg)
        self.runners = [
            _DreamerRunner.remote(cfg.env_id, cfg.num_envs_per_runner,
                                  cfg_blob, cfg.seed + 1000 * (i + 1))
            for i in range(cfg.num_env_runners)]
        self.buffer = _SequenceBuffer(cfg.buffer_size, self.obs_dim,
                                      seed=cfg.seed)
        self.key = jax.random.PRNGKey(cfg.seed + 13)
        self._env_steps = 0
        self._num_updates = 0

    def _make_wm_wrapper(self):
        cfg = self.config
        wm_keys = self._wm_keys
        raw = make_world_model_update(self.world_opt, cfg, self.num_actions)

        def update(params, opt_state, batch, key):
            wm_params = {k: params[k] for k in wm_keys}
            new_wm, opt_state, feats, metrics = raw(
                wm_params, opt_state, batch, key)
            return {**params, **new_wm}, opt_state, feats, metrics

        return update

    def training_step(self) -> dict:
        cfg = self.config
        from ray_tpu._private import serialization as ser

        warmup = self._env_steps < cfg.learning_starts
        blob = ser.dumps(jax.device_get(
            {k: self.params[k] for k in
             ("encoder", "gru", "posterior", "actor")}))
        refs = [r.sample.remote(blob, cfg.rollout_fragment_length,
                                random_actions=warmup)
                for r in self.runners]
        for s in ray_tpu.get(refs, timeout=300):
            self.buffer.add_rollout(s)
            self._env_steps += int(s["rewards"].size)
            self._episode_returns.extend(s["episode_returns"])
        metrics: dict = {"env_steps": self._env_steps}
        if warmup:
            return metrics
        m: dict = {}
        for _ in range(cfg.num_updates_per_step):
            batch = self.buffer.sample(cfg.batch_size_B, cfg.batch_length_T)
            if batch is None:
                break
            jb = {
                # time-major for the scans; the RSSM consumes the PREVIOUS
                # action at each step, matching the acting policy
                "obs": jnp.asarray(np.swapaxes(batch["obs"], 0, 1)),
                "actions_onehot": jax.nn.one_hot(
                    jnp.asarray(np.swapaxes(batch["prev_actions"], 0, 1)),
                    self.num_actions),
                "rewards": jnp.asarray(np.swapaxes(batch["rewards"], 0, 1)),
                "dones": jnp.asarray(np.swapaxes(batch["dones"], 0, 1)),
                "is_first": jnp.asarray(
                    np.swapaxes(batch["is_first"], 0, 1).astype(np.float32)),
            }
            self.key, k1, k2 = jax.random.split(self.key, 3)
            wm_opt = self.opt_states["world"]
            self.params, wm_opt, feats, m = self._wm_update(
                self.params, wm_opt, jb, k1)
            self.opt_states["world"] = wm_opt
            feats0 = feats.reshape(-1, feats.shape[-1])
            (self.params, self.slow_critic, ac_states, self.ret_ema,
             dm) = self._dream_update(
                self.params, self.slow_critic,
                {"actor": self.opt_states["actor"],
                 "critic": self.opt_states["critic"]},
                self.ret_ema, feats0, k2)
            self.opt_states["actor"] = ac_states["actor"]
            self.opt_states["critic"] = ac_states["critic"]
            m.update(dm)
            self._num_updates += 1
        metrics.update({k: float(v) for k, v in m.items()})
        metrics["num_updates"] = self._num_updates
        return metrics

    def compute_single_action(self, obs) -> int:
        """Greedy action through the posterior-free prior path is not
        meaningful without history; evaluation uses the actor on a
        fresh posterior step with empty recurrent state."""
        cfg = self.config
        e = _dense(self.params["encoder"], jnp.asarray(obs)[None])
        h = jnp.zeros((1, cfg.deter_dim))
        z = jnp.zeros((1, cfg.stoch_dims * cfg.stoch_classes))
        h = _gru(self.params["gru"],
                 jnp.concatenate([z, jnp.zeros((1, self.num_actions))], -1),
                 h)
        post_lg = _dense(self.params["posterior"],
                         jnp.concatenate([h, e], -1))
        z, _ = _sample_z(post_lg, jax.random.PRNGKey(0),
                         cfg.stoch_dims, cfg.stoch_classes)
        logits = _dense(self.params["actor"], jnp.concatenate([h, z], -1))
        return int(jnp.argmax(logits[0]))

    def stop(self):
        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
        self.runners.clear()


DreamerV3Config.algo_class = DreamerV3
