"""IMPALA: asynchronous actor-learner RL with V-trace off-policy correction.

Decoupled architecture (reference: rllib/algorithms/impala/ — IMPALA's
aggregated async sampling + learner thread; Espeholt et al. 2018): rollout
actors STREAM trajectory batches continuously (num_returns="streaming"
generators with backpressure) using whatever weights they last received;
the learner consumes batches as they arrive, corrects the off-policy gap
with V-trace, updates, and pushes fresh weights back asynchronously. No
synchronous sample→update barrier anywhere — the pattern the synchronous
PPO/DQN implementations don't exercise.

V-trace targets (vs) and the policy-gradient advantage:
  rho_t = min(rho_bar, pi(a|s)/mu(a|s)),  c_t = min(c_bar, pi/mu)
  delta_t = rho_t (r_t + gamma V(x_{t+1}) - V(x_t))
  vs_t = V_t + delta_t + gamma c_t (vs_{t+1} - V_{t+1})
  adv_t = rho_t (r_t + gamma vs_{t+1} - V_t)
"""

from __future__ import annotations

import functools

import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.env import make_vec_env
from ray_tpu.rllib.env_runner import EnvRunner


class IMPALAConfig(AlgorithmConfig):
    algo_class = None  # set below

    def __init__(self):
        super().__init__()
        self.rho_bar = 1.0
        self.c_bar = 1.0
        self.batches_per_iteration = 8


def _vtrace(target_logp, behavior_logp, rewards, values, dones, last_value,
            *, gamma, rho_bar, c_bar):
    """All inputs time-major [T, N]; returns (vs [T, N], pg_adv [T, N])."""
    import jax
    import jax.numpy as jnp

    rho = jnp.minimum(rho_bar, jnp.exp(target_logp - behavior_logp))
    c = jnp.minimum(c_bar, jnp.exp(target_logp - behavior_logp))
    not_done = 1.0 - dones.astype(jnp.float32)
    v_next = jnp.concatenate([values[1:], last_value[None]], axis=0) * not_done
    delta = rho * (rewards + gamma * v_next - values)

    def step(carry, xs):
        acc = carry  # vs_{t+1} - V_{t+1}
        d, c_t, nd = xs
        acc = d + gamma * c_t * nd * acc
        return acc, acc

    _, adv_stack = jax.lax.scan(step, jnp.zeros_like(delta[0]),
                                (delta, c, not_done), reverse=True)
    vs = values + adv_stack
    vs_next = jnp.concatenate([vs[1:], last_value[None]], axis=0) * not_done
    pg_adv = rho * (rewards + gamma * vs_next - values)
    return jax.lax.stop_gradient(vs), jax.lax.stop_gradient(pg_adv)


class ImpalaLearner:
    """Jitted V-trace actor-critic update over time-major batches.

    The loss is a pluggable method (`_loss`): APPO reuses ALL of the
    init/optimizer/jit/update/weights plumbing here and overrides only the
    surrogate (appo.py)."""

    def __init__(self, obs_dim: int, num_actions: int, *, lr: float = 5e-4,
                 hidden=(64, 64), vf_coef: float = 0.5, ent_coef: float = 0.01,
                 gamma: float = 0.99, rho_bar: float = 1.0, c_bar: float = 1.0,
                 seed: int = 0):
        import jax
        import optax

        from ray_tpu.rllib import rl_module

        self._rl = rl_module
        self.gamma, self.rho_bar, self.c_bar = gamma, rho_bar, c_bar
        self.vf_coef, self.ent_coef = vf_coef, ent_coef
        self.params = rl_module.init(jax.random.PRNGKey(seed), obs_dim,
                                     num_actions, hidden=tuple(hidden))
        # target/anchor params: unused by IMPALA's loss, refreshed by APPO
        self.target_params = self.params
        self.opt = optax.chain(optax.clip_by_global_norm(40.0),
                               optax.adam(lr))
        self.opt_state = self.opt.init(self.params)
        self.version = 0
        loss = self._loss

        @functools.partial(jax.jit)
        def update(params, target_params, opt_state, batch):
            (l, aux), grads = jax.value_and_grad(
                lambda p: loss(p, target_params, batch), has_aux=True)(params)
            updates, opt_state = self.opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, l, aux

        self._update = update

    def _policy_terms(self, p, batch):
        """Shared forward pass + V-trace targets. Returns
        (target_logp, logp_all, values, vs, pg_adv), all time-major."""
        import jax
        import jax.numpy as jnp

        T, N = batch["rewards"].shape
        obs = batch["obs"].reshape(T * N, -1)
        logits, values = self._rl.forward(p, obs)
        logp_all = jax.nn.log_softmax(logits)
        target_logp = logp_all[
            jnp.arange(T * N), batch["actions"].reshape(T * N)]
        target_logp = target_logp.reshape(T, N)
        values = values.reshape(T, N)
        _, last_value = self._rl.forward(p, batch["bootstrap_obs"])
        vs, pg_adv = _vtrace(
            target_logp, batch["behavior_logp"], batch["rewards"],
            values, batch["dones"], last_value,
            gamma=self.gamma, rho_bar=self.rho_bar, c_bar=self.c_bar)
        return target_logp, logp_all, values, vs, pg_adv

    def _loss(self, p, target_params, batch):
        import jax.numpy as jnp

        target_logp, logp_all, values, vs, pg_adv = self._policy_terms(
            p, batch)
        pg_loss = -jnp.mean(target_logp * pg_adv)
        vf_loss = 0.5 * jnp.mean((vs - values) ** 2)
        ent = -jnp.mean(jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
        loss = pg_loss + self.vf_coef * vf_loss - self.ent_coef * ent
        return loss, {"pg_loss": pg_loss, "vf_loss": vf_loss, "entropy": ent}

    def _post_update(self):
        """Hook: APPO refreshes its target network here."""

    def update(self, batch: dict) -> dict:
        import jax.numpy as jnp

        jb = {k: jnp.asarray(v) for k, v in batch.items()
              if k in ("obs", "actions", "behavior_logp", "rewards", "dones",
                       "bootstrap_obs")}
        self.params, self.opt_state, loss, aux = self._update(
            self.params, self.target_params, self.opt_state, jb)
        self.version += 1
        self._post_update()
        out = {"loss": float(loss), "weights_version": self.version}
        out.update({k: float(v) for k, v in aux.items()})
        return out

    def get_weights_blob(self) -> bytes:
        from ray_tpu._private import serialization as ser

        return ser.dumps(self.params)


class IMPALA(Algorithm):
    def _setup(self):
        cfg = self.config
        probe = make_vec_env(cfg.env_id, 1, cfg.seed)
        self.learner = ImpalaLearner(
            probe.obs_dim, probe.num_actions, lr=cfg.lr,
            hidden=cfg.model_hidden, vf_coef=cfg.vf_loss_coeff,
            ent_coef=cfg.entropy_coeff, gamma=cfg.gamma,
            rho_bar=getattr(cfg, "rho_bar", 1.0),
            c_bar=getattr(cfg, "c_bar", 1.0), seed=cfg.seed)
        self._streams: list = []
        self._runners: list = []
        for i in range(cfg.num_env_runners):
            self._start_runner(i)

    def _start_runner(self, seed_offset: int):
        cfg = self.config
        runner = EnvRunner.options(max_concurrency=2).remote(
            cfg.env_id, cfg.num_envs_per_runner,
            cfg.seed + 1000 * (seed_offset + 1))
        runner.set_weights.remote(self.learner.get_weights_blob())
        stream = runner.stream_rollouts.options(
            num_returns="streaming").remote(cfg.rollout_fragment_length)
        self._runners.append(runner)
        self._streams.append(stream)

    def training_step(self) -> dict:
        cfg = self.config
        out: dict = {}
        consumed = 0
        idx = 0
        budget = cfg.batches_per_iteration
        while consumed < budget and self._streams:
            i = idx % len(self._streams)
            idx += 1
            try:
                # bounded wait: a HUNG runner (alive but stuck) must also
                # trip the restart path, not block for a day
                ref = self._streams[i].next_item(timeout=120.0)
                batch = ray_tpu.get(ref, timeout=120.0)
            except StopIteration:
                # stream exhausted (bounded runs): restart it
                self._restart(i)
                continue
            except Exception:
                # runner died mid-iteration (reference: FaultAwareApply
                # restarts failed env runners) — replace it and keep going
                self._restart(i)
                continue
            self._episode_returns.extend(batch.pop("episode_returns", ()))
            out = self.learner.update(batch)
            consumed += 1
            # async weight push: the runner picks it up for its NEXT batch;
            # no barrier — staleness is what V-trace corrects
            try:
                self._runners[i].set_weights.remote(
                    self.learner.get_weights_blob())
            except Exception:
                self._restart(i)
        out["batches_consumed"] = consumed
        out["num_healthy_runners"] = len(self._runners)
        return out

    def _restart(self, i: int):
        try:
            ray_tpu.kill(self._runners[i])
        except Exception:
            pass
        self._runners.pop(i)
        self._streams.pop(i)
        self._start_runner(len(self._runners) + np.random.randint(100, 10_000))

    def stop(self):
        for r in self._runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
        self._runners.clear()
        self._streams.clear()


IMPALAConfig.algo_class = IMPALA
