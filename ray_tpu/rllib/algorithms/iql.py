"""IQL — implicit Q-learning for offline continuous control.

(reference: rllib/algorithms/iql/ — IQLConfig/IQL per Kostrikov et al.
2021: never queries Q on out-of-distribution actions. Three pieces:
  1. a state-value net V trained by EXPECTILE regression toward the
     target critics' value of the DATA action (tau > 0.5 biases V toward
     the upper envelope of behavior-supported returns),
  2. twin critics trained by MSE toward r + gamma * V(s') — no actor in
     the backup at all,
  3. the policy extracted by advantage-weighted regression:
     max E[exp(beta * (Q - V)) * log pi(a_data | s)].
Reuses the SAC actor/critic networks (sac.py) and the transition loader
from cql.py.)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.algorithms.cql import load_transitions
from ray_tpu.rllib.algorithms.sac import _mlp, _mlp_init, q_value


class IQLConfig(AlgorithmConfig):
    algo_class = None  # set below

    def __init__(self):
        super().__init__()
        self.offline_data = None
        self.obs_dim = None
        self.action_dim = None
        self.action_scale = 1.0
        self.train_batch_size = 256
        self.num_updates_per_step = 200
        self.tau = 0.005               # polyak for target critics
        self.expectile = 0.7           # V regression expectile (paper: 0.7)
        self.beta = 3.0                # AWR inverse temperature
        self.max_weight = 100.0        # AWR weight clip

    def offline(self, *, offline_data=None, obs_dim=None, action_dim=None,
                action_scale=None, train_batch_size=None,
                num_updates_per_step=None, expectile=None, beta=None,
                max_weight=None, tau=None, **_ignored) -> "IQLConfig":
        for name, val in (("offline_data", offline_data),
                          ("obs_dim", obs_dim), ("action_dim", action_dim),
                          ("action_scale", action_scale),
                          ("train_batch_size", train_batch_size),
                          ("num_updates_per_step", num_updates_per_step),
                          ("expectile", expectile), ("beta", beta),
                          ("max_weight", max_weight), ("tau", tau)):
            if val is not None:
                setattr(self, name, val)
        return self


def _gaussian_logp_of(actor_params, obs, actions):
    """log pi(a|s) of DATA actions under a plain Gaussian actor: an MLP
    mean plus a state-INDEPENDENT learnable log-std (the original IQL
    implementation's policy class). Unlike SAC's tanh-squashed Gaussian,
    weighted regression toward data actions stays well-conditioned — no
    atanh blow-up near the action boundary, and the shared std cannot
    collapse per-state around a wrong mean early in training. Actions are
    clipped to the valid range only at evaluation time."""
    mu = _mlp(actor_params["net"], obs)
    log_std = jnp.clip(actor_params["log_std"], -5.0, 2.0)
    return jnp.sum(-0.5 * ((actions - mu) / jnp.exp(log_std)) ** 2 - log_std
                   - 0.5 * jnp.log(2 * jnp.pi), axis=-1)


def _gaussian_mean(actor_params, obs, action_scale: float):
    return jnp.clip(_mlp(actor_params["net"], obs), -action_scale,
                    action_scale)


def make_iql_update(actor_opt, q_opt, v_opt, *, gamma: float, tau: float,
                    action_scale: float, expectile: float, beta: float,
                    max_weight: float):
    @jax.jit
    def update(params, target_q, opt_states, batch):
        # --- V: expectile regression toward min target-Q of data actions
        tq = jnp.minimum(
            q_value(target_q["q1"], batch["obs"], batch["actions"]),
            q_value(target_q["q2"], batch["obs"], batch["actions"]))

        def v_loss_fn(v_params):
            v = _mlp(v_params, batch["obs"])[:, 0]
            diff = tq - v
            w = jnp.where(diff > 0, expectile, 1.0 - expectile)
            return jnp.mean(w * diff ** 2), v

        (v_loss, v_now), v_grads = jax.value_and_grad(
            v_loss_fn, has_aux=True)(params["v"])
        v_updates, v_state = v_opt.update(v_grads, opt_states["v"], params["v"])
        v_params = optax.apply_updates(params["v"], v_updates)

        # --- critics: MSE toward r + gamma * V(s'); V (not the actor)
        # carries the policy-improvement signal
        v_next = _mlp(v_params, batch["next_obs"])[:, 0]
        nonterminal = 1.0 - batch["dones"].astype(jnp.float32)
        target = jax.lax.stop_gradient(
            batch["rewards"] + gamma * nonterminal * v_next)

        def q_loss_fn(q_params):
            q1 = q_value(q_params["q1"], batch["obs"], batch["actions"])
            q2 = q_value(q_params["q2"], batch["obs"], batch["actions"])
            return (jnp.mean((q1 - target) ** 2)
                    + jnp.mean((q2 - target) ** 2)), jnp.mean(q1)

        q_params = {"q1": params["q1"], "q2": params["q2"]}
        (q_loss, q_mean), q_grads = jax.value_and_grad(
            q_loss_fn, has_aux=True)(q_params)
        q_updates, q_state = q_opt.update(q_grads, opt_states["q"], q_params)
        q_params = optax.apply_updates(q_params, q_updates)

        # --- policy: advantage-weighted regression on data actions
        adv = jax.lax.stop_gradient(tq - v_now)
        weights = jnp.minimum(jnp.exp(beta * adv), max_weight)

        def pi_loss_fn(actor_params):
            logp = _gaussian_logp_of(actor_params, batch["obs"],
                                     batch["actions"])
            return -jnp.mean(weights * logp)

        pi_loss, pi_grads = jax.value_and_grad(pi_loss_fn)(params["actor"])
        pi_updates, pi_state = actor_opt.update(pi_grads, opt_states["actor"],
                                                params["actor"])
        actor_params = optax.apply_updates(params["actor"], pi_updates)

        new_params = {"actor": actor_params, "q1": q_params["q1"],
                      "q2": q_params["q2"], "v": v_params}
        new_target = jax.tree.map(lambda t, o: (1 - tau) * t + tau * o,
                                  target_q, q_params)
        metrics = {"v_loss": v_loss, "q_loss": q_loss, "pi_loss": pi_loss,
                   "q_mean": q_mean, "v_mean": jnp.mean(v_now),
                   "adv_mean": jnp.mean(adv),
                   "mean_weight": jnp.mean(weights)}
        return new_params, new_target, \
            {"q": q_state, "actor": pi_state, "v": v_state}, metrics

    return update


class IQL(Algorithm):
    def _setup(self):
        cfg = self.config
        if cfg.offline_data is None or cfg.obs_dim is None or cfg.action_dim is None:
            raise ValueError(
                "IQL needs .offline(offline_data=..., obs_dim=..., "
                "action_dim=...)")
        self._data = load_transitions(cfg.offline_data)
        key = jax.random.PRNGKey(cfg.seed)
        k1, k2, ka, kv = jax.random.split(key, 4)
        # twin critics + a plain-Gaussian actor (see _gaussian_logp_of) and
        # an expectile V net; IQL has no temperature, so no log_alpha leaf
        self.params = {
            "q1": _mlp_init(k1, (cfg.obs_dim + cfg.action_dim,
                                 *cfg.model_hidden, 1)),
            "q2": _mlp_init(k2, (cfg.obs_dim + cfg.action_dim,
                                 *cfg.model_hidden, 1)),
            "actor": {
                "net": _mlp_init(ka, (cfg.obs_dim, *cfg.model_hidden,
                                      cfg.action_dim)),
                "log_std": jnp.zeros((cfg.action_dim,), jnp.float32),
            },
            "v": _mlp_init(kv, (cfg.obs_dim, *cfg.model_hidden, 1)),
        }
        self.target_q = {"q1": self.params["q1"], "q2": self.params["q2"]}
        self.actor_opt = optax.adam(cfg.lr)
        self.q_opt = optax.adam(cfg.lr)
        self.v_opt = optax.adam(cfg.lr)
        self.opt_states = {
            "actor": self.actor_opt.init(self.params["actor"]),
            "q": self.q_opt.init({"q1": self.params["q1"],
                                  "q2": self.params["q2"]}),
            "v": self.v_opt.init(self.params["v"]),
        }
        self._update = make_iql_update(
            self.actor_opt, self.q_opt, self.v_opt, gamma=cfg.gamma,
            tau=cfg.tau, action_scale=cfg.action_scale,
            expectile=cfg.expectile, beta=cfg.beta,
            max_weight=cfg.max_weight)
        self._rng = np.random.default_rng(cfg.seed)
        self._num_updates = 0

    def training_step(self) -> dict:
        cfg = self.config
        n = len(self._data["rewards"])
        m: dict = {}
        for _ in range(cfg.num_updates_per_step):
            sel = self._rng.integers(0, n, cfg.train_batch_size)
            batch = {k: jnp.asarray(v[sel]) for k, v in self._data.items()}
            self.params, self.target_q, self.opt_states, m = self._update(
                self.params, self.target_q, self.opt_states, batch)
            self._num_updates += 1
        out = {k: float(v) for k, v in m.items()}
        out["num_updates"] = self._num_updates
        return out

    def compute_single_action(self, obs) -> np.ndarray:
        return np.asarray(_gaussian_mean(self.params["actor"],
                                         jnp.asarray(obs)[None],
                                         self.config.action_scale))[0]


IQLConfig.algo_class = IQL
