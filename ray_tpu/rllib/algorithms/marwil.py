"""MARWIL — monotonic advantage re-weighted imitation learning.

(reference: rllib/algorithms/marwil/ — MARWILConfig/MARWIL trains from
logged episodes by exponentially advantage-weighted behavior cloning plus
a value-function baseline; Wang et al. 2018. beta=0 degenerates to plain
BC. Offline like BC: the data source is a ray_tpu.data Dataset or an
in-memory list of {obs, action, reward, done} rows in episode order.)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib import rl_module
from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig


class MARWILConfig(AlgorithmConfig):
    algo_class = None  # set below

    def __init__(self):
        super().__init__()
        self.offline_data = None       # Dataset | list of rows, episode order
        self.obs_dim = None
        self.num_actions = None
        self.train_batch_size = 256
        self.beta = 1.0                # 0 => plain BC
        self.vf_coeff = 1.0
        self.moving_average_sqd_adv_norm_update_rate = 1e-2

    def offline(self, *, offline_data=None, obs_dim=None, num_actions=None,
                train_batch_size=None, beta=None, vf_coeff=None,
                **_ignored) -> "MARWILConfig":
        for name, val in (("offline_data", offline_data),
                          ("obs_dim", obs_dim),
                          ("num_actions", num_actions),
                          ("train_batch_size", train_batch_size),
                          ("beta", beta), ("vf_coeff", vf_coeff)):
            if val is not None:
                setattr(self, name, val)
        return self


def make_marwil_update(optimizer, *, beta: float, vf_coeff: float,
                       ma_rate: float):
    @jax.jit
    def update(params, opt_state, ma_sqd_adv, batch):
        def loss_fn(p):
            logits, value = rl_module.forward(p, batch["obs"])
            adv = batch["returns"] - value
            vf_loss = jnp.mean(adv ** 2)
            # advantage scale tracked as a moving average OUTSIDE the
            # gradient (paper's c normalizer), so exp() stays bounded
            scale = jnp.sqrt(jax.lax.stop_gradient(ma_sqd_adv)) + 1e-8
            # cap the exp weight (paper's numerical guard; RLlib clips the
            # exponent) so a few large advantages can't dominate the batch
            weights = (jnp.minimum(jnp.exp(jnp.clip(
                beta * jax.lax.stop_gradient(adv) / scale, -20.0, 20.0)),
                20.0)
                if beta else jnp.ones_like(adv))
            logp = jax.nn.log_softmax(logits)
            nll = -jnp.take_along_axis(logp, batch["actions"][:, None],
                                       axis=1)[:, 0]
            pi_loss = jnp.mean(weights * nll)
            loss = pi_loss + vf_coeff * vf_loss
            acc = jnp.mean((jnp.argmax(logits, axis=-1)
                            == batch["actions"]).astype(jnp.float32))
            return loss, {"policy_loss": pi_loss, "vf_loss": vf_loss,
                          "imitation_accuracy": acc,
                          "mean_weight": jnp.mean(weights),
                          "sqd_adv": jnp.mean(jax.lax.stop_gradient(adv) ** 2)}

        (loss, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        ma_sqd_adv = (1 - ma_rate) * ma_sqd_adv + ma_rate * metrics.pop("sqd_adv")
        metrics["total_loss"] = loss
        return params, opt_state, ma_sqd_adv, metrics

    return update


def _returns_to_go(rewards: np.ndarray, dones: np.ndarray,
                   gamma: float) -> np.ndarray:
    """Discounted return-to-go per timestep, resetting at episode ends."""
    out = np.zeros_like(rewards, dtype=np.float64)
    acc = 0.0
    for i in range(len(rewards) - 1, -1, -1):
        if dones[i]:
            acc = 0.0
        acc = rewards[i] + gamma * acc
        out[i] = acc
    return out.astype(np.float32)


class MARWIL(Algorithm):
    def _setup(self):
        cfg = self.config
        if cfg.offline_data is None or cfg.obs_dim is None or cfg.num_actions is None:
            raise ValueError(
                "MARWIL needs .offline(offline_data=..., obs_dim=..., "
                "num_actions=...)")
        rows_iter = (cfg.offline_data.iter_rows()
                     if hasattr(cfg.offline_data, "iter_rows")
                     else iter(cfg.offline_data))
        obs, acts, rews, dones = [], [], [], []
        for row in rows_iter:
            obs.append(np.asarray(row["obs"], np.float32))
            acts.append(int(row["action"]))
            rews.append(float(row.get("reward", 0.0)))
            dones.append(bool(row.get("done", False)))
        self._obs = np.stack(obs)
        self._actions = np.asarray(acts, np.int32)
        self._returns = _returns_to_go(
            np.asarray(rews, np.float32), np.asarray(dones, bool), cfg.gamma)
        self.params = rl_module.init(jax.random.PRNGKey(cfg.seed),
                                     cfg.obs_dim, cfg.num_actions,
                                     cfg.model_hidden)
        self.optimizer = optax.adam(cfg.lr)
        self.opt_state = self.optimizer.init(self.params)
        # start the advantage normalizer at the data's return scale (V≈0 at
        # init, so adv≈returns): starting at 1.0 makes the first hundreds of
        # exp-weights astronomically hot and destabilizes the policy before
        # the moving average can catch up
        self.ma_sqd_adv = jnp.float32(max(float(np.mean(self._returns ** 2)),
                                          1e-6))
        self._update = make_marwil_update(
            self.optimizer, beta=cfg.beta, vf_coeff=cfg.vf_coeff,
            ma_rate=cfg.moving_average_sqd_adv_norm_update_rate)
        self._rng = np.random.default_rng(cfg.seed)

    def training_step(self) -> dict:
        cfg = self.config
        n = len(self._actions)
        order = self._rng.permutation(n)
        last = None
        trained = 0
        for lo in range(0, n, cfg.train_batch_size):
            sel = order[lo:lo + cfg.train_batch_size]
            batch = {"obs": jnp.asarray(self._obs[sel]),
                     "actions": jnp.asarray(self._actions[sel]),
                     "returns": jnp.asarray(self._returns[sel])}
            self.params, self.opt_state, self.ma_sqd_adv, last = self._update(
                self.params, self.opt_state, self.ma_sqd_adv, batch)
            trained += len(sel)
        # convert once, after the loop: float() inside it would block the
        # dispatch pipeline on every minibatch
        metrics = ({k: float(v) for k, v in last.items()} if last else {})
        metrics["num_samples_trained"] = trained
        return metrics

    def predict(self, obs) -> np.ndarray:
        return np.asarray(rl_module.forward_inference(
            self.params, jnp.asarray(obs, jnp.float32)))


MARWILConfig.algo_class = MARWIL
