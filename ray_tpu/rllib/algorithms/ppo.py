"""PPO: clipped-surrogate policy optimization.

(reference: rllib/algorithms/ppo/ — PPOConfig + PPO(Algorithm);
training_step (algorithm.py:2274 pattern): sample from EnvRunnerGroup →
GAE → epochs of minibatch SGD on the Learner → sync weights back to
runners. The update itself is one jitted XLA program (learner.py).)
"""

from __future__ import annotations

import numpy as np

from ray_tpu.rllib import learner as learner_mod
from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.env import make_vec_env
from ray_tpu.rllib.env_runner import EnvRunnerGroup


class PPOConfig(AlgorithmConfig):
    algo_class = None  # set below


class PPO(Algorithm):
    def _setup(self):
        cfg = self.config
        probe = make_vec_env(cfg.env_id, 1, cfg.seed)
        self.learner = learner_mod.Learner(
            probe.obs_dim, probe.num_actions, lr=cfg.lr,
            hidden=cfg.model_hidden, clip=cfg.clip_param,
            vf_coef=cfg.vf_loss_coeff, ent_coef=cfg.entropy_coeff,
            seed=cfg.seed)
        self.runner_group = EnvRunnerGroup(
            cfg.env_id, num_runners=cfg.num_env_runners,
            num_envs_per_runner=cfg.num_envs_per_runner, seed=cfg.seed)

    def training_step(self) -> dict:
        cfg = self.config
        blob = self.learner.get_weights_blob()
        samples = self.runner_group.sample(blob, cfg.rollout_fragment_length)
        if not samples:
            return {}
        batches = []
        import jax.numpy as jnp

        for s in samples:
            advs, rets = learner_mod.compute_gae(
                jnp.asarray(s["rewards"]), jnp.asarray(s["values"]),
                jnp.asarray(s["dones"]), jnp.asarray(s["last_value"]),
                gamma=cfg.gamma, lam=cfg.lam)
            T, N = s["rewards"].shape
            batches.append({
                "obs": s["obs"].reshape(T * N, -1),
                "actions": s["actions"].reshape(T * N),
                "logp_old": s["logp"].reshape(T * N),
                "advantages": np.asarray(advs).reshape(T * N),
                "returns": np.asarray(rets).reshape(T * N),
            })
            self._episode_returns.extend(s["episode_returns"])
        batch = {k: np.concatenate([b[k] for b in batches]) for k in batches[0]}
        mb = min(cfg.minibatch_size, batch["obs"].shape[0])
        return self.learner.update(batch, minibatch_size=mb,
                                   num_epochs=cfg.num_epochs, rng=self.rng)


PPOConfig.algo_class = PPO
