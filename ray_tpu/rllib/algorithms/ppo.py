"""PPO: clipped-surrogate policy optimization.

(reference: rllib/algorithms/ppo/ — PPOConfig + PPO(Algorithm);
training_step (algorithm.py:2274 pattern): sample from EnvRunnerGroup →
GAE → epochs of minibatch SGD on the Learner → sync weights back to
runners. The update itself is one jitted XLA program (learner.py).)
"""

from __future__ import annotations

import numpy as np

from ray_tpu.rllib import learner as learner_mod
from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.env import make_vec_env
from ray_tpu.rllib.env_runner import EnvRunnerGroup


class PPOConfig(AlgorithmConfig):
    algo_class = None  # set below


class PPO(Algorithm):
    def _setup(self):
        cfg = self.config
        if cfg.policies is not None:
            self._setup_multi_agent()
            return
        probe = make_vec_env(cfg.env_id, 1, cfg.seed)
        self.learner = learner_mod.Learner(
            probe.obs_dim, probe.num_actions, lr=cfg.lr,
            hidden=cfg.model_hidden, clip=cfg.clip_param,
            vf_coef=cfg.vf_loss_coeff, ent_coef=cfg.entropy_coeff,
            seed=cfg.seed)
        self.runner_group = EnvRunnerGroup(
            cfg.env_id, num_runners=cfg.num_env_runners,
            num_envs_per_runner=cfg.num_envs_per_runner, seed=cfg.seed)

    def _setup_multi_agent(self):
        """Per-policy Learners + MultiAgentEnvRunnerGroup (reference:
        PPO handles multi-agent through the same Algorithm class once
        config.multi_agent() is set; learners are per-module —
        core/learner + multi_rl_module.py)."""
        cfg = self.config
        from ray_tpu.rllib.multi_agent_env import make_multi_agent_env
        from ray_tpu.rllib.multi_agent_runner import MultiAgentEnvRunnerGroup
        from ray_tpu.rllib.multi_rl_module import RLModuleSpec

        mapping = cfg.policy_mapping_fn or (lambda agent_id: "default_policy")
        probe = make_multi_agent_env(cfg.env_id, 1, cfg.seed,
                                     **cfg.env_config)
        # infer unspecified policy specs from the first agent mapped there
        specs: dict[str, RLModuleSpec] = {}
        for pid, spec in cfg.policies.items():
            served = [a for a in probe.agent_ids if mapping(a) == pid]
            if not served:
                raise ValueError(
                    f"policy {pid!r} has no agents under policy_mapping_fn")
            # every agent a policy serves must share one interface — a
            # mismatch would otherwise only surface as a shape error
            # inside the remote runner, where the fault-tolerant group
            # swallows it into a silent kill/respawn loop
            dims = {(probe.obs_dims[a], probe.num_actions[a])
                    for a in served}
            if len(dims) > 1:
                raise ValueError(
                    f"policy {pid!r} serves agents with mismatched "
                    f"(obs_dim, num_actions): "
                    f"{ {a: (probe.obs_dims[a], probe.num_actions[a]) for a in served} }")
            obs_dim, n_act = next(iter(dims))
            if spec is not None:
                if (spec.obs_dim, spec.num_actions) != (obs_dim, n_act):
                    raise ValueError(
                        f"policy {pid!r} spec ({spec.obs_dim}, "
                        f"{spec.num_actions}) does not match its agents' "
                        f"env interface ({obs_dim}, {n_act})")
                specs[pid] = spec
                continue
            specs[pid] = RLModuleSpec(obs_dim, n_act, cfg.model_hidden)
        unmapped = [a for a in probe.agent_ids
                    if mapping(a) not in cfg.policies]
        if unmapped:
            raise ValueError(
                f"agents {unmapped} map outside configured policies "
                f"{sorted(cfg.policies)}")
        self.learners = {
            pid: learner_mod.Learner(
                s.obs_dim, s.num_actions, lr=cfg.lr, hidden=s.hidden,
                clip=cfg.clip_param, vf_coef=cfg.vf_loss_coeff,
                ent_coef=cfg.entropy_coeff, seed=cfg.seed + 31 * i)
            for i, (pid, s) in enumerate(sorted(specs.items()))
        }
        self.runner_group = MultiAgentEnvRunnerGroup(
            cfg.env_id, num_runners=cfg.num_env_runners,
            num_envs_per_runner=cfg.num_envs_per_runner,
            mapping_fn=mapping, seed=cfg.seed, env_config=cfg.env_config)
        self._agent_episode_returns = {a: [] for a in probe.agent_ids}

    def _multi_agent_step(self) -> dict:
        import jax.numpy as jnp

        from ray_tpu._private import serialization as ser
        import jax

        cfg = self.config
        blob = ser.dumps({pid: jax.device_get(lrn.params)
                          for pid, lrn in self.learners.items()})
        samples = self.runner_group.sample(blob, cfg.rollout_fragment_length)
        if not samples:
            return {}
        metrics: dict = {}
        for pid, lrn in self.learners.items():
            batches = []
            for s in samples:
                if pid not in s:
                    continue
                b = s[pid]
                advs, rets = learner_mod.compute_gae(
                    jnp.asarray(b["rewards"]), jnp.asarray(b["values"]),
                    jnp.asarray(b["dones"]), jnp.asarray(b["last_value"]),
                    gamma=cfg.gamma, lam=cfg.lam)
                T, M = b["rewards"].shape
                batches.append({
                    "obs": b["obs"].reshape(T * M, -1),
                    "actions": b["actions"].reshape(T * M),
                    "logp_old": b["logp"].reshape(T * M),
                    "advantages": np.asarray(advs).reshape(T * M),
                    "returns": np.asarray(rets).reshape(T * M),
                })
            if not batches:
                continue
            batch = {k: np.concatenate([x[k] for x in batches])
                     for k in batches[0]}
            mb = min(cfg.minibatch_size, batch["obs"].shape[0])
            metrics[pid] = lrn.update(batch, minibatch_size=mb,
                                      num_epochs=cfg.num_epochs,
                                      rng=self.rng)
        for s in samples:
            per_agent = s.get("__episode_returns__", {})
            step_all: list[float] = []
            for a, vals in per_agent.items():
                self._agent_episode_returns.setdefault(a, []).extend(vals)
                step_all.extend(vals)
            self._episode_returns.extend(step_all)
        return metrics

    def training_step(self) -> dict:
        cfg = self.config
        if cfg.policies is not None:
            return self._multi_agent_step()
        blob = self.learner.get_weights_blob()
        samples = self.runner_group.sample(blob, cfg.rollout_fragment_length)
        if not samples:
            return {}
        batches = []
        import jax.numpy as jnp

        for s in samples:
            advs, rets = learner_mod.compute_gae(
                jnp.asarray(s["rewards"]), jnp.asarray(s["values"]),
                jnp.asarray(s["dones"]), jnp.asarray(s["last_value"]),
                gamma=cfg.gamma, lam=cfg.lam)
            T, N = s["rewards"].shape
            batches.append({
                "obs": s["obs"].reshape(T * N, -1),
                "actions": s["actions"].reshape(T * N),
                "logp_old": s["logp"].reshape(T * N),
                "advantages": np.asarray(advs).reshape(T * N),
                "returns": np.asarray(rets).reshape(T * N),
            })
            self._episode_returns.extend(s["episode_returns"])
        batch = {k: np.concatenate([b[k] for b in batches]) for k in batches[0]}
        mb = min(cfg.minibatch_size, batch["obs"].shape[0])
        return self.learner.update(batch, minibatch_size=mb,
                                   num_epochs=cfg.num_epochs, rng=self.rng)


PPOConfig.algo_class = PPO
