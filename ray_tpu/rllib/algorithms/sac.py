"""SAC: soft actor-critic for continuous control.

(reference: rllib/algorithms/sac/ — SACConfig/SAC with twin Q networks,
polyak-averaged targets, tanh-squashed Gaussian policy, and automatic
entropy-temperature tuning; Haarnoja et al. 2018. Off-policy like DQN:
remote env runners fill the replay buffer, the learner runs jitted updates
over uniform samples.)
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax

import ray_tpu
from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.env import make_vec_env
from ray_tpu.rllib.replay import ReplayBuffer

LOG_STD_MIN, LOG_STD_MAX = -20.0, 2.0


class SACConfig(AlgorithmConfig):
    algo_class = None  # set below

    def __init__(self):
        super().__init__()
        self.buffer_size = 100_000
        self.train_batch_size = 128
        self.tau = 0.005                  # polyak for target critics
        self.num_updates_per_step = 16
        self.learning_starts = 1_000
        self.initial_alpha = 0.1
        self.autotune_alpha = True
        self.target_entropy = None        # default: -action_dim

    def training(self, *, buffer_size=None, train_batch_size=None, tau=None,
                 num_updates_per_step=None, learning_starts=None,
                 initial_alpha=None, autotune_alpha=None,
                 target_entropy=None, **kwargs) -> "SACConfig":
        super().training(**kwargs)
        for name, val in (("buffer_size", buffer_size),
                          ("train_batch_size", train_batch_size),
                          ("tau", tau),
                          ("num_updates_per_step", num_updates_per_step),
                          ("learning_starts", learning_starts),
                          ("initial_alpha", initial_alpha),
                          ("autotune_alpha", autotune_alpha),
                          ("target_entropy", target_entropy)):
            if val is not None:
                setattr(self, name, val)
        return self


# ------------------------------------------------------------- sac networks


def _mlp_init(key, sizes):
    params = {}
    keys = jax.random.split(key, len(sizes) - 1)
    for i in range(len(sizes) - 1):
        params[str(i)] = {
            "w": jax.random.normal(keys[i], (sizes[i], sizes[i + 1]))
            * jnp.sqrt(2.0 / sizes[i]),
            "b": jnp.zeros((sizes[i + 1],)),
        }
    return params


def _mlp(params, x, final_linear=True):
    n = len(params)
    for i in range(n):
        layer = params[str(i)]
        x = x @ layer["w"] + layer["b"]
        if i < n - 1 or not final_linear:
            x = jnp.tanh(x)
    return x


def init_sac_params(key, obs_dim: int, action_dim: int,
                    hidden=(64, 64), initial_alpha: float = 0.1) -> dict:
    ka, k1, k2 = jax.random.split(key, 3)
    return {
        "actor": _mlp_init(ka, (obs_dim, *hidden, 2 * action_dim)),
        "q1": _mlp_init(k1, (obs_dim + action_dim, *hidden, 1)),
        "q2": _mlp_init(k2, (obs_dim + action_dim, *hidden, 1)),
        "log_alpha": jnp.asarray(np.log(initial_alpha), jnp.float32),
    }


def actor_sample(actor_params, obs, key, action_scale: float):
    """Tanh-squashed Gaussian: returns (action, log_prob). The tanh
    log-det-Jacobian correction uses the numerically-stable softplus
    form: log(1 - tanh(u)^2) = 2 (log 2 - u - softplus(-2u))."""
    out = _mlp(actor_params, obs)
    mu, log_std = jnp.split(out, 2, axis=-1)
    log_std = jnp.clip(log_std, LOG_STD_MIN, LOG_STD_MAX)
    std = jnp.exp(log_std)
    u = mu + std * jax.random.normal(key, mu.shape)
    logp_u = jnp.sum(-0.5 * ((u - mu) / std) ** 2 - log_std
                     - 0.5 * jnp.log(2 * jnp.pi), axis=-1)
    a = jnp.tanh(u)
    logp = logp_u - jnp.sum(
        2.0 * (jnp.log(2.0) - u - jax.nn.softplus(-2.0 * u)), axis=-1)
    return a * action_scale, logp


def actor_mean(actor_params, obs, action_scale: float):
    out = _mlp(actor_params, obs)
    mu, _ = jnp.split(out, 2, axis=-1)
    return jnp.tanh(mu) * action_scale


def q_value(q_params, obs, action):
    return _mlp(q_params, jnp.concatenate([obs, action], axis=-1))[:, 0]


# --------------------------------------------------------------- env runner


@ray_tpu.remote
class _SACRunner:
    """Remote rollout actor: samples stochastic actions from the current
    actor network (jax on CPU in the worker) and returns transitions."""

    def __init__(self, env_id, num_envs: int, seed: int = 0,
                 action_scale: float = 1.0):
        self.env = make_vec_env(env_id, num_envs, seed)
        self.obs = self.env.reset(seed)
        self.key = jax.random.PRNGKey(seed)
        self.action_scale = action_scale
        self._sample = jax.jit(functools.partial(
            actor_sample, action_scale=action_scale))

    def sample(self, actor_blob: bytes, num_steps: int,
               random_actions: bool = False) -> dict:
        from ray_tpu._private import serialization as ser

        actor = None if random_actions else ser.loads(actor_blob)
        N = self.env.num_envs
        obs_l, act_l, rew_l, next_l, done_l = [], [], [], [], []
        for _ in range(num_steps):
            if random_actions:
                self.key, sub = jax.random.split(self.key)
                a = np.asarray(jax.random.uniform(
                    sub, (N, self.env.action_dim), minval=-1.0, maxval=1.0)
                    * self.action_scale)
            else:
                self.key, sub = jax.random.split(self.key)
                a, _ = self._sample(actor, jnp.asarray(self.obs), sub)
                a = np.asarray(a)
            nxt, r, d, info = self.env.step(a)
            obs_l.append(self.obs)
            act_l.append(a)
            rew_l.append(r)
            # time-limit truncations are NOT terminals: bootstrap through
            # them from the pre-reset final observation
            truncated = info.get("truncated")
            if truncated is not None and truncated.any():
                stored_next = nxt.copy()
                stored_next[truncated] = info["final_obs"][truncated]
                next_l.append(stored_next)
                done_l.append(d & ~truncated)
            else:
                next_l.append(nxt)
                done_l.append(d)
            self.obs = nxt
        return {
            "obs": np.concatenate(obs_l, 0),
            "actions": np.concatenate(act_l, 0),
            "rewards": np.concatenate(rew_l, 0),
            "next_obs": np.concatenate(next_l, 0),
            "dones": np.concatenate(done_l, 0),
            "episode_returns": self.env.drain_episode_returns(),
        }


# ------------------------------------------------------------------ learner


def make_sac_update(actor_opt, q_opt, alpha_opt, *, gamma: float, tau: float,
                    action_scale: float, target_entropy: float,
                    autotune: bool):
    @jax.jit
    def update(params, target_q, opt_states, batch, key):
        k1, k2 = jax.random.split(key)

        # --- critics: soft Bellman backup against target twins
        def q_loss_fn(q_params):
            a_next, logp_next = actor_sample(params["actor"],
                                             batch["next_obs"], k1,
                                             action_scale)
            tq1 = q_value(target_q["q1"], batch["next_obs"], a_next)
            tq2 = q_value(target_q["q2"], batch["next_obs"], a_next)
            alpha = jnp.exp(params["log_alpha"])
            soft_q = jnp.minimum(tq1, tq2) - alpha * logp_next
            nonterminal = 1.0 - batch["dones"].astype(jnp.float32)
            target = jax.lax.stop_gradient(
                batch["rewards"] + gamma * nonterminal * soft_q)
            q1 = q_value(q_params["q1"], batch["obs"], batch["actions"])
            q2 = q_value(q_params["q2"], batch["obs"], batch["actions"])
            loss = jnp.mean((q1 - target) ** 2) + jnp.mean((q2 - target) ** 2)
            return loss, jnp.mean(q1)

        q_params = {"q1": params["q1"], "q2": params["q2"]}
        (q_loss, q_mean), q_grads = jax.value_and_grad(
            q_loss_fn, has_aux=True)(q_params)
        q_updates, q_state = q_opt.update(q_grads, opt_states["q"], q_params)
        q_params = optax.apply_updates(q_params, q_updates)

        # --- actor: maximize soft value under the fresh critics
        def pi_loss_fn(actor_params):
            a, logp = actor_sample(actor_params, batch["obs"], k2,
                                   action_scale)
            q1 = q_value(q_params["q1"], batch["obs"], a)
            q2 = q_value(q_params["q2"], batch["obs"], a)
            alpha = jax.lax.stop_gradient(jnp.exp(params["log_alpha"]))
            return jnp.mean(alpha * logp - jnp.minimum(q1, q2)), logp

        (pi_loss, logp), pi_grads = jax.value_and_grad(
            pi_loss_fn, has_aux=True)(params["actor"])
        pi_updates, pi_state = actor_opt.update(pi_grads, opt_states["actor"],
                                                params["actor"])
        actor_params = optax.apply_updates(params["actor"], pi_updates)

        # --- temperature: match the target entropy
        def alpha_loss_fn(log_alpha):
            return -jnp.mean(jnp.exp(log_alpha)
                             * jax.lax.stop_gradient(logp + target_entropy))

        if autotune:
            a_loss, a_grad = jax.value_and_grad(alpha_loss_fn)(
                params["log_alpha"])
            a_updates, a_state = alpha_opt.update(
                a_grad, opt_states["alpha"], params["log_alpha"])
            log_alpha = optax.apply_updates(params["log_alpha"], a_updates)
        else:
            a_loss = jnp.float32(0)
            a_state = opt_states["alpha"]
            log_alpha = params["log_alpha"]

        new_params = {"actor": actor_params, "q1": q_params["q1"],
                      "q2": q_params["q2"], "log_alpha": log_alpha}
        new_target = jax.tree.map(lambda t, o: (1 - tau) * t + tau * o,
                                  target_q, q_params)
        metrics = {"q_loss": q_loss, "pi_loss": pi_loss, "alpha_loss": a_loss,
                   "alpha": jnp.exp(log_alpha), "q_mean": q_mean,
                   "entropy": -jnp.mean(logp)}
        return (new_params, new_target,
                {"q": q_state, "actor": pi_state, "alpha": a_state}, metrics)

    return update


class SAC(Algorithm):
    def _setup(self):
        cfg = self.config
        probe = make_vec_env(cfg.env_id, 1, cfg.seed)
        if getattr(probe, "action_dim", 0) < 1:
            raise ValueError(
                f"SAC needs a continuous-action env; {cfg.env_id!r} has no "
                "action_dim (use DQN/PPO/IMPALA/APPO for discrete actions)")
        self.obs_dim = probe.obs_dim
        self.action_dim = probe.action_dim
        self.action_scale = float(getattr(probe, "action_high", 1.0))
        target_entropy = (cfg.target_entropy if cfg.target_entropy is not None
                          else -float(self.action_dim))
        self.params = init_sac_params(
            jax.random.PRNGKey(cfg.seed), self.obs_dim, self.action_dim,
            hidden=cfg.model_hidden, initial_alpha=cfg.initial_alpha)
        self.target_q = {"q1": self.params["q1"], "q2": self.params["q2"]}
        self.actor_opt = optax.adam(cfg.lr)
        self.q_opt = optax.adam(cfg.lr)
        self.alpha_opt = optax.adam(cfg.lr)
        self.opt_states = {
            "actor": self.actor_opt.init(self.params["actor"]),
            "q": self.q_opt.init({"q1": self.params["q1"],
                                  "q2": self.params["q2"]}),
            "alpha": self.alpha_opt.init(self.params["log_alpha"]),
        }
        self._update = make_sac_update(
            self.actor_opt, self.q_opt, self.alpha_opt, gamma=cfg.gamma,
            tau=cfg.tau, action_scale=self.action_scale,
            target_entropy=target_entropy, autotune=cfg.autotune_alpha)
        # replay over continuous actions
        self.buffer = ReplayBuffer(cfg.buffer_size, self.obs_dim,
                                   seed=cfg.seed,
                                   action_dim=self.action_dim)
        self.runners = [
            _SACRunner.remote(cfg.env_id, cfg.num_envs_per_runner,
                              cfg.seed + 1000 * (i + 1),
                              action_scale=self.action_scale)
            for i in range(cfg.num_env_runners)]
        self.key = jax.random.PRNGKey(cfg.seed + 7)
        self._env_steps = 0
        self._num_updates = 0

    def training_step(self) -> dict:
        cfg = self.config
        from ray_tpu._private import serialization as ser

        blob = ser.dumps(jax.device_get(self.params["actor"]))
        warmup = self._env_steps < cfg.learning_starts
        refs = [r.sample.remote(blob, cfg.rollout_fragment_length,
                                random_actions=warmup)
                for r in self.runners]
        for s in ray_tpu.get(refs, timeout=300):
            self.buffer.add_batch(s["obs"], s["actions"], s["rewards"],
                                  s["next_obs"], s["dones"])
            self._env_steps += len(s["rewards"])
            self._episode_returns.extend(s["episode_returns"])
        metrics: dict = {"env_steps": self._env_steps,
                         "buffer_size": len(self.buffer)}
        if len(self.buffer) < cfg.learning_starts:
            return metrics
        m: dict = {}
        for _ in range(cfg.num_updates_per_step):
            batch = {k: jnp.asarray(v)
                     for k, v in self.buffer.sample(cfg.train_batch_size).items()}
            self.key, sub = jax.random.split(self.key)
            self.params, self.target_q, self.opt_states, m = self._update(
                self.params, self.target_q, self.opt_states, batch, sub)
            self._num_updates += 1
        metrics.update({k: float(v) for k, v in m.items()})
        metrics["num_updates"] = self._num_updates
        return metrics

    def compute_single_action(self, obs) -> np.ndarray:
        """Deterministic (mean) action for evaluation."""
        return np.asarray(actor_mean(self.params["actor"],
                                     jnp.asarray(obs)[None],
                                     self.action_scale))[0]

    def save(self, path: str) -> str:
        import os

        from ray_tpu.llm import checkpoint_io

        os.makedirs(path, exist_ok=True)
        checkpoint_io.save_params(self.params, os.path.join(path, "module"))
        return path

    def restore(self, path: str) -> None:
        import os

        from ray_tpu.llm import checkpoint_io

        loaded = checkpoint_io.load_params(os.path.join(path, "module"))
        self.params = jax.tree.map(
            lambda old, new: new.astype(old.dtype)
            if hasattr(old, "dtype") else new,
            self.params, loaded)
        self.target_q = {"q1": self.params["q1"], "q2": self.params["q2"]}
        self.opt_states = {
            "actor": self.actor_opt.init(self.params["actor"]),
            "q": self.q_opt.init({"q1": self.params["q1"],
                                  "q2": self.params["q2"]}),
            "alpha": self.alpha_opt.init(self.params["log_alpha"]),
        }

    def stop(self):
        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
        self.runners.clear()


SACConfig.algo_class = SAC
