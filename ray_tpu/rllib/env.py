"""Vectorized environments (numpy, dependency-free).

(reference: RLlib consumes gymnasium envs via EnvRunners
(rllib/env/single_agent_env_runner.py:68); the framework ships a built-in
vectorized CartPole so rollout/learning paths are self-contained — physics
per the classic control formulation.)
"""

from __future__ import annotations

import numpy as np


class VectorEnv:
    """Batch-first env API: reset()->obs [N,obs]; step(actions [N]) ->
    (obs, reward [N], done [N], info). Auto-resets finished sub-envs."""

    num_envs: int
    obs_dim: int
    num_actions: int

    def reset(self, seed: int | None = None) -> np.ndarray:
        raise NotImplementedError

    def step(self, actions: np.ndarray):
        raise NotImplementedError


class CartPoleVecEnv(VectorEnv):
    """N independent CartPole-v1 dynamics, vectorized over numpy."""

    GRAVITY = 9.8
    CART_MASS = 1.0
    POLE_MASS = 0.1
    POLE_HALF_LEN = 0.5
    FORCE = 10.0
    DT = 0.02
    X_LIMIT = 2.4
    THETA_LIMIT = 12 * np.pi / 180
    MAX_STEPS = 500

    def __init__(self, num_envs: int = 16, seed: int = 0):
        self.num_envs = num_envs
        self.obs_dim = 4
        self.num_actions = 2
        self.rng = np.random.default_rng(seed)
        self.state = np.zeros((num_envs, 4), np.float64)
        self.steps = np.zeros(num_envs, np.int64)
        self.episode_returns = np.zeros(num_envs, np.float64)
        self.completed_returns: list[float] = []

    def reset(self, seed: int | None = None) -> np.ndarray:
        if seed is not None:
            self.rng = np.random.default_rng(seed)
        self.state = self.rng.uniform(-0.05, 0.05, (self.num_envs, 4))
        self.steps[:] = 0
        self.episode_returns[:] = 0
        return self.state.astype(np.float32)

    def _reset_rows(self, rows: np.ndarray):
        self.state[rows] = self.rng.uniform(-0.05, 0.05, (rows.sum(), 4))
        self.steps[rows] = 0
        self.episode_returns[rows] = 0

    def step(self, actions: np.ndarray):
        x, x_dot, th, th_dot = self.state.T
        force = np.where(actions == 1, self.FORCE, -self.FORCE)
        total_mass = self.CART_MASS + self.POLE_MASS
        pole_ml = self.POLE_MASS * self.POLE_HALF_LEN
        cos, sin = np.cos(th), np.sin(th)
        tmp = (force + pole_ml * th_dot**2 * sin) / total_mass
        th_acc = (self.GRAVITY * sin - cos * tmp) / (
            self.POLE_HALF_LEN * (4.0 / 3.0 - self.POLE_MASS * cos**2 / total_mass))
        x_acc = tmp - pole_ml * th_acc * cos / total_mass
        x = x + self.DT * x_dot
        x_dot = x_dot + self.DT * x_acc
        th = th + self.DT * th_dot
        th_dot = th_dot + self.DT * th_acc
        self.state = np.stack([x, x_dot, th, th_dot], axis=1)
        self.steps += 1
        terminated = (np.abs(x) > self.X_LIMIT) | (np.abs(th) > self.THETA_LIMIT)
        truncated = self.steps >= self.MAX_STEPS
        done = terminated | truncated
        reward = np.ones(self.num_envs, np.float32)
        self.episode_returns += reward
        for r in self.episode_returns[done]:
            self.completed_returns.append(float(r))
        if done.any():
            self._reset_rows(done)
        return self.state.astype(np.float32), reward, done, {}

    def drain_episode_returns(self) -> list[float]:
        out, self.completed_returns = self.completed_returns, []
        return out


class PendulumVecEnv(VectorEnv):
    """N independent Pendulum-v1 dynamics (classic control formulation):
    continuous torque in [-2, 2], obs = (cos th, sin th, th_dot), reward
    -(th^2 + 0.1 th_dot^2 + 0.001 a^2), 200-step episodes."""

    MAX_SPEED = 8.0
    MAX_TORQUE = 2.0
    DT = 0.05
    G = 10.0
    M = 1.0
    L = 1.0
    MAX_STEPS = 200

    def __init__(self, num_envs: int = 16, seed: int = 0):
        self.num_envs = num_envs
        self.obs_dim = 3
        self.num_actions = 0          # discrete-action API: none
        self.action_dim = 1           # continuous torque
        self.action_low = -self.MAX_TORQUE
        self.action_high = self.MAX_TORQUE
        self.rng = np.random.default_rng(seed)
        self.th = np.zeros(num_envs)
        self.th_dot = np.zeros(num_envs)
        self.steps = np.zeros(num_envs, np.int64)
        self.episode_returns = np.zeros(num_envs, np.float64)
        self.completed_returns: list[float] = []

    def _obs(self) -> np.ndarray:
        return np.stack([np.cos(self.th), np.sin(self.th), self.th_dot],
                        axis=1).astype(np.float32)

    def reset(self, seed: int | None = None) -> np.ndarray:
        if seed is not None:
            self.rng = np.random.default_rng(seed)
        self.th = self.rng.uniform(-np.pi, np.pi, self.num_envs)
        self.th_dot = self.rng.uniform(-1.0, 1.0, self.num_envs)
        self.steps[:] = 0
        self.episode_returns[:] = 0
        return self._obs()

    def step(self, actions: np.ndarray):
        a = np.clip(np.asarray(actions, np.float64).reshape(self.num_envs),
                    -self.MAX_TORQUE, self.MAX_TORQUE)
        th_norm = ((self.th + np.pi) % (2 * np.pi)) - np.pi
        reward = -(th_norm ** 2 + 0.1 * self.th_dot ** 2 + 0.001 * a ** 2)
        self.th_dot = np.clip(
            self.th_dot + (3 * self.G / (2 * self.L) * np.sin(self.th)
                           + 3.0 / (self.M * self.L ** 2) * a) * self.DT,
            -self.MAX_SPEED, self.MAX_SPEED)
        self.th = self.th + self.th_dot * self.DT
        self.steps += 1
        self.episode_returns += reward
        done = self.steps >= self.MAX_STEPS
        info = {}
        if done.any():
            # Pendulum never terminates — done is always a TIME-LIMIT
            # truncation. Bootstrapping code needs the pre-reset final
            # observation and the truncation mask, or it would zero the
            # continuation value at step 200 (a biased Bellman target).
            info = {"truncated": done.copy(), "final_obs": self._obs()}
            self.completed_returns.extend(self.episode_returns[done].tolist())
            rows = done
            self.th[rows] = self.rng.uniform(-np.pi, np.pi, rows.sum())
            self.th_dot[rows] = self.rng.uniform(-1.0, 1.0, rows.sum())
            self.steps[rows] = 0
            self.episode_returns[rows] = 0
        return self._obs(), reward.astype(np.float32), done, info

    def drain_episode_returns(self) -> list[float]:
        out, self.completed_returns = self.completed_returns, []
        return out


ENV_REGISTRY = {"CartPole-v1": CartPoleVecEnv,
                "Pendulum-v1": PendulumVecEnv}


def make_vec_env(env_id, num_envs: int, seed: int = 0) -> VectorEnv:
    if callable(env_id):
        return env_id(num_envs=num_envs, seed=seed)
    return ENV_REGISTRY[env_id](num_envs=num_envs, seed=seed)
