"""EnvRunner: the rollout actor.

(reference: rllib/env/single_agent_env_runner.py:68 — owns a vector env +
inference-only module copy; sample() returns batched trajectories;
EnvRunnerGroup (env/env_runner_group.py:69) fans out across actors and
restarts failed ones (FaultAwareApply, env/env_runner.py:36).)
"""

from __future__ import annotations

import numpy as np

import ray_tpu


@ray_tpu.remote
class EnvRunner:
    def __init__(self, env_id, num_envs: int, seed: int = 0):
        import jax

        from ray_tpu.rllib.env import make_vec_env

        self.env = make_vec_env(env_id, num_envs, seed)
        self.obs = self.env.reset(seed)
        self.key = jax.random.PRNGKey(seed)
        self.num_envs = num_envs
        self._params_blob = None  # pushed by set_weights (IMPALA streaming)

    def _rollout(self, params, num_steps: int) -> dict:
        """Shared on-policy rollout loop: time-major buffers for one
        fragment (sample() adds values/bootstrap for GAE; stream_rollouts
        relabels logp as the behavior policy for V-trace)."""
        import jax

        from ray_tpu.rllib import rl_module

        T, N = num_steps, self.num_envs
        obs_buf = np.zeros((T, N, self.env.obs_dim), np.float32)
        act_buf = np.zeros((T, N), np.int32)
        logp_buf = np.zeros((T, N), np.float32)
        val_buf = np.zeros((T, N), np.float32)
        rew_buf = np.zeros((T, N), np.float32)
        done_buf = np.zeros((T, N), np.bool_)
        for t in range(T):
            self.key, sub = jax.random.split(self.key)
            action, logp, value = rl_module.forward_exploration(
                params, self.obs, sub)
            action = np.asarray(action)
            obs_buf[t] = self.obs
            act_buf[t] = action
            logp_buf[t] = np.asarray(logp)
            val_buf[t] = np.asarray(value)
            self.obs, rew_buf[t], done_buf[t], _ = self.env.step(action)
        return {"obs": obs_buf, "actions": act_buf, "logp": logp_buf,
                "values": val_buf, "rewards": rew_buf, "dones": done_buf}

    def sample(self, params_blob: bytes, num_steps: int) -> dict:
        """Roll `num_steps` per sub-env; returns time-major arrays
        [T, N, ...] plus bootstrap values for GAE."""
        from ray_tpu._private import serialization as ser
        from ray_tpu.rllib import rl_module

        params = ser.loads(params_blob)
        out = self._rollout(params, num_steps)
        _, last_value = rl_module.forward(params, self.obs)
        out["last_value"] = np.asarray(last_value)
        out["episode_returns"] = self.env.drain_episode_returns()
        return out

    def sample_epsilon_greedy(self, params_blob: bytes, num_steps: int,
                              epsilon: float) -> dict:
        """Off-policy rollout: epsilon-greedy over Q-values (the module's
        pi head doubles as the Q head). Returns transitions incl. next_obs
        for replay (reference: DQN rollout workers)."""
        import jax
        import numpy as np  # noqa: F811 — module-level np also imported

        from ray_tpu._private import serialization as ser
        from ray_tpu.rllib import rl_module

        params = ser.loads(params_blob)
        T, N = num_steps, self.num_envs
        obs_buf = np.zeros((T, N, self.env.obs_dim), np.float32)
        act_buf = np.zeros((T, N), np.int32)
        rew_buf = np.zeros((T, N), np.float32)
        next_buf = np.zeros((T, N, self.env.obs_dim), np.float32)
        done_buf = np.zeros((T, N), np.bool_)
        rng = np.random.default_rng(int(jax.random.randint(
            self.key, (), 0, 2**31 - 1)))
        self.key, _ = jax.random.split(self.key)
        for t in range(T):
            greedy = np.asarray(rl_module.forward_inference(params, self.obs))
            explore = rng.random(N) < epsilon
            random_a = rng.integers(0, self.env.num_actions, N)
            action = np.where(explore, random_a, greedy).astype(np.int32)
            obs_buf[t] = self.obs
            act_buf[t] = action
            self.obs, rew_buf[t], done_buf[t], _ = self.env.step(action)
            next_buf[t] = self.obs
        return {
            "obs": obs_buf, "actions": act_buf, "rewards": rew_buf,
            "next_obs": next_buf, "dones": done_buf,
            "episode_returns": self.env.drain_episode_returns(),
        }

    def set_weights(self, params_blob: bytes) -> None:
        """Async weight push from the learner (IMPALA): picked up by the
        streaming rollout loop at its next batch boundary. Runs on a second
        concurrency slot while stream_rollouts occupies the first."""
        self._params_blob = params_blob

    def stream_rollouts(self, num_steps: int, max_batches: int = 1_000_000):
        """Continuous trajectory stream (IMPALA's decoupled sampling):
        yields time-major batches produced with the most recently pushed
        weights, tagging each with the behavior policy's logp so the
        learner can V-trace-correct the off-policy gap. Producer-side
        backpressure bounds how far ahead of the learner this runs."""
        import time as _time

        from ray_tpu._private import serialization as ser

        while self._params_blob is None:  # first weight push may race us in
            _time.sleep(0.01)
        for _ in range(max_batches):
            params = ser.loads(self._params_blob)
            roll = self._rollout(params, num_steps)
            yield {
                "obs": roll["obs"], "actions": roll["actions"],
                "behavior_logp": roll["logp"], "rewards": roll["rewards"],
                "dones": roll["dones"],
                "bootstrap_obs": np.asarray(self.obs, np.float32),
                "episode_returns": self.env.drain_episode_returns(),
            }

    def ping(self) -> bool:
        return True


class EnvRunnerGroup:
    """(reference: env/env_runner_group.py:69 — healthy-set management +
    restart of dead runners.)"""

    def __init__(self, env_id, *, num_runners: int = 2, num_envs_per_runner: int = 8,
                 seed: int = 0):
        self.env_id = env_id
        self.num_envs_per_runner = num_envs_per_runner
        self.seed = seed
        self.runners = [self._make_runner(seed + 1000 * i)
                        for i in range(num_runners)]

    def _make_runner(self, seed: int):
        """Runner factory — subclasses (MultiAgentEnvRunnerGroup) override
        this so __init__ and the fault-tolerant replace path share it."""
        return EnvRunner.remote(self.env_id, self.num_envs_per_runner, seed)

    def _collect(self, refs) -> list[dict]:
        out = []
        for i, ref in refs:
            try:
                out.append(ray_tpu.get(ref, timeout=120.0))
            except Exception:
                # fault tolerance: replace the failed runner; its sample is lost
                # this iteration (reference: FaultAwareApply restart semantics).
                # Kill first — a merely-slow runner would otherwise leak alive.
                try:
                    ray_tpu.kill(self.runners[i])
                except Exception:
                    pass
                self.runners[i] = self._make_runner(self.seed + 7777 + i)
        return out

    def sample(self, params_blob: bytes, num_steps: int) -> list[dict]:
        return self._collect([(i, r.sample.remote(params_blob, num_steps))
                              for i, r in enumerate(self.runners)])

    def sample_epsilon_greedy(self, params_blob: bytes, num_steps: int,
                              epsilon: float) -> list[dict]:
        return self._collect(
            [(i, r.sample_epsilon_greedy.remote(params_blob, num_steps, epsilon))
             for i, r in enumerate(self.runners)])

    def shutdown(self):
        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
