"""Learner / LearnerGroup: the SGD side of RL training.

(reference: rllib/core/learner/learner.py:112 + learner_group.py:101 — the
reference scales learners with torch DDP; here the PPO update is ONE jitted
program and scales across chips by data-parallel sharding of the minibatch
over a jax Mesh (XLA inserts the gradient psum — SPMD, not DDP).)
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib import rl_module


@functools.partial(jax.jit, static_argnames=("gamma", "lam"))
def compute_gae(rewards, values, dones, last_value, *, gamma: float = 0.99,
                lam: float = 0.95):
    """Time-major [T, N] inputs → (advantages, returns) [T, N] via a reverse
    lax.scan (XLA-friendly: no Python loop over T)."""

    def step(carry, xs):
        adv_next = carry
        r, v, d, v_next = xs
        nonterminal = 1.0 - d.astype(jnp.float32)
        delta = r + gamma * v_next * nonterminal - v
        adv = delta + gamma * lam * nonterminal * adv_next
        return adv, adv

    v_next_seq = jnp.concatenate([values[1:], last_value[None]], axis=0)
    _, advs = jax.lax.scan(
        step, jnp.zeros_like(last_value),
        (rewards, values, dones, v_next_seq), reverse=True)
    return advs, advs + values


def make_ppo_update(optimizer, *, clip: float = 0.2, vf_coef: float = 0.5,
                    ent_coef: float = 0.01):
    @jax.jit
    def update(params, opt_state, batch):
        def loss_fn(p):
            logits, value = rl_module.forward(p, batch["obs"])
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, batch["actions"][:, None], axis=1)[:, 0]
            ratio = jnp.exp(logp - batch["logp_old"])
            adv = batch["advantages"]
            adv = (adv - adv.mean()) / (adv.std() + 1e-8)
            pg = -jnp.minimum(
                ratio * adv,
                jnp.clip(ratio, 1 - clip, 1 + clip) * adv).mean()
            vf = 0.5 * jnp.mean((value - batch["returns"]) ** 2)
            ent = -jnp.mean(jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
            total = pg + vf_coef * vf - ent_coef * ent
            return total, {"policy_loss": pg, "vf_loss": vf, "entropy": ent,
                           "approx_kl": jnp.mean(batch["logp_old"] - logp)}

        (loss, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        metrics["total_loss"] = loss
        return params, opt_state, metrics

    return update


class Learner:
    """Single-controller learner owning params + optimizer state on device.
    (reference: core/learner/learner.py:112 — update(batch) → metrics.)"""

    def __init__(self, obs_dim: int, num_actions: int, *, lr: float = 3e-4,
                 hidden=(64, 64), clip: float = 0.2, vf_coef: float = 0.5,
                 ent_coef: float = 0.01, seed: int = 0):
        self.params = rl_module.init(jax.random.PRNGKey(seed), obs_dim,
                                     num_actions, hidden)
        self.optimizer = optax.adam(lr)
        self.opt_state = self.optimizer.init(self.params)
        self._update = make_ppo_update(self.optimizer, clip=clip,
                                       vf_coef=vf_coef, ent_coef=ent_coef)

    def update(self, batch: dict, *, minibatch_size: int, num_epochs: int,
               rng: np.random.Generator) -> dict:
        n = batch["obs"].shape[0]
        metrics = {}
        for _ in range(num_epochs):
            perm = rng.permutation(n)
            for start in range(0, n - minibatch_size + 1, minibatch_size):
                idx = perm[start:start + minibatch_size]
                mb = {k: jnp.asarray(v[idx]) for k, v in batch.items()}
                self.params, self.opt_state, metrics = self._update(
                    self.params, self.opt_state, mb)
        return {k: float(v) for k, v in metrics.items()}

    def get_weights_blob(self) -> bytes:
        from ray_tpu._private import serialization as ser

        return ser.dumps(jax.device_get(self.params))
