"""Multi-agent vectorized environments.

(reference: rllib/env/multi_agent_env.py:30 — MultiAgentEnv hosts multiple
agents identified by string AgentIDs; reset/step speak per-agent dicts and
per-agent termination. The reference's canonical test envs are
MultiAgentCartPole — one independent CartPole per agent — and the
rock-paper-scissors / coordination matrix games in rllib/examples/envs.

TPU-first design difference: the reference steps ONE env per runner and
vectorizes via many runner processes; here each env object is itself
vectorized over N sub-envs (batch-first numpy, like env.py's VectorEnv),
so a single policy forward per step serves N x n_agents decisions — the
batched geometry XLA wants. All agents act every step (simultaneous-move
games); per-agent termination is a per-agent [N] bool with independent
auto-reset, the vector equivalent of the reference's per-agent "done"
dict + "__all__".)
"""

from __future__ import annotations

import numpy as np

from ray_tpu.rllib.env import CartPoleVecEnv


class MultiAgentVecEnv:
    """Batch-first multi-agent API.

    reset() -> {agent_id: obs [N, obs_dim]}
    step({agent_id: actions [N]}) ->
        ({agent_id: obs}, {agent_id: rew [N]}, {agent_id: done [N]}, info)

    `agent_ids` is the fixed roster (reference: MultiAgentEnv.possible_agents);
    every agent observes and acts each step. Sub-env auto-reset is per
    agent, so agents' episodes are independent unless the env couples them.
    """

    agent_ids: list[str]
    num_envs: int
    obs_dims: dict[str, int]
    num_actions: dict[str, int]

    def reset(self, seed: int | None = None) -> dict[str, np.ndarray]:
        raise NotImplementedError

    def step(self, actions: dict[str, np.ndarray]):
        raise NotImplementedError

    def drain_episode_returns(self) -> dict[str, list[float]]:
        raise NotImplementedError


class MultiAgentCartPoleVecEnv(MultiAgentVecEnv):
    """K independent CartPole dynamics, one per agent, vectorized over N
    sub-envs (reference: rllib/examples/envs/classes/multi_agent/...
    MultiAgentCartPole — the standard multi-agent smoke/learning env).
    Agents are physically independent; what's shared is the runner's
    batched inference and, under a shared policy mapping, the weights."""

    def __init__(self, num_envs: int = 16, seed: int = 0, num_agents: int = 2):
        self.agent_ids = [f"agent_{i}" for i in range(num_agents)]
        self.num_envs = num_envs
        self._envs = {
            a: CartPoleVecEnv(num_envs=num_envs, seed=seed + 131 * i)
            for i, a in enumerate(self.agent_ids)
        }
        self.obs_dims = {a: 4 for a in self.agent_ids}
        self.num_actions = {a: 2 for a in self.agent_ids}

    def reset(self, seed: int | None = None):
        return {a: e.reset(None if seed is None else seed + 131 * i)
                for i, (a, e) in enumerate(self._envs.items())}

    def step(self, actions):
        obs, rews, dones = {}, {}, {}
        for a, e in self._envs.items():
            obs[a], rews[a], dones[a], _ = e.step(actions[a])
        return obs, rews, dones, {}

    def drain_episode_returns(self):
        return {a: e.drain_episode_returns() for a, e in self._envs.items()}


class CoordinationGameVecEnv(MultiAgentVecEnv):
    """Two-player repeated coordination game where the agents' rewards are
    COUPLED — the env that makes policy interaction observable (reference:
    the matrix-game examples under rllib/examples/envs; same role as
    rock_paper_scissors for testing multi-policy learning).

    Each step both agents pick one of A actions. Payoff: +1 to both if the
    actions match on action 0, +0.5 if they match on any other action, 0 on
    mismatch — so the unique optimum needs BOTH policies to converge on
    action 0. Obs is the one-hot of the opponent's previous action (plus a
    leading "first step" flag), episodes are fixed `episode_len` steps.
    Random play scores ~episode_len * (1 + 0.5*(A-1))/A^2; coordinated play
    scores episode_len."""

    def __init__(self, num_envs: int = 16, seed: int = 0, *,
                 num_actions: int = 3, episode_len: int = 25):
        self.agent_ids = ["player_0", "player_1"]
        self.num_envs = num_envs
        self.A = num_actions
        self.episode_len = episode_len
        self.obs_dims = {a: num_actions + 1 for a in self.agent_ids}
        self.num_actions = {a: num_actions for a in self.agent_ids}
        self.rng = np.random.default_rng(seed)
        self.steps = np.zeros(num_envs, np.int64)
        self.prev = {a: np.full(num_envs, -1, np.int64) for a in self.agent_ids}
        self.episode_returns = {a: np.zeros(num_envs) for a in self.agent_ids}
        self.completed: dict[str, list[float]] = {a: [] for a in self.agent_ids}

    def _obs_for(self, agent: str) -> np.ndarray:
        other = self.agent_ids[1 - self.agent_ids.index(agent)]
        prev = self.prev[other]
        out = np.zeros((self.num_envs, self.A + 1), np.float32)
        first = prev < 0
        out[first, 0] = 1.0
        rows = ~first
        out[rows, 1 + prev[rows]] = 1.0
        return out

    def reset(self, seed: int | None = None):
        if seed is not None:
            self.rng = np.random.default_rng(seed)
        self.steps[:] = 0
        for a in self.agent_ids:
            self.prev[a][:] = -1
            self.episode_returns[a][:] = 0
        return {a: self._obs_for(a) for a in self.agent_ids}

    def step(self, actions):
        # copy: these are stored into self.prev and mutated on reset —
        # never alias caller-owned action buffers
        a0 = np.array(actions["player_0"], np.int64, copy=True)
        a1 = np.array(actions["player_1"], np.int64, copy=True)
        match = a0 == a1
        rew = np.where(match & (a0 == 0), 1.0,
                       np.where(match, 0.5, 0.0)).astype(np.float32)
        self.prev["player_0"], self.prev["player_1"] = a0, a1
        self.steps += 1
        done = self.steps >= self.episode_len
        rews = {}
        for a in self.agent_ids:
            self.episode_returns[a] += rew
            rews[a] = rew
        if done.any():
            for a in self.agent_ids:
                self.completed[a].extend(
                    self.episode_returns[a][done].tolist())
                self.episode_returns[a][done] = 0
                self.prev[a][done] = -1
            self.steps[done] = 0
        obs = {a: self._obs_for(a) for a in self.agent_ids}
        return obs, rews, {a: done.copy() for a in self.agent_ids}, {}

    def drain_episode_returns(self):
        out = {a: self.completed[a] for a in self.agent_ids}
        self.completed = {a: [] for a in self.agent_ids}
        return out


MULTI_AGENT_ENV_REGISTRY = {
    "MultiAgentCartPole": MultiAgentCartPoleVecEnv,
    "CoordinationGame": CoordinationGameVecEnv,
}


def make_multi_agent_env(env_id, num_envs: int, seed: int = 0,
                         **env_config) -> MultiAgentVecEnv:
    if callable(env_id):
        return env_id(num_envs=num_envs, seed=seed, **env_config)
    return MULTI_AGENT_ENV_REGISTRY[env_id](num_envs=num_envs, seed=seed,
                                            **env_config)
