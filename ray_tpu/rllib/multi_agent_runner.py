"""MultiAgentEnvRunner: the multi-agent rollout actor.

(reference: rllib/env/multi_agent_env_runner.py:68 — owns ONE
MultiAgentEnv + a MultiRLModule; maps each agent's observation through
the policy-mapping function to the module that serves it, and returns
per-module sample batches. rllib/env/env_runner_group.py:69 fans runners
out across actors and replaces failed ones.

TPU-first shape: all agents mapped to a module are batched into ONE
forward per step — [n_mapped_agents * N, obs_dim] — so a runner does
len(modules) XLA calls per step regardless of agent count, and the
returned per-module batches are time-major [T, n_mapped * N, ...], ready
for the same jitted GAE + PPO update the single-agent path uses.)
"""

from __future__ import annotations

import numpy as np

import ray_tpu
from ray_tpu.rllib.env_runner import EnvRunnerGroup


@ray_tpu.remote
class MultiAgentEnvRunner:
    def __init__(self, env_id, num_envs: int, mapping_blob: bytes,
                 seed: int = 0, env_config: dict | None = None):
        import jax

        from ray_tpu._private import serialization as ser
        from ray_tpu.rllib.multi_agent_env import make_multi_agent_env

        self.env = make_multi_agent_env(env_id, num_envs, seed,
                                        **(env_config or {}))
        self.obs = self.env.reset(seed)
        self.key = jax.random.PRNGKey(seed)
        self.num_envs = num_envs
        # policy_mapping_fn(agent_id) -> module_id, fixed for the run
        # (reference: AlgorithmConfig.multi_agent(policy_mapping_fn=...))
        self.mapping = ser.loads(mapping_blob)
        self.agents_of: dict[str, list[str]] = {}
        for a in self.env.agent_ids:
            self.agents_of.setdefault(self.mapping(a), []).append(a)

    def _forward_policy(self, params, agents: list[str], key):
        """One batched exploration forward for every agent this module
        serves: obs [n_agents * N, obs] -> per-agent action slices."""
        from ray_tpu.rllib import rl_module

        stacked = np.concatenate([self.obs[a] for a in agents], axis=0)
        action, logp, value = rl_module.forward_exploration(
            params, stacked, key)
        return (np.asarray(action), np.asarray(logp), np.asarray(value),
                stacked)

    def sample(self, params_blob: bytes, num_steps: int) -> dict:
        """Roll `num_steps`; returns {module_id: time-major batch} where
        the batch axis is n_mapped_agents * N (agent-major blocks), plus
        bootstrap values and per-agent episode returns."""
        import jax

        from ray_tpu._private import serialization as ser
        from ray_tpu.rllib import rl_module

        params_multi = ser.loads(params_blob)
        T, N = num_steps, self.num_envs
        bufs = {}
        for mid, agents in self.agents_of.items():
            M = len(agents) * N
            obs_dim = self.env.obs_dims[agents[0]]
            bufs[mid] = {
                "obs": np.zeros((T, M, obs_dim), np.float32),
                "actions": np.zeros((T, M), np.int32),
                "logp": np.zeros((T, M), np.float32),
                "values": np.zeros((T, M), np.float32),
                "rewards": np.zeros((T, M), np.float32),
                "dones": np.zeros((T, M), np.bool_),
            }
        for t in range(T):
            act_dict = {}
            for mid, agents in self.agents_of.items():
                self.key, sub = jax.random.split(self.key)
                action, logp, value, stacked = self._forward_policy(
                    params_multi[mid], agents, sub)
                b = bufs[mid]
                b["obs"][t] = stacked
                b["actions"][t] = action
                b["logp"][t] = logp
                b["values"][t] = value
                for j, a in enumerate(agents):
                    act_dict[a] = action[j * N:(j + 1) * N]
            self.obs, rews, dones, _ = self.env.step(act_dict)
            for mid, agents in self.agents_of.items():
                b = bufs[mid]
                b["rewards"][t] = np.concatenate(
                    [rews[a] for a in agents])
                b["dones"][t] = np.concatenate([dones[a] for a in agents])
        out = {}
        for mid, agents in self.agents_of.items():
            stacked = np.concatenate([self.obs[a] for a in agents], axis=0)
            _, last_value = rl_module.forward(params_multi[mid], stacked)
            b = bufs[mid]
            b["last_value"] = np.asarray(last_value)
            out[mid] = b
        out["__episode_returns__"] = self.env.drain_episode_returns()
        return out

    def ping(self) -> bool:
        return True


class MultiAgentEnvRunnerGroup(EnvRunnerGroup):
    """(reference: env/env_runner_group.py:69 — the same healthy-set
    management as the single-agent group; only the runner factory differs,
    so sample()'s kill-and-replace fault tolerance is inherited.)"""

    def __init__(self, env_id, *, num_runners: int = 2,
                 num_envs_per_runner: int = 8, mapping_fn=None, seed: int = 0,
                 env_config: dict | None = None):
        from ray_tpu._private import serialization as ser

        # set before super().__init__ — the base constructor calls
        # _make_runner, which needs these
        self.env_config = env_config or {}
        self._mapping_blob = ser.dumps(mapping_fn)
        super().__init__(env_id, num_runners=num_runners,
                         num_envs_per_runner=num_envs_per_runner, seed=seed)

    def _make_runner(self, seed: int):
        return MultiAgentEnvRunner.remote(
            self.env_id, self.num_envs_per_runner, self._mapping_blob,
            seed, self.env_config)
