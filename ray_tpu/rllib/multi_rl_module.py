"""MultiRLModule: a ModuleID -> policy-params mapping.

(reference: rllib/core/rl_module/multi_rl_module.py:48 — MultiRLModule
holds n sub-RLModules keyed by ModuleID; which module serves which agent
is the CALLER's policy-mapping decision, not the module's. Here each
sub-module is the same pure-functional (init, forward) pair as
rl_module.py, so the whole thing stays a jax pytree: per-policy updates
jit independently, and a shared policy is literally the same params leaf
referenced by every mapped agent.)
"""

from __future__ import annotations

import dataclasses

import jax

from ray_tpu.rllib import rl_module


@dataclasses.dataclass(frozen=True)
class RLModuleSpec:
    """Per-policy network spec (reference: core/rl_module/rl_module.py
    RLModuleSpec — obs/action spaces + model config)."""

    obs_dim: int
    num_actions: int
    hidden: tuple = (64, 64)


@dataclasses.dataclass(frozen=True)
class MultiRLModuleSpec:
    """(reference: multi_rl_module.py MultiRLModuleSpec — dict of
    ModuleID -> RLModuleSpec.)"""

    module_specs: dict  # ModuleID -> RLModuleSpec

    def keys(self):
        return self.module_specs.keys()

    def __getitem__(self, module_id: str) -> RLModuleSpec:
        return self.module_specs[module_id]


def init_multi(key, spec: MultiRLModuleSpec) -> dict:
    """-> {module_id: params pytree}; independent init per policy."""
    keys = jax.random.split(key, max(1, len(spec.module_specs)))
    return {
        mid: rl_module.init(k, s.obs_dim, s.num_actions, s.hidden)
        for k, (mid, s) in zip(keys, sorted(spec.module_specs.items()))
    }
