"""Replay buffer for off-policy algorithms.

(reference: rllib/utils/replay_buffers/ — EpisodeReplayBuffer and the
prioritized variants behind DQN/SAC; here a flat uniform ring buffer in
numpy, sampled into jitted update batches.)
"""

from __future__ import annotations

import numpy as np


class ReplayBuffer:
    """Uniform-sampling ring buffer over transitions."""

    def __init__(self, capacity: int, obs_dim: int, seed: int = 0,
                 action_dim: int = 0):
        """action_dim=0 → discrete int actions; >0 → continuous
        [capacity, action_dim] float actions (SAC)."""
        self.capacity = int(capacity)
        self.obs = np.zeros((capacity, obs_dim), np.float32)
        if action_dim > 0:
            self.actions = np.zeros((capacity, action_dim), np.float32)
        else:
            self.actions = np.zeros((capacity,), np.int32)
        self.rewards = np.zeros((capacity,), np.float32)
        self.next_obs = np.zeros((capacity, obs_dim), np.float32)
        self.dones = np.zeros((capacity,), np.bool_)
        self.rng = np.random.default_rng(seed)
        self._idx = 0
        self._size = 0

    def __len__(self) -> int:
        return self._size

    def add_batch(self, obs, actions, rewards, next_obs, dones) -> None:
        """Append [B, ...] arrays of transitions."""
        n = len(actions)
        idx = (self._idx + np.arange(n)) % self.capacity
        self.obs[idx] = obs
        self.actions[idx] = actions
        self.rewards[idx] = rewards
        self.next_obs[idx] = next_obs
        self.dones[idx] = dones
        self._idx = int((self._idx + n) % self.capacity)
        self._size = int(min(self._size + n, self.capacity))

    def sample(self, batch_size: int) -> dict:
        idx = self.rng.integers(0, self._size, size=batch_size)
        return {
            "obs": self.obs[idx],
            "actions": self.actions[idx],
            "rewards": self.rewards[idx],
            "next_obs": self.next_obs[idx],
            "dones": self.dones[idx],
        }
