"""RLModule: the policy/value network abstraction, pure-functional jax.

(reference: rllib/core/rl_module/ — RLModule defines forward_inference /
forward_exploration / forward_train over the checkpointable module state;
here the module is (init, forward) over a params pytree so the learner can
jit/shard it like any other ray_tpu model.)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def init(key, obs_dim: int, num_actions: int, hidden: tuple = (64, 64)) -> dict:
    sizes = (obs_dim, *hidden)
    # dict-of-dicts (not a list) so flat path checkpoints round-trip
    params: dict = {"layers": {}}
    keys = jax.random.split(key, len(sizes))
    for i in range(len(sizes) - 1):
        k1, _ = jax.random.split(keys[i])
        params["layers"][str(i)] = {
            "w": jax.random.normal(k1, (sizes[i], sizes[i + 1])) * jnp.sqrt(2.0 / sizes[i]),
            "b": jnp.zeros((sizes[i + 1],)),
        }
    kp, kv = jax.random.split(keys[-1])
    params["pi"] = {"w": jax.random.normal(kp, (sizes[-1], num_actions)) * 0.01,
                    "b": jnp.zeros((num_actions,))}
    params["vf"] = {"w": jax.random.normal(kv, (sizes[-1], 1)) * 1.0,
                    "b": jnp.zeros((1,))}
    return params


def forward(params: dict, obs: jnp.ndarray):
    """obs [B, obs_dim] → (logits [B, A], value [B])."""
    x = obs
    for i in sorted(params["layers"], key=int):
        layer = params["layers"][i]
        x = jnp.tanh(x @ layer["w"] + layer["b"])
    logits = x @ params["pi"]["w"] + params["pi"]["b"]
    value = (x @ params["vf"]["w"] + params["vf"]["b"])[:, 0]
    return logits, value


@jax.jit
def forward_inference(params, obs):
    logits, _ = forward(params, obs)
    return jnp.argmax(logits, axis=-1)


@jax.jit
def forward_exploration(params, obs, key):
    logits, value = forward(params, obs)
    action = jax.random.categorical(key, logits, axis=-1)
    logp = jax.nn.log_softmax(logits)[jnp.arange(obs.shape[0]), action]
    return action, logp, value
