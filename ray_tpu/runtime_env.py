"""Runtime environments: per-task/actor env_vars, working_dir, py_modules.

Reference capability: python/ray/_private/runtime_env/ — the per-node
runtime-env agent materializes envs before worker start
(agent/runtime_env_agent.py:165, GetOrCreateRuntimeEnv:303), packages
working_dir/py_modules into content-addressed zips cached by URI
(packaging.py, uri_cache.py).

TPU build: the driver normalizes + hashes the env, packages directories
into zips stored in the GCS KV (content-addressed — the URI cache), and the
scheduler spawns workers whose process env matches the task's runtime-env
hash; worker_main materializes the env (extract zips, set cwd/sys.path)
before executing anything.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import zipfile
from typing import Any, Optional

_PKG_PREFIX = "renv_pkg:"  # GCS KV key prefix for packaged zips
ENV_DIR_BASE = "/tmp/ray_tpu/runtime_envs"
MAX_PACKAGE_BYTES = 512 * 1024 * 1024


def _zip_dir(path: str) -> bytes:
    """Deterministic zip of a directory tree (sorted entries, zeroed mtimes
    so the content hash is stable across machines)."""
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for root, dirs, files in os.walk(path):
            dirs.sort()
            if "__pycache__" in dirs:
                dirs.remove("__pycache__")
            for name in sorted(files):
                full = os.path.join(root, name)
                rel = os.path.relpath(full, path)
                info = zipfile.ZipInfo(rel, date_time=(1980, 1, 1, 0, 0, 0))
                info.external_attr = (os.stat(full).st_mode & 0xFFFF) << 16
                with open(full, "rb") as f:
                    zf.writestr(info, f.read())
    data = buf.getvalue()
    if len(data) > MAX_PACKAGE_BYTES:
        raise ValueError(
            f"runtime_env package {path!r} is {len(data)} bytes "
            f"(limit {MAX_PACKAGE_BYTES})")
    return data


def _content_uri(data: bytes) -> str:
    return hashlib.sha1(data).hexdigest()[:20]


def package(runtime_env: dict, kv_put, kv_get) -> dict:
    """Normalize a user runtime_env: upload working_dir / py_modules as
    content-addressed zips (skipping uploads the KV already has — the URI
    cache) and replace paths with pkg URIs. Returns the normalized env."""
    env = dict(runtime_env or {})
    out: dict[str, Any] = {}
    ev = env.pop("env_vars", None)
    if ev:
        if not all(isinstance(k, str) and isinstance(v, str)
                   for k, v in ev.items()):
            raise TypeError("runtime_env['env_vars'] must be Dict[str, str]")
        out["env_vars"] = dict(sorted(ev.items()))
    wd = env.pop("working_dir", None)
    if wd:
        out["working_dir"] = _upload_dir(wd, kv_put, kv_get)
    mods = env.pop("py_modules", None)
    if mods:
        out["py_modules"] = [_upload_dir(m, kv_put, kv_get) for m in mods]
    pip_spec = env.pop("pip", None)
    if pip_spec is None:
        pip_spec = env.pop("uv", None)  # uv schema: same requirement lines
    if pip_spec:
        from ray_tpu._private.runtime_env_pip import normalize_pip

        out["pip"] = normalize_pip(pip_spec)
    conda_spec = env.pop("conda", None)
    if conda_spec is not None:
        if pip_spec:
            raise ValueError(
                "runtime_env cannot set both 'pip' and 'conda' (the conda "
                "spec's dependencies list takes pip sub-entries instead)")
        from ray_tpu._private.runtime_env_conda import normalize_conda

        out["conda"] = normalize_conda(conda_spec)
    image = env.pop("image_uri", None)
    if image is not None:
        from ray_tpu._private.runtime_env_container import normalize_image_uri

        out["image_uri"] = normalize_image_uri(image)
    if env:
        raise ValueError(f"unsupported runtime_env keys: {sorted(env)} "
                         "(supported: env_vars, working_dir, py_modules, "
                         "pip, uv, conda, image_uri)")
    return out


def _upload_dir(path: str, kv_put, kv_get) -> str:
    if isinstance(path, str) and path.startswith("pkg:"):
        return path  # already packaged (e.g. env reused across submissions)
    if not os.path.isdir(path):
        raise ValueError(f"runtime_env path {path!r} is not a directory")
    data = _zip_dir(path)
    uri = _content_uri(data)
    key = _PKG_PREFIX + uri
    if kv_get(key) is None:  # URI cache hit check
        kv_put(key, data)
    return f"pkg:{uri}"


def env_hash(normalized: Optional[dict]) -> str:
    """Stable fingerprint used to key worker-pool compatibility (reference:
    worker pool keyed by runtime-env hash, worker_pool.h)."""
    if not normalized:
        return ""
    return hashlib.sha1(
        json.dumps(normalized, sort_keys=True).encode()).hexdigest()[:16]


def materialize(normalized: dict, kv_get) -> dict:
    """Worker-side: download + extract packages, returning
    {"env_vars": ..., "cwd": path|None, "sys_path": [paths]}.
    Extraction is cached per-URI under ENV_DIR_BASE (shared across workers
    on the host; the .ready marker makes concurrent extraction safe)."""
    result = {"env_vars": normalized.get("env_vars") or {},
              "cwd": None, "sys_path": []}
    wd = normalized.get("working_dir")
    if wd:
        result["cwd"] = _ensure_extracted(wd, kv_get)
        result["sys_path"].append(result["cwd"])
    for m in normalized.get("py_modules") or ():
        result["sys_path"].append(_ensure_extracted(m, kv_get))
    return result


def _ensure_extracted(pkg_uri: str, kv_get) -> str:
    uri = pkg_uri.split(":", 1)[1]
    dest = os.path.join(ENV_DIR_BASE, uri)
    marker = dest + ".ready"
    if os.path.exists(marker):
        return dest
    data = kv_get(_PKG_PREFIX + uri)
    if data is None:
        raise RuntimeError(f"runtime_env package {pkg_uri} not found in GCS")
    tmp = dest + f".tmp{os.getpid()}"
    os.makedirs(tmp, exist_ok=True)
    with zipfile.ZipFile(io.BytesIO(data)) as zf:
        zf.extractall(tmp)
    try:
        os.rename(tmp, dest)
    except OSError:
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)  # another worker won the race
    with open(marker, "w"):
        pass
    return dest


def apply_to_process(normalized: dict, kv_get) -> None:
    """Apply a runtime env to THIS process (worker_main calls it before the
    exec loop; reference: worker started through the runtime-env agent)."""
    import sys

    mat = materialize(normalized, kv_get)
    os.environ.update(mat["env_vars"])
    for p in reversed(mat["sys_path"]):
        if p not in sys.path:
            sys.path.insert(0, p)
    if mat["cwd"]:
        os.chdir(mat["cwd"])
