"""Shared helpers for the scripts in this package."""

from __future__ import annotations

import json
import os
import time


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def write_artifact(name: str, payload: dict) -> str:
    """Write a timestamped benchmark artifact at the repo root."""
    path = os.path.join(repo_root(), name)
    with open(path, "w") as f:
        json.dump({"ts": time.strftime("%Y-%m-%d %H:%M"), **payload}, f,
                  indent=1)
    return path


def merge_artifact(name: str, section: str, payload) -> str:
    """Write ONE top-level section of a shared artifact, preserving every
    other section (the merge discipline llm_load_bench uses for
    LLM_BENCH.json's ``pd`` section): SERVE_BENCH.json is shared by
    serve_bench's baseline ``results`` and serve_shard_bench's ``sharded``
    section — a rerun of either must not clobber the other."""
    path = os.path.join(repo_root(), name)
    prior = {}
    try:
        with open(path) as f:
            prior = json.load(f)
    except (OSError, ValueError):
        pass
    prior.pop("ts", None)
    prior[section] = payload
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"ts": time.strftime("%Y-%m-%d %H:%M"), **prior}, f,
                  indent=1)
    os.replace(tmp, path)
    return path
