"""Shared helpers for the scripts in this package."""

from __future__ import annotations

import json
import os
import time


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def write_artifact(name: str, payload: dict) -> str:
    """Write a timestamped benchmark artifact at the repo root."""
    path = os.path.join(repo_root(), name)
    with open(path, "w") as f:
        json.dump({"ts": time.strftime("%Y-%m-%d %H:%M"), **payload}, f,
                  indent=1)
    return path
