"""`ray_tpu` ops CLI — status / list / logs / microbenchmark / job submit.

Run as `python -m ray_tpu.scripts.cli <command>` (or the `ray-tpu` shim).

(reference capability: python/ray/scripts/scripts.py — `ray status`/`ray
list`/`ray logs`/`ray submit`; state listing mirrors util/state/state_cli.py
but reads the GCS `cluster_state`/`list_nodes` messages directly over the
session socket instead of a dashboard head.)
"""

from __future__ import annotations

import argparse
import glob
import itertools
import json
import os
import sys
import time


def find_sessions(base: str = "/tmp/ray_tpu") -> list[str]:
    """Session dirs with a live GCS socket, newest first."""
    dirs = sorted(glob.glob(os.path.join(base, "session_*")),
                  key=os.path.getmtime, reverse=True)
    return [d for d in dirs if os.path.exists(os.path.join(d, "gcs.sock"))]


class GcsClient:
    """Thin read-only client on the session socket (no worker registration)."""

    def __init__(self, session_dir: str):
        from ray_tpu._private.protocol import connect_unix

        self.session_dir = session_dir
        self.conn = connect_unix(os.path.join(session_dir, "gcs.sock"), timeout=5.0)
        self._rid = itertools.count(1)

    def rpc(self, msg: dict) -> dict:
        msg["rid"] = next(self._rid)
        self.conn.send(msg)
        return self.conn.recv()

    def close(self):
        self.conn.close()


def _pick_session(args) -> str:
    if getattr(args, "session", None):
        return args.session
    sessions = find_sessions()
    if not sessions:
        print("no live ray_tpu session found under /tmp/ray_tpu", file=sys.stderr)
        sys.exit(1)
    return sessions[0]


def cmd_status(args):
    sd = _pick_session(args)
    c = GcsClient(sd)
    try:
        state = c.rpc({"type": "cluster_state"})["state"]
    finally:
        c.close()
    if args.json:
        print(json.dumps(state, indent=1, default=str))
        return
    print(f"session: {os.path.basename(sd)}")
    print(f"workers: {state['num_workers']}   live actors: {state['num_actors']}   "
          f"pending tasks: {state['pending_tasks']}")
    print("resources:")
    total, avail = state["total_resources"], state["available_resources"]
    for k in sorted(total):
        print(f"  {k:24s} {total[k] - avail.get(k, 0):.1f} / {total[k]:.1f} used")
    tc = state.get("task_counter", {})
    if tc:
        print("tasks: " + "  ".join(f"{k}={v}" for k, v in sorted(tc.items())))
    demand = state.get("pending_demand") or {}
    if any(demand.values()):
        print("pending demand: " + "  ".join(
            f"{k}={v}" for k, v in sorted(demand.items()) if v))
    draining = {nid: i for nid, i in (state.get("nodes") or {}).items()
                if i.get("draining")}
    if draining:
        print("draining nodes:")
        now = time.time()
        for nid, info in draining.items():
            deadline = info.get("drain_deadline")
            left = (f"  {max(0.0, deadline - now):.0f}s left"
                    if deadline else "")
            print(f"  {nid}  reason={info.get('drain_reason') or '?'}{left}")
    pend = {a: i for a, i in state.get("actors", {}).items()
            if i["state"] not in ("alive", "dead")}
    if pend:
        print("non-running actors (`ray_tpu explain <id>` says why):")
        for aid, info in pend.items():
            print(f"  {aid}  {info['state']}  name={info.get('name')}")


def cmd_list(args):
    sd = _pick_session(args)
    c = GcsClient(sd)
    try:
        if args.kind == "nodes":
            rows = c.rpc({"type": "list_nodes"})["nodes"]
        elif args.kind == "actors":
            state = c.rpc({"type": "cluster_state"})["state"]
            rows = [{"actor_id": aid, **info}
                    for aid, info in state.get("actors", {}).items()]
        elif args.kind == "placement-groups":
            rows_map = c.rpc({"type": "pg_table"})["table"]
            rows = [{"pg_id": k, **v} for k, v in rows_map.items()]
        elif args.kind == "tasks":
            rows = c.rpc({"type": "task_events"})["events"]
        elif args.kind == "objects":
            rows = c.rpc({"type": "list_objects"})["objects"]
        elif args.kind == "workers":
            rows = c.rpc({"type": "list_workers"})["workers"]
        elif args.kind == "jobs":
            keys = c.rpc({"type": "kv_keys", "prefix": "job:"})["keys"]
            rows = []
            for k in keys:
                v = c.rpc({"type": "kv_get", "key": k})["value"]
                if v:
                    rows.append(json.loads(v) if isinstance(v, (str, bytes)) else v)
        else:
            print(f"unknown kind {args.kind}", file=sys.stderr)
            sys.exit(2)
    finally:
        c.close()
    print(json.dumps(rows, indent=1, default=str))


def cmd_logs(args):
    sd = _pick_session(args)
    log_dir = os.path.join(sd, "logs")
    names = sorted(os.listdir(log_dir)) if os.path.isdir(log_dir) else []
    if args.source is None:
        for n in names:
            path = os.path.join(log_dir, n)
            print(f"{n}\t{os.path.getsize(path)} bytes")
        return
    matches = [n for n in names if n.startswith(args.source)]
    if not matches:
        print(f"no log matching {args.source!r} (have: {', '.join(names)})",
              file=sys.stderr)
        sys.exit(1)
    path = os.path.join(log_dir, matches[0])
    with open(path, "rb") as f:
        if args.follow:
            f.seek(0, os.SEEK_END if args.tail == 0 else os.SEEK_SET)
            if args.tail:
                _print_tail(f, args.tail)
            while True:
                chunk = f.read()
                if chunk:
                    sys.stdout.write(chunk.decode("utf-8", "replace"))
                    sys.stdout.flush()
                else:
                    time.sleep(0.25)
        elif args.tail:
            _print_tail(f, args.tail)
        else:
            sys.stdout.write(f.read().decode("utf-8", "replace"))


def _print_tail(f, n_lines: int):
    f.seek(0)
    lines = f.read().decode("utf-8", "replace").splitlines()
    for line in lines[-n_lines:]:
        print(line)


def cmd_stack(args):
    """Dump live thread stacks of a worker (reference capability: dashboard
    on-demand py-spy profiling of live workers)."""
    sd = _pick_session(args)
    c = GcsClient(sd)
    try:
        workers = c.rpc({"type": "list_workers"})["workers"]
        live = [w for w in workers if not w["dead"]]
        if args.worker is None:
            for w in live:
                print(f"{w['wid'][:12]}  pid={w['pid']:<7} kind={w['kind']:<7} "
                      f"node={w['node_id']} actor={w['actor_id'] or '-'}")
            return
        target = next((w for w in live
                       if w["wid"].startswith(args.worker)
                       or str(w["pid"]) == args.worker), None)
        if target is None:
            print(f"no live worker matching {args.worker!r}", file=sys.stderr)
            sys.exit(1)
        if getattr(args, "profile", 0):
            reply = c.rpc({"type": "worker_profile", "wid": target["wid"],
                           "duration_s": args.profile,
                           "hz": getattr(args, "hz", 50.0)})
        else:
            reply = c.rpc({"type": "worker_stacks", "wid": target["wid"]})
        if not reply.get("ok"):
            print(f"stack dump failed: {reply.get('error')}", file=sys.stderr)
            sys.exit(1)
        print(reply["stacks"])
    finally:
        c.close()


def cmd_start(args):
    """Start a head session (`ray_tpu start --head`) or join an existing one
    as a follower host (`ray_tpu start --address host:port`) and block.
    (reference capability: `ray start` head/worker modes, scripts.py:679.)"""
    if args.head:
        from ray_tpu._private.node import Node

        node = Node(num_cpus=args.num_cpus, num_tpus=args.num_tpus,
                    num_workers=args.num_workers,
                    max_workers=args.max_workers)
        print(f"head started: session={node.session_id}")
        print(f"  session dir: {node.session_dir}")
        print(f"  address:     {node.address}")
        print(f"  join:        ray_tpu start --address {node.address}")
        print(f"  driver:      ray_tpu.init(address={node.address!r})")
        if args.dashboard:
            from ray_tpu.dashboard import start_dashboard

            head = start_dashboard(node.session_dir, port=args.dashboard_port)
            print(f"  dashboard:   http://127.0.0.1:{head.port}")
        monitor_proc = None
        if args.autoscaling_config:
            # the autoscaler runs as its own MONITOR process (reference:
            # autoscaler/_private/monitor.py spawned by `ray start --head`)
            import subprocess

            monitor_proc = subprocess.Popen(
                [sys.executable, "-m", "ray_tpu._private.monitor",
                 "--address", node.address,
                 "--autoscaling-config", args.autoscaling_config]
                + (["--keep-nodes-on-exit"] if args.keep_nodes_on_exit
                   else []))
            print(f"  monitor:     pid {monitor_proc.pid} "
                  f"({args.autoscaling_config})")
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            if monitor_proc is not None:
                monitor_proc.terminate()
            node.shutdown()
    elif args.address:
        if args.autoscaling_config:
            print("warning: --autoscaling-config only applies to --head "
                  "(the monitor runs next to the GCS); ignoring",
                  file=sys.stderr)
        from ray_tpu._private.node_agent import NodeAgent

        agent = NodeAgent(address=args.address,
                          num_cpus=args.num_cpus, num_tpus=args.num_tpus)
        print(f"node agent {agent.host_id} joined {args.address}")
        agent.serve_forever()
    else:
        print("specify --head or --address", file=sys.stderr)
        sys.exit(2)


def cmd_unquarantine(args):
    """Re-enable TPU chips quarantined by an OOM kill, once the operator
    has confirmed the host device pool is healthy again (the GCS-side
    recovery path for `unquarantine_chips`)."""
    sd = _pick_session(args)
    c = GcsClient(sd)
    try:
        msg = {"type": "unquarantine_chips"}
        if args.node:
            msg["node_id"] = args.node
        if args.chips:
            msg["chips"] = [int(x) for x in args.chips.split(",")]
        reply = c.rpc(msg)
        restored = reply.get("restored") or []
        if restored:
            print(f"restored chips: {restored}")
        else:
            print("no quarantined chips matched")
    finally:
        c.close()


def cmd_drain(args):
    """Mark a node DRAINING ahead of planned maintenance or a known
    preemption: the scheduler stops placing work there, resident train
    workers get the drain notice (grace checkpoint at the next step
    boundary), and an attached autoscaler terminates the node after the
    grace window."""
    from ray_tpu._private.ray_config import RayConfig

    sd = _pick_session(args)
    c = GcsClient(sd)
    try:
        grace = (RayConfig.get("drain_grace_s") if args.grace is None
                 else float(args.grace))
        reply = c.rpc({"type": "node_drain", "node_id": args.node_id,
                       "grace_s": grace, "reason": args.reason})
        if reply.get("ok"):
            print(f"node {args.node_id} draining (grace {grace}s)")
        else:
            print(f"drain failed: {reply.get('error')}", file=sys.stderr)
            sys.exit(1)
    finally:
        c.close()


def cmd_monitor(args):
    from ray_tpu._private import monitor

    argv = ["--address", args.address,
            "--autoscaling-config", args.autoscaling_config]
    if args.keep_nodes_on_exit:
        argv.append("--keep-nodes-on-exit")
    return monitor.main(argv)


def cmd_timeline(args):
    """Export collected task events as a chrome://tracing JSON file
    (reference capability: `ray timeline`, GcsTaskManager + profile events).
    Rows for actor workers are labeled with the actor's class/name from the
    GCS actor table; compiled-DAG step spans group under their DAG id."""
    from ray_tpu._private.task_events import (export_chrome_trace,
                                              fetch_worker_names)

    sd = _pick_session(args)
    c = GcsClient(sd)
    try:
        events = c.rpc({"type": "task_events"}).get("events", [])
        # control-plane event log rides along as one `ctrl:<node>` row per
        # node, so scheduling churn lines up against the task spans
        cluster = c.rpc({"type": "list_events"}).get("events", [])
        names = fetch_worker_names(c.rpc)
    finally:
        c.close()
    out = args.output or "timeline.json"
    export_chrome_trace(events + cluster, out, names)
    print(f"wrote {len(events)} task + {len(cluster)} cluster events to "
          f"{out} (open in chrome://tracing)")


def _print_event_row(ev: dict) -> None:
    ts = time.strftime("%H:%M:%S", time.localtime(ev.get("ts", 0)))
    extras = " ".join(
        f"{k}={v}" for k, v in sorted(ev.items())
        if k not in ("seq", "ts", "etype", "severity", "source", "node",
                     "message") and v not in (None, "", [], {}))
    print(f"{ev.get('seq', 0):>6} {ts} {ev.get('severity', ''):<7} "
          f"{ev.get('etype', ''):<20} {ev.get('node', '') or '-':<12} "
          f"{ev.get('message', '')}" + (f"  [{extras}]" if extras else ""))


def cmd_events(args):
    """Structured cluster event log (reference capability: `ray list
    cluster-events` / the dashboard event feed): node joins/leaves/drains,
    actor lifecycle with death causes, PG placement, autoscaler instance
    transitions, serve reconciles, train attempts. --follow polls on the
    server-side seq watermark so only new events ship."""
    sd = _pick_session(args)
    c = GcsClient(sd)

    def fetch(after_seq: int = 0, limit: int = 0) -> list:
        return c.rpc({"type": "list_events",
                      "severity": args.severity or "",
                      "etype": args.type or "", "node": args.node or "",
                      "after_seq": after_seq,
                      "limit": limit}).get("events", [])

    try:
        rows = fetch(limit=args.limit)
        if args.json:
            print(json.dumps(rows, indent=1, default=str))
            if not args.follow:
                return
        else:
            for ev in rows:
                _print_event_row(ev)
        if not args.follow:
            return
        last = max((ev.get("seq", 0) for ev in rows), default=0)
        while True:
            time.sleep(1.0)
            fresh = fetch(after_seq=last)
            for ev in fresh:
                last = max(last, ev.get("seq", 0))
                if args.json:
                    print(json.dumps(ev, default=str))
                else:
                    _print_event_row(ev)
    except KeyboardInterrupt:
        pass
    finally:
        c.close()


def cmd_explain(args):
    """Scheduler decision attribution (\"why is my actor pending\"): the
    live per-node rejection table for a pending actor/PG, or the recorded
    decision trace (queue wait, node, lease RTT) once it placed."""
    sd = _pick_session(args)
    c = GcsClient(sd)
    try:
        reply = c.rpc({"type": "sched_explain", "target": args.target})
    finally:
        c.close()
    if args.json:
        print(json.dumps(reply, indent=1, default=str))
        return
    if not reply.get("found"):
        print(reply.get("error") or f"no actor or placement group "
                                    f"{args.target!r}", file=sys.stderr)
        sys.exit(1)
    kind, state = reply.get("kind"), reply.get("state")
    print(f"{kind} {args.target}: {state}")
    trace = reply.get("trace") or {}
    if trace:
        items = "  ".join(f"{k}={v}" for k, v in sorted(trace.items())
                          if k != "history" and v is not None)
        print(f"  trace: {items}")
    if reply.get("queue_wait_s") is not None:
        print(f"  waiting for {reply['queue_wait_s']:.1f}s")
    rej = reply.get("rejections")
    if rej:
        print("  per-node rejection table:")
        width = max(len(k) for k in rej)
        for node_id, why in sorted(rej.items()):
            print(f"    {node_id:<{width}}  {why}")
    elif reply.get("note"):
        print(f"  {reply['note']}")


def cmd_dag(args):
    """Compiled-DAG registry: `ray_tpu dag list` shows every live compiled
    DAG (plane, actors, channels, fallback reason); `ray_tpu dag show <id>`
    prints one DAG's full record plus per-node step-phase timing aggregated
    from the always-on ray_tpu_dag_step_* histograms."""
    from ray_tpu.util.state import summarize_dag_metrics

    sd = _pick_session(args)
    c = GcsClient(sd)
    try:
        dags = c.rpc({"type": "dag_list"}).get("dags", [])
        if args.action == "list":
            if args.json:
                print(json.dumps(dags, indent=1, default=str))
                return
            print(f"{'dag_id':<18} {'plane':<9} {'actors':>6} "
                  f"{'channels':>8}  fallback_reason")
            for d in sorted(dags, key=lambda d: d.get("created_at", 0)):
                print(f"{d['dag_id']:<18} {d.get('plane', '?'):<9} "
                      f"{len(d.get('actors', [])):>6} "
                      f"{d.get('channels', 0):>8}  "
                      f"{d.get('fallback_reason') or '-'}")
            return
        # show: an exact id always wins; a prefix must be unambiguous
        matches = [d for d in dags if d["dag_id"] == args.dag_id]
        if not matches and args.dag_id:
            matches = [d for d in dags
                       if d["dag_id"].startswith(args.dag_id)]
        if args.dag_id is None or not matches:
            print(f"no compiled DAG matching {args.dag_id!r} "
                  f"(have: {', '.join(d['dag_id'] for d in dags) or 'none'})",
                  file=sys.stderr)
            sys.exit(1)
        if len(matches) > 1:
            print(f"ambiguous DAG prefix {args.dag_id!r}: "
                  f"{', '.join(d['dag_id'] for d in matches)}",
                  file=sys.stderr)
            sys.exit(1)
        rec = matches[0]
        snap = c.rpc({"type": "metrics_snapshot"}).get("metrics", {})
    finally:
        c.close()
    print(json.dumps({"dag": rec,
                      "steps": summarize_dag_metrics(snap, rec["dag_id"])},
                     indent=1, default=str))


def _print_span(span: dict, depth: int = 0) -> None:
    start, end = span.get("start"), span.get("end")
    dur = f"{(end - start) * 1e3:9.2f} ms" if start and end else " " * 12
    line = f"{dur}  {'  ' * depth}{span.get('name') or span.get('span_kind')}"
    if not span.get("ok", True):
        line += "  [FAILED]"
    if span.get("pid"):
        line += f"  (pid {span['pid']})"
    print(line)
    for child in span.get("children", ()):
        _print_span(child, depth + 1)


def cmd_trace(args):
    """Serve request tracing: `ray_tpu trace list` shows the flight-recorder
    log of recent request summaries (always-on, last N per process);
    `ray_tpu trace show <request_id>` prints the sampled cross-process span
    tree for one request (trace id == request id), falling back to the
    flight-recorder summary when that request wasn't span-sampled."""
    from ray_tpu.util.tracing import assemble

    sd = _pick_session(args)
    c = GcsClient(sd)
    try:
        if args.action == "list":
            rows = c.rpc({"type": "list_requests"}).get("requests", [])
            if args.json:
                print(json.dumps(rows, indent=1, default=str))
                return
            print(f"{'request_id':<34} {'component':<11} {'status':<7} "
                  f"{'dur_ms':>9}  phases")
            for r in rows[-50:]:
                phases = " ".join(
                    f"{k}={v * 1e3:.1f}ms"
                    for k, v in (r.get("phases") or {}).items())
                print(f"{r.get('request_id', '?'):<34} "
                      f"{r.get('component', '?'):<11} "
                      f"{str(r.get('status', '')):<7} "
                      f"{(r.get('duration_s') or 0) * 1e3:>9.2f}  {phases}")
            return
        if not args.request_id:
            print("trace show needs a request id", file=sys.stderr)
            sys.exit(2)
        events = c.rpc({"type": "task_events"}).get("events", [])
        tree = assemble(events, args.request_id)
        if tree is not None:
            print(f"trace {args.request_id}")
            _print_span(tree["root"])
            return
        rows = [r for r in c.rpc({"type": "list_requests"}).get(
            "requests", []) if r.get("request_id") == args.request_id]
        if rows:
            print(f"request {args.request_id} was not span-sampled "
                  "(RAY_TPU_SERVE_SPAN_SAMPLE_EVERY); flight-recorder "
                  "summary:")
            print(json.dumps(rows, indent=1, default=str))
            return
        print(f"no trace or request summary for {args.request_id!r}",
              file=sys.stderr)
        sys.exit(1)
    finally:
        c.close()


def cmd_dashboard(args):
    from ray_tpu.dashboard.head import DashboardHead

    sd = _pick_session(args)
    head = DashboardHead(sd, args.host, args.port)
    print(f"dashboard on http://{args.host}:{head.port}")
    try:
        head.httpd.serve_forever()
    except KeyboardInterrupt:
        head.stop()


def cmd_summary(args):
    """Aggregate task counts/failures/time per task name (reference
    capability: `ray summary tasks`, util/state summarize)."""
    from ray_tpu.util.state import summarize_task_events

    sd = _pick_session(args)
    c = GcsClient(sd)
    try:
        events = c.rpc({"type": "task_events"}).get("events", [])
    finally:
        c.close()
    summary = summarize_task_events(events)
    print(f"{'task':<32} {'count':>7} {'failed':>7} {'total_s':>9}")
    for name, rec in sorted(summary.items(),
                            key=lambda kv: -kv[1]["count"]):
        print(f"{name[:32]:<32} {rec['count']:>7} {rec['failed']:>7} "
              f"{rec['total_s']:>9.3f}")


def cmd_grafana(args):
    """Write Grafana dashboard JSON + provisioning YAML + a Prometheus
    scrape config (reference capability: the dashboard's
    grafana_dashboard_factory + metrics_head artifact generation)."""
    from ray_tpu.dashboard.grafana import provision

    written = provision(args.out, dashboard_host=args.dashboard_host,
                        prometheus_host=args.prometheus_host)
    for p in written:
        print(p)


def cmd_client_proxy(args):
    """Serve Ray-Client-style proxied connections (util/client/proxier)."""
    import time as _time

    from ray_tpu.util.client import start_proxy

    proxy = start_proxy(args.address, args.host, args.port)
    print(f"client proxy on {proxy.address} -> {args.address}")
    try:
        while True:
            _time.sleep(3600)
    except KeyboardInterrupt:
        proxy.stop()


def cmd_microbenchmark(args):
    from ray_tpu._private import ray_perf

    ray_perf.main()


def cmd_submit(args):
    from ray_tpu.job_submission import JobSubmissionClient

    client = JobSubmissionClient()
    job_id = client.submit_job(
        entrypoint=" ".join(args.entrypoint),
        metadata={"submitted_via": "cli"})
    print(f"submitted job {job_id}")
    if args.no_wait:
        return
    status = client.wait_until_finished(job_id)
    for line in client.get_job_logs(job_id).splitlines():
        print(line)
    print(f"job {job_id}: {status}")
    sys.exit(0 if status == "SUCCEEDED" else 1)


def cmd_serve(args):
    """Declarative serve workflow (reference: serve/scripts.py —
    `serve deploy config.yaml`, `serve build import_path`, `serve status`)."""
    import yaml

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.serve import schema as serve_schema

    if args.action == "build":
        if not args.target:
            raise SystemExit("serve build needs an import_path "
                             "(module:attribute)")
        app_schema = serve_schema.ServeApplicationSchema(
            import_path=args.target)
        target = app_schema.resolve_target()
        cfg = serve_schema.build(target, import_path=args.target)
        text = yaml.safe_dump(cfg, sort_keys=False)
        if args.output:
            with open(args.output, "w") as f:
                f.write(text)
            print(f"wrote {args.output}")
        else:
            print(text, end="")
        return
    addr = args.address or os.environ.get("RAY_TPU_ADDRESS")
    if addr:
        ray_tpu.init(address=addr)
    else:  # attach to the newest live session on this host
        sd = _pick_session(args)
        os.environ["RAY_TPU_ADDRESS"] = f"unix:{os.path.join(sd, 'gcs.sock')}"
        os.environ["RAY_TPU_SESSION"] = os.path.basename(sd)[len("session_"):]
        ray_tpu.init()
    if args.action == "deploy":
        if not args.target:
            raise SystemExit("serve deploy needs a config YAML path")
        serve.deploy(args.target)
        print(f"deployed applications from {args.target}")
    elif args.action == "status":
        out = {"applications": serve.status()}
        try:
            plane = serve.proxy_status()
        except Exception:  # noqa: BLE001 — controller without the RPC yet
            plane = None
        if plane is not None:
            out["proxy_plane"] = plane
        print(json.dumps(out, indent=1, default=str))


def cmd_job(args):
    from ray_tpu.job_submission import JobSubmissionClient

    client = JobSubmissionClient()
    if args.action == "status":
        print(client.get_job_status(args.job_id))
    elif args.action == "logs":
        print(client.get_job_logs(args.job_id))
    elif args.action == "stop":
        client.stop_job(args.job_id)
        print(f"stop requested for {args.job_id}")
    elif args.action == "list":
        print(json.dumps(client.list_jobs(), indent=1, default=str))


def main(argv=None):
    p = argparse.ArgumentParser(prog="ray_tpu", description=__doc__)
    p.add_argument("--session", help="session dir (default: newest live one)")
    sub = p.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("status", help="cluster resources / actors / tasks")
    sp.add_argument("--json", action="store_true")
    sp.set_defaults(fn=cmd_status)

    sp = sub.add_parser("list", help="list cluster state")
    sp.add_argument("kind", choices=["nodes", "actors", "placement-groups",
                                     "jobs", "tasks", "objects", "workers"])
    sp.set_defaults(fn=cmd_list)

    sp = sub.add_parser("logs", help="show/tail a process log")
    sp.add_argument("source", nargs="?", help="e.g. worker-0 (omit to list)")
    sp.add_argument("-f", "--follow", action="store_true")
    sp.add_argument("-n", "--tail", type=int, default=0)
    sp.set_defaults(fn=cmd_logs)

    sp = sub.add_parser("microbenchmark", help="run core runtime microbenchmarks")
    sp.set_defaults(fn=cmd_microbenchmark)

    sp = sub.add_parser("stack", help="live thread stacks of a worker")
    sp.add_argument("--profile", type=float, default=0, metavar="SECONDS",
                    help="sample for SECONDS and print a collapsed-stack "
                         "profile instead of one snapshot")
    sp.add_argument("--hz", type=float, default=50.0)
    sp.add_argument("worker", nargs="?", help="wid prefix or pid (omit to list)")
    sp.set_defaults(fn=cmd_stack)

    sp = sub.add_parser("start", help="start a head session or join as follower")
    sp.add_argument("--head", action="store_true")
    sp.add_argument("--address", help="GCS host:port to join as follower")
    sp.add_argument("--num-cpus", type=float, default=None)
    sp.add_argument("--num-tpus", type=float, default=None)
    sp.add_argument("--num-workers", type=int, default=0)
    sp.add_argument("--max-workers", type=int, default=16)
    sp.add_argument("--dashboard", action="store_true")
    sp.add_argument("--dashboard-port", type=int, default=0)
    sp.add_argument("--autoscaling-config", default=None,
                    help="JSON/YAML autoscaler config; spawns the monitor "
                         "process (see ray_tpu/_private/monitor.py)")
    sp.add_argument("--keep-nodes-on-exit", action="store_true",
                    help="monitor leaves provider nodes running on exit")
    sp.set_defaults(fn=cmd_start)

    sp = sub.add_parser("unquarantine",
                        help="re-enable chips quarantined by an OOM kill")
    sp.add_argument("--node", help="node id (default: the head's local node)")
    sp.add_argument("--chips", help="comma-separated chip ids (default: all)")
    sp.set_defaults(fn=cmd_unquarantine)

    sp = sub.add_parser("drain",
                        help="drain a node: stop scheduling there, notify "
                             "resident train workers, then terminate")
    sp.add_argument("node_id", help="node id (see `list --what nodes`)")
    sp.add_argument("--grace", type=float, default=None,
                    help="grace window seconds (default: drain_grace_s)")
    sp.add_argument("--reason", default="cli",
                    help="recorded with the drain (default: cli)")
    sp.set_defaults(fn=cmd_drain)

    sp = sub.add_parser("monitor",
                        help="run the autoscaler monitor process "
                             "against a live cluster")
    sp.add_argument("--address", required=True)
    sp.add_argument("--autoscaling-config", required=True)
    sp.add_argument("--keep-nodes-on-exit", action="store_true")
    sp.set_defaults(fn=cmd_monitor)

    sp = sub.add_parser("timeline", help="export task timeline (chrome trace)")
    sp.add_argument("-o", "--output", help="output path (default timeline.json)")
    sp.set_defaults(fn=cmd_timeline)

    sp = sub.add_parser("events",
                        help="structured cluster event log (node/actor/PG "
                             "lifecycle, drains, autoscaler, serve, train)")
    sp.add_argument("-f", "--follow", action="store_true",
                    help="poll for new events (seq watermark)")
    sp.add_argument("--severity",
                    help="minimum severity (DEBUG/INFO/WARNING/ERROR)")
    sp.add_argument("--type", help="exact event type, e.g. node.drain")
    sp.add_argument("--node", help="only events attributed to this node")
    sp.add_argument("-n", "--limit", type=int, default=0,
                    help="newest N matching events (default: all retained)")
    sp.add_argument("--json", action="store_true")
    sp.set_defaults(fn=cmd_events)

    sp = sub.add_parser("explain",
                        help="why is this actor/placement-group pending? "
                             "(per-node rejection table / decision trace)")
    sp.add_argument("target", help="actor id or placement group id")
    sp.add_argument("--json", action="store_true")
    sp.set_defaults(fn=cmd_explain)

    sp = sub.add_parser("dag", help="compiled-DAG registry: list / show")
    sp.add_argument("action", choices=["list", "show"])
    sp.add_argument("dag_id", nargs="?",
                    help="show: dag id (or unique prefix)")
    sp.add_argument("--json", action="store_true",
                    help="list: raw JSON instead of the table")
    sp.set_defaults(fn=cmd_dag)

    sp = sub.add_parser("trace",
                        help="serve request tracing: list recent request "
                             "summaries / show one request's span tree")
    sp.add_argument("action", choices=["list", "show"])
    sp.add_argument("request_id", nargs="?",
                    help="show: the request id (from trace list, the "
                         "flight recorder, or /api/requests)")
    sp.add_argument("--json", action="store_true",
                    help="list: raw JSON instead of the table")
    sp.set_defaults(fn=cmd_trace)

    sp = sub.add_parser("dashboard", help="serve the HTTP dashboard")
    sp.add_argument("--host", default="127.0.0.1")
    sp.add_argument("--port", type=int, default=0)
    sp.set_defaults(fn=cmd_dashboard)

    sp = sub.add_parser("summary", help="per-task-name execution summary")
    sp.set_defaults(fn=cmd_summary)

    sp = sub.add_parser("grafana",
                        help="write Grafana/Prometheus provisioning artifacts")
    sp.add_argument("--out", default="./ray_tpu_metrics",
                    help="output directory (default ./ray_tpu_metrics)")
    sp.add_argument("--dashboard-host", default="127.0.0.1:8265",
                    help="where Prometheus scrapes /metrics")
    sp.add_argument("--prometheus-host", default="127.0.0.1:9090",
                    help="where Grafana reaches Prometheus")
    sp.set_defaults(fn=cmd_grafana)

    sp = sub.add_parser("client-proxy",
                        help="serve proxied client connections (ray client)")
    sp.add_argument("--address", required=True,
                    help="GCS address (host:port) to bridge clients to")
    sp.add_argument("--host", default="127.0.0.1")
    sp.add_argument("--port", type=int, default=10001)
    sp.set_defaults(fn=cmd_client_proxy)

    sp = sub.add_parser("serve",
                        help="declarative serve: deploy/build/status "
                             "(reference: `serve deploy` / `serve build`)")
    sp.add_argument("action", choices=["deploy", "build", "status"])
    sp.add_argument("target", nargs="?",
                    help="deploy: config YAML path; build: import_path "
                         "(module:attribute) of a bound Application")
    sp.add_argument("-o", "--output", help="build: write YAML here "
                                           "(default stdout)")
    sp.add_argument("--address", help="GCS address of a running cluster "
                                      "(deploy/status attach to it)")
    sp.set_defaults(fn=cmd_serve)

    sp = sub.add_parser("submit", help="submit a job (command) to the cluster")
    sp.add_argument("--no-wait", action="store_true")
    sp.add_argument("entrypoint", nargs=argparse.REMAINDER)
    sp.set_defaults(fn=cmd_submit)

    sp = sub.add_parser("job", help="job status / logs / stop / list")
    sp.add_argument("action", choices=["status", "logs", "stop", "list"])
    sp.add_argument("job_id", nargs="?")
    sp.set_defaults(fn=cmd_job)

    args = p.parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
