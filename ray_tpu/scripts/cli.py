"""`ray_tpu` ops CLI — status / list / logs / microbenchmark / job submit.

Run as `python -m ray_tpu.scripts.cli <command>` (or the `ray-tpu` shim).

(reference capability: python/ray/scripts/scripts.py — `ray status`/`ray
list`/`ray logs`/`ray submit`; state listing mirrors util/state/state_cli.py
but reads the GCS `cluster_state`/`list_nodes` messages directly over the
session socket instead of a dashboard head.)
"""

from __future__ import annotations

import argparse
import glob
import itertools
import json
import os
import sys
import time


def find_sessions(base: str = "/tmp/ray_tpu") -> list[str]:
    """Session dirs with a live GCS socket, newest first."""
    dirs = sorted(glob.glob(os.path.join(base, "session_*")),
                  key=os.path.getmtime, reverse=True)
    return [d for d in dirs if os.path.exists(os.path.join(d, "gcs.sock"))]


class GcsClient:
    """Thin read-only client on the session socket (no worker registration)."""

    def __init__(self, session_dir: str):
        from ray_tpu._private.protocol import connect_unix

        self.session_dir = session_dir
        self.conn = connect_unix(os.path.join(session_dir, "gcs.sock"), timeout=5.0)
        self._rid = itertools.count(1)

    def rpc(self, msg: dict) -> dict:
        msg["rid"] = next(self._rid)
        self.conn.send(msg)
        return self.conn.recv()

    def close(self):
        self.conn.close()


def _pick_session(args) -> str:
    if getattr(args, "session", None):
        return args.session
    sessions = find_sessions()
    if not sessions:
        print("no live ray_tpu session found under /tmp/ray_tpu", file=sys.stderr)
        sys.exit(1)
    return sessions[0]


def cmd_status(args):
    sd = _pick_session(args)
    c = GcsClient(sd)
    try:
        state = c.rpc({"type": "cluster_state"})["state"]
    finally:
        c.close()
    if args.json:
        print(json.dumps(state, indent=1, default=str))
        return
    print(f"session: {os.path.basename(sd)}")
    print(f"workers: {state['num_workers']}   live actors: {state['num_actors']}   "
          f"pending tasks: {state['pending_tasks']}")
    print("resources:")
    total, avail = state["total_resources"], state["available_resources"]
    for k in sorted(total):
        print(f"  {k:24s} {total[k] - avail.get(k, 0):.1f} / {total[k]:.1f} used")
    tc = state.get("task_counter", {})
    if tc:
        print("tasks: " + "  ".join(f"{k}={v}" for k, v in sorted(tc.items())))
    pend = {a: i for a, i in state.get("actors", {}).items()
            if i["state"] not in ("alive", "dead")}
    if pend:
        print("non-running actors:")
        for aid, info in pend.items():
            print(f"  {aid}  {info['state']}  name={info.get('name')}")


def cmd_list(args):
    sd = _pick_session(args)
    c = GcsClient(sd)
    try:
        if args.kind == "nodes":
            rows = c.rpc({"type": "list_nodes"})["nodes"]
        elif args.kind == "actors":
            state = c.rpc({"type": "cluster_state"})["state"]
            rows = [{"actor_id": aid, **info}
                    for aid, info in state.get("actors", {}).items()]
        elif args.kind == "placement-groups":
            rows_map = c.rpc({"type": "pg_table"})["table"]
            rows = [{"pg_id": k, **v} for k, v in rows_map.items()]
        elif args.kind == "jobs":
            keys = c.rpc({"type": "kv_keys", "prefix": "job:"})["keys"]
            rows = []
            for k in keys:
                v = c.rpc({"type": "kv_get", "key": k})["value"]
                if v:
                    rows.append(json.loads(v) if isinstance(v, (str, bytes)) else v)
        else:
            print(f"unknown kind {args.kind}", file=sys.stderr)
            sys.exit(2)
    finally:
        c.close()
    print(json.dumps(rows, indent=1, default=str))


def cmd_logs(args):
    sd = _pick_session(args)
    log_dir = os.path.join(sd, "logs")
    names = sorted(os.listdir(log_dir)) if os.path.isdir(log_dir) else []
    if args.source is None:
        for n in names:
            path = os.path.join(log_dir, n)
            print(f"{n}\t{os.path.getsize(path)} bytes")
        return
    matches = [n for n in names if n.startswith(args.source)]
    if not matches:
        print(f"no log matching {args.source!r} (have: {', '.join(names)})",
              file=sys.stderr)
        sys.exit(1)
    path = os.path.join(log_dir, matches[0])
    with open(path, "rb") as f:
        if args.follow:
            f.seek(0, os.SEEK_END if args.tail == 0 else os.SEEK_SET)
            if args.tail:
                _print_tail(f, args.tail)
            while True:
                chunk = f.read()
                if chunk:
                    sys.stdout.write(chunk.decode("utf-8", "replace"))
                    sys.stdout.flush()
                else:
                    time.sleep(0.25)
        elif args.tail:
            _print_tail(f, args.tail)
        else:
            sys.stdout.write(f.read().decode("utf-8", "replace"))


def _print_tail(f, n_lines: int):
    f.seek(0)
    lines = f.read().decode("utf-8", "replace").splitlines()
    for line in lines[-n_lines:]:
        print(line)


def cmd_microbenchmark(args):
    from ray_tpu._private import ray_perf

    ray_perf.main()


def cmd_submit(args):
    from ray_tpu.job_submission import JobSubmissionClient

    client = JobSubmissionClient()
    job_id = client.submit_job(
        entrypoint=" ".join(args.entrypoint),
        metadata={"submitted_via": "cli"})
    print(f"submitted job {job_id}")
    if args.no_wait:
        return
    status = client.wait_until_finished(job_id)
    for line in client.get_job_logs(job_id).splitlines():
        print(line)
    print(f"job {job_id}: {status}")
    sys.exit(0 if status == "SUCCEEDED" else 1)


def cmd_job(args):
    from ray_tpu.job_submission import JobSubmissionClient

    client = JobSubmissionClient()
    if args.action == "status":
        print(client.get_job_status(args.job_id))
    elif args.action == "logs":
        print(client.get_job_logs(args.job_id))
    elif args.action == "stop":
        client.stop_job(args.job_id)
        print(f"stop requested for {args.job_id}")
    elif args.action == "list":
        print(json.dumps(client.list_jobs(), indent=1, default=str))


def main(argv=None):
    p = argparse.ArgumentParser(prog="ray_tpu", description=__doc__)
    p.add_argument("--session", help="session dir (default: newest live one)")
    sub = p.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("status", help="cluster resources / actors / tasks")
    sp.add_argument("--json", action="store_true")
    sp.set_defaults(fn=cmd_status)

    sp = sub.add_parser("list", help="list cluster state")
    sp.add_argument("kind", choices=["nodes", "actors", "placement-groups", "jobs"])
    sp.set_defaults(fn=cmd_list)

    sp = sub.add_parser("logs", help="show/tail a process log")
    sp.add_argument("source", nargs="?", help="e.g. worker-0 (omit to list)")
    sp.add_argument("-f", "--follow", action="store_true")
    sp.add_argument("-n", "--tail", type=int, default=0)
    sp.set_defaults(fn=cmd_logs)

    sp = sub.add_parser("microbenchmark", help="run core runtime microbenchmarks")
    sp.set_defaults(fn=cmd_microbenchmark)

    sp = sub.add_parser("submit", help="submit a job (command) to the cluster")
    sp.add_argument("--no-wait", action="store_true")
    sp.add_argument("entrypoint", nargs=argparse.REMAINDER)
    sp.set_defaults(fn=cmd_submit)

    sp = sub.add_parser("job", help="job status / logs / stop / list")
    sp.add_argument("action", choices=["status", "logs", "stop", "list"])
    sp.add_argument("job_id", nargs="?")
    sp.set_defaults(fn=cmd_job)

    args = p.parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
