"""LLM serving microbenchmark — `python -m ray_tpu.scripts.llm_bench`.

Measures the continuous-batching engine's TTFT (time to first streamed
token), per-request decode throughput, and aggregate tokens/s under
concurrent load; writes LLM_MICROBENCH.json at the repo root so numbers are
committed round-over-round. On the CPU mesh this characterizes engine
OVERHEAD (batching, paging, scheduling); the same harness run on the real
chip gives the serving numbers (reference: vLLM-style serving benchmarks —
release/serve_tests + llm benchmarks).

Env: RAY_TPU_LLM_BENCH_{LAYERS,DMODEL,SLOTS,MAXLEN,CONCURRENCY,MAXTOKENS}
override the toy defaults.
"""

from __future__ import annotations

import os
import threading
import time


def main():
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.llm import SamplingParams, TPUEngine
    from ray_tpu.models import llama_config, transformer

    E = lambda k, d: int(os.environ.get(f"RAY_TPU_LLM_BENCH_{k}", d))
    # TPU is OPT-IN (RAY_TPU_LLM_BENCH_TPU=1): the driver computes in-process
    # here, and on this platform initializing the TPU plugin against a
    # wedged device pool hangs indefinitely — default to the CPU backend
    # exactly like bench.py's cpu child
    on_tpu = os.environ.get("RAY_TPU_LLM_BENCH_TPU") == "1"
    if on_tpu:
        # probe OUT of process with a deadline (bench.py's strategy): a
        # wedged pool must degrade to the CPU run, not hang this process
        import subprocess
        import sys as _sys

        try:
            r = subprocess.run(
                [_sys.executable, "-c",
                 "import jax; print(jax.devices()[0].platform)"],
                capture_output=True, text=True, timeout=240)
            on_tpu = r.returncode == 0 and r.stdout.strip().endswith("tpu")
        except subprocess.TimeoutExpired:
            on_tpu = False
        if not on_tpu:
            print("TPU requested but unavailable; falling back to cpu",
                  flush=True)
    import jax

    if not on_tpu:
        os.environ["JAX_PLATFORMS"] = "cpu"
        jax.config.update("jax_platforms", "cpu")
    if on_tpu:
        cfg = llama_config("tiny", vocab_size=32000, max_seq_len=2048,
                           d_model=E("DMODEL", 1024), n_layers=E("LAYERS", 8),
                           n_heads=16, n_kv_heads=8, d_ff=4096,
                           dtype=jnp.bfloat16)
        slots, max_len, conc, max_tokens = (E("SLOTS", 16), E("MAXLEN", 1024),
                                            E("CONCURRENCY", 16),
                                            E("MAXTOKENS", 64))
    else:
        cfg = llama_config("tiny", vocab_size=512, max_seq_len=256,
                           d_model=E("DMODEL", 128), n_layers=E("LAYERS", 2),
                           n_heads=4, n_kv_heads=2, d_ff=256,
                           dtype=jnp.float32)
        slots, max_len, conc, max_tokens = (E("SLOTS", 4), E("MAXLEN", 128),
                                            E("CONCURRENCY", 4),
                                            E("MAXTOKENS", 12))

    params = transformer.init(jax.random.PRNGKey(0), cfg)
    eng = TPUEngine(cfg, params, max_slots=slots, max_len=max_len,
                    min_bucket=8)
    rng = np.random.default_rng(0)
    prompt = lambda n: rng.integers(1, cfg.vocab_size, n).tolist()

    results = []

    # warm: compile the decode step AND every prefill bucket the runs below
    # will hit (16/32/64) — a first-compile inside a timed window would
    # masquerade as throughput collapse
    for n in (16, 32, 40):
        eng.generate(prompt(n), SamplingParams(max_tokens=2))

    # TTFT + single-stream decode rate
    ttfts, rates = [], []
    for _ in range(3):
        t0 = time.perf_counter()
        first = None
        n = 0
        for _tok in eng.stream(prompt(32), SamplingParams(max_tokens=max_tokens)):
            if first is None:
                first = time.perf_counter() - t0
            n += 1
        dt = time.perf_counter() - t0
        ttfts.append(first)
        if n > 1 and dt > first:
            rates.append((n - 1) / (dt - first))
    ttfts = [t for t in ttfts if t is not None]
    med = lambda xs: sorted(xs)[len(xs) // 2] if xs else float("nan")
    results.append({"name": "ttft_ms_p50",
                    "value": round(med(ttfts) * 1e3, 1) if ttfts else None})
    results.append({"name": "decode_tokens_per_s_single",
                    "value": round(med(rates), 1) if rates else None})
    print(f"TTFT p50: {results[-2]['value']} ms; "
          f"single-stream decode: {results[-1]['value']} tok/s", flush=True)

    # aggregate throughput under concurrency
    done = []
    lock = threading.Lock()

    def client(i):
        out = eng.generate(prompt(24 + (i % 3) * 8),
                           SamplingParams(max_tokens=max_tokens))
        with lock:
            done.append(len(out))

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(conc)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    total = sum(done)
    results.append({"name": f"aggregate_tokens_per_s_c{conc}",
                    "value": round(total / wall, 1)})
    results.append({"name": "requests_completed", "value": len(done)})
    print(f"aggregate: {total/wall:,.0f} tok/s over {conc} concurrent "
          f"requests ({total} tokens in {wall:.1f}s)", flush=True)
    stats = eng.stats()
    eng.shutdown()

    from ray_tpu.scripts._artifacts import write_artifact

    # LLM_BENCH.json is owned by benchmarks/llm_serving_bench.py
    # (flat schema); this CLI microbenchmark keeps its own artifact
    print("wrote", write_artifact("LLM_MICROBENCH.json", {
        "backend": "tpu" if on_tpu else "cpu",
        "config": {"d_model": cfg.d_model, "layers": cfg.n_layers,
                   "slots": slots, "concurrency": conc},
        "engine_stats": stats, "results": results}))


if __name__ == "__main__":
    main()
