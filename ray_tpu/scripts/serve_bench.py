"""Serve data-plane microbenchmark — `python -m ray_tpu.scripts.serve_bench`.

Measures noop HTTP latency (sequential + concurrent), handle-path latency,
and concurrent SSE streaming; writes SERVE_BENCH.json at the repo root so
numbers are committed round-over-round.

(reference: the serve microbenchmarks under release/serve_tests — noop
latency / throughput over the proxy; VERDICT round-2 weak item 5.)
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request


def _post(url, payload, timeout=60):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, resp.read()


def main():
    import ray_tpu
    from ray_tpu import serve

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=32, num_workers=2, max_workers=10)
    results = []

    @serve.deployment(num_replicas=2, max_ongoing_requests=32)
    def noop(req):
        return {"ok": True}

    @serve.deployment(num_replicas=1, max_ongoing_requests=32)
    class Streamer:
        def stream_request(self, req):
            for i in range(((req.get("body") or {}).get("n") or 16)):
                yield {"i": i}

        def __call__(self, req):
            return {"ok": True}

    serve.run(noop.bind(), name="noop", route_prefix="/noop")
    serve.run(Streamer.bind(), name="stream", route_prefix="/stream")
    serve.start(http_port=0)
    host, port = serve.http_address()
    url = f"http://{host}:{port}/noop"
    _post(url, {})  # warm

    # sequential noop latency over one keep-alive connection
    import http.client

    conn = http.client.HTTPConnection(host, port, timeout=30)
    N = 300
    t0 = time.perf_counter()
    for _ in range(N):
        conn.request("POST", "/noop", body=b"{}",
                     headers={"Content-Type": "application/json"})
        conn.getresponse().read()
    dt = (time.perf_counter() - t0) / N
    conn.close()
    results.append({"name": "http_noop_sequential",
                    "ops_per_s": round(1 / dt, 1),
                    "us_per_op": round(dt * 1e6, 1)})
    print(f"http_noop_sequential: {1/dt:,.0f} req/s  ({dt*1e3:.2f} ms)")

    # concurrent noop throughput (16 client threads, keep-alive each)
    CT, PER = 16, 60
    done = []

    def worker():
        c = http.client.HTTPConnection(host, port, timeout=30)
        n = 0
        for _ in range(PER):
            c.request("POST", "/noop", body=b"{}",
                      headers={"Content-Type": "application/json"})
            r = c.getresponse()
            r.read()
            if r.status == 200:
                n += 1
        c.close()
        done.append(n)

    threads = [threading.Thread(target=worker) for _ in range(CT)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    ok = sum(done)
    results.append({"name": "http_noop_concurrent16",
                    "ops_per_s": round(ok / wall, 1),
                    "us_per_op": round(wall / max(ok, 1) * 1e6, 1)})
    print(f"http_noop_concurrent16: {ok/wall:,.0f} req/s ({ok} ok)")

    # handle path (no HTTP)
    handle = serve.get_deployment_handle("noop", app_name="noop")
    t0 = time.perf_counter()
    for _ in range(N):
        handle.remote({}).result(timeout_s=30)
    dt = (time.perf_counter() - t0) / N
    results.append({"name": "handle_noop_sequential",
                    "ops_per_s": round(1 / dt, 1),
                    "us_per_op": round(dt * 1e6, 1)})
    print(f"handle_noop_sequential: {1/dt:,.0f} req/s  ({dt*1e3:.2f} ms)")

    # concurrent SSE streams: 8 clients x 32 events
    SC, EVENTS = 8, 32
    stream_ok = []

    def stream_worker():
        req = urllib.request.Request(
            f"http://{host}:{port}/stream",
            data=json.dumps({"n": EVENTS, "stream": True}).encode(),
            headers={"Content-Type": "application/json",
                     "Accept": "text/event-stream"})
        with urllib.request.urlopen(req, timeout=60) as resp:
            n = sum(1 for ln in resp if ln.startswith(b"data:")) - 1  # [DONE]
        stream_ok.append(n)

    sthreads = [threading.Thread(target=stream_worker) for _ in range(SC)]
    t0 = time.perf_counter()
    for t in sthreads:
        t.start()
    for t in sthreads:
        t.join()
    wall = time.perf_counter() - t0
    events = sum(stream_ok)
    assert all(n == EVENTS for n in stream_ok), stream_ok
    results.append({"name": "sse_stream_concurrent8_events_per_s",
                    "ops_per_s": round(events / wall, 1),
                    "us_per_op": round(wall / max(events, 1) * 1e6, 1)})
    print(f"sse_concurrent8: {events/wall:,.0f} events/s ({len(stream_ok)} streams complete)")

    serve.shutdown()
    ray_tpu.shutdown()
    from ray_tpu.scripts._artifacts import merge_artifact

    # section-preserving write: serve_shard_bench owns the "sharded" section
    print("wrote", merge_artifact("SERVE_BENCH.json", "results", results))


if __name__ == "__main__":
    main()
