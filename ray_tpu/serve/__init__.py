"""ray_tpu.serve — model serving.

(reference: python/ray/serve/ — deployments + controller-reconciled replica
actors, DeploymentHandles with power-of-two routing, per-node HTTP proxy,
ongoing-request autoscaling, dynamic batching, model multiplexing.)
"""

from ray_tpu.serve.api import (
    delete,
    get_app_handle,
    get_deployment_handle,
    http_address,
    proxy_status,
    run,
    shutdown,
    start,
    status,
)
from ray_tpu.serve.batching import batch
from ray_tpu.serve.config import AutoscalingConfig, DeploymentConfig
from ray_tpu.serve.deployment import Application, Deployment, deployment
from ray_tpu.serve.handle import DeploymentHandle, DeploymentResponse
from ray_tpu.serve.multiplex import multiplexed
from ray_tpu.serve.replica import get_multiplexed_model_id
from ray_tpu.serve.rpc_ingress import RPCClient, start_rpc_ingress
from ray_tpu.serve.schema import (SchemaError, ServeDeploySchema, build,
                                  deploy, load_config)

__all__ = [
    "Application",
    "AutoscalingConfig",
    "Deployment",
    "DeploymentConfig",
    "DeploymentHandle",
    "DeploymentResponse",
    "batch",
    "delete",
    "deployment",
    "get_app_handle",
    "get_deployment_handle",
    "get_multiplexed_model_id",
    "http_address",
    "multiplexed",
    "proxy_status",
    "run",
    "shutdown",
    "start",
    "start_rpc_ingress",
    "RPCClient",
    "SchemaError",
    "ServeDeploySchema",
    "build",
    "deploy",
    "load_config",
    "status",
]
