"""serve public API: run / delete / status / shutdown / handles / proxy.

(reference: python/ray/serve/api.py — serve.run:694 deploys an Application
through the controller and returns the ingress DeploymentHandle; serve.start
brings up the proxy; serve.status/delete/shutdown manage lifecycle.)
"""

from __future__ import annotations

import dataclasses
import time

import ray_tpu
from ray_tpu.serve.controller import CONTROLLER_NAME, ServeController
from ray_tpu.serve.deployment import Application
from ray_tpu.serve.handle import DeploymentHandle

_proxy = None
_proxy_plane_addr = None  # (host, port) of the sharded ingress, when up


def _get_controller(create: bool = False):
    try:
        return ray_tpu.get_actor(CONTROLLER_NAME, namespace="_system")
    except ValueError:
        if not create:
            raise RuntimeError("serve is not running; call serve.run/start first") from None
    # create path. The name may transiently be held by a DYING controller
    # (a concurrent serve.shutdown's kill not yet tombstoned) or won by a
    # concurrent creator — loop resolve→create with backoff so both the
    # "now tombstoned: create again" and "other creator won: resolve it"
    # transitions succeed instead of failing the caller.
    deadline = time.monotonic() + 10.0
    backoff = 0.05
    while True:
        try:
            # crash-restartable control plane: the GCS restarts the
            # controller in place (same actor id, name kept) and its
            # __init__ rebuilds from the persisted serve table; in-flight
            # calls retry on the restarted incarnation (mutations are
            # idempotent — deploys compare blobs, persists are upserts)
            return ServeController.options(
                name=CONTROLLER_NAME, namespace="_system", num_cpus=0.5,
                max_restarts=-1, max_task_retries=-1).remote()
        except ValueError as e:
            # only a NAME conflict is retryable (dying actor or a creation
            # race); any other GCS rejection must surface, not be retried
            # into a misleading "name stayed held" timeout
            if "already exists" not in str(e):
                raise
        try:
            return ray_tpu.get_actor(CONTROLLER_NAME, namespace="_system")
        except ValueError:
            pass  # tombstoned between the two attempts: create next pass
        if time.monotonic() >= deadline:
            raise RuntimeError(
                "could not create or resolve the serve controller "
                f"(the name {CONTROLLER_NAME!r} stayed held)")
        time.sleep(backoff)
        backoff = min(backoff * 2, 0.5)


def _resolve_controller(timeout_s: float = 5.0):
    """Re-resolve the controller by name with retry/backoff (reference:
    serve clients look the controller up by name rather than caching a
    dead handle). Used by routers/proxies healing after a controller death
    and by creation races."""
    deadline = time.monotonic() + timeout_s
    backoff = 0.05
    while True:
        try:
            return ray_tpu.get_actor(CONTROLLER_NAME, namespace="_system")
        except ValueError:
            if time.monotonic() >= deadline:
                raise RuntimeError(
                    "serve is not running; call serve.run/start first") from None
            time.sleep(backoff)
            backoff = min(backoff * 2, 0.5)


def start(*, http_host: str = "127.0.0.1", http_port: int = 8000,
          proxy: bool = True, num_proxies: int | None = None):
    """Ensure controller (and optionally the HTTP ingress) are up.

    ``num_proxies`` selects the ingress topology: 0 (the default, via
    `RayConfig.serve_num_proxies`) keeps the original single in-driver
    ProxyActor; >= 1 starts the controller-managed sharded proxy plane —
    N workers accepting on ONE port (SO_REUSEPORT, or fd-passed acceptor
    where unavailable), routing from the controller's shm routing-table
    broadcast."""
    global _proxy, _proxy_plane_addr
    from ray_tpu._private.ray_config import RayConfig

    controller = _get_controller(create=True)
    if num_proxies is None:
        num_proxies = RayConfig.instance().serve_num_proxies
    if not proxy:
        return controller
    if num_proxies and num_proxies > 0:
        if _proxy_plane_addr is None:
            st = ray_tpu.get(controller.start_proxy_plane.remote(
                http_host, http_port, int(num_proxies)), timeout=60.0)
            _proxy_plane_addr = (st["host"], st["port"])
            # wait until at least one shard is accepting: callers (and
            # every existing test idiom) expect start() to return a
            # connectable ingress
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                st = ray_tpu.get(controller.proxy_status.remote())
                if st and any(s.get("state") == "running"
                              for s in st["shards"].values()):
                    break
                time.sleep(0.05)
    elif _proxy is None:
        from ray_tpu.serve.proxy import ProxyActor

        _proxy = ProxyActor.options(num_cpus=0.5, max_concurrency=32).remote(
            http_host, http_port)
        ray_tpu.get(_proxy.address.remote())  # wait until listening
    return controller


def run(target: Application, *, name: str = "default",
        route_prefix: str | None = "/", _blocking: bool = False,
        proxy: bool = False) -> DeploymentHandle:
    """Deploy an application; returns a handle to its ingress deployment."""
    from ray_tpu._private import serialization as ser

    if not isinstance(target, Application):
        raise TypeError("serve.run expects a bound deployment: d.bind(...)")
    controller = start(proxy=proxy) if proxy else _get_controller(create=True)

    apps = target.flatten()
    specs = []
    for app in apps:
        # replace nested Applications in init args with handles to them
        def to_handle(a):
            if isinstance(a, Application):
                return DeploymentHandle(f"{name}_{a.deployment.name}", controller)
            return a

        args = tuple(to_handle(a) for a in app.init_args)
        kwargs = {k: to_handle(v) for k, v in app.init_kwargs.items()}
        cfg = app.deployment.config
        cfg_dict = {
            "initial_replicas": cfg.initial_replicas,
            "max_ongoing_requests": cfg.max_ongoing_requests,
            "max_queued_requests": cfg.max_queued_requests,
            "ray_actor_options": cfg.ray_actor_options,
            "user_config": cfg.user_config,
            "autoscaling_config": (dataclasses.asdict(cfg.autoscaling_config)
                                   if cfg.autoscaling_config else None),
            "request_router": cfg.request_router,
            "health_check_period_s": cfg.health_check_period_s,
            "health_check_timeout_s": cfg.health_check_timeout_s,
            "graceful_shutdown_timeout_s": cfg.graceful_shutdown_timeout_s,
        }
        specs.append({
            "name": app.deployment.name,
            "callable_blob": ser.dumps(app.deployment.func_or_class),
            "init_args_blob": ser.dumps((args, kwargs)),
            "config": cfg_dict,
        })
    ingress = target.deployment.name
    ray_tpu.get(controller.deploy_application.remote(name, specs, route_prefix, ingress))
    handle = DeploymentHandle(f"{name}_{ingress}", controller)
    return handle


def get_app_handle(name: str = "default") -> DeploymentHandle:
    controller = _get_controller()
    table = ray_tpu.get(controller.get_routing_table.remote(-1))
    ingress = table.get("apps", {}).get(name)
    if ingress is None:
        raise ValueError(f"no application named {name!r}")
    return DeploymentHandle(ingress, controller)


def get_deployment_handle(deployment_name: str, app_name: str = "default") -> DeploymentHandle:
    return DeploymentHandle(f"{app_name}_{deployment_name}", _get_controller())


def status() -> dict:
    return ray_tpu.get(_get_controller().status.remote())


def delete(name: str = "default"):
    ray_tpu.get(_get_controller().delete_application.remote(name))


def http_address() -> tuple[str, int] | None:
    if _proxy_plane_addr is not None:
        return tuple(_proxy_plane_addr)
    if _proxy is None:
        return None
    return tuple(ray_tpu.get(_proxy.address.remote()))


def proxy_status() -> dict | None:
    """Sharded proxy plane status (shard states/health), or None when the
    plane isn't running."""
    return ray_tpu.get(_get_controller().proxy_status.remote())


def shutdown():
    global _proxy, _proxy_plane_addr
    _proxy_plane_addr = None  # plane teardown rides controller.shutdown
    try:
        controller = _get_controller()
    except RuntimeError:
        controller = None
    if _proxy is not None:
        try:
            ray_tpu.get(_proxy.shutdown.remote())
            ray_tpu.kill(_proxy)
        except Exception:
            pass
        _proxy = None
    if controller is not None:
        try:
            ray_tpu.get(controller.shutdown.remote())
            ray_tpu.kill(controller)
        except Exception:
            pass
        # wait until the controller actor is actually DEAD (not merely
        # kill-requested): a next serve.run in this session must either
        # find no actor under the name (→ create) or a live one — never a
        # dying one whose in-flight deploys die with it
        from ray_tpu._private.api import _get_worker

        w = _get_worker()
        if hasattr(w, "rpc"):
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                try:
                    info = w.rpc({"type": "actor_info",
                                  "aid": controller.actor_id})
                except Exception:  # noqa: BLE001
                    break
                if not info.get("found") or info.get("state") == "dead":
                    break
                time.sleep(0.05)
