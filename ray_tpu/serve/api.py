"""serve public API: run / delete / status / shutdown / handles / proxy.

(reference: python/ray/serve/api.py — serve.run:694 deploys an Application
through the controller and returns the ingress DeploymentHandle; serve.start
brings up the proxy; serve.status/delete/shutdown manage lifecycle.)
"""

from __future__ import annotations

import dataclasses

import ray_tpu
from ray_tpu.serve.controller import CONTROLLER_NAME, ServeController
from ray_tpu.serve.deployment import Application
from ray_tpu.serve.handle import DeploymentHandle

_proxy = None


def _get_controller(create: bool = False):
    try:
        return ray_tpu.get_actor(CONTROLLER_NAME, namespace="_system")
    except ValueError:
        if not create:
            raise RuntimeError("serve is not running; call serve.run/start first") from None
        return ServeController.options(
            name=CONTROLLER_NAME, namespace="_system", num_cpus=0.5).remote()


def start(*, http_host: str = "127.0.0.1", http_port: int = 8000,
          proxy: bool = True):
    """Ensure controller (and optionally the HTTP proxy) are up."""
    global _proxy
    controller = _get_controller(create=True)
    if proxy and _proxy is None:
        from ray_tpu.serve.proxy import ProxyActor

        _proxy = ProxyActor.options(num_cpus=0.5, max_concurrency=32).remote(
            http_host, http_port)
        ray_tpu.get(_proxy.address.remote())  # wait until listening
    return controller


def run(target: Application, *, name: str = "default",
        route_prefix: str | None = "/", _blocking: bool = False,
        proxy: bool = False) -> DeploymentHandle:
    """Deploy an application; returns a handle to its ingress deployment."""
    from ray_tpu._private import serialization as ser

    if not isinstance(target, Application):
        raise TypeError("serve.run expects a bound deployment: d.bind(...)")
    controller = start(proxy=proxy) if proxy else _get_controller(create=True)

    apps = target.flatten()
    specs = []
    for app in apps:
        # replace nested Applications in init args with handles to them
        def to_handle(a):
            if isinstance(a, Application):
                return DeploymentHandle(f"{name}_{a.deployment.name}", controller)
            return a

        args = tuple(to_handle(a) for a in app.init_args)
        kwargs = {k: to_handle(v) for k, v in app.init_kwargs.items()}
        cfg = app.deployment.config
        cfg_dict = {
            "initial_replicas": cfg.initial_replicas,
            "max_ongoing_requests": cfg.max_ongoing_requests,
            "ray_actor_options": cfg.ray_actor_options,
            "user_config": cfg.user_config,
            "autoscaling_config": (dataclasses.asdict(cfg.autoscaling_config)
                                   if cfg.autoscaling_config else None),
            "request_router": cfg.request_router,
        }
        specs.append({
            "name": app.deployment.name,
            "callable_blob": ser.dumps(app.deployment.func_or_class),
            "init_args_blob": ser.dumps((args, kwargs)),
            "config": cfg_dict,
        })
    ingress = target.deployment.name
    ray_tpu.get(controller.deploy_application.remote(name, specs, route_prefix, ingress))
    handle = DeploymentHandle(f"{name}_{ingress}", controller)
    return handle


def get_app_handle(name: str = "default") -> DeploymentHandle:
    controller = _get_controller()
    table = ray_tpu.get(controller.get_routing_table.remote(-1))
    ingress = table.get("apps", {}).get(name)
    if ingress is None:
        raise ValueError(f"no application named {name!r}")
    return DeploymentHandle(ingress, controller)


def get_deployment_handle(deployment_name: str, app_name: str = "default") -> DeploymentHandle:
    return DeploymentHandle(f"{app_name}_{deployment_name}", _get_controller())


def status() -> dict:
    return ray_tpu.get(_get_controller().status.remote())


def delete(name: str = "default"):
    ray_tpu.get(_get_controller().delete_application.remote(name))


def http_address() -> tuple[str, int] | None:
    if _proxy is None:
        return None
    return tuple(ray_tpu.get(_proxy.address.remote()))


def shutdown():
    global _proxy
    try:
        controller = _get_controller()
    except RuntimeError:
        controller = None
    if _proxy is not None:
        try:
            ray_tpu.get(_proxy.shutdown.remote())
            ray_tpu.kill(_proxy)
        except Exception:
            pass
        _proxy = None
    if controller is not None:
        try:
            ray_tpu.get(controller.shutdown.remote())
            ray_tpu.kill(controller)
        except Exception:
            pass
