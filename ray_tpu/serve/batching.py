"""@serve.batch — transparent dynamic request batching.

(reference: python/ray/serve/batching.py — queued requests are flushed to the
wrapped function as a list when max_batch_size is reached or
batch_wait_timeout_s elapses; each caller gets its own element back. The
reference is asyncio; here callers are replica threads (max_concurrency > 1)
blocking on futures, flushed by a dedicated thread per wrapped function.)
"""

from __future__ import annotations

import functools
import threading
from concurrent.futures import Future


class _BatchQueue:
    def __init__(self, fn, max_batch_size: int, batch_wait_timeout_s: float):
        self.fn = fn
        self.max_batch_size = max_batch_size
        self.timeout_s = batch_wait_timeout_s
        self.items: list[tuple[object, Future]] = []
        self.lock = threading.Lock()
        self.not_empty = threading.Condition(self.lock)
        self.thread = threading.Thread(target=self._flush_loop, daemon=True,
                                       name="serve-batch")
        self.thread.start()

    def submit(self, instance, item) -> Future:
        fut: Future = Future()
        with self.lock:
            self.items.append((instance, item, fut))
            self.not_empty.notify()
        return fut

    def _flush_loop(self):
        while True:
            with self.not_empty:
                while not self.items:
                    self.not_empty.wait()
                # wait for more work up to the batch window; only items for
                # the instance at the head of the queue count toward a full
                # batch (that's all the flush below will take)
                head = self.items[0][0]

                def _head_count():
                    return sum(1 for inst, _, _ in self.items if inst is head)

                if _head_count() < self.max_batch_size:
                    self.not_empty.wait_for(
                        lambda: _head_count() >= self.max_batch_size,
                        timeout=self.timeout_s)
                # flush only items bound to the same instance — a queue is
                # per-function per-process, but a decorated method may be
                # called on several instances, and a batch must run against
                # the instance its callers used
                inst0 = self.items[0][0]
                batch, rest = [], []
                for tup in self.items:
                    if len(batch) < self.max_batch_size and tup[0] is inst0:
                        batch.append(tup)
                    else:
                        rest.append(tup)
                self.items = rest
                if rest:
                    self.not_empty.notify()
            instance = batch[0][0]
            inputs = [item for _, item, _ in batch]
            futures = [f for _, _, f in batch]
            try:
                outputs = (self.fn(instance, inputs) if instance is not None
                           else self.fn(inputs))
                if len(outputs) != len(inputs):
                    raise ValueError(
                        f"batch function returned {len(outputs)} results "
                        f"for {len(inputs)} inputs")
                for f, out in zip(futures, outputs):
                    f.set_result(out)
            except Exception as e:  # noqa: BLE001 — propagate to all callers
                for f in futures:
                    f.set_exception(e)


# lazy-creation guard: module-level so wrapped functions stay picklable
# (closures must hold only plain data — they ship to replicas by value)
_create_lock = threading.Lock()


def batch(_fn=None, *, max_batch_size: int = 8, batch_wait_timeout_s: float = 0.01,
          result_timeout_s: float | None = None):
    """Decorator for methods/functions taking a single request; the wrapped
    implementation receives a list and returns a list. `result_timeout_s`
    bounds each caller's wait (None = wait for the batch however long)."""

    def wrap(fn):
        state: dict = {"queue": None}  # per-process queue, created on first call

        def get_queue():
            # import at call time: this closure ships to replicas by value,
            # so it must not capture locks/classes as globals
            from ray_tpu.serve import batching as _b

            q = state["queue"]
            if q is None:
                with _b._create_lock:
                    q = state["queue"]
                    if q is None:
                        q = state["queue"] = _b._BatchQueue(
                            fn, max_batch_size, batch_wait_timeout_s)
            return q

        @functools.wraps(fn)
        def method_wrapper(self, item):
            return get_queue().submit(self, item).result(timeout=result_timeout_s)

        @functools.wraps(fn)
        def fn_wrapper(item):
            return get_queue().submit(None, item).result(timeout=result_timeout_s)

        import inspect

        params = list(inspect.signature(fn).parameters)
        wrapper = method_wrapper if params and params[0] == "self" else fn_wrapper
        wrapper._is_serve_batch = True  # noqa: SLF001
        return wrapper

    if _fn is not None:
        return wrap(_fn)
    return wrap
